"""Bisect the bench-loop slowdown (dev tool)."""
import time
import jax, jax.numpy as jnp

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import state as st, step as step_lib
from hermes_tpu.workload import ycsb


def make(donate):
    cfg = HermesConfig(
        n_replicas=8, n_keys=1 << 20, value_words=8, n_sessions=4096,
        replay_slots=256, ops_per_session=128,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    r = cfg.n_replicas
    rs = jax.device_put(jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), st.init_replica_state(cfg)))
    stream = jax.device_put(jax.tree.map(jnp.asarray, ycsb.make_streams(cfg)))
    return cfg, rs, stream, step_lib.build_step_batched(cfg, donate=donate)


def loop(tag, donate, fresh_ctl, n=30):
    cfg, rs, stream, step = make(donate)
    ctl0 = step_lib.make_ctl(cfg, 0)
    for s in range(5):
        rs, _ = step(rs, stream, step_lib.make_ctl(cfg, s) if fresh_ctl else ctl0)
    jax.block_until_ready(rs)
    t0 = time.perf_counter()
    for s in range(5, 5 + n):
        rs, _ = step(rs, stream, step_lib.make_ctl(cfg, s) if fresh_ctl else ctl0)
    jax.block_until_ready(rs)
    print(f"{tag:40s}: {(time.perf_counter() - t0) / n * 1e3:8.2f} ms/step")


if __name__ == "__main__":
    loop("donate=False fresh_ctl=False", False, False)
    loop("donate=False fresh_ctl=True", False, True)
    loop("donate=True  fresh_ctl=False", True, False)
    loop("donate=True  fresh_ctl=True", True, True)
