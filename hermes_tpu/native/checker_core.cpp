// Native linearizability witness checker (SURVEY.md §2 "Linearizability
// checker" row: C++ core for bench-scale histories).
//
// Port of checker/linearizability.py::_check_witness over packed columns:
// per key, updates ordered by protocol timestamp form a candidate
// linearization (each read placed after the update that wrote its value);
// verifying it is O(n log n).  Keys whose witness fails — or where it does
// not apply — are returned as "suspects" for the exact (Wing&Gong) Python
// search, so the shortcut can never produce a false PASS or a false FAIL.
//
// Build: g++ -O2 -shared -fPIC -o libhermes_checker.so checker_core.cpp
// ABI (ctypes, checker/fast.py):
//   kind: 0=read, 1=write, 2=rmw, 3=maybe_w (incomplete update)
//   inv/resp: doubled step times (read resp=2s, update resp=2s+1),
//             resp=INT64_MAX for incomplete
//   wuid/ruid: (uint32(hi)<<32)|uint32(lo); ruid=INT64_MIN when absent
//   ts: (int64(ver)<<32)|uint32(fc); INT64_MIN when absent

#include <algorithm>
#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr int64_t kNone = INT64_MIN;

struct Group {
  std::vector<int64_t> ops;  // indices into the column arrays
};

}  // namespace

extern "C" {

// Returns the number of suspect keys (written to out_keys, up to max_out;
// the count may exceed max_out — callers should size generously).
// A negative return value signals invalid arguments.
int64_t hc_check_witness(int64_t n, const int32_t* key, const int8_t* kind,
                         const int64_t* inv, const int64_t* resp,
                         const int64_t* wuid, const int64_t* ruid,
                         const int64_t* ts, int32_t* out_keys,
                         int64_t max_out) {
  if (n < 0 || max_out < 0) return -1;

  std::unordered_map<int32_t, Group> by_key;
  by_key.reserve(static_cast<size_t>(n) / 4 + 16);
  for (int64_t i = 0; i < n; ++i) by_key[key[i]].ops.push_back(i);

  std::vector<int32_t> suspects;

  for (auto& [k, g] : by_key) {
    bool suspect = false;

    // observed read-values (for admitting maybe_w updates) and reads-by-uid
    std::unordered_set<int64_t> observed;
    std::unordered_map<int64_t, std::vector<int64_t>> reads_by_uid;
    for (int64_t i : g.ops) {
      if (ruid[i] != kNone) observed.insert(ruid[i]);
      if (kind[i] == 0) reads_by_uid[ruid[i]].push_back(i);
    }

    // updates: w/rmw always; maybe_w only if its value was observed
    std::vector<int64_t> updates;
    for (int64_t i : g.ops) {
      if (kind[i] == 1 || kind[i] == 2 ||
          (kind[i] == 3 && observed.count(wuid[i]))) {
        if (ts[i] == kNone) {
          suspect = true;  // witness inapplicable
          break;
        }
        updates.push_back(i);
      }
    }
    if (!suspect) {
      std::sort(updates.begin(), updates.end(),
                [&](int64_t a, int64_t b) { return ts[a] < ts[b]; });
      for (size_t j = 1; j < updates.size(); ++j) {
        if (ts[updates[j]] == ts[updates[j - 1]]) {
          suspect = true;  // duplicate timestamps: protocol bug
          break;
        }
      }
    }

    if (!suspect) {
      for (auto& [uid, rl] : reads_by_uid) {
        std::sort(rl.begin(), rl.end(),
                  [&](int64_t a, int64_t b) { return inv[a] < inv[b]; });
      }
      // candidate order: reads(initial), then per ts-ordered update: the
      // update then reads of its value; greedy real-time feasibility
      const uint64_t hi = static_cast<uint32_t>(-1);
      const int64_t initial =
          static_cast<int64_t>((hi << 32) | static_cast<uint32_t>(k));
      std::unordered_set<int64_t> known{initial};
      int64_t cur = initial;
      int64_t p = INT64_MIN;
      auto feed = [&](int64_t i) {
        p = std::max(p, inv[i]);
        if (p > resp[i]) suspect = true;
      };
      auto feed_reads = [&](int64_t uid) {
        auto it = reads_by_uid.find(uid);
        if (it == reads_by_uid.end()) return;
        for (int64_t i : it->second) {
          feed(i);
          if (suspect) return;
        }
      };
      feed_reads(initial);
      for (int64_t u : updates) {
        if (suspect) break;
        if (kind[u] == 2 && ruid[u] != cur) {
          suspect = true;  // RMW observed a value other than its predecessor
          break;
        }
        feed(u);
        if (suspect) break;
        cur = wuid[u];
        known.insert(cur);
        feed_reads(cur);
      }
      if (!suspect) {
        for (auto& [uid, rl] : reads_by_uid) {
          if (!known.count(uid)) {
            suspect = true;  // read of an unknown value
            break;
          }
        }
      }
    }

    if (suspect) suspects.push_back(k);
  }

  int64_t n_out = std::min<int64_t>(suspects.size(), max_out);
  for (int64_t i = 0; i < n_out; ++i) out_keys[i] = suspects[i];
  return static_cast<int64_t>(suspects.size());
}

}  // extern "C"
