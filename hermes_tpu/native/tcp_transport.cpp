// TCP net-transport core (SURVEY.md §2 "Net-transport: tcp", §5.8).
//
// The reference's transport plugin layer moves INV/ACK/VAL batches between
// replicas; its `tcp` backend is a socket implementation behind the same
// interface as `rdma`.  This is the rebuild's native equivalent: a small
// C++ full-mesh exchanger doing step-synchronous block exchange between
// replica processes.  The Python side (hermes_tpu/transport/tcp.py) binds it
// with ctypes and adapts it to the HostTransport interface.
//
// Design: one listening socket per rank at base_port+rank; every ordered
// pair (i -> j) communicates over the connection i dialed to j.  An exchange
// sends one length-prefixed block to every peer (a sender thread per peer,
// so large blocks cannot deadlock against full send buffers) and receives
// exactly one block from every peer.  TCP gives per-edge FIFO + reliability,
// matching the sim transport's channel semantics with zero-step delay.
//
// Build: g++ -O2 -shared -fPIC -o libhermes_tcp.so tcp_transport.cpp -pthread

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Mesh {
  int my_rank = 0;
  int n_ranks = 0;
  // fds[r]: the socket carrying traffic between this rank and rank r
  // (for r == my_rank, -1: self-delivery is done in Python by memcpy).
  std::vector<int> fds;
  int listen_fd = -1;
};

int set_common_opts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return 0;
}

bool send_all(int fd, const uint8_t* buf, size_t n) {
  while (n > 0) {
    ssize_t w = ::send(fd, buf, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    buf += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, uint8_t* buf, size_t n) {
  while (n > 0) {
    ssize_t r = ::recv(fd, buf, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    buf += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// recv_all with a deadline: a peer that is alive but wedged (stopped,
// GIL-stuck) never closes its socket, so a bare recv() would block every
// other rank forever.  Steady state gets the same bounded-wait discipline as
// the ht_create accept/dial path.
bool recv_all_timeout(int fd, uint8_t* buf, size_t n, int timeout_ms) {
  while (n > 0) {
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, timeout_ms);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return false;  // timeout or poll error
    ssize_t r = ::recv(fd, buf, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    buf += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

}  // namespace

extern "C" {

// Create the full mesh.  hosts: comma-separated peer IPs (n_ranks entries).
// Returns an opaque handle (heap pointer) or nullptr on failure.
void* ht_create(int my_rank, int n_ranks, const char* hosts_csv, int base_port) {
  auto* m = new Mesh();
  m->my_rank = my_rank;
  m->n_ranks = n_ranks;
  m->fds.assign(n_ranks, -1);

  std::vector<std::string> hosts;
  {
    std::string s(hosts_csv ? hosts_csv : "");
    size_t pos = 0;
    while (pos <= s.size()) {
      size_t c = s.find(',', pos);
      if (c == std::string::npos) c = s.size();
      hosts.push_back(s.substr(pos, c - pos));
      pos = c + 1;
    }
  }
  if (static_cast<int>(hosts.size()) < n_ranks) {
    delete m;
    return nullptr;
  }

  // Listen for lower ranks (they dial us).
  m->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(m->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(base_port + my_rank));
  if (bind(m->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(m->listen_fd, n_ranks) != 0) {
    ::close(m->listen_fd);
    delete m;
    return nullptr;
  }

  // Dial higher ranks; accept lower ranks.  Each accepted/established
  // connection starts with a 4-byte rank handshake.
  std::thread acceptor([m]() {
    int need = m->my_rank;  // ranks 0..my_rank-1 dial us
    for (int i = 0; i < need; ++i) {
      // Bounded wait (matches the ~60s dial retry budget): if a lower rank
      // never shows up, ht_create must FAIL, not hang forever in accept().
      pollfd pfd{m->listen_fd, POLLIN, 0};
      int pr = ::poll(&pfd, 1, 60 * 1000);
      if (pr <= 0) return;
      int fd = ::accept(m->listen_fd, nullptr, nullptr);
      if (fd < 0) return;
      int32_t peer = -1;
      if (!recv_all(fd, reinterpret_cast<uint8_t*>(&peer), 4) || peer < 0 ||
          peer >= m->n_ranks) {
        ::close(fd);
        return;
      }
      set_common_opts(fd);
      m->fds[peer] = fd;
    }
  });

  bool ok = true;
  for (int peer = m->my_rank + 1; peer < n_ranks; ++peer) {
    int fd = -1;
    for (int attempt = 0; attempt < 600; ++attempt) {  // ~60s of retries
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in pa{};
      pa.sin_family = AF_INET;
      pa.sin_port = htons(static_cast<uint16_t>(base_port + peer));
      inet_pton(AF_INET, hosts[peer].c_str(), &pa.sin_addr);
      if (connect(fd, reinterpret_cast<sockaddr*>(&pa), sizeof(pa)) == 0) break;
      ::close(fd);
      fd = -1;
      usleep(100 * 1000);
    }
    if (fd < 0) {
      ok = false;
      break;
    }
    int32_t me = m->my_rank;
    if (!send_all(fd, reinterpret_cast<const uint8_t*>(&me), 4)) {
      ok = false;
      ::close(fd);
      break;
    }
    set_common_opts(fd);
    m->fds[peer] = fd;
  }

  acceptor.join();
  for (int r = 0; r < n_ranks && ok; ++r) {
    if (r != m->my_rank && m->fds[r] < 0) ok = false;
  }
  if (!ok) {
    for (int fd : m->fds)
      if (fd >= 0) ::close(fd);
    if (m->listen_fd >= 0) ::close(m->listen_fd);
    delete m;
    return nullptr;
  }
  return m;
}

// Exchange fixed-size blocks with every peer.
//   out: n_ranks * block_size bytes; slice r goes to rank r.
//   in:  n_ranks * block_size bytes; slice r receives from rank r.
// The self slice is copied locally.  Returns 0 on success.
int ht_exchange(void* handle, const uint8_t* out, uint64_t block_size, uint8_t* in) {
  auto* m = static_cast<Mesh*>(handle);
  std::vector<std::thread> senders;
  senders.reserve(m->n_ranks);
  std::atomic<bool> send_ok{true};
  for (int r = 0; r < m->n_ranks; ++r) {
    if (r == m->my_rank) {
      std::memcpy(in + r * block_size, out + r * block_size, block_size);
      continue;
    }
    senders.emplace_back([m, r, out, block_size, &send_ok]() {
      if (!send_all(m->fds[r], out + r * block_size, block_size))
        send_ok.store(false, std::memory_order_relaxed);
    });
  }
  bool recv_ok = true;
  for (int r = 0; r < m->n_ranks; ++r) {
    if (r == m->my_rank) continue;
    if (!recv_all_timeout(m->fds[r], in + r * block_size, block_size, 60 * 1000))
      recv_ok = false;
  }
  for (auto& t : senders) t.join();
  return (send_ok.load(std::memory_order_relaxed) && recv_ok) ? 0 : -1;
}

void ht_destroy(void* handle) {
  auto* m = static_cast<Mesh*>(handle);
  if (!m) return;
  for (int fd : m->fds)
    if (fd >= 0) ::close(fd);
  if (m->listen_fd >= 0) ::close(m->listen_fd);
  delete m;
}

}  // extern "C"
