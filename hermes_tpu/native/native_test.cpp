// Standalone sanitizer harness for the native components (SURVEY.md §5.2).
//
// Exercises the C++ TCP transport (threads + sockets: the race-prone code)
// and the checker core WITHOUT Python/JAX in the address space, so
// ASan/UBSan/TSan findings are actionable and belong to OUR code.
//
// Build+run (scripts/native_sanitize.sh):
//   g++ -fsanitize=... native_test.cpp tcp_transport.cpp checker_core.cpp

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* ht_create(int my_rank, int n_ranks, const char* hosts_csv, int base_port);
int ht_exchange(void* handle, const uint8_t* out, uint64_t block_size,
                uint8_t* in);
void ht_destroy(void* handle);

int64_t hc_check_witness(int64_t n, const int32_t* key, const int8_t* kind,
                         const int64_t* inv, const int64_t* resp,
                         const int64_t* wuid, const int64_t* ruid,
                         const int64_t* ts, int32_t* out_keys, int64_t max_out);
}

static void tcp_mesh_test(int n_ranks, int steps, uint64_t block) {
  std::string hosts = "127.0.0.1";
  for (int i = 1; i < n_ranks; ++i) hosts += ",127.0.0.1";
  std::vector<std::thread> threads;
  for (int r = 0; r < n_ranks; ++r) {
    threads.emplace_back([=]() {
      void* h = ht_create(r, n_ranks, hosts.c_str(), 31500 + 64 * n_ranks);
      assert(h);
      std::vector<uint8_t> out(n_ranks * block), in(n_ranks * block);
      for (int s = 0; s < steps; ++s) {
        for (int d = 0; d < n_ranks; ++d)
          memset(&out[d * block], (r * steps + s) & 0xFF, block);
        int rc = ht_exchange(h, out.data(), block, in.data());
        assert(rc == 0);
        for (int src = 0; src < n_ranks; ++src)
          for (uint64_t b = 0; b < block; ++b)
            assert(in[src * block + b] == ((src * steps + s) & 0xFF));
      }
      ht_destroy(h);
    });
  }
  for (auto& t : threads) t.join();
  printf("tcp mesh: %d ranks x %d steps x %llu B ok\n", n_ranks, steps,
         (unsigned long long)block);
}

static int64_t pack_uid(int32_t lo, int32_t hi) {
  return (int64_t)(((uint64_t)(uint32_t)hi << 32) | (uint32_t)lo);
}

static void checker_test() {
  constexpr int64_t NONE = INT64_MIN;
  // clean history on key 5: w(ts1) -> r -> w(ts2) -> r
  {
    int32_t key[] = {5, 5, 5, 5};
    int8_t kind[] = {1, 0, 1, 0};
    int64_t inv[] = {0, 2, 4, 6};
    int64_t resp[] = {1, 2, 5, 6};
    int64_t wuid[] = {pack_uid(100, 0), NONE, pack_uid(200, 0), NONE};
    int64_t ruid[] = {NONE, pack_uid(100, 0), NONE, pack_uid(200, 0)};
    int64_t ts[] = {(1LL << 32), NONE, (2LL << 32), NONE};
    int32_t out[8];
    int64_t ns = hc_check_witness(4, key, kind, inv, resp, wuid, ruid, ts, out, 8);
    assert(ns == 0);
  }
  // stale read (reads old value after a newer write): suspect
  {
    int32_t key[] = {7, 7, 7};
    int8_t kind[] = {1, 1, 0};
    int64_t inv[] = {0, 2, 8};
    int64_t resp[] = {1, 3, 8};
    int64_t wuid[] = {pack_uid(1, 0), pack_uid(2, 0), NONE};
    int64_t ruid[] = {NONE, NONE, pack_uid(1, 0)};
    int64_t ts[] = {(1LL << 32), (2LL << 32), NONE};
    int32_t out[8];
    int64_t ns = hc_check_witness(3, key, kind, inv, resp, wuid, ruid, ts, out, 8);
    assert(ns == 1 && out[0] == 7);
  }
  // read of the initial value only: clean
  {
    int32_t key[] = {9};
    int8_t kind[] = {0};
    int64_t inv[] = {0};
    int64_t resp[] = {0};
    int64_t wuid[] = {NONE};
    int64_t ruid[] = {pack_uid(9, -1)};
    int64_t ts[] = {NONE};
    int32_t out[8];
    int64_t ns = hc_check_witness(1, key, kind, inv, resp, wuid, ruid, ts, out, 8);
    assert(ns == 0);
  }
  printf("checker core: witness cases ok\n");
}

int main() {
  checker_test();
  tcp_mesh_test(3, 20, 4096);
  tcp_mesh_test(5, 10, 64);
  printf("native sanitizer harness: all ok\n");
  return 0;
}
