"""Bounded backend-availability probe, shared by every driver entry path.

Round-2/3 lesson (BENCH_r02.json, MULTICHIP_r03.json): PJRT init against a
wedged tunneled-TPU claim hangs indefinitely and ignores signals, so any
process that touches the default backend first — bench.py, or a harness
running ``entry()`` before ``dryrun_multichip`` — times out to rc=124 with
nothing diagnosable in the tail.  The fix is to initialize the backend in a
SUBPROCESS with a bound first; only when the probe child succeeds does the
caller initialize its own backend.

On timeout the child is ABANDONED, never killed: the pool's recorded
failure mode is that killing a claim-queue process can leave its grant held
pool-side (wedging the chip for an hour+), while an abandoned waiter either
completes later and exits cleanly (releasing) or idles without blocking new
processes (verified against a stuck claimer in round 2).
"""

import os
import subprocess
import sys
import tempfile


def probe_backend(timeout_s: float, cmd=None):
    """Returns (ok, info): info is the platform name on success, else a
    one-line diagnosis.  Skipped (trivially ok) when JAX_PLATFORMS=cpu —
    CPU init cannot hang.  The probe child initializes the default backend,
    prints a marker, and exits cleanly (releasing its claim); only then
    should the caller initialize its own."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return True, "cpu"
    if cmd is None:
        code = ("import jax; "
                "print('HERMES_BACKEND_OK', jax.devices()[0].platform)")
        cmd = [sys.executable, "-c", code]

    with tempfile.TemporaryFile(mode="w+") as out:
        p = subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT,
                             text=True)
        try:
            p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return False, (
                f"backend init did not complete within {timeout_s:.0f}s "
                f"(TPU claim wedged or pool unreachable); probe child "
                f"pid={p.pid} left running — do NOT kill it mid-claim")
        out.seek(0)
        txt = out.read()
    if p.returncode != 0 or "HERMES_BACKEND_OK" not in txt:
        tail = [l for l in txt.strip().splitlines() if l.strip()][-1:]
        return False, (f"backend init failed rc={p.returncode}: "
                       f"{tail[0] if tail else 'no output'}")
    return True, txt.split()[-1]
