"""Multi-process replica runner over the TCP transport (SURVEY.md §2, M5).

One OS process = one Hermes replica (the reference's deployment shape: one
process per machine).  The protocol phases are the SAME per-replica
functions the in-process backends run — only the exchange substrate differs
(TcpMesh block exchange instead of collectives), which is the whole point of
the transport plugin seam.

Usage (one process per rank, same command on each host):

    python -m hermes_tpu.distributed --rank R --n-ranks N [--steps S]
        [--base-port P] [--hosts ip0,ip1,...] [--out out_rank_R.npz]

Each rank writes its completion history + final table to ``--out``;
``combine_and_check(paths)`` merges them and runs the linearizability gate.
"""

from __future__ import annotations

import argparse
import os
import pickle

import numpy as np


def parse_wire_faults(spec: str):
    """Parse the ``--wire-faults`` mini-spec: semicolon-separated
    ``op:src:dst:from:until[:param]`` windows (op per chaos.net.WIRE_OPS;
    src/dst of -1 match any endpoint).  Every rank passes the SAME spec, so
    the per-rank interposers make consistent seeded decisions with no
    coordination."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        toks = part.split(":")
        if not (5 <= len(toks) <= 6):
            raise ValueError(
                f"bad wire fault {part!r}: want op:src:dst:from:until[:param]")
        out.append((toks[0], int(toks[1]), int(toks[2]), int(toks[3]),
                    int(toks[4]), int(toks[5]) if len(toks) == 6 else 0))
    return out


def fleet_base_port(base_port: int, fleet_group: int, n_ranks: int) -> int:
    """Process-to-group placement for TCP fleets (round-13): each
    key-sharded group is an independent n_ranks-process mesh, so group g
    binds a disjoint port window — one listener per rank, strided with
    headroom so co-hosted groups can never collide even if the native
    mesh claims a few extra ports per rank."""
    if fleet_group < 0:
        raise ValueError("fleet_group must be >= 0")
    return base_port + fleet_group * 4 * n_ranks


def run_replica(
    cfg,
    rank: int,
    n_ranks: int,
    steps: int,
    base_port: int = 29500,
    hosts: str | None = None,
    out_path: str | None = None,
    wire_seed: int = 0,
    wire_faults: str | None = None,
    fleet_group: int = 0,
):
    import jax
    import jax.numpy as jnp

    from hermes_tpu.checker.history import HistoryRecorder
    from hermes_tpu.core import state as st, step as step_lib
    from hermes_tpu.transport.tcp import TcpHostTransport
    from hermes_tpu.workload import ycsb

    base_port = fleet_base_port(base_port, fleet_group, n_ranks)
    tcp_t = TcpHostTransport(cfg, rank, n_ranks, hosts=hosts,
                             base_port=base_port)
    transport = tcp_t
    wire = None
    if wire_faults:
        # adversarial wire chaos over the REAL socket transport (round-11):
        # the interposer runs per rank on the inbound path; identical specs
        # + seed on every rank give a consistent global adversary
        from hermes_tpu.chaos.net import FaultingTransport

        wire = FaultingTransport(tcp_t, n_ranks, seed=wire_seed,
                                 local_rank=rank)
        for op, src, dst, from_step, until, param in parse_wire_faults(
                wire_faults):
            wire.add(op, src, dst, from_step, until, param)
        transport = wire
    rs = st.init_replica_state(cfg)
    stream = jax.tree.map(jnp.asarray, ycsb.make_stream(cfg, rank))
    recorder = HistoryRecorder(cfg)

    ph = {k: jax.jit(v) for k, v in step_lib.phase_fns(cfg).items()}

    to_j = lambda b: jax.tree.map(jnp.asarray, b)

    for step in range(steps):
        ctl = st.Ctl(
            step=jnp.int32(step),
            my_cid=jnp.int32(rank),
            epoch=jnp.int32(0),
            live_mask=jnp.int32(cfg.full_mask),
            frozen=jnp.bool_(False),
        )
        # the shared step body (core/step._step_core) with TCP exchanges
        rs, comp = step_lib._step_core(
            cfg,
            ph,
            lambda blk, s=step: to_j(transport.exchange_inv(blk, s)),
            lambda blk, s=step: to_j(transport.exchange_ack(blk, s)),
            lambda blk, s=step: to_j(transport.exchange_val(blk, s)),
            rs,
            stream,
            ctl,
        )
        comp_np = jax.device_get(comp)
        recorder.record_step(jax.tree.map(lambda x: np.asarray(x)[None], comp_np))

    sess_np = jax.device_get(rs.sess)
    ops = recorder.finalize(jax.tree.map(lambda x: np.asarray(x)[None], sess_np))
    # stamp the true replica id (recorder saw a leading axis of size 1)
    import dataclasses

    ops = [dataclasses.replace(o, replica=rank) for o in ops]
    result = dict(
        rank=rank,
        fleet_group=fleet_group,
        ops=ops,
        aborted=recorder.aborted_uids,
        table_state=np.asarray(jax.device_get(rs.table.state)),
        table_ver=np.asarray(jax.device_get(rs.table.ver)),
        table_fc=np.asarray(jax.device_get(rs.table.fc)),
        table_val=np.asarray(jax.device_get(rs.table.val)),
        sess_status=np.asarray(jax.device_get(rs.sess.status)),
        counters=dict(
            n_read=int(jax.device_get(rs.meta.n_read)),
            n_write=int(jax.device_get(rs.meta.n_write)),
            n_rmw=int(jax.device_get(rs.meta.n_rmw)),
            n_abort=int(jax.device_get(rs.meta.n_abort)),
        ),
        corrupt_dropped=tcp_t.corrupt_dropped,
        wire=(dict(counters=dict(wire.counters),
                   fault_log_len=len(wire.fault_log))
              if wire is not None else None),
    )
    if out_path:
        with open(out_path, "wb") as f:
            pickle.dump(result, f)
    tcp_t.close()
    return result


def combine_and_check(paths):
    """Merge per-rank results and run the linearizability gate."""
    from hermes_tpu.checker import linearizability as lin

    results = []
    for p in paths:
        with open(p, "rb") as f:
            results.append(pickle.load(f))
    ops = [o for r in results for o in r["ops"]]
    aborted = set().union(*[r["aborted"] for r in results])
    verdict = lin.check_history(ops, aborted_uids=aborted)
    return verdict, results


def _main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--n-ranks", type=int, required=True)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--base-port", type=int, default=29500)
    ap.add_argument("--hosts", type=str, default=None)
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--n-keys", type=int, default=256)
    ap.add_argument("--n-sessions", type=int, default=8)
    ap.add_argument("--ops-per-session", type=int, default=24)
    ap.add_argument("--read-frac", type=float, default=0.5)
    ap.add_argument("--rmw-frac", type=float, default=0.2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fleet-group", type=int, default=0,
                    help="key-sharded fleet group this replica process "
                    "belongs to (round-13): groups are independent "
                    "n-ranks meshes on disjoint port windows "
                    "(fleet_base_port), so co-hosted groups never share "
                    "a socket")
    ap.add_argument("--wire-seed", type=int, default=0,
                    help="seed for the adversarial wire interposer")
    ap.add_argument("--wire-faults", type=str, default=None,
                    help="semicolon-separated op:src:dst:from:until[:param] "
                         "windows injected by chaos.net.FaultingTransport "
                         "over the tcp transport (same spec on every rank)")
    args = ap.parse_args()

    from hermes_tpu.config import HermesConfig, WorkloadConfig

    cfg = HermesConfig(
        n_replicas=args.n_ranks,
        n_keys=args.n_keys,
        n_sessions=args.n_sessions,
        ops_per_session=args.ops_per_session,
        workload=WorkloadConfig(
            read_frac=args.read_frac, rmw_frac=args.rmw_frac, seed=args.seed
        ),
    )
    run_replica(
        cfg,
        args.rank,
        args.n_ranks,
        args.steps,
        base_port=args.base_port,
        hosts=args.hosts,
        out_path=args.out,
        wire_seed=args.wire_seed,
        wire_faults=args.wire_faults,
        fleet_group=args.fleet_group,
    )


if __name__ == "__main__":
    _main()
