"""Multi-process replica runner over the TCP transport (SURVEY.md §2, M5).

One OS process = one Hermes replica (the reference's deployment shape: one
process per machine).  The protocol phases are the SAME per-replica
functions the in-process backends run — only the exchange substrate differs
(TcpMesh block exchange instead of collectives), which is the whole point of
the transport plugin seam.

Usage (one process per rank, same command on each host):

    python -m hermes_tpu.distributed --rank R --n-ranks N [--steps S]
        [--base-port P] [--hosts ip0,ip1,...] [--out out_rank_R.npz]

Each rank writes its completion history + final table to ``--out``;
``combine_and_check(paths)`` merges them and runs the linearizability gate.
"""

from __future__ import annotations

import argparse
import os
import pickle

import numpy as np


def run_replica(
    cfg,
    rank: int,
    n_ranks: int,
    steps: int,
    base_port: int = 29500,
    hosts: str | None = None,
    out_path: str | None = None,
):
    import jax
    import jax.numpy as jnp

    from hermes_tpu.checker.history import HistoryRecorder
    from hermes_tpu.core import state as st, step as step_lib
    from hermes_tpu.transport import codec
    from hermes_tpu.transport.tcp import TcpMesh
    from hermes_tpu.workload import ycsb

    mesh = TcpMesh(rank, n_ranks, hosts=hosts, base_port=base_port)
    rs = st.init_replica_state(cfg)
    stream = jax.tree.map(jnp.asarray, ycsb.make_stream(cfg, rank))
    recorder = HistoryRecorder(cfg)

    ph = {k: jax.jit(v) for k, v in step_lib.phase_fns(cfg).items()}

    inv_t = st.empty_invs(cfg)
    ack_row_t = jax.tree.map(lambda x: x[0], st.empty_acks(cfg, lead=(n_ranks,)))
    val_t = st.empty_vals(cfg)

    def bcast(kind_template, block):
        """Broadcast: same serialized block to every peer."""
        b = codec.pack(jax.device_get(block))
        inb = mesh.exchange(np.tile(b[None], (n_ranks, 1)))
        return codec.stack([codec.unpack(kind_template, inb[r]) for r in range(n_ranks)])

    def route_ack(block):
        """Acks: row p of my (R, L) block goes to rank p."""
        blk = jax.device_get(block)
        rows = [codec.pack(jax.tree.map(lambda x: np.asarray(x)[p], blk)) for p in range(n_ranks)]
        inb = mesh.exchange(np.stack(rows))
        return codec.stack([codec.unpack(ack_row_t, inb[r]) for r in range(n_ranks)])

    to_j = lambda b: jax.tree.map(jnp.asarray, b)

    for step in range(steps):
        ctl = st.Ctl(
            step=jnp.int32(step),
            my_cid=jnp.int32(rank),
            epoch=jnp.int32(0),
            live_mask=jnp.int32(cfg.full_mask),
            frozen=jnp.bool_(False),
        )
        # the shared step body (core/step._step_core) with TCP exchanges
        rs, comp = step_lib._step_core(
            cfg,
            ph,
            lambda blk: to_j(bcast(inv_t, blk)),
            lambda blk: to_j(route_ack(blk)),
            lambda blk: to_j(bcast(val_t, blk)),
            rs,
            stream,
            ctl,
        )
        comp_np = jax.device_get(comp)
        recorder.record_step(jax.tree.map(lambda x: np.asarray(x)[None], comp_np))

    sess_np = jax.device_get(rs.sess)
    ops = recorder.finalize(jax.tree.map(lambda x: np.asarray(x)[None], sess_np))
    # stamp the true replica id (recorder saw a leading axis of size 1)
    import dataclasses

    ops = [dataclasses.replace(o, replica=rank) for o in ops]
    result = dict(
        rank=rank,
        ops=ops,
        aborted=recorder.aborted_uids,
        table_state=np.asarray(jax.device_get(rs.table.state)),
        table_ver=np.asarray(jax.device_get(rs.table.ver)),
        table_fc=np.asarray(jax.device_get(rs.table.fc)),
        table_val=np.asarray(jax.device_get(rs.table.val)),
        sess_status=np.asarray(jax.device_get(rs.sess.status)),
        counters=dict(
            n_read=int(jax.device_get(rs.meta.n_read)),
            n_write=int(jax.device_get(rs.meta.n_write)),
            n_rmw=int(jax.device_get(rs.meta.n_rmw)),
            n_abort=int(jax.device_get(rs.meta.n_abort)),
        ),
    )
    if out_path:
        with open(out_path, "wb") as f:
            pickle.dump(result, f)
    mesh.close()
    return result


def combine_and_check(paths):
    """Merge per-rank results and run the linearizability gate."""
    from hermes_tpu.checker import linearizability as lin

    results = []
    for p in paths:
        with open(p, "rb") as f:
            results.append(pickle.load(f))
    ops = [o for r in results for o in r["ops"]]
    aborted = set().union(*[r["aborted"] for r in results])
    verdict = lin.check_history(ops, aborted_uids=aborted)
    return verdict, results


def _main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--n-ranks", type=int, required=True)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--base-port", type=int, default=29500)
    ap.add_argument("--hosts", type=str, default=None)
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--n-keys", type=int, default=256)
    ap.add_argument("--n-sessions", type=int, default=8)
    ap.add_argument("--ops-per-session", type=int, default=24)
    ap.add_argument("--read-frac", type=float, default=0.5)
    ap.add_argument("--rmw-frac", type=float, default=0.2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from hermes_tpu.config import HermesConfig, WorkloadConfig

    cfg = HermesConfig(
        n_replicas=args.n_ranks,
        n_keys=args.n_keys,
        n_sessions=args.n_sessions,
        ops_per_session=args.ops_per_session,
        workload=WorkloadConfig(
            read_frac=args.read_frac, rmw_frac=args.rmw_frac, seed=args.seed
        ),
    )
    run_replica(
        cfg,
        args.rank,
        args.n_ranks,
        args.steps,
        base_port=args.base_port,
        hosts=args.hosts,
        out_path=args.out,
    )


if __name__ == "__main__":
    _main()
