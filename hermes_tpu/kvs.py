"""Client-facing KVS API (SURVEY.md §1 L5, §2 "KVS client API + sessions").

The reference multiplexes client sessions onto worker threads, each session
holding one in-flight get/put/RMW (worker.c session arrays).  The rebuild
exposes the same session model over the bulk-synchronous runtime: callers
enqueue operations on (replica, session) slots; every ``step()`` injects one
op per idle session into the device-side op stream, runs one protocol round,
and resolves the completions that came back.

The north star keeps this API untouched (BASELINE.json:5: "the KVS API and
linearizability guarantees are untouched") — gets are local (serve from the
replica's own table, stall while the key is Invalid), puts/RMWs run the
INV/ACK/VAL broadcast round and linearize at quorum.

Values are ``value_words - 2`` int32 payload words: words 0-1 of every
stored value carry the device-derived unique write id (the linearizability
witness, checker/history.py), so checked runs work unchanged over client
traffic.

Keys are dense slot ids ``[0, n_keys)`` by default; ``sparse_keys=True``
accepts arbitrary unsigned 64-bit client keys through the exact
open-addressing index of ``hermes_tpu/keyindex.py`` (the MICA-index
analog, SURVEY.md §1 L2) — completions echo the client key, and inserting
more than ``n_keys`` distinct keys raises ``keyindex.KeyspaceFull``.

Usage::

    kvs = KVS(HermesConfig(n_replicas=3, n_keys=1024, value_words=6))
    f1 = kvs.put(replica=0, session=0, key=7, value=[1, 2, 3, 4])
    f2 = kvs.get(replica=1, session=0, key=7)
    kvs.run_until([f1, f2])
    assert f2.result().value == [1, 2, 3, 4]   # after the VAL reaches replica 1
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import types as t
from hermes_tpu.runtime import FastRuntime


# client-level completion code for ops LOST to a replica crash
# (chaos.recovery.restart_replica): the server died holding the op; the
# client is told loudly instead of waiting forever.  Negative on purpose —
# it can never collide with the device C_* codes (types.py, all >= 0).
C_LOST = -2
# client-level completion code for ops REJECTED by elastic operations
# (round-10, hermes_tpu/elastic): the op targeted a retired replica or a
# key range that is draining/migrated away.  The op never entered the
# store (no history impact) — the client retries against the range's new
# owner (keyindex.RangeRouter names it).  Distinct from C_LOST: a
# rejected op definitively did NOT happen; a lost op is a maybe.
C_REJECTED = -3
# client-level completion code for updates shed by WAL backpressure
# (round-22, cfg.wal_dirty_window): the durability log's dirty window is
# full — the write never entered the store (no history impact, no slot
# claimed); the client retries after the flusher drains.  Loud shed,
# never a silent stall behind a slow disk.  Negative on purpose, like
# its siblings above.
C_RETRY_AFTER = -4


class StuckOpError(RuntimeError):
    """Strict-mode stuck-op watchdog verdict (cfg.op_timeout_rounds): at
    least one client op out-aged the timeout; ``diagnostics`` carries the
    per-session evidence (coordinator, session, phase, age)."""

    def __init__(self, diagnostics):
        self.diagnostics = diagnostics
        super().__init__(
            f"{len(diagnostics)} client op(s) stuck past op_timeout_rounds: "
            + "; ".join(
                f"r{d['replica']}/s{d['session']} {d['kind']} key={d['key']} "
                f"phase={d['phase']}"
                + (f" drill={d['drill']}" if "drill" in d else "")
                + (f" net={d['net']}" if "net" in d else "")
                + (f" tenant={d['tenant']}" if "tenant" in d else "")
                + (f" deadline_left_us={d['deadline_left_us']}"
                   if "deadline_left_us" in d else "")
                + f" age={d['age_rounds']}"
                for d in diagnostics[:4]))


@dataclasses.dataclass
class Completion:
    """Result of one client op."""

    # 'get' | 'put' | 'rmw' | 'rmw_abort' | 'lost' (replica crash; op MAY
    # have applied) | 'rejected' (elastic fence/retire; op definitively
    # did NOT apply — retry against the range's new owner) |
    # 'retry_after' (round-22 WAL backpressure: the durability log's
    # dirty window is full; op definitively did NOT apply — retry after
    # the flusher drains)
    kind: str
    key: int
    value: Optional[List[int]] = None  # payload read (get / rmw read-part)
    # value heap (round-17, cfg.max_value_bytes > 0): the variable-length
    # byte payload behind the row's packed heap ref — what a heap-mode
    # get/rmw read-part actually returns (``value`` then carries the raw
    # payload words, word 0 being the ref).  None = the key was never
    # written (the null ref).
    data: Optional[bytes] = None
    uid: Optional[Tuple[int, int]] = None  # unique id of the written value
    step: int = -1
    # sparse-key mode only: False when a get probed a key never written
    # (the read completes immediately, value=None, and does NOT claim a
    # dense slot — read-only probes cannot exhaust the keyspace).  Dense
    # mode reads of unwritten slots return the zero-initialized value with
    # found=True, matching a preloaded-table store.
    found: bool = True
    # committed updates only (round-16): the globally re-anchored
    # protocol (ver, fc) of this write — what a caller hands back to
    # ``KVS.pin_read_fence`` to make its later local reads RYW-fenced
    # under its own session token (the serving front-end does exactly
    # this per tenant)
    ts: Optional[Tuple[int, int]] = None
    # round-22 durability contract this completion was resolved under
    # (committed updates on a WAL-enabled store only, else None):
    #   'commit'              — the write's log record was fsync-durable
    #                           BEFORE this completion resolved;
    #   'round:not-fsynced-at-resolve' / 'off:not-fsynced-at-resolve'
    #                         — relaxed modes, loudly labeled: the record
    #                           was appended but this resolution did not
    #                           wait for the fsync.
    durability: Optional[str] = None


class Future:
    def __init__(self):
        self._result: Optional[Completion] = None

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> Completion:
        assert self._result is not None, "op not complete; call KVS.step()/run_until()"
        return self._result


class BatchFutures:
    """Array-form futures for a ``KVS.submit_batch`` call (round-3 verdict
    item 5: array-in, futures-out).  Results land in preallocated numpy
    columns — no per-op Python objects anywhere on the completion path:

      ``code``  (n,) int32 — 0 while pending, else the completion code
                (types.C_READ/C_WRITE/C_RMW/C_RMW_ABORT)
      ``value`` (n, value_words-2) int32 — payload read (gets / rmw
                read-part; zeros otherwise)
      ``uid``   (n, 2) int32 — unique id of the written value
      ``found`` (n,) bool — sparse mode: False for gets of never-written
                keys (completed immediately, no slot claimed)

    ``future(i)`` materializes a classic per-op Future view lazily for
    callers that want one."""

    def __init__(self, kinds: np.ndarray, keys: np.ndarray, u: int,
                 heap=None):
        n = kinds.shape[0]
        self.kind = kinds
        self.key = keys
        self.code = np.zeros(n, np.int32)
        self.value = np.zeros((n, u), np.int32)
        self.uid = np.zeros((n, 2), np.int32)
        self.found = np.ones(n, bool)
        # heap mode (round-17): per-op byte payloads, resolved EAGERLY at
        # completion time off the mirror (an extent referenced by a read
        # stays immutable until the next GC, which flushes completions
        # first — resolving late could cross a compaction)
        self._heap = heap
        self.data: List[Optional[bytes]] = [None] * n
        # completing protocol round per op (-1 while pending / for reads
        # completed without a round) — parity with the per-op path's
        # Completion.step, so batched callers keep step observability
        self.step = np.full(n, -1, np.int32)
        # committed updates' re-anchored protocol timestamps (round-16):
        # the batch-path analogue of Completion.ts, so batched writers
        # can pin read fences too
        self.tsv = np.zeros(n, np.int64)
        self.tsf = np.zeros(n, np.int32)
        # round-22: the store's durability label for committed updates
        # (one per store, not per op — set by submit_batch, surfaced in
        # completion())
        self.durability: Optional[str] = None

    def __len__(self) -> int:
        return self.code.shape[0]

    def done_count(self) -> int:
        return int(np.count_nonzero(self.code))

    def all_done(self) -> bool:
        return bool((self.code != 0).all())

    _KINDSTR = {t.OP_READ: "get", t.OP_WRITE: "put", t.OP_RMW: "rmw"}

    def completion(self, i: int) -> Completion:
        assert self.code[i] != 0, "op not complete; run KVS.run_batch()"
        c = int(self.code[i])
        if c == C_LOST:
            return Completion(kind="lost", key=int(self.key[i]),
                              step=int(self.step[i]), found=False)
        if c == C_REJECTED:
            return Completion(kind="rejected", key=int(self.key[i]),
                              step=int(self.step[i]), found=False)
        if c == C_RETRY_AFTER:
            return Completion(kind="retry_after", key=int(self.key[i]),
                              step=int(self.step[i]), found=False)
        kind = ("rmw_abort" if c == t.C_RMW_ABORT
                else self._KINDSTR[int(self.kind[i])])
        done = Completion(kind=kind, key=int(self.key[i]),
                          step=int(self.step[i]), found=bool(self.found[i]))
        if c in (t.C_READ, t.C_RMW) and self.found[i]:
            done.value = self.value[i].tolist()
            done.data = self.data[i]
        if c in (t.C_WRITE, t.C_RMW):
            done.uid = (int(self.uid[i, 0]), int(self.uid[i, 1]))
            done.ts = (int(self.tsv[i]), int(self.tsf[i]))
            done.durability = self.durability
        return done

    def future(self, i: int) -> Future:
        fut = Future()
        if self.code[i] != 0:
            fut._result = self.completion(i)
        return fut


class MultiGetResult:
    """Result of one ``KVS.multi_get``/``scan`` call (round-16): the same
    preallocated-column shape as BatchFutures —

      ``key``   (n,) the CLIENT keys echoed (fleet/sparse callers see the
                keys they submitted, never dense slots)
      ``code``  (n,) 0 pending, else types.C_READ / kvs.C_REJECTED
      ``value`` (n, value_words-2) payload words (uid words stripped,
                like Completion.value)
      ``found`` (n,) bool (sparse mode: False for never-written keys)
      ``local`` (n,) bool — answered by the device-resident fast path
                (False = round-trip fallback or immediate refusal)
      ``step``  (n,) protocol round the answer is anchored to

    Keys the fast path could not serve (Invalid at the serving replica,
    read-your-writes fence unsatisfied, or no healthy replica) ride a
    fallback ``BatchFutures`` through the normal round path — drive it
    with ``KVS.step()`` / ``run_reads`` until ``all_done()``."""

    def __init__(self, keys: np.ndarray, u: int, heap=None):
        n = keys.shape[0]
        self.key = keys
        self.code = np.zeros(n, np.int32)
        self.value = np.zeros((n, u), np.int32)
        self.found = np.ones(n, bool)
        self.local = np.zeros(n, bool)
        self.step = np.full(n, -1, np.int32)
        self._fallback: Optional[Tuple[BatchFutures, np.ndarray]] = None
        # heap mode (round-17): the byte payload per key (None = never
        # written / not served); local answers resolve at serve time,
        # fallback answers ride the BatchFutures' own eager resolution
        self._heap = heap
        self.data: List[Optional[bytes]] = [None] * n

    def __len__(self) -> int:
        return self.key.shape[0]

    def _pull(self) -> None:
        if self._fallback is None:
            return
        bf, gix = self._fallback
        done = (bf.code != 0) & (self.code[gix] == 0)
        if done.any():
            di = gix[done]
            self.code[di] = bf.code[done]
            self.value[di] = bf.value[done]
            self.found[di] = bf.found[done]
            self.step[di] = bf.step[done]
            if self._heap is not None:
                for j, i in zip(np.nonzero(done)[0], di):
                    self.data[int(i)] = bf.data[int(j)]

    def done_count(self) -> int:
        self._pull()
        return int(np.count_nonzero(self.code))

    def all_done(self) -> bool:
        return self.done_count() == len(self)

    @property
    def local_served(self) -> int:
        return int(np.count_nonzero(self.local))

    @property
    def fallbacks(self) -> int:
        return 0 if self._fallback is None else int(self._fallback[1].size)


class KVS:
    """A replicated, linearizable KVS served by the Hermes protocol.

    One instance drives all R replicas of a single-process deployment (the
    reference's test/bench shape, BASELINE.json:7); each (replica, session)
    slot accepts one op at a time, queued FIFO beyond that.
    """

    def __init__(self, cfg: HermesConfig, backend: str = "batched", mesh=None,
                 record: bool = False, sparse_keys: bool = False,
                 strict_timeouts: bool = False):
        if cfg.value_words < 3:
            raise ValueError("KVS needs value_words >= 3 (2 uid words + payload)")
        if cfg.read_unroll != 1:
            raise ValueError(
                "KVS uses a one-deep rewritable stream (one client op per "
                "session in flight); read_unroll > 1 would re-execute the "
                "same op within a round — drive throughput with more "
                "sessions instead")
        if cfg.device_stream:
            raise ValueError("KVS drives ops through the stream; device_stream "
                             "would replace client requests with hash-generated ops")
        # One-deep, rewritable stream: wrap_stream makes idle sessions reload
        # slot op_idx % 1 == 0 every round, so the host can inject ops by
        # rewriting the (R, S, 1) stream between rounds.
        self.cfg = dataclasses.replace(cfg, ops_per_session=1, wrap_stream=True)
        r, s, u = cfg.n_replicas, cfg.n_sessions, cfg.value_words - 2
        self._op = np.zeros((r, s, 1), np.int32)  # OP_NOP
        self._key = np.zeros((r, s, 1), np.int32)
        self._uval = np.zeros((r, s, 1, u), np.int32)
        from hermes_tpu.core import state as st

        stream = st.OpStream(op=self._op, key=self._key, uval=self._uval)
        self.rt = FastRuntime(self.cfg, backend=backend, mesh=mesh, record=record,
                              stream=stream)
        # the runtime's rebase quiesce drain must step THROUGH this layer:
        # a raw rt.step_once() there would drop Completions on the floor and
        # strand the matching client futures forever
        self.rt.comp_sink = self.step
        # pipelined serving (round-8, cfg.pipeline_depth >= 2): one round's
        # BULK completion readback + future resolution is deferred so it
        # overlaps with the next device round (see _step_pipelined); the
        # runtime's rebase/drain boundaries force it out via this hook
        self.rt.comp_flush = self.flush
        self._depth = self.cfg.pipeline_depth
        self._pending = None  # (round_idx, device comp handles, done_mask, code)
        self._queues: Dict[Tuple[int, int], collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        self._inflight: Dict[Tuple[int, int], Tuple[str, Future, int]] = {}
        # completion matching is vectorized (round-2 verdict weak 5): the
        # per-slot op kind mirrored as an array lets step() find finished
        # slots with one numpy mask instead of a Python scan over every
        # in-flight op; _ready tracks idle slots with queued work so the
        # injection pass touches only those.
        self._kindarr = np.zeros((r, s), np.int32)
        self._ready: set = set()
        # slots whose legacy FIFO queue is non-empty (maintained at enqueue
        # and pop): the batch paths consult this instead of scanning every
        # deque _queues ever defaulted — a defaultdict retains empty deques
        # for every slot ever used, which would make those scans O(all
        # slots touched) per step
        self._queued_slots: set = set()
        self._dirty = True
        # batched client path (round-3 verdict item 5): active submit_batch
        # calls keyed by a stable id; per-slot (batch id, batch index) so
        # completions resolve into the BatchFutures columns vectorized
        self._bat: Dict[int, dict] = {}
        self._next_bid = 0
        self._slot_bid = np.full((r, s), -1, np.int32)
        self._slot_bix = np.zeros((r, s), np.int32)
        # stuck-op watchdog (round-9, cfg.op_timeout_rounds): the round
        # each slot's current op was injected (-1 = idle), the per-session
        # diagnostics surfaced so far, and a once-per-op flag set so a
        # stuck op reports exactly once instead of every round
        self._slot_inject = np.full((r, s), -1, np.int64)
        self._stuck_flagged: set = set()
        self.stuck_ops: List[dict] = []
        self.strict_timeouts = strict_timeouts
        # elastic operations (round-10, hermes_tpu/elastic): replicas
        # retired by a live shrink accept no new ops (their queued/future
        # traffic is rejected loudly); fenced dense-slot ranges are
        # draining or migrated away — ops on them reject with
        # kind='rejected' instead of entering a store that no longer (or
        # soon won't) own the key.  drill_phase tags the active drill
        # stage (fence/drain/flip) into stuck-op diagnostics so a wedged
        # op is attributable from the timeline alone.
        self._retired: set = set()
        self._fence_mask = np.zeros(cfg.n_keys, bool)
        self.drill_phase: Optional[str] = None
        self.rejected_ops = 0
        # adversarial wire chaos (round-11, hermes_tpu/chaos/net.py):
        # net_phase tags the active adversary window (partition / net-fault
        # spec + affected peer pairs, set by chaos.ChaosRunner — the
        # drill_phase pattern for the wire) into stuck-op diagnostics so
        # soak triage needs no log cross-referencing.  Bounded retry
        # (cfg.op_retry_limit): per-(replica, session) escalation state of
        # the stuck-op watchdog — next re-examination step and how many
        # backoff windows have elapsed.  Degraded mode
        # (cfg.min_healthy_for_writes): on quorum loss new writes shed
        # loudly (kind='rejected') instead of wedging; shed_writes counts
        # them and the transition lands on the obs timeline.
        self.net_phase: Optional[dict] = None
        # serving front-end tags (round-14, hermes_tpu/serving): when a
        # Frontend drives this KVS it installs a per-op diagnostics hook
        # — the watchdog calls it with the stuck (replica, session) and
        # merges whatever it returns (tenant id, remaining deadline
        # budget) into the diagnostic, the per-op generalization of the
        # drill_phase / net_phase tags
        self.diag_hook = None
        self._retry_next: Dict[Tuple[int, int], int] = {}
        self._retry_k: Dict[Tuple[int, int], int] = {}
        self.retried_ops = 0
        self.shed_writes = 0
        self._degraded = False
        # sparse-key mode (SURVEY.md §1 L2, MICA-index parity): arbitrary
        # 64-bit client keys map to dense device slots through an exact
        # open-addressing index (hermes_tpu/keyindex.py); completions
        # report the client key.  Inserting more distinct keys than n_keys
        # raises keyindex.KeyspaceFull.
        if sparse_keys:
            from hermes_tpu.keyindex import KeyIndex

            self.index: Optional[KeyIndex] = KeyIndex(cfg.n_keys)
        else:
            self.index = None
        # local-read fast path (round-16, core/readpath.py): one jitted
        # dispatch answers a whole multi_get/scan against the resident
        # FastState table — zero round involvement.  _ryw is the
        # read-your-writes fence: per (replica, session) lane, the
        # globally-re-anchored (ver, fc) of its latest COMMITTED write
        # per dense slot; a local read of that slot must observe a row
        # timestamp >= the fence or it falls back to the round path
        # (which stalls until the key revalidates).  Entries prune on
        # first satisfaction — the table's row ts only ever grows, so a
        # once-satisfied fence stays satisfied.
        self._reader = None
        self._ryw: Dict[Tuple[int, int], Dict[int, Tuple[int, int]]] = {}
        self.local_reads = 0
        self.fallback_reads = 0
        self.ryw_fallbacks = 0
        # value heap (round-17, hermes_tpu/heap): variable-length byte
        # values behind ONE packed ref word in payload word 0.  The
        # extent lands in the heap at submission — BEFORE the INV issues
        # — so the round moves only the ref word (census unchanged).
        # Dead extents compact at rebase boundaries (rt.rebase_hook) and
        # on allocation pressure (append raises HeapFull -> heap_gc ->
        # one retry) under the same quiesce the version rebase uses.
        if self.cfg.use_heap:
            from hermes_tpu.heap import ValueHeap

            self.heap: Optional[ValueHeap] = ValueHeap(self.cfg)
            self.rt.rebase_hook = self._heap_rebase_hook
        else:
            self.heap = None
        self._in_heap_gc = False
        # round-22 durability tier (hermes_tpu/wal, cfg.wal_dir): the
        # write-ahead extent+commit log rides the harvest path
        # (rt.attach_wal -> harvest_comp appends each round's committed
        # writes).  Under wal_sync='commit' a round's resolution is GATED:
        # _gated_resolve parks the harvested round as (lsn, args) until
        # the group-commit flusher reports its log batch durable, so a
        # client future only ever resolves 'committed' after its record
        # survives a power cut.  Relaxed modes resolve immediately with a
        # loud durability label.  A full dirty window sheds NEW updates
        # with kind='retry_after' (wal_shed counts them) — loud, never a
        # silent stall.
        if self.cfg.use_wal:
            from hermes_tpu.wal import GroupCommitWal

            self.wal: Optional[GroupCommitWal] = GroupCommitWal(self.cfg)
            self.rt.attach_wal(self.wal, heap=self.heap)
        else:
            self.wal = None
        self._wal_defer: collections.deque = collections.deque()
        self.wal_shed = 0
        self._wal_bp = False
        # refs appended for work being STAGED right now (a batch mid-
        # build, a migration mid-transfer): a heap-pressure GC can fire
        # between two appends of the same call, and refs not yet
        # registered anywhere else must still be rooted and remapped —
        # each entry is a 1-D int32 array view whose nonzero entries are
        # live refs (see _heap_staging)
        self._staging: List[np.ndarray] = []
        # per-op tracing (round-18, obs/tracing.py): a seeded deterministic
        # sampler mints a trace id for ~1 in cfg.trace_sample submissions
        # (0 = off).  The id rides the FUTURE (fut._trace + the submit /
        # inject rounds), never the queue tuples or the device stream — the
        # compiled round cannot see it, so the lowered program is identical
        # at any rate.  _staged_trace carries an id minted UPSTREAM (the
        # serving Frontend, off the wire field) into the next _enqueue.
        if self.cfg.trace_sample:
            from hermes_tpu.obs.tracing import TraceSampler

            self._sampler: Optional[object] = TraceSampler(
                self.cfg.trace_sample, seed=self.cfg.workload.seed)
        else:
            self._sampler = None
        self._trace_seq = 0
        self._staged_trace = 0
        self._op_tracer_cache = None

    def _op_tracer(self):
        """Span writer bound to the runtime's CURRENT obs context (None
        while none is attached — the unsampled/unattached fast path)."""
        obs = self.rt.obs
        if obs is None:
            return None
        c = self._op_tracer_cache
        if c is None or c.obs is not obs:
            from hermes_tpu.obs.tracing import OpTracer

            c = self._op_tracer_cache = OpTracer(obs)
        return c

    # -- client ops ----------------------------------------------------------

    def _enqueue(self, kind, replica, session, key, value) -> Future:
        cfg = self.cfg
        if not (0 <= replica < cfg.n_replicas):
            raise ValueError(f"replica {replica} out of range [0, {cfg.n_replicas})")
        if not (0 <= session < cfg.n_sessions):
            raise ValueError(f"session {session} out of range [0, {cfg.n_sessions})")
        if kind != "get" and self._degraded_now():
            # quorum-loss degraded mode (round-11): the cluster cannot
            # commit writes right now — shed loudly instead of wedging the
            # session until the watchdog complains.  BEFORE the sparse-key
            # index insert: a shed op must not consume a dense slot
            # (KeyIndex never deletes; an outage of novel-key puts would
            # otherwise burn the keyspace).  Counted in shed_writes ONLY
            # (rejected_ops stays the elastic fence/retire count).
            self.shed_writes += 1
            fut = Future()
            fut._result = Completion(kind="rejected", key=int(key),
                                     found=False)
            return fut
        if kind != "get" and self._wal_backpressured():
            # WAL backpressure (round-22): the durability log's dirty
            # window is full — shed NEW updates loudly (retry later)
            # instead of queueing writes whose durability promise cannot
            # currently be kept.  Same pre-index placement rationale as
            # the degraded shed above.
            self.wal_shed += 1
            fut = Future()
            fut._result = Completion(kind="retry_after", key=int(key),
                                     found=False)
            return fut
        if self.index is not None:
            client_key = int(key)
            if not (0 <= client_key < (1 << 64) - 1):
                raise ValueError("sparse keys are unsigned 64-bit "
                                 "(0xFFFF...FF reserved)")
            # writes allocate (no delete: a written key holds its dense slot
            # for good); gets probe WITHOUT inserting — an absent key's read
            # completes immediately as not-found instead of burning a slot
            if kind == "get":
                slot = self.index.slot(client_key, insert=False)
                if slot < 0:
                    fut = Future()
                    fut._result = Completion(kind="get", key=client_key,
                                             found=False)
                    return fut
            else:
                slot = self.index.slot(client_key, insert=True)
        else:
            if not (0 <= key < cfg.n_keys):
                raise ValueError(f"key {key} out of range [0, {cfg.n_keys})")
            client_key, slot = int(key), int(key)
        if replica in self._retired or self._fence_mask[slot]:
            # elastic rejection (round-10): retired replica or fenced /
            # migrated-away range — the op never enters the store; the
            # client is told NOW, not stranded
            return self._rejected_future(client_key)
        fut = Future()
        # trace mint (round-18): adopt an id staged by the serving layer,
        # else sample one; the submit sequence ticks for EVERY accepted
        # submission so replays sample the same ops.  Unsampled futures
        # never grow the attributes (getattr default keeps them free).
        trace, self._staged_trace = self._staged_trace, 0
        if not trace and self._sampler is not None:
            trace = self._sampler.sample(self._trace_seq)
        self._trace_seq += 1
        if trace:
            fut._trace = trace
            fut._trace_r0 = self.rt.step_idx
        self._queues[(replica, session)].append(
            (kind, slot, client_key, value, fut, 0))
        self._queued_slots.add((replica, session))
        if (replica, session) not in self._inflight:
            self._ready.add((replica, session))
        return fut

    def _degraded_now(self) -> bool:
        """Quorum-loss degraded mode (cfg.min_healthy_for_writes): too few
        healthy un-retired replicas to commit new writes.  Transitions land
        on the obs timeline as ``degraded`` / ``degraded_clear``."""
        floor = self.cfg.min_healthy_for_writes
        if not floor:
            return False
        healthy = [r for r in self.rt.healthy_replicas()
                   if r not in self._retired]
        degraded = len(healthy) < floor
        if degraded != self._degraded:
            self._degraded = degraded
            self.rt._trace("degraded" if degraded else "degraded_clear",
                           healthy=len(healthy), floor=floor)
        return degraded

    def _wal_backpressured(self) -> bool:
        """Round-22 WAL backpressure: more appended-but-not-durable
        records than cfg.wal_dirty_window.  Transitions land on the obs
        timeline (``wal_backpressure`` / ``wal_backpressure_clear``);
        while backpressured the flusher is kicked every probe so the
        window drains as fast as the disk allows."""
        if self.wal is None:
            return False
        bp = self.wal.backpressured()
        if bp != self._wal_bp:
            self._wal_bp = bp
            self.rt._trace(
                "wal_backpressure" if bp else "wal_backpressure_clear",
                dirty=self.wal.dirty_records(),
                window=self.cfg.wal_dirty_window)
        if bp:
            self.wal.kick()
        return bp

    def degraded(self) -> bool:
        """Public view of the quorum-loss degraded mode (round-14: the
        serving front-end's shed ladder composes with it — degraded =>
        writes shed at the front door instead of entering the store just
        to be rejected)."""
        return self._degraded_now()

    def _rejected_future(self, client_key: int) -> Future:
        self.rejected_ops += 1
        fut = Future()
        fut._result = Completion(kind="rejected", key=client_key, found=False)
        return fut

    def get(self, replica: int, session: int, key: int) -> Future:
        """Local linearizable read: served from ``replica``'s own table,
        stalling while the key is Invalid (SURVEY.md §3.2)."""
        return self._enqueue("get", replica, session, key, None)

    def put(self, replica: int, session: int, key: int, value: Sequence[int]) -> Future:
        """Replicated write: commits after the INV/ACK round (quorum of live
        replicas), linearizing at commit (SURVEY.md §3.1)."""
        return self._enqueue("put", replica, session, key, self._payload(value))

    def rmw(self, replica: int, session: int, key: int, value: Sequence[int]) -> Future:
        """Conditional update (YCSB-F, BASELINE.json:8): writes ``value`` and
        returns the value it displaced; aborts (kind='rmw_abort') if a
        concurrent higher-ts update intervenes."""
        return self._enqueue("rmw", replica, session, key, self._payload(value))

    def _payload(self, value) -> np.ndarray:
        u = self.cfg.value_words - 2
        if self.heap is not None:
            # heap mode: the payload IS bytes; the extent lands in the
            # log now and only the packed ref word rides the round
            if not isinstance(value, (bytes, bytearray, memoryview)):
                raise TypeError(
                    "heap mode (cfg.max_value_bytes > 0) takes byte "
                    f"payloads, got {type(value).__name__}; fixed-word "
                    "values need max_value_bytes=0")
            out = np.zeros(u, np.int32)
            out[0] = self._heap_append(bytes(value))
            return out
        arr = np.asarray(list(value), np.int32)
        if arr.ndim != 1 or arr.shape[0] > u:
            raise ValueError(f"value must be <= {u} int32 words")
        return np.pad(arr, (0, u - arr.shape[0]))

    def _heap_append(self, data: bytes) -> int:
        """Land one extent, compacting ONCE on allocation pressure (the
        heap-full -> GC -> retry path); a heap that stays full after
        compaction is genuinely out of space and HeapFull propagates."""
        from hermes_tpu.heap import HeapFull

        try:
            return self.heap.append(data)
        except HeapFull:
            if self._in_heap_gc:
                raise
            self.heap_gc(reason="full")
            return self.heap.append(data)

    @contextlib.contextmanager
    def _heap_staging(self, refs: np.ndarray):
        """Root the nonzero entries of ``refs`` (a 1-D int32 view) for
        any GC that fires inside the with-block, and remap them in place
        when one does — the bridge between 'appended' and 'registered in
        queues/batches/rows' that a multi-append call needs."""
        self._staging.append(refs)
        try:
            yield refs
        finally:
            self._staging.remove(refs)

    # -- batched client path (array-in, futures-out) -------------------------

    GET, PUT, RMW = t.OP_READ, t.OP_WRITE, t.OP_RMW

    def submit_batch(self, kinds, keys, values=None) -> BatchFutures:
        """Enqueue a whole op mix at once: ``kinds`` (n,) of KVS.GET/PUT/RMW,
        ``keys`` (n,) client keys, ``values`` (n, <=value_words-2) int32
        payloads (rows for gets ignored).  Ops flow through idle (replica,
        session) slots in submission order, as many per round as there are
        free slots — the whole path (slot fill, completion match, result
        store) is numpy-vectorized, no per-op Python objects (round-3
        verdict item 5: the public L5 API at engine-scale throughput).
        Returns a BatchFutures; drive it with run_batch()/step()."""
        opc = np.ascontiguousarray(np.asarray(kinds, np.int32))
        n = opc.shape[0]
        bad = ~np.isin(opc, (t.OP_READ, t.OP_WRITE, t.OP_RMW))
        if bad.any():
            raise ValueError(f"unknown op kind(s) {np.unique(opc[bad])}")
        keys_arr = np.asarray(keys)
        if keys_arr.shape != (n,):
            raise ValueError("keys must be shape (n,)")
        u = self.cfg.value_words - 2
        uval = np.zeros((n, u), np.int32)
        if values is not None and self.heap is not None:
            # heap mode: values is a sequence of byte payloads (None /
            # anything for gets — rows for reads are ignored, as in the
            # word path); each update's extent lands NOW and only the
            # packed ref word enters the op stream
            if len(values) != n:
                raise ValueError(f"values must carry {n} byte payloads")
            upd = opc != t.OP_READ
            # the ref column is a GC root WHILE the batch is still being
            # staged: a heap-pressure compaction between two appends
            # must remap the refs already written here
            with self._heap_staging(uval[:, 0]):
                for i in np.nonzero(upd)[0]:
                    v = values[int(i)]
                    if not isinstance(v, (bytes, bytearray, memoryview)):
                        raise TypeError(
                            "heap mode takes byte payloads per update, got "
                            f"{type(v).__name__} at index {int(i)}")
                    uval[i, 0] = self._heap_append(bytes(v))
        elif values is not None:
            v = np.asarray(values, np.int32)
            if v.ndim != 2 or v.shape[0] != n or v.shape[1] > u:
                raise ValueError(f"values must be (n, <={u}) int32 words")
            uval[:, : v.shape[1]] = v
        elif self.heap is not None and (opc != t.OP_READ).any():
            # heap mode: an update without a byte payload would commit
            # the null ref — a silent data-less write; refuse like the
            # per-op path does
            raise TypeError(
                "heap mode (cfg.max_value_bytes > 0) needs a byte payload "
                "per update op; got values=None with "
                f"{int((opc != t.OP_READ).sum())} update(s) in the batch")
        bf = BatchFutures(opc.copy(), keys_arr.copy(), u, heap=self.heap)
        bf.durability = self._wal_label()
        if self._degraded_now():
            # quorum-loss degraded mode (round-11): shed writes loudly
            # BEFORE the sparse-key index mapping — a shed op must not
            # consume a dense slot; gets still serve
            shed = opc != t.OP_READ
            if shed.any():
                bf.code[shed] = C_REJECTED
                bf.found[shed] = False
                self.shed_writes += int(shed.sum())
        if self._wal_backpressured():
            # WAL backpressure (round-22): shed NEW updates loudly with
            # C_RETRY_AFTER before the index mapping, mirroring the
            # degraded shed — the durability log cannot absorb them yet
            shed = (opc != t.OP_READ) & (bf.code == 0)
            if shed.any():
                bf.code[shed] = C_RETRY_AFTER
                bf.found[shed] = False
                self.wal_shed += int(shed.sum())
        if self.index is not None:
            k64 = keys_arr.astype(np.uint64)
            slots = np.zeros(n, np.int32)
            wr = (opc != t.OP_READ) & (bf.code == 0)
            if wr.any():
                slots[wr] = self.index.get_slots(k64[wr])
            rd = (opc == t.OP_READ) & (bf.code == 0)
            if rd.any():
                got = self.index.get_slots(k64[rd], insert=False)
                gi = np.nonzero(rd)[0]
                miss = got < 0
                # absent keys: the get completes immediately as not-found
                # without claiming a dense slot (same rule as get())
                bf.code[gi[miss]] = t.C_READ
                bf.found[gi[miss]] = False
                slots[gi[~miss]] = got[~miss]
        else:
            kmin, kmax = (int(keys_arr.min()), int(keys_arr.max())) if n else (0, 0)
            if n and not (0 <= kmin and kmax < self.cfg.n_keys):
                raise ValueError(
                    f"keys out of range [0, {self.cfg.n_keys})")
            slots = keys_arr.astype(np.int32)
        if self._fence_mask.any():
            # elastic rejection (round-10): ops on fenced / migrated-away
            # slots complete immediately as C_REJECTED — never injected,
            # never silently dropped
            fenced = (bf.code == 0) & self._fence_mask[slots]
            if fenced.any():
                bf.code[fenced] = C_REJECTED
                bf.found[fenced] = False
                self.rejected_ops += int(fenced.sum())
        pend = np.nonzero(bf.code == 0)[0].astype(np.int32)
        if pend.size:
            self._bat[self._next_bid] = dict(
                bf=bf, gix=pend, opc=opc[pend], slots=slots[pend],
                uval=uval[pend], cursor=0)
            self._next_bid += 1
        return bf

    def run_batch(self, bf: BatchFutures, max_steps: int = 50_000) -> bool:
        """Step until every op of ``bf`` resolves (or the budget runs out)."""
        for _ in range(max_steps):
            if bf.all_done():
                return True
            self.step()
        self.flush()  # pipelined: the last round's resolution may be deferred
        return bf.all_done()

    def _inject_batches(self) -> None:
        free = self._kindarr == t.OP_NOP
        for r in self._retired:
            free[r] = False  # retired replicas accept no new injections
        if self._depth > 1:
            # pipelined: a slot retired at the last sync point but whose
            # resolution is still deferred looks NOP here — it must keep
            # its (bid, bix) mapping until the deferred _resolve lands
            free &= self._slot_bid < 0
            for rs_key in self._inflight:
                free[rs_key] = False
        # slots with queued per-op traffic keep their FIFO promise
        for rs_key in self._queued_slots:
            free[rs_key] = False
        rows, cols = np.nonzero(free)
        if rows.size == 0:
            return
        p = 0
        for bid, b in self._bat.items():
            if p >= rows.size:
                break
            cur, total = b["cursor"], b["opc"].shape[0]
            if cur >= total:
                continue
            take = min(total - cur, rows.size - p)
            rr, cc = rows[p : p + take], cols[p : p + take]
            sl = slice(cur, cur + take)
            self._op[rr, cc, 0] = b["opc"][sl]
            self._key[rr, cc, 0] = b["slots"][sl]
            self._uval[rr, cc, 0] = b["uval"][sl]
            self._kindarr[rr, cc] = b["opc"][sl]
            self._slot_bid[rr, cc] = bid
            self._slot_bix[rr, cc] = b["gix"][sl]
            self._slot_inject[rr, cc] = self.rt.step_idx
            b["cursor"] = cur + take
            p += take
            self._dirty = True

    # -- stepping ------------------------------------------------------------

    _OPC = {"get": t.OP_READ, "put": t.OP_WRITE, "rmw": t.OP_RMW}

    def _inject_ready(self) -> None:
        """Inject queued per-op traffic into idle slots (only slots marked
        ready — enqueue and completion maintain the invariant that every
        idle slot with queued work is in _ready).  A slot currently owned
        by a batch op is NOT idle: injecting over it would clobber the
        batch's in-flight stream entry and strand both ops — such slots
        wait (batch retirement re-readies them)."""
        waiting = set()
        for rs_key in self._ready:
            q = self._queues.get(rs_key)
            if rs_key in self._inflight or not q:
                continue
            if rs_key[0] in self._retired:
                # the replica retired after these ops were queued: reject
                # them loudly (shrink() sweeps too; this covers races)
                while q:
                    _k, _sl, ck, _v, fut, _n = q.popleft()
                    fut._result = Completion(kind="rejected", key=ck,
                                             found=False)
                    self.rejected_ops += 1
                self._queued_slots.discard(rs_key)
                continue
            if self._slot_bid[rs_key] >= 0:
                waiting.add(rs_key)
                continue
            kind, slot, client_key, value, fut, nretry = q.popleft()
            if self._fence_mask[slot]:
                # the range fenced after this op was queued (fence_slots
                # sweeps the queues, but an op enqueued mid-drain by a
                # client callback lands here): reject, keep the slot ready
                # for whatever sits behind it in the queue
                if not q:
                    self._queued_slots.discard(rs_key)
                fut._result = Completion(kind="rejected", key=client_key,
                                         found=False)
                self.rejected_ops += 1
                waiting.add(rs_key)
                continue
            if not q:
                self._queued_slots.discard(rs_key)
            r, s = rs_key
            self._op[r, s, 0] = self._OPC[kind]
            self._key[r, s, 0] = slot
            if value is not None:
                self._uval[r, s, 0] = value
            self._inflight[rs_key] = (kind, fut, client_key, value, nretry)
            self._kindarr[r, s] = self._OPC[kind]
            self._slot_inject[r, s] = self.rt.step_idx
            trace = getattr(fut, "_trace", 0)
            if trace:
                # close the client-queue-wait span (submit -> injection)
                # and pin the inject round for the op_rounds span
                fut._trace_inject = self.rt.step_idx
                tr = self._op_tracer()
                if tr is not None:
                    tr.span("op_queue", trace, r0=fut._trace_r0,
                            r1=self.rt.step_idx, replica=r, session=s,
                            op=kind, key=client_key)
            self._dirty = True
        self._ready.clear()
        self._ready |= waiting

    def _sync_stream(self) -> None:
        """Push the staged host op arrays to the device-side stream."""
        if not self._dirty:
            return
        from hermes_tpu.core import faststep as fst
        from hermes_tpu.core import state as st

        self.rt.stream = fst.prep_stream(st.OpStream(
            op=self._op, key=self._key, uval=self._uval,
        ))
        self._dirty = False

    def _done_mask(self, code: np.ndarray, ckey: np.ndarray) -> np.ndarray:
        """One vectorized mask finds the finished slots (kind matches code,
        completion echoes the injected slot id); Python touches only
        those, so step cost does not scale with the in-flight count."""
        k = self._kindarr
        return (
            (((k == t.OP_READ) & (code == t.C_READ))
             | ((k == t.OP_WRITE) & (code == t.C_WRITE))
             | ((k == t.OP_RMW)
                & ((code == t.C_RMW) | (code == t.C_RMW_ABORT))))
            & (ckey == self._key[:, :, 0])
        )

    def _retire(self, done_mask: np.ndarray) -> None:
        """Blank completed slots in the staged stream so the NEXT dispatched
        round cannot re-issue them (the idle session reloads its one-deep
        stream slot every round).  Future/batch bookkeeping is _resolve's
        job — in pipelined mode it runs one round later."""
        rows, cols = np.nonzero(done_mask)
        if rows.size:
            self._op[rows, cols, 0] = t.OP_NOP
            self._kindarr[rows, cols] = t.OP_NOP
            self._slot_inject[rows, cols] = -1
            self._dirty = True

    def _resolve(self, done_mask, code, rval, wval, round_idx: int,
                 ver=None, fc=None) -> int:
        """Resolve the futures of one round's completed slots (the slots
        were already retired by _retire).  Returns the op count.
        ``ver``/``fc`` (when the caller fetched them) feed the round-16
        read-your-writes fence: a per-op committed update pins its
        re-anchored timestamp so the session's later local reads must
        observe it or fall back to the round path."""
        ndone = 0
        # batch-owned slots: results land in the BatchFutures columns with
        # three fancy-index stores, then the slots retire vectorized
        bdone = done_mask & (self._slot_bid >= 0)
        if bdone.any():
            rows, cols = np.nonzero(bdone)
            bids = self._slot_bid[rows, cols]
            for bid in np.unique(bids):
                m = bids == bid
                rr, cc = rows[m], cols[m]
                b = self._bat[bid]
                bf: BatchFutures = b["bf"]
                gi = self._slot_bix[rr, cc]
                bf.code[gi] = code[rr, cc]
                bf.value[gi] = rval[rr, cc, 2:]
                bf.uid[gi] = wval[rr, cc, :2]
                bf.step[gi] = round_idx
                if self.heap is not None:
                    # heap mode: resolve read payloads eagerly while the
                    # referenced extents are provably un-compacted (GC
                    # flushes every completion before it moves bytes)
                    ccode = code[rr, cc]
                    crefs = rval[rr, cc, 2]
                    for j in np.nonzero(
                            (ccode == t.C_READ) | (ccode == t.C_RMW))[0]:
                        ref = int(crefs[j])
                        bf.data[int(gi[j])] = (
                            self.heap.read(ref) if ref else None)
                if ver is not None:
                    bf.tsv[gi] = ver[rr, cc]
                    bf.tsf[gi] = fc[rr, cc]
                if b["cursor"] >= b["opc"].shape[0] and bf.all_done():
                    del self._bat[bid]
            self._slot_bid[rows, cols] = -1
            ndone += rows.size
            # freed slots with waiting per-op traffic become injectable
            # again (O(#queued slots), not O(#retired))
            for rs_key in self._queued_slots:
                if self._slot_bid[rs_key] < 0 \
                        and rs_key not in self._inflight:
                    self._ready.add(rs_key)
        for r, s in np.argwhere(done_mask & ~bdone):
            r, s = int(r), int(s)
            kind, fut, client_key, _value, _nretry = self._inflight.pop((r, s))
            self._retry_next.pop((r, s), None)
            self._retry_k.pop((r, s), None)
            c = int(code[r, s])
            done = Completion(
                kind="rmw_abort" if c == t.C_RMW_ABORT else kind,
                key=client_key,
                step=round_idx,
            )
            if c in (t.C_READ, t.C_RMW):
                done.value = rval[r, s, 2:].tolist()
                if self.heap is not None:
                    ref = int(rval[r, s, 2])
                    done.data = self.heap.read(ref) if ref else None
            if c in (t.C_WRITE, t.C_RMW):
                done.uid = (int(wval[r, s, 0]), int(wval[r, s, 1]))
                done.durability = self._wal_label()
                if ver is not None:
                    done.ts = (int(ver[r, s]), int(fc[r, s]))
                    # RYW fence (round-16): this lane's later local reads
                    # of the slot must observe ts >= this committed write
                    slot = (client_key if self.index is None
                            else self.index.slot(client_key, insert=False))
                    self._ryw.setdefault((r, s), {})[int(slot)] = done.ts
            trace = getattr(fut, "_trace", 0)
            if trace:
                # device-rounds span: injection round -> resolution round
                tr = self._op_tracer()
                if tr is not None:
                    tr.span("op_rounds", trace,
                            r0=getattr(fut, "_trace_inject", round_idx),
                            r1=round_idx, replica=r, session=s,
                            op=done.kind, key=client_key)
            fut._result = done
            if self._queues.get((r, s)):
                self._ready.add((r, s))
            ndone += 1
        return ndone

    # -- stuck-op watchdog (round-9, cfg.op_timeout_rounds) ------------------

    _PHASE = {t.S_IDLE: "idle", t.S_READ: "read-stall", t.S_ISSUE: "issue",
              t.S_INFL: "ack-wait", t.S_DONE: "done"}

    def _watchdog(self) -> None:
        """Surface client ops pending past ``cfg.op_timeout_rounds``: one
        ``stuck_op`` obs event + one ``self.stuck_ops`` diagnostic per op
        (coordinator replica, session, protocol phase, gathered-ack bitmap,
        age in rounds) the first time it out-ages the budget — instead of
        hanging silently when its quorum is frozen/partitioned away.  The
        per-session device inspection runs only when a NEW stuck op exists
        (the steady-state cost is one numpy compare).  Strict mode
        (``strict_timeouts``) raises StuckOpError after reporting."""
        tmo = self.cfg.op_timeout_rounds
        if not tmo:
            return
        active = self._slot_inject >= 0
        if not active.any():
            return
        age = self.rt.step_idx - self._slot_inject
        stuck = active & (age > tmo)
        fresh = []
        for r, s in zip(*np.nonzero(stuck)):
            tag = (int(r), int(s), int(self._slot_inject[r, s]))
            if tag not in self._stuck_flagged:
                self._stuck_flagged.add(tag)
                fresh.append((int(r), int(s)))
        new_diags = []
        if fresh:
            sess = self.rt.fs.sess
            status = np.asarray(jax.device_get(sess.status))
            acks = np.asarray(jax.device_get(sess.acks))
            for r, s in fresh:
                # report the CLIENT's key: in sparse-key mode the staged
                # stream holds the dense device slot, which the client never
                # saw — the per-op inflight entry / batch columns carry the
                # submitted key
                if (r, s) in self._inflight:
                    ckey = self._inflight[(r, s)][2]
                elif self._slot_bid[r, s] >= 0:
                    b = self._bat.get(int(self._slot_bid[r, s]))
                    ckey = (int(b["bf"].key[int(self._slot_bix[r, s])])
                            if b is not None else int(self._key[r, s, 0]))
                else:
                    ckey = int(self._key[r, s, 0])
                diag = dict(
                    replica=r, session=s,
                    key=int(ckey),
                    kind=BatchFutures._KINDSTR.get(
                        int(self._kindarr[r, s]), "?"),
                    phase=self._PHASE.get(int(status[r, s]), "?"),
                    acks=int(acks[r, s]),
                    age_rounds=int(age[r, s]),
                    at_step=self.rt.step_idx,
                )
                if self.rt.group is not None:
                    # fleet deployments (round-13): the diagnostic names
                    # its group, so a fleet-wide soak triages stuck ops
                    # without cross-referencing which KVS raised
                    diag["group"] = self.rt.group
                if self.drill_phase is not None:
                    # an elastic drill (fence/drain/flip) is active: a
                    # wedged op must be attributable to it from the
                    # timeline alone
                    diag["drill"] = self.drill_phase
                if self.net_phase is not None:
                    # adversarial wire window active (round-11): the diag
                    # carries the partition/drop spec and affected peer
                    # pairs, so soak triage needs no log cross-referencing
                    diag["net"] = self.net_phase
                if self.diag_hook is not None:
                    # serving front-end attached (round-14): tag the op's
                    # tenant + remaining deadline budget
                    extra = self.diag_hook(r, s)
                    if extra:
                        diag.update(extra)
                new_diags.append(diag)
                self.stuck_ops.append(diag)
                self.rt._trace("stuck_op", **diag)
        if new_diags and self.rt.obs is not None:
            # flight recorder (round-18): a wedged op is exactly the
            # moment the black box exists for — dump BEFORE any strict
            # raise so the archive holds the diagnostics (no-op unless a
            # dump dir is configured; see obs/flightrec.py)
            self.rt.obs.flight_dump("stuck_op", extra=dict(diags=new_diags))
        if self.cfg.op_retry_limit:
            self._escalate_stuck(stuck)
        if self.strict_timeouts and new_diags:
            raise StuckOpError(new_diags)

    def _escalate_stuck(self, stuck: np.ndarray) -> None:
        """Bounded retry with backoff (round-11, cfg.op_retry_limit): a
        stuck per-op future whose coordinator is FENCED (not live, frozen,
        or retired — e.g. partitioned away and ejected by the detector) is
        salvaged and re-submitted on a healthy replica; a stuck op on a
        healthy coordinator is re-examined after an exponential backoff
        window instead (it may yet commit — blind retry would
        double-write)."""
        step = self.rt.step_idx
        healthy = set(self.rt.healthy_replicas()) - self._retired
        for rs_key in [k for k in list(self._inflight) if stuck[k]]:
            if rs_key not in self._inflight:
                continue  # resolved by an earlier salvage's pipeline flush
            r, s = rs_key
            nxt = self._retry_next.get(rs_key)
            if nxt is None:
                self._retry_next[rs_key] = step  # examine immediately
            elif step < nxt:
                continue
            if r in healthy:
                # coordinator healthy: back off — the op may still commit
                k = self._retry_k.get(rs_key, 0)
                self._retry_k[rs_key] = k + 1
                self._retry_next[rs_key] = step + (
                    self.cfg.op_timeout_rounds * self.cfg.op_backoff ** (k + 1))
                continue
            self._salvage_retry(r, s, sorted(healthy))

    def _salvage_retry(self, r: int, s: int, healthy: list) -> None:
        """Salvage one wedged per-op future off fenced coordinator ``r``
        (exactly the crash model, per slot: history fold as maybe_w for
        updates, volatile wipe so the dead uid never re-mints, staged
        stream slot cleared) and re-enqueue it on a healthy replica with
        the SAME future; exhausted retries (or no healthy replica, or a
        fenced range) resolve loudly instead."""
        from hermes_tpu.chaos import recovery as recovery_lib

        rt = self.rt
        rt.flush_pipeline()  # a deferred round may have completed this op
        if (r, s) not in self._inflight or self._slot_inject[r, s] < 0:
            self._retry_next.pop((r, s), None)
            self._retry_k.pop((r, s), None)
            return
        kind, fut, ck, value, nretry = self._inflight.pop((r, s))
        slot = int(self._key[r, s, 0])
        mask = np.zeros((self.cfg.n_replicas, self.cfg.n_sessions), bool)
        mask[r, s] = True
        if kind != "get" and rt.recorder is not None:
            # the wedged broadcast may still commit via replay: the history
            # must be ALLOWED — not required — to linearize it
            rt.recorder.fold_pending(rt._sess_view(), mask=mask)
        recovery_lib.wipe_volatile(rt, mask)
        self._op[r, s, 0] = t.OP_NOP
        self._kindarr[r, s] = t.OP_NOP
        self._slot_inject[r, s] = -1
        self._dirty = True
        self._retry_next.pop((r, s), None)
        self._retry_k.pop((r, s), None)
        terminal = None
        if self._fence_mask[slot]:
            terminal = "rejected"  # the range migrated away mid-wedge
        elif nretry >= self.cfg.op_retry_limit or not healthy:
            terminal = "lost"  # retries exhausted / nowhere to go
        if terminal is not None:
            fut._result = Completion(kind=terminal, key=ck, found=False)
            if terminal == "rejected":
                self.rejected_ops += 1
            rt._trace("op_retry_exhausted", replica=r, session=s, key=ck,
                      outcome=terminal, retries=nretry)
        else:
            target = healthy[(r + 1 + nretry) % len(healthy)]
            self.retried_ops += 1
            rt._trace("op_retry", replica=r, session=s, key=ck,
                      target=target, attempt=nretry + 1)
            self._queues[(target, s)].append(
                (kind, slot, ck, value, fut, nretry + 1))
            self._queued_slots.add((target, s))
            if (target, s) not in self._inflight:
                self._ready.add((target, s))
        if self._queues.get((r, s)):
            self._ready.add((r, s))  # traffic queued behind the salvaged op

    def step(self) -> int:
        """Inject queued ops, run one protocol round, resolve completions.
        Returns the number of ops completed (with ``cfg.pipeline_depth >=
        2``, the number resolved from the PREVIOUS round — resolution lags
        one round so it overlaps with device execution)."""
        self._inject_ready()
        if self._bat:
            self._inject_batches()
        if self._depth > 1:
            n = self._step_pipelined()
            self._watchdog()
            return n
        self._sync_stream()
        comp = self.rt.step_once()
        code = np.asarray(comp.code)
        done_mask = self._done_mask(code, np.asarray(comp.key))
        self._retire(done_mask)
        n = self._gated_resolve(done_mask, code, np.asarray(comp.rval),
                                np.asarray(comp.wval), self.rt.step_idx - 1,
                                np.asarray(comp.ver), np.asarray(comp.fc))
        self._watchdog()
        return n

    def _step_pipelined(self) -> int:
        """Round-8 overlapped serving: dispatch round k from the staged
        stream, then — while the device executes it — resolve round k-1's
        futures (the BULK value readback + numpy matching + Future/batch
        stores, via the runtime's harvest path so recording and version
        re-anchoring are identical to the sync mode) and stage the next
        client ops.  The only synchronous fetch is round k's small
        code/key columns: round k+1's stream must retire round k's
        completed slots before it dispatches, or idle sessions would
        re-issue the same client op.  That data dependency caps the KVS
        at one bulk-deferred round (effective depth 2) regardless of
        cfg.pipeline_depth."""
        self._sync_stream()
        comp = self.rt.dispatch_round()
        k = self.rt.step_idx - 1
        # resolve round k-1 while the device runs round k (non-blocking:
        # under wal_sync='commit' a round whose log batch is not yet
        # durable stays parked — the public flush() is what forces the
        # group commit out)
        ndone = self._flush_round()
        # intake freed by that resolution stages NOW — inside the
        # device-busy window — for the round-k+1 dispatch (the next call's
        # top-of-step injection pass runs after the sync point below, i.e.
        # with the device idle, and only picks up ops enqueued since; it
        # finds these queues already drained)
        self._inject_ready()
        if self._bat:
            self._inject_batches()
        # sync point: ONE fetch of the small columns only (code + echoed key)
        code, ckey = (np.asarray(a) for a in
                      jax.device_get((comp.code, comp.key)))
        done_mask = self._done_mask(code, ckey)
        self._retire(done_mask)
        self._pending = (k, comp, done_mask, code)
        return ndone

    def _flush_round(self) -> int:
        """Harvest the deferred round (pipelined mode; no-op at depth 1)
        and resolve what durability allows: under wal_sync='commit' the
        round parks in _wal_defer until its log batch fsyncs — this
        method NEVER blocks on the disk (it runs on the per-round hot
        path inside _step_pipelined's device-busy window)."""
        if self._pending is None:
            return self._drain_wal_defer()
        pk, pcomp, done_mask, code = self._pending
        self._pending = None
        comp_np = self.rt.harvest_comp(pcomp, round_idx=pk)
        return self._gated_resolve(done_mask, code,
                                   np.asarray(comp_np.rval),
                                   np.asarray(comp_np.wval), pk,
                                   np.asarray(comp_np.ver),
                                   np.asarray(comp_np.fc))

    def flush(self) -> int:
        """Resolve EVERY in-flight completion: the deferred pipelined
        round, plus — under wal_sync='commit' — a forced group commit so
        all durability-parked rounds resolve too.  Installed as the
        runtime's ``comp_flush`` hook so rebase/drain/snapshot boundaries
        leave nothing unresolved."""
        n = self._flush_round()
        if self._wal_defer:
            n += self._drain_wal_defer(wait=True)
        return n

    def _gated_resolve(self, done_mask, code, rval, wval, round_idx,
                       ver, fc) -> int:
        """Resolve one harvested round now — or, under wal_sync='commit',
        park it keyed by the round's WAL batch LSN until the group-commit
        flusher reports that batch durable.  Rounds always resolve in
        round order (the deque is FIFO and LSNs are monotone)."""
        wal = self.wal
        if wal is None or self.cfg.wal_sync != "commit":
            if wal is not None:
                wal.kick()  # relaxed modes: fsync soon, just don't wait
            return self._resolve(done_mask, code, rval, wval, round_idx,
                                 ver=ver, fc=fc)
        self._wal_defer.append((self.rt.wal_last_lsn, done_mask, code,
                                rval, wval, round_idx, ver, fc))
        wal.kick()
        return self._drain_wal_defer()

    def _drain_wal_defer(self, wait: bool = False) -> int:
        """Resolve durability-parked rounds whose log batches are durable;
        ``wait=True`` (the public flush) forces the group commit first —
        the fsync wait lands on the obs timeline as a ``wal_sync`` span."""
        wal = self.wal
        if wal is None or not self._wal_defer:
            return 0
        if wait:
            target = self._wal_defer[-1][0]
            obs = self.rt.obs
            if obs is not None:
                with obs.tracer.span("wal_sync", lsn=target,
                                     parked_rounds=len(self._wal_defer)):
                    wal.sync(target)
            else:
                wal.sync(target)
        n = 0
        durable = wal.durable_lsn()
        while self._wal_defer and self._wal_defer[0][0] <= durable:
            _lsn, done_mask, code, rval, wval, k, ver, fc = (
                self._wal_defer.popleft())
            n += self._resolve(done_mask, code, rval, wval, k,
                               ver=ver, fc=fc)
        return n

    def _wal_label(self) -> Optional[str]:
        """The durability label committed updates carry (round-22):
        'commit' when resolution waited for the fsync, a loud
        ':not-fsynced-at-resolve' suffix for the relaxed modes."""
        if self.wal is None:
            return None
        mode = self.cfg.wal_sync
        return ("commit" if mode == "commit"
                else f"{mode}:not-fsynced-at-resolve")

    def run_until(self, futures: Sequence[Future], max_steps: int = 10_000) -> bool:
        """Step until every future resolves (or the step budget runs out)."""
        for _ in range(max_steps):
            if all(f.done() for f in futures):
                return True
            self.step()
        self.flush()  # pipelined: the last round's resolution may be deferred
        return all(f.done() for f in futures)

    # -- local-read fast path (round-16, core/readpath.py) -------------------

    def _get_reader(self):
        if self._reader is None:
            from hermes_tpu.core.readpath import LocalReader

            self._reader = LocalReader(self.rt)
        return self._reader

    def _record_local_reads(self, slots: np.ndarray, vals: np.ndarray) -> None:
        """Feed locally-served reads into the recorded history (both
        recorder kinds) so the fast path is linearizability-CHECKED, not
        assumed: each read linearizes at the upcoming round's read point
        (inv = resp = 2 * step in the doubled clock — after the last
        harvested round's commits, before the next round's)."""
        rec = self.rt.recorder
        if rec is None or slots.size == 0:
            return
        from hermes_tpu.core import state as st

        n = slots.shape[0]
        step = np.full((1, n), self.rt.step_idx, np.int32)
        rec.record_step(st.Completions(
            code=np.full((1, n), t.C_READ, np.int32),
            key=slots.reshape(1, n).astype(np.int32),
            wval=np.zeros((1, n, self.cfg.value_words), np.int32),
            rval=vals.reshape(1, n, -1).astype(np.int32),
            ver=np.zeros((1, n), np.int32),
            fc=np.zeros((1, n), np.int32),
            invoke_step=step,
            commit_step=step,
        ))

    def _ryw_unserved(self, session, slots: np.ndarray, serve: np.ndarray,
                      pts: np.ndarray) -> None:
        """Clear ``serve`` bits whose row timestamp has not yet caught up
        with the session's own committed writes (the read-your-writes
        fence): the fallback round-path read stalls until the key
        revalidates at >= the fence ts, so the session can never observe
        a value older than a write it saw commit.  Satisfied entries
        prune — the row ts only grows.  ``session`` is any hashable
        token: the per-op write path pins fences under its (replica,
        session) lane automatically; batch-path / serving callers pin
        under their own token via ``pin_read_fence``."""
        fence = self._ryw.get(session) if session is not None else None
        if not fence:
            return
        from hermes_tpu.core import faststep as fst

        base = self._ver_base_of(slots)
        for j in np.nonzero(serve)[0]:
            slot = int(slots[j])
            want = fence.get(slot)
            if want is None:
                continue
            row = (int(pts[j]) >> fst.PTS_FC_BITS) + int(base[j]), \
                int(pts[j]) & fst.FC_MASK
            if row < want:
                serve[j] = False
                self.ryw_fallbacks += 1
            else:
                del fence[slot]

    def _ver_base_of(self, slots: np.ndarray) -> np.ndarray:
        """Per-slot rebase delta re-anchoring device-era row timestamps
        into the recorder's global version space (FastRuntime._ver_base;
        zero before the first rebase)."""
        vb = getattr(self.rt, "_ver_base", None)
        if vb is None:
            return np.zeros(slots.shape[0], np.int64)
        return vb[np.asarray(slots)]

    def _serve_reads(self, res: MultiGetResult, slots: np.ndarray,
                     pend: np.ndarray, session, ans) -> None:
        """Shared tail of multi_get/scan: fill locally-answerable rows of
        ``res`` from a ReadAnswer, route the rest through the round path
        as a fallback read batch."""
        pi = np.nonzero(pend)[0]
        if pi.size == 0:
            return
        serve = np.zeros(pi.size, bool)
        if ans is not None:
            serve = np.asarray(ans.valid).copy()
            self._ryw_unserved(session, slots[pi], serve,
                               np.asarray(ans.pts))
            si = pi[serve]
            if si.size:
                vals = np.asarray(ans.val)[serve]
                res.code[si] = t.C_READ
                res.value[si] = vals[:, 2:]
                res.local[si] = True
                res.step[si] = self.rt.step_idx
                self.local_reads += int(si.size)
                if self.heap is not None:
                    # resolve the served rows' byte payloads off the
                    # mirror NOW (the row's ref word was read atomically
                    # with its uid in the one bank gather)
                    for i, ref in zip(si, vals[:, 2]):
                        res.data[int(i)] = (self.heap.read(int(ref))
                                            if int(ref) else None)
                self._record_local_reads(slots[si], vals)
        fb = pi[~serve]
        if fb.size:
            # Invalid at the serving replica (a write is in flight), RYW
            # fence unsatisfied, or no healthy replica: the round path
            # serves these — its read stalls until the key is Valid,
            # exactly the reference's read-stall rule, so no stale bytes
            # can ever take this exit either
            self.fallback_reads += int(fb.size)
            bf = self.submit_batch(
                np.full(fb.size, t.OP_READ, np.int32),
                np.asarray(res.key)[fb])
            res._fallback = (bf, fb)

    def multi_get(self, keys, session: Optional[Tuple[int, int]] = None,
                  wait: bool = True, max_steps: int = 50_000
                  ) -> MultiGetResult:
        """Batched device-resident read (round-16): ONE jitted dispatch
        answers every Valid key of ``keys`` straight from the resident
        table — zero wire traffic, zero round involvement (Hermes' local
        read, PAPER.md).  Keys the fast path must not answer (Invalid —
        a write is in flight; read-your-writes fence unsatisfied for
        ``session``; fenced/migrating ranges; no healthy replica) fall
        back to the normal round path instead of returning stale bytes.
        ``session`` is the calling (replica, session) lane — its own
        committed writes fence its reads.  With ``wait`` (default) the
        fallback batch is driven to completion before returning."""
        # sparse client keys are unsigned 64-bit: coerce EXPLICITLY — a
        # bare asarray of a >int64 python int silently promotes the whole
        # batch to float64 and shears the low bits off every key
        keys_arr = np.atleast_1d(
            np.asarray(keys, np.uint64) if self.index is not None
            else np.asarray(keys))
        n = keys_arr.shape[0]
        u = self.cfg.value_words - 2
        res = MultiGetResult(keys_arr.copy(), u, heap=self.heap)
        if n == 0:
            return res
        if self.index is not None:
            slots = self.index.get_slots(keys_arr, insert=False)
            miss = slots < 0
            if miss.any():
                # absent sparse keys: answered not-found immediately, no
                # dense slot claimed (the get() rule)
                res.code[miss] = t.C_READ
                res.found[miss] = False
                res.step[miss] = self.rt.step_idx
                slots = np.where(miss, 0, slots)
        else:
            kmin = int(keys_arr.min())
            kmax = int(keys_arr.max())
            if not (0 <= kmin and kmax < self.cfg.n_keys):
                raise ValueError(f"keys out of range [0, {self.cfg.n_keys})")
            slots = keys_arr.astype(np.int32)
        pend = res.code == 0
        if self._fence_mask.any():
            fenced = pend & self._fence_mask[slots]
            if fenced.any():
                res.code[fenced] = C_REJECTED
                res.found[fenced] = False
                self.rejected_ops += int(fenced.sum())
                pend &= ~fenced
        if pend.any():
            # the ReadAnswer is aligned with the pending subset — exactly
            # the order _serve_reads consumes
            ans = self._get_reader().multi_get(slots[np.nonzero(pend)[0]])
            self._serve_reads(res, slots, pend, session, ans)
        if wait and res._fallback is not None:
            self.run_batch(res._fallback[0], max_steps=max_steps)
            res._pull()
        return res

    def scan(self, lo: int, hi: int,
             session: Optional[Tuple[int, int]] = None, wait: bool = True,
             max_steps: int = 50_000) -> MultiGetResult:
        """Range scan over dense slots ``[lo, hi)`` via the zero-sparse-op
        contiguous read program (one dynamic_slice — core/readpath.py).
        Dense mode echoes slot ids as keys; sparse mode clamps to the
        allocated frontier and echoes the CLIENT key of each slot
        (slots allocate in first-write order, so a sparse scan is a
        write-order scan).  Same Valid/RYW/fence fallback rules as
        ``multi_get``."""
        if not (0 <= lo < hi <= self.cfg.n_keys):
            raise ValueError(
                f"scan range [{lo}, {hi}) outside [0, {self.cfg.n_keys})")
        u = self.cfg.value_words - 2
        if self.index is not None:
            hi = min(hi, self.index.n_used)
            if lo >= hi:
                return MultiGetResult(np.zeros(0, np.uint64), u,
                                      heap=self.heap)
            keys_arr = self.index._rev[lo:hi].copy()
        else:
            keys_arr = np.arange(lo, hi, dtype=np.int64)
        slots = np.arange(lo, hi, dtype=np.int32)
        res = MultiGetResult(keys_arr, u, heap=self.heap)
        pend = np.ones(hi - lo, bool)
        if self._fence_mask.any():
            fenced = self._fence_mask[lo:hi]
            if fenced.any():
                res.code[fenced] = C_REJECTED
                res.found[fenced] = False
                self.rejected_ops += int(fenced.sum())
                pend &= ~fenced
        ans = self._get_reader().scan(lo, hi)
        if ans is not None and not pend.all():
            pi = np.nonzero(pend)[0]  # align with the pending subset
            ans = type(ans)(valid=np.asarray(ans.valid)[pi],
                            val=np.asarray(ans.val)[pi],
                            pts=np.asarray(ans.pts)[pi])
        self._serve_reads(res, slots, pend, session, ans)
        if wait and res._fallback is not None:
            self.run_batch(res._fallback[0], max_steps=max_steps)
            res._pull()
        return res

    def pin_read_fence(self, session, client_key: int,
                       ts: Tuple[int, int]) -> None:
        """Pin a read-your-writes fence under an arbitrary session token
        (round-16): the caller observed a commit with protocol timestamp
        ``ts`` (Completion.ts / BatchFutures.tsv+tsf) and wants every
        later ``multi_get(..., session=token)`` on the key to observe it
        or fall back to the round path.  The per-op future path pins its
        (replica, session) lane automatically; this is the hook for
        batch writers and the serving front-end's per-tenant fencing."""
        slot = (int(client_key) if self.index is None
                else self.index.slot(int(client_key), insert=False))
        if slot < 0:
            return  # absent sparse key: nothing committed to fence on
        self._ryw.setdefault(session, {})[slot] = (int(ts[0]), int(ts[1]))

    def read_stats(self) -> dict:
        """Fast-path accounting: locally-served vs round-path fallback
        reads, RYW fence misses, and read dispatches issued."""
        rd = self._reader
        return dict(local_reads=self.local_reads,
                    fallback_reads=self.fallback_reads,
                    ryw_fallbacks=self.ryw_fallbacks,
                    read_dispatches=0 if rd is None else rd.dispatches)

    # -- value-heap GC (round-17, hermes_tpu/heap) ---------------------------

    def _heap_rebase_hook(self) -> None:
        """Installed as the runtime's ``rebase_hook``: heap compaction
        rides every version rebase — the store is already quiesced,
        drained, and flushed at that boundary, so the GC skips its own
        drain."""
        if not self._in_heap_gc:
            self.heap_gc(quiesce=False, reason="rebase")

    def _heap_roots(self):
        """Every place a live heap ref can hide while the store is
        drained: table rows (every replica copy — a frozen replica's
        stale rows keep their extents alive until overwritten, the
        conservative rule), the staged device stream (ops injected but
        not yet consumed under quiesce), queued per-op traffic, and
        staged-but-uninjected batch rows.  Returns (bank_refcol,
        staged_mask, root_concat)."""
        from hermes_tpu.core import faststep as fst
        from hermes_tpu.transport import codec

        bank = np.asarray(jax.device_get(self.rt.fs.table.bank))
        rows32 = codec.rows_to_words(bank)
        refcol = rows32[:, fst.BANK_VAL + 2].copy()
        roots = [refcol.astype(np.int64)]
        staged_mask = self._kindarr != t.OP_NOP
        roots.append(self._uval[:, :, 0, 0][staged_mask].astype(np.int64))
        for rs_key in self._queued_slots:
            for item in self._queues[rs_key]:
                if item[3] is not None:
                    roots.append(np.asarray([item[3][0]], np.int64))
        for b in self._bat.values():
            roots.append(b["uval"][b["cursor"]:, 0].astype(np.int64))
        for arr in self._staging:
            roots.append(arr[arr != 0].astype(np.int64))
        return refcol, staged_mask, np.concatenate(roots)

    def heap_gc(self, quiesce: bool = True, reason: str = "full",
                max_quiesce_rounds: int = 512) -> dict:
        """Compact the value heap: quiesce-drain in-flight writes (the
        rebase discipline — FastCtl.quiesce pauses intake/issues while
        pending broadcasts finish), flush every completion, copy the
        LIVE extents to the front of a fresh log, and remap the packed
        ref words everywhere they live (table rows on device, staged
        stream, client queues, pending batches).  Lands on the obs
        timeline as a ``heap_gc`` span + ``heap_util`` gauge.

        If in-flight ops cannot drain (a frozen coordinator pins them),
        the compaction is SKIPPED loudly (``heap_gc_skipped`` event) —
        an undrainable op's device-side ref cannot be remapped, so
        moving its extent would corrupt the row it eventually commits.
        Returns the post-GC heap stats (empty dict when skipped)."""
        if self.heap is None:
            raise RuntimeError("heap_gc needs cfg.max_value_bytes > 0")
        if self._in_heap_gc:
            return {}
        rt = self.rt
        self._in_heap_gc = True
        try:
            if rt.obs is not None:
                with rt.obs.tracer.span("heap_gc", step=rt.step_idx,
                                        reason=reason):
                    return self._heap_gc_body(quiesce, reason,
                                              max_quiesce_rounds)
            return self._heap_gc_body(quiesce, reason, max_quiesce_rounds)
        finally:
            self._in_heap_gc = False

    def _heap_gc_body(self, quiesce: bool, reason: str,
                      max_quiesce_rounds: int) -> dict:
        import jax.numpy as jnp

        from hermes_tpu.core import faststep as fst
        from hermes_tpu.heap import ValueHeap
        from hermes_tpu.transport import codec

        rt = self.rt
        if quiesce:
            prev = rt.quiesce
            rt.quiesce = True
            try:
                for _ in range(max_quiesce_rounds):
                    if rt._inflight_count() == 0:
                        break
                    self.step()
            finally:
                rt.quiesce = prev
        rt.flush_pipeline()
        self.flush()
        if rt._inflight_count() != 0:
            # an undrainable in-flight write holds a device-side ref the
            # remap cannot reach — refuse to move bytes under it
            rt._trace("heap_gc_skipped", reason=reason,
                      inflight=rt._inflight_count())
            return {}
        refcol, staged_mask, roots = self._heap_roots()
        old, new = self.heap.compact(roots)
        # table rows: remap the ref word column of every replica copy in
        # one dense byte-column update (4 bytes per row at the payload-
        # word-0 offset; batched = the one shared copy, sharded = all R)
        newcol = ValueHeap.remap(refcol, old, new).astype(np.int32)
        if not np.array_equal(newcol, refcol):
            col = 4 * (fst.BANK_VAL + 2)
            col_bytes = codec.words_to_rows(newcol[:, None])
            tbl = rt.fs.table
            rt.fs = rt.fs._replace(table=tbl._replace(
                bank=tbl.bank.at[:, col:col + 4].set(jnp.asarray(col_bytes))))
        # staged stream rows (injected, unconsumed): remap in place;
        # idle rows' stale payloads are zeroed so a dead ref can never
        # masquerade as live at the next collection
        vals = self._uval[:, :, 0, 0]
        vals[staged_mask] = ValueHeap.remap(
            vals[staged_mask], old, new).astype(np.int32)
        vals[~staged_mask] = 0
        self._dirty = True
        # queued per-op payload arrays mutate in place (the deque items
        # hold the very np array the eventual injection will read)
        for rs_key in self._queued_slots:
            for item in self._queues[rs_key]:
                if item[3] is not None:
                    item[3][0] = int(ValueHeap.remap(
                        np.asarray([item[3][0]], np.int64), old, new)[0])
        for b in self._bat.values():
            pend = b["uval"][b["cursor"]:, 0]
            b["uval"][b["cursor"]:, 0] = ValueHeap.remap(
                pend.astype(np.int64), old, new).astype(np.int32)
        for arr in self._staging:
            nz = arr != 0
            if nz.any():
                arr[nz] = ValueHeap.remap(
                    arr[nz].astype(np.int64), old, new).astype(arr.dtype)
        if self.wal is not None and old.size:
            # round-22: log the ref rewrite so the un-truncated WAL tail
            # stays interpretable (bookkeeping — each record's extent
            # BYTES remain authoritative for replay)
            self.wal.note_remap(old, new)
        stats = self.heap.stats()
        if rt.obs is not None:
            rt.obs.registry.gauge(
                "heap_util",
                help="live heap bytes / heap capacity").set(
                    stats["live_bytes"] / stats["capacity_bytes"])
        rt._trace("heap_gc", reason=reason,
                  live_bytes=stats["live_bytes"],
                  used_bytes=stats["used_bytes"],
                  reclaimed_bytes=self.heap.gc_reclaimed_bytes)
        return stats

    def heap_stats(self) -> Optional[dict]:
        """Heap accounting (None when the heap is disabled)."""
        return None if self.heap is None else self.heap.stats()

    # -- elastic operations (round-10, hermes_tpu/elastic) -------------------

    def fence_slots(self, lo: int, hi: int) -> int:
        """Reject-new over dense slots ``[lo, hi)`` — the first step of a
        key-range migration's drain.  Queued-but-uninjected ops on the
        range are rejected NOW (their futures resolve kind='rejected');
        in-flight ops keep running (drain flushes them).  The fence stays
        until ``release_slots`` — after a flip it stays forever on the
        source: the range has a new owner.  Returns the number of queued
        ops rejected.  Sparse-key mode requires ``hi <= len(index)``:
        fresh client keys allocate slots at the dense frontier, and a
        fence over unallocated slots would let new keys land INSIDE a
        draining range."""
        if not (0 <= lo < hi <= self.cfg.n_keys):
            raise ValueError(f"range [{lo}, {hi}) outside "
                             f"[0, {self.cfg.n_keys})")
        if self.index is not None and hi > self.index.n_used:
            raise ValueError(
                f"fence [{lo}, {hi}) reaches past the allocated slot "
                f"frontier ({self.index.n_used}): a fresh sparse key could "
                "allocate into the draining range; migrate allocated "
                "ranges only")
        self._fence_mask[lo:hi] = True
        rejected = 0
        # sweep queued per-op traffic on the range
        for rs_key in list(self._queued_slots):
            q = self._queues[rs_key]
            keep = collections.deque()
            while q:
                item = q.popleft()
                if lo <= item[1] < hi:
                    item[4]._result = Completion(kind="rejected",
                                                 key=item[2], found=False)
                    rejected += 1
                else:
                    keep.append(item)
            if keep:
                self._queues[rs_key] = keep
            else:
                self._queued_slots.discard(rs_key)
        # sweep staged-but-uninjected batch items on the range
        for bid, b in list(self._bat.items()):
            n = b["opc"].shape[0]
            idx = np.arange(n)
            rej = (idx >= b["cursor"]) & (b["slots"] >= lo) & (b["slots"] < hi)
            if rej.any():
                bf: BatchFutures = b["bf"]
                bf.code[b["gix"][rej]] = C_REJECTED
                bf.found[b["gix"][rej]] = False
                rejected += int(rej.sum())
                keep = ~rej
                for f in ("opc", "slots", "uval", "gix"):
                    b[f] = b[f][keep]
                if b["cursor"] >= b["opc"].shape[0] and bf.all_done():
                    del self._bat[bid]
        self.rejected_ops += rejected
        return rejected

    def release_slots(self, lo: int, hi: int) -> None:
        """Clear a fence (migration abort path — after a flip the source's
        fence stays: the keys live elsewhere now)."""
        self._fence_mask[lo:hi] = False

    def range_inflight(self, lo: int, hi: int) -> int:
        """Client ops currently in flight whose dense slot is in
        ``[lo, hi)`` — the drain-progress poll of a range migration."""
        active = self._kindarr != t.OP_NOP
        in_range = (self._key[:, :, 0] >= lo) & (self._key[:, :, 0] < hi)
        return int(np.count_nonzero(active & in_range))

    def salvage_slots(self, lo: int, hi: int) -> int:
        """Forced cutover (round-10): client ops on ``[lo, hi)`` that did
        NOT drain are salvaged, never silently dropped — the recorder folds
        still-in-flight updates as ``maybe_w`` (their broadcast may yet
        commit via replay; the checker may — but need not — linearize
        them), their futures resolve loudly as kind='lost', and their
        session/replay slots lose their volatile state exactly like a
        crash (chaos.recovery.wipe_volatile) so the range's coordination
        dies with the migration.  Returns the number of ops salvaged."""
        from hermes_tpu.chaos import recovery as recovery_lib

        rt = self.rt
        rt.flush_pipeline()  # land every already-produced completion first
        key = self._key[:, :, 0]
        mask = (self._kindarr != t.OP_NOP) & (key >= lo) & (key < hi)
        if rt.recorder is not None and mask.any():
            rt.recorder.fold_pending(rt._sess_view(), mask=mask)
        # replay slots re-broadcasting range keys die with the cutover: a
        # post-flip replay commit on the source would change rows the
        # destination already copied
        rp_key = np.asarray(jax.device_get(rt.fs.replay.key))
        rp_active = np.asarray(jax.device_get(rt.fs.replay.active))
        replay_mask = rp_active & (rp_key >= lo) & (rp_key < hi)
        salvaged = 0
        if mask.any() or replay_mask.any():
            recovery_lib.wipe_volatile(rt, mask, replay_mask)
        if mask.any():
            for r, s in np.argwhere(mask):
                r, s = int(r), int(s)
                if (r, s) in self._inflight:
                    _kind, fut, ck, _v, _n = self._inflight.pop((r, s))
                    fut._result = Completion(kind="lost", key=ck, found=False)
                    salvaged += 1
                elif self._slot_bid[r, s] >= 0:
                    bid = int(self._slot_bid[r, s])
                    b = self._bat.get(bid)
                    if b is not None:
                        bf: BatchFutures = b["bf"]
                        gi = int(self._slot_bix[r, s])
                        bf.code[gi] = C_LOST
                        bf.found[gi] = False
                        if b["cursor"] >= b["opc"].shape[0] and bf.all_done():
                            del self._bat[bid]
                    self._slot_bid[r, s] = -1
                    salvaged += 1
            rows, cols = np.nonzero(mask)
            self._op[rows, cols, 0] = t.OP_NOP
            self._kindarr[rows, cols] = t.OP_NOP
            self._slot_inject[rows, cols] = -1
            self._dirty = True
            # freed slots with queued per-op traffic become injectable
            # again (the same re-ready _on_replica_crash does): without
            # this, an op queued BEHIND a salvaged one would strand —
            # _ready is only refreshed on the empty->nonempty enqueue
            # transition and at completion of the op it waited behind
            for rs_key in self._queued_slots:
                if mask[rs_key]:
                    self._ready.add(rs_key)
        return salvaged

    def _replica_busy(self, replica: int) -> bool:
        return (any(rs[0] == replica for rs in self._inflight)
                or bool((self._slot_bid[replica] >= 0).any()))

    def shrink(self, replica: int, drain_steps: int = 2000) -> None:
        """Live resize OUT under traffic: retire ``replica`` (no new
        injections; its queued ops reject loudly), drain its in-flight
        client ops to normal completion — zero checker impact — then
        fence + remove it from quorums (FastRuntime.shrink).  A replica
        that cannot drain (its quorum is gone) raises rather than
        silently wedging; crash-restart it instead."""
        if not (int(self.rt.live[0]) >> replica) & 1:
            # validate BEFORE mutating client state: retiring a non-live
            # replica would reject its traffic forever while the runtime
            # (rejoined by heal/crash-restart, which never touch the KVS
            # retirement set) says it is serving
            raise ValueError(f"replica {replica} is not live")
        self._retired.add(replica)
        # reject queued traffic targeted at the retiring replica
        for rs_key in list(self._queued_slots):
            if rs_key[0] != replica:
                continue
            q = self._queues[rs_key]
            while q:
                _k, _sl, ck, _v, fut, _n = q.popleft()
                fut._result = Completion(kind="rejected", key=ck, found=False)
                self.rejected_ops += 1
            self._queued_slots.discard(rs_key)
        for _ in range(drain_steps):
            if not self._replica_busy(replica):
                break
            self.step()
        else:
            self._retired.discard(replica)
            raise RuntimeError(
                f"shrink: replica {replica} did not drain its in-flight "
                f"ops in {drain_steps} rounds (quorum gone?); use "
                "chaos.restart_replica for a non-cooperative removal")
        self.flush()
        self.rt.shrink(replica)

    def grow(self, replica: int, from_replica: Optional[int] = None) -> None:
        """Live resize IN: value-sync via the join state-transfer path,
        re-admit into quorums, and resume accepting client ops."""
        self.rt.grow(replica, from_replica)
        self._retired.discard(replica)
        # slots freed while retired may hold queued traffic again
        for rs_key in self._queued_slots:
            if rs_key[0] == replica and rs_key not in self._inflight:
                self._ready.add(rs_key)

    # -- crash support (chaos.recovery.restart_replica) ----------------------

    def _on_replica_crash(self, replica: int) -> int:
        """Client-side fallout of a full host-crash of ``replica``: its
        in-flight futures resolve loudly as kind='lost' (batch slots get
        C_LOST) — the server died holding them; whether the write took
        effect is decided by replay, and the history records it as a
        maybe_w.  Queued-but-uninjected traffic survives (it lives in the
        client library) and re-injects after the rejoin.  Returns the
        number of client ops lost."""
        lost = 0
        for rs_key in [k for k in self._inflight if k[0] == replica]:
            _kind, fut, client_key, _v, _n = self._inflight.pop(rs_key)
            fut._result = Completion(kind="lost", key=client_key, found=False)
            lost += 1
        for s in np.nonzero(self._slot_bid[replica] >= 0)[0]:
            bid = int(self._slot_bid[replica, s])
            b = self._bat.get(bid)
            if b is not None:
                bf: BatchFutures = b["bf"]
                gi = int(self._slot_bix[replica, s])
                bf.code[gi] = C_LOST
                bf.found[gi] = False
                if b["cursor"] >= b["opc"].shape[0] and bf.all_done():
                    del self._bat[bid]
            lost += 1
        self._slot_bid[replica] = -1
        self._op[replica] = t.OP_NOP
        self._kindarr[replica] = t.OP_NOP
        self._slot_inject[replica] = -1
        self._dirty = True
        for rs_key in [k for k in self._retry_next if k[0] == replica]:
            self._retry_next.pop(rs_key, None)
            self._retry_k.pop(rs_key, None)
        for rs_key in self._queued_slots:
            if rs_key[0] == replica:
                self._ready.add(rs_key)
        return lost

    # -- membership / failure passthrough ------------------------------------

    def freeze(self, replica: int) -> None:
        self.rt.freeze(replica)

    def remove(self, replica: int) -> None:
        self.rt.remove(replica)

    def join(self, replica: int, from_replica: int) -> None:
        self.rt.join(replica, from_replica)

    def counters(self) -> dict:
        return self.rt.counters()


def drive_mix(kvs: KVS, op_keys, is_get, value_of, max_steps: int = 50_000):
    """Drive a get/put client mix through the batched public API
    (KVS.submit_batch — array-in, futures-out) — the shared drive loop of
    scripts/kvs_scale.py and acceptance.run_sparse_variant.  ``value_of(i)``
    supplies the payload for op i.  Returns (batch_futures, drained,
    enqueue_seconds, drive_seconds)."""
    import time

    is_get = np.asarray(is_get, bool)
    n = len(op_keys)
    t0 = time.perf_counter()
    kinds = np.where(is_get, KVS.GET, KVS.PUT).astype(np.int32)
    u = kvs.cfg.value_words - 2
    values = np.zeros((n, u), np.int32)
    for i in np.nonzero(~is_get)[0]:
        v = np.asarray(value_of(int(i)), np.int32)
        values[i, : v.shape[0]] = v
    bf = kvs.submit_batch(kinds, np.asarray(op_keys), values)
    enqueue_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    drained = kvs.run_batch(bf, max_steps=max_steps)
    return bf, drained, enqueue_s, time.perf_counter() - t0
