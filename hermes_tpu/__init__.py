"""hermes_tpu — a TPU-native implementation of the Hermes replication protocol.

Hermes (ASPLOS'20) is a broadcast-invalidation, linearizable, fault-tolerant
replication protocol for in-memory key-value stores.  This package rebuilds the
capabilities of the reference repo ``A-Kokolis/Hermes`` from scratch with an
idiomatic JAX/XLA/Pallas design (see ``SURVEY.md`` for the full blueprint and
its §0 integrity note: the reference mount was empty when this was written, so
behavioral citations point at ``BASELINE.json`` / the public protocol paper
rather than reference file:line).

Architecture (SURVEY.md §7): instead of the reference's per-thread C worker
loops, the protocol runs as a bulk-synchronous step — all per-key protocol
logic is data-parallel over a struct-of-arrays key-state table, and the
INV/ACK/VAL message batches move between replicas as XLA collectives
(`all_gather` / `all_to_all`) over an ICI mesh, one TPU chip = one Hermes
replica (BASELINE.json:5, ``transport=tpu_ici``).
"""

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import types

__version__ = "0.1.0"

__all__ = ["HermesConfig", "types", "__version__"]
