"""hermes_tpu — a TPU-native implementation of the Hermes replication protocol.

Hermes (ASPLOS'20) is a broadcast-invalidation, linearizable, fault-tolerant
replication protocol for in-memory key-value stores.  This package rebuilds the
capabilities of the reference repo ``A-Kokolis/Hermes`` from scratch with an
idiomatic JAX/XLA/Pallas design (see ``SURVEY.md`` for the full blueprint and
its §0 integrity note: the reference mount was empty when this was written, so
behavioral citations point at ``BASELINE.json`` / the public protocol paper
rather than reference file:line).

Architecture (SURVEY.md §7): instead of the reference's per-thread C worker
loops, the protocol runs as a bulk-synchronous step — all per-key protocol
logic is data-parallel over a struct-of-arrays key-state table, and the
INV/ACK/VAL message batches move between replicas as XLA collectives
(`all_gather` / `all_to_all`) over an ICI mesh, one TPU chip = one Hermes
replica (BASELINE.json:5, ``transport=tpu_ici``).
"""

from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig
from hermes_tpu.core import types

__version__ = "0.2.0"

__all__ = ["HermesConfig", "WorkloadConfig", "FleetConfig", "types", "KVS",
           "MultiGetResult", "KeyIndex", "RangeRouter", "Fleet",
           "FleetRouter", "FastRuntime", "Runtime", "Frontend",
           "ServingConfig", "__version__"]


def __getattr__(name):
    # Lazy top-level exports: `hermes_tpu.KVS` etc. without importing jax
    # (and the runtimes behind it) at package import time — config-only
    # consumers (tooling, tests collecting) stay light.  Resolved names are
    # cached in module globals, so __getattr__ runs once per name.
    if name == "KVS":
        from hermes_tpu.kvs import KVS as obj
    elif name == "MultiGetResult":
        from hermes_tpu.kvs import MultiGetResult as obj
    elif name == "KeyIndex":
        from hermes_tpu.keyindex import KeyIndex as obj
    elif name == "RangeRouter":
        from hermes_tpu.keyindex import RangeRouter as obj
    elif name == "Fleet":
        from hermes_tpu.fleet import Fleet as obj
    elif name == "FleetRouter":
        from hermes_tpu.fleet.router import FleetRouter as obj
    elif name in ("FastRuntime", "Runtime"):
        from hermes_tpu import runtime

        obj = getattr(runtime, name)
    elif name in ("Frontend", "ServingConfig"):
        from hermes_tpu.serving import server as _serving_server

        obj = getattr(_serving_server, name)
    else:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    globals()[name] = obj
    return obj


def __dir__():
    return sorted(set(globals()) | set(__all__))
