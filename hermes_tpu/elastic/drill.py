"""Elastic drills (round-10): rolling restarts, rolling resizes, and the
migration drill — scripted production exercises of the chaos/recovery and
elastic machinery, with the linearizability checker gating every step and
the throughput DIP measured, not guessed.

The dip number: a drill is only "live" if traffic keeps flowing, so every
drill samples cumulative committed writes at a fixed round cadence
(``RateSampler``) and reports the WORST window's rate against a clean
baseline — ``dip_pct`` is the bounded-degradation number CI gates on
(scripts/check_elastic.py → ELASTIC_SOAK.json; ``bench.py --chaos`` →
CHAOS_BENCH.json).
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np


class RateSampler:
    """Cumulative committed-write samples at a fixed round cadence.

    Install as a ``ChaosRunner`` ``on_step`` (or call ``note(step)`` from
    any drive loop); each boundary does ONE counters() poll — the standard
    Meta fetch every serving loop already pays at its own cadence."""

    def __init__(self, rt, window: int):
        if window < 1:
            raise ValueError("window must be >= 1 round")
        self.rt = rt
        self.window = window
        # (round, wall_s, cumulative committed writes)
        self.samples: List[Tuple[int, float, int]] = []
        self._mark()

    def _mark(self) -> None:
        c = self.rt.counters()
        self.samples.append((self.rt.step_idx, time.perf_counter(),
                             int(c["n_write"] + c["n_rmw"])))

    def note(self, step: int) -> None:
        if (step + 1) % self.window == 0:
            self._mark()

    def finish(self) -> None:
        if self.samples and self.rt.step_idx > self.samples[-1][0]:
            self._mark()

    def windows(self) -> List[dict]:
        out = []
        for (r0, t0, w0), (r1, t1, w1) in zip(self.samples, self.samples[1:]):
            if r1 == r0:
                continue
            out.append(dict(
                rounds=(r0, r1),
                writes=w1 - w0,
                wall_s=round(t1 - t0, 4),
                writes_per_sec=round((w1 - w0) / max(1e-9, t1 - t0), 1),
            ))
        return out

    def report(self, clean_rate: Optional[float] = None) -> dict:
        """Worst-window rate + ``dip_pct`` against ``clean_rate`` (falls
        back to the drill's own BEST window when no clean cell ran —
        honest about it in the record)."""
        wins = self.windows()
        if not wins:
            return dict(windows=0, dip_pct=None)
        worst = min(wins, key=lambda w: w["writes_per_sec"])
        baseline = clean_rate
        src = "clean_cell"
        if baseline is None:
            baseline = max(w["writes_per_sec"] for w in wins)
            src = "best_window"
        dip = 100.0 * (1.0 - worst["writes_per_sec"] / max(1e-9, baseline))
        return dict(
            windows=len(wins),
            window_rounds=self.window,
            worst_window=worst,
            clean_rate=round(float(baseline), 1),
            clean_rate_source=src,
            dip_pct=round(max(0.0, dip), 1),
        )


def _rt_of(target):
    return target.rt if (hasattr(target, "rt")
                         and hasattr(target, "index")) else target


def run_rolling_restart(target, start: int = 4, spacing: int = 12,
                        steps: Optional[int] = None,
                        window: Optional[int] = None,
                        check: bool = False, heal: bool = True,
                        clean_rate: Optional[float] = None,
                        min_healthy: int = 2, warmup: int = 2,
                        snapshot_path: Optional[str] = None) -> dict:
    """Crash-restart EVERY replica in sequence under load (the rolling-
    restart drill): replica i is crash-restarted at round ``start + i *
    spacing`` via the chaos subsystem (full host-crash semantics — lost
    in-flight ops fold as maybe_w, fence/remove, snapshot-or-peer restore,
    rejoin with state transfer), while the workload keeps issuing.
    Returns the ChaosRunner result extended with ``restarts`` (must equal
    n_replicas for a completed drill) and the measured ``dip`` report."""
    from hermes_tpu import chaos

    rt = _rt_of(target)
    cfg = rt.cfg
    sched = chaos.Schedule.rolling_restart(cfg, start=start, spacing=spacing)
    if steps is None:
        steps = start + spacing * cfg.n_replicas + spacing
    # warm the compiled round before the first sampled window: the first
    # dispatch's compile wall would otherwise masquerade as the drill dip
    step = target.step if hasattr(target, "step") else rt.step_once
    for _ in range(warmup):
        step()
    sampler = RateSampler(rt, window or spacing)
    runner = chaos.ChaosRunner(
        target, sched, spec=chaos.ChaosSpec(min_healthy=min_healthy),
        snapshot_path=snapshot_path, on_step=sampler.note)
    res = runner.run(steps, heal=heal, check=check)
    sampler.finish()
    res["restarts"] = sum(1 for e in runner.log
                          if e["kind"] == "crash_restart")
    res["dip"] = sampler.report(clean_rate)
    return res


def submit_drill_mix(kvs, n_ops: int, seed: int = 0,
                     read_frac: float = 0.5, lo: int = 0,
                     hi: Optional[int] = None):
    """Enqueue a seeded get/put mix over dense keys ``[lo, hi)`` through
    the batched client API — the standing load every drill runs under.
    Returns the BatchFutures (drive it with ``kvs.step()``; drills step
    the KVS themselves)."""
    from hermes_tpu.kvs import KVS

    cfg = kvs.cfg
    hi = cfg.n_keys if hi is None else hi
    rng = np.random.default_rng(seed)
    keys = rng.integers(lo, hi, size=n_ops).astype(np.int64)
    kinds = np.where(rng.random(n_ops) < read_frac,
                     KVS.GET, KVS.PUT).astype(np.int32)
    u = cfg.value_words - 2
    values = rng.integers(0, 1 << 20, size=(n_ops, u)).astype(np.int32)
    return kvs.submit_batch(kinds, keys, values)


def migration_drill(cfg, backend: str = "batched", mesh=None,
                    record=True, lo: Optional[int] = None,
                    hi: Optional[int] = None, load_ops: int = 256,
                    seed: int = 0, drain_steps: int = 2000,
                    check: bool = True) -> dict:
    """The composed live-migration drill (shared by ``cli --drill
    migrate`` and scripts/check_elastic.py): two KVS groups + a
    RangeRouter, a standing client mix on the source, migrate the middle
    range under that load, then verify — post-flip reads on the
    destination observe the migrated values, mid-drain ops landed as
    rejected (counted, never dropped), boundary routing is exact at
    ``lo``/``hi-1``, and BOTH groups' histories pass the checker."""
    from hermes_tpu.keyindex import RangeRouter
    from hermes_tpu.kvs import KVS

    from hermes_tpu.elastic.migrate import migrate_range

    if lo is None:
        lo = cfg.n_keys // 3
    if hi is None:
        hi = 2 * cfg.n_keys // 3
    src = KVS(cfg, backend=backend, mesh=mesh, record=record)
    dst = KVS(cfg, backend=backend, mesh=mesh, record=record)
    router = RangeRouter(cfg.n_keys, default_group=0)

    # seed the range with known values, then keep a mixed load running
    seed_bf = submit_drill_mix(src, load_ops, seed=seed, read_frac=0.0)
    if not src.run_batch(seed_bf):
        raise RuntimeError("migration drill: seed load did not drain")
    live_bf = submit_drill_mix(src, load_ops, seed=seed + 1)
    for _ in range(4):
        src.step()

    res = migrate_range(src, dst, lo, hi, router=router, dst_group=1,
                        drain_steps=drain_steps)
    # the standing load keeps issuing around the moved range
    src.run_batch(live_bf)
    src.flush()

    codes = np.asarray(live_bf.code)
    from hermes_tpu import kvs as kvs_lib

    res["live_rejected"] = int((codes == kvs_lib.C_REJECTED).sum())
    res["live_lost"] = int((codes == kvs_lib.C_LOST).sum())
    res["live_done"] = int(live_bf.done_count())
    if not live_bf.all_done():
        raise RuntimeError("migration drill: standing load stranded "
                           f"{len(live_bf) - live_bf.done_count()} op(s)")

    # boundary exactness + post-flip service
    assert int(router.owner(lo)) == 1 and int(router.owner(hi - 1)) == 1
    if lo > 0:
        assert int(router.owner(lo - 1)) == 0
    if hi < cfg.n_keys:
        assert int(router.owner(hi)) == 0
    probe = [lo, (lo + hi) // 2, hi - 1]
    futs = [dst.get(0, i % cfg.n_sessions, k) for i, k in enumerate(probe)]
    if not dst.run_until(futs):
        raise RuntimeError("migration drill: destination reads stalled")
    res["dst_reads"] = len(probe)
    rej = src.get(0, 0, lo)
    assert rej.done() and rej.result().kind == "rejected"

    if check and record:
        for name, g in (("src", src), ("dst", dst)):
            v = g.rt.check()
            res[f"{name}_checked_ok"] = bool(v.ok)
            if not v.ok:
                res[f"{name}_check_failures"] = [
                    getattr(f, "reason", str(f))[:200]
                    for f in (v.failures + v.undecided)[:3]]
    return res


def rolling_resize(kvs, hold_steps: int = 8, window: Optional[int] = None,
                   check: bool = False,
                   clean_rate: Optional[float] = None) -> dict:
    """Live resize drill: every replica is shrunk out of the group (fence
    + drain its client ops + remove from quorums) and grown back (value
    sync via join state transfer) in sequence, while the other replicas'
    sessions keep issuing.  Zero checker impact by construction — shrink
    drains to normal completion; nothing is salvaged or lost."""
    from hermes_tpu.kvs import KVS

    if not isinstance(kvs, KVS):
        raise TypeError("rolling_resize drives the client layer (kvs.KVS)")
    rt = kvs.rt
    for _ in range(2):  # compile outside the first sampled window
        kvs.step()
    sampler = RateSampler(rt, window or hold_steps)
    cycles = []
    for r in range(rt.cfg.n_replicas):
        t0 = rt.step_idx
        kvs.shrink(r)
        for s in range(hold_steps):
            kvs.step()
            sampler.note(rt.step_idx - 1)
        kvs.grow(r)
        for s in range(hold_steps):
            kvs.step()
            sampler.note(rt.step_idx - 1)
        cycles.append(dict(replica=r, rounds=rt.step_idx - t0))
    sampler.finish()
    res: dict = dict(cycles=cycles, resizes=len(cycles),
                     rejected_ops=kvs.rejected_ops,
                     dip=sampler.report(clean_rate))
    if check:
        v = rt.check()
        res["checked_ok"] = bool(v.ok)
        res["check_failures"] = [
            getattr(f, "reason", str(f))[:200]
            for f in (v.failures + v.undecided)[:3]]
    return res
