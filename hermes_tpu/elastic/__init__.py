"""hermes_tpu.elastic — elastic operations as a first-class subsystem
(round-10; ROADMAP item 5, the integration layer pod-scale key-sharded
groups will drive through).

Three legs, each composed from machinery earlier rounds built and each
gated by the linearizability checker under pipelined client load:

  1. **Live group resize** — ``FastRuntime.grow/shrink`` (+ the KVS
     facade's client-aware versions): fence + remove with the pipeline
     flushed and queued client traffic rejected loudly, value sync via
     the join state-transfer path, administrative removals distinguished
     from detector ejections on the membership log
     (``MembershipService.note_shrink``).
  2. **Live key-range migration** — ``migrate_range``: fence → drain →
     snapshot (scope-tagged range archive, snapshot.save_range) →
     transfer (uid re-mint into the migration namespace, destination
     history seeded via ``recorder.record_migration``) → atomic routing
     flip (keyindex.RangeRouter) → release, with ``maybe_w`` salvage for
     ops caught mid-flip so nothing is ever silently dropped.
  3. **Drills** — ``run_rolling_restart`` (every replica crash-restarted
     in sequence under load) and ``rolling_resize`` (every replica
     shrunk/grown in sequence), with the worst-window throughput dip
     measured (``RateSampler``) and recorded as ``dip_pct``
     (ELASTIC_SOAK.json via scripts/check_elastic.py; CHAOS_BENCH.json
     via ``bench.py --chaos``).
"""

from hermes_tpu.elastic.drill import (
    RateSampler,
    migration_drill,
    rolling_resize,
    run_rolling_restart,
    submit_drill_mix,
)
from hermes_tpu.elastic.migrate import migrate_range

__all__ = [
    "RateSampler", "migrate_range", "migration_drill", "rolling_resize",
    "run_rolling_restart", "submit_drill_mix",
]
