"""Live key-range migration (round-10): move a dense key-slot range
between replica groups under traffic, with the checker green throughout.

Hermes coordinates per key (PAPER.md), so a key range can change owner
without stopping the world — the prerequisite for pod-scale key-sharded
groups (ROADMAP item 2).  ``migrate_range`` composes machinery earlier
rounds built into the drill:

  fence    — the router marks the range draining and the source KVS
             rejects new ops on it loudly (kind='rejected'; never entered
             the store, so zero history impact);
  drain    — the source steps until no client op on the range is in
             flight (the round-8 pipeline flush semantics: every
             already-produced completion lands first).  Ops that cannot
             drain are SALVAGED, never dropped: recorder folds them as
             ``maybe_w`` (their broadcast may yet commit via replay; the
             checker may — but need not — linearize them), futures
             resolve kind='lost', session/replay slots are wiped
             (chaos.recovery.wipe_volatile);
  snapshot — just the range's table rows, normalized to canonical
             committed form, into a scope-tagged checksummed archive
             (snapshot.save_range; ``load`` refuses to treat it as
             crash-recovery state);
  transfer — rows are re-minted with migration write uids
             (lo=dest_slot, hi=-(2+dst_step)) so the destination's
             checker sees the migration as ONE synthetic committed write
             per key (recorder.record_migration), linearized strictly
             before any post-flip op — uid spaces of the two groups never
             alias;
  restore  — rows land in the destination table (every replica copy),
             the destination's version re-anchoring (``_ver_base``)
             adopts the source's cumulative deltas so recorded versions
             stay globally monotone across the move;
  flip     — the router moves ownership and clears the drain in ONE host
             update (no lookup can observe the half-flipped state); the
             source's fence stays forever — the keys live elsewhere now;
  release  — the destination serves the range (it was never fenced
             there).

Sparse-key mode re-maps through the key indexes: each migrated slot's
client key allocates a fresh dense slot in the destination's KeyIndex, so
the two groups' slot spaces stay independent.

Failure discipline: everything refusable is refused BEFORE the fence
(destination capacity/freshness, mode mismatch), so a rejected migration
has zero side effects; an error after fencing but before the flip takes
the ABORT path — the fence and router drain release and the source keeps
serving the range (already-salvaged ops stay honestly lost).  Only a
completed flip leaves the source fenced for good.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional

import jax
import numpy as np

from hermes_tpu import snapshot as snapshot_lib
from hermes_tpu.core import types as t


def _kvs_of(target):
    if hasattr(target, "rt") and hasattr(target, "index"):
        return target, target.rt
    raise TypeError(
        "migrate_range drives the client layer (kvs.KVS): fencing and "
        "salvage are client-visible contracts, not runtime internals")


def _donor_base(rt) -> int:
    """Flat-row offset of the donor replica's table copy (0 when the
    authoritative table is shared — the batched engine)."""
    K = rt.cfg.n_keys
    if rt.fs.table.vpts.shape[0] == K:
        return 0
    live = int(rt.live[0])
    cands = [r for r in range(rt.cfg.n_replicas)
             if (live >> r) & 1 and not rt.frozen[r]]
    if not cands:
        raise RuntimeError("migration needs a live unfrozen source replica")
    return cands[0] * K


def _normalize_range(rt, lo: int, hi: int) -> None:
    """Rewrite the range's rows to canonical committed form on every
    replica copy: state VALID, row pts mirroring vpts, one uniform sst
    step.  After a clean drain this is semantically a no-op (the rows are
    already converged VALID); after a forced salvage it DECIDES the
    salvaged ``maybe_w`` ops as applied-at-cutover — one of the outcomes
    the checker allows them — and re-converges replica copies whose sst
    bytes differ (coordinator WRITE vs peer INVALID)."""
    from hermes_tpu.core import faststep as fst

    n = hi - lo
    base = _donor_base(rt)
    vpts = np.asarray(jax.device_get(
        jax.lax.dynamic_slice_in_dim(rt.fs.table.vpts, base + lo, n)))
    bank = np.asarray(jax.device_get(
        jax.lax.dynamic_slice_in_dim(rt.fs.table.bank, base + lo, n)))
    rows32 = snapshot_lib._rows_to_i32(bank).copy()
    rows32[:, fst.BANK_PTS] = vpts
    rows32[:, fst.BANK_SST] = (rt.step_idx << fst.SST_STEP_SHIFT) | t.VALID
    snapshot_lib.write_rows(rt, np.arange(lo, hi), vpts, rows32)


def migrate_range(src, dst, lo: int, hi: int, router=None,
                  dst_group: int = 1, path: Optional[str] = None,
                  drain_steps: int = 2000, force: bool = False,
                  dest_slots=None) -> dict:
    """Move dense slots ``[lo, hi)`` from the ``src`` KVS group to ``dst``
    (module docstring: fence → drain → snapshot → transfer → flip →
    release).  ``router`` (keyindex.RangeRouter, optional) carries the
    fleet-level routing flip; ``path`` keeps the transfer archive
    (default: a temp file, removed after restore).  ``force`` salvages
    ops that fail to drain within ``drain_steps`` instead of raising.
    ``dest_slots`` (dense mode only) places the migrated rows on chosen
    destination slots instead of mirroring the source slot ids — the
    round-13 fleet composes groups whose slot spaces are BOTH full of
    their own keys, so the fleet allocates the destination's spare slots
    and threads them through here (sparse mode allocates through the
    destination KeyIndex instead and refuses the argument).
    Returns a summary dict (also traced as ``migrate_out``/``migrate_in``
    obs events on the two runtimes)."""
    src_kvs, src_rt = _kvs_of(src)
    dst_kvs, dst_rt = _kvs_of(dst)
    if src_rt.cfg.value_words != dst_rt.cfg.value_words:
        raise ValueError("source and destination value_words differ; rows "
                         "are not portable across value widths")
    if (src_kvs.heap is None) != (dst_kvs.heap is None):
        raise ValueError(
            "source and destination must agree on value-heap mode "
            "(cfg.max_value_bytes): a packed heap ref is meaningless in a "
            "fixed-word store and vice versa")
    if src_kvs.heap is not None and (
            src_rt.cfg.max_value_bytes > dst_rt.cfg.max_value_bytes):
        raise ValueError(
            f"destination max_value_bytes={dst_rt.cfg.max_value_bytes} "
            f"cannot hold the source's {src_rt.cfg.max_value_bytes}-byte "
            "extents")
    if (src_kvs.index is None) != (dst_kvs.index is None):
        raise ValueError("source and destination must agree on sparse-key "
                         "mode (the client-key remap needs both indexes)")
    if not (0 <= lo < hi <= src_rt.cfg.n_keys):
        raise ValueError(f"range [{lo}, {hi}) outside "
                         f"[0, {src_rt.cfg.n_keys})")
    if dest_slots is not None:
        if src_kvs.index is not None:
            raise ValueError(
                "dest_slots is a dense-mode placement; sparse mode "
                "allocates destination slots through the KeyIndex")
        dest_slots = np.asarray(dest_slots, np.int64)
        if dest_slots.shape != (hi - lo,):
            raise ValueError(
                f"dest_slots must place every slot of [{lo}, {hi}) "
                f"(want shape ({hi - lo},), got {dest_slots.shape})")
        if np.unique(dest_slots).size != dest_slots.size:
            raise ValueError("dest_slots must be distinct")
        if dest_slots.size and not (
                (dest_slots >= 0) & (dest_slots < dst_rt.cfg.n_keys)).all():
            raise ValueError(
                f"dest_slots outside the destination's slot space "
                f"[0, {dst_rt.cfg.n_keys})")

    # -- validate the DESTINATION before any destructive step: a migration
    # that can be refused must be refused BEFORE the fence rejects client
    # ops and the salvage loses in-flight ones.  A slot with committed
    # writes already has history the preload would contradict (a key must
    # live in exactly one group); nothing steps either group between here
    # and the restore, so the check cannot go stale.
    from hermes_tpu.core import faststep as fst

    dbase = _donor_base(dst_rt)
    fresh_err = ("destination slots are not fresh (committed writes "
                 "present); a key must live in exactly one group")
    if src_kvs.index is None:
        if dest_slots is None and hi > dst_rt.cfg.n_keys:
            raise ValueError(
                f"dense migration needs destination n_keys >= {hi} "
                "(or caller-chosen dest_slots)")
        if dest_slots is None:
            dst_vpts = np.asarray(jax.device_get(
                jax.lax.dynamic_slice_in_dim(
                    dst_rt.fs.table.vpts, dbase + lo, hi - lo)))
        else:
            dst_vpts = np.asarray(jax.device_get(
                dst_rt.fs.table.vpts))[dbase + dest_slots]
        if (dst_vpts != 0).any():
            raise ValueError(fresh_err)
    else:
        if hi > src_kvs.index.n_used:
            raise ValueError(
                f"range [{lo}, {hi}) reaches past the source's allocated "
                f"slot frontier ({src_kvs.index.n_used}); migrate "
                "allocated ranges only")
        # client keys already present in the destination index must sit on
        # never-written slots (keys newly allocated at transfer time are
        # fresh by construction)
        pre_keys = np.array(
            [src_kvs.index.key_of(s) for s in range(lo, hi)], np.uint64)
        got = dst_kvs.index.get_slots(pre_keys, insert=False)
        n_new = int((got < 0).sum())
        if dst_kvs.index.n_used + n_new > dst_rt.cfg.n_keys:
            raise ValueError(
                f"sparse migration needs {n_new} fresh destination slot(s) "
                f"but the destination index holds {dst_kvs.index.n_used} of "
                f"n_keys={dst_rt.cfg.n_keys}; size the destination to the "
                "combined working set")
        present = got[got >= 0].astype(np.int64)
        if present.size:
            dst_vpts = np.asarray(jax.device_get(dst_rt.fs.table.vpts))
            if (dst_vpts[dbase + present] != 0).any():
                raise ValueError(fresh_err)

    summary: dict = dict(lo=lo, hi=hi, rows=hi - lo)
    flipped = False
    tmp_dir = None
    try:
        # -- fence: reject-new on the range ---------------------------------
        src_kvs.drill_phase = "fence"
        if router is not None:
            router.begin_drain(lo, hi)
        summary["rejected_at_fence"] = src_kvs.fence_slots(lo, hi)
        src_rt._trace("migrate_fence", lo=lo, hi=hi)

        # -- drain: flush in-flight range ops to normal completion ----------
        src_kvs.drill_phase = "drain"
        drained = False
        for _ in range(drain_steps):
            if src_kvs.range_inflight(lo, hi) == 0:
                drained = True
                break
            src_kvs.step()
        src_kvs.flush()
        src_rt.flush_pipeline()
        if not drained and src_kvs.range_inflight(lo, hi) and not force:
            raise RuntimeError(
                f"range [{lo}, {hi}) did not drain in {drain_steps} rounds "
                f"({src_kvs.range_inflight(lo, hi)} op(s) still in flight); "
                "pass force=True to salvage them as maybe_w/lost")
        # forced cutover: whatever still holds the range is salvaged —
        # maybe_w history rows + loudly-lost futures + volatile wipe.  In
        # the clean path this also clears orphaned replay slots on the
        # range (a post-flip replay commit would mutate rows the
        # destination already copied).
        summary["salvaged"] = src_kvs.salvage_slots(lo, hi)
        summary["drained"] = drained

        # -- snapshot: canonical rows, scope-tagged archive -----------------
        _normalize_range(src_rt, lo, hi)
        if path is None:
            tmp_dir = tempfile.mkdtemp(prefix="hermes_migrate_")
            path = os.path.join(tmp_dir, f"range_{lo}_{hi}.npz")
        # the FACADE is passed so heap-mode extents ride the archive
        # (snapshot.save_range captures the range's live value bytes
        # beside the rows, under the same checksummed manifest)
        manifest = snapshot_lib.save_range(path, src_kvs, lo, hi)
        summary["archive_step"] = manifest["step"]

        # -- transfer: verify + read back + re-map + re-mint uids -----------
        _m, slots, vpts, rows32, ver_base = snapshot_lib.read_range(path)
        if src_kvs.index is not None:
            # sparse: each migrated client key allocates a fresh dense slot
            # in the destination's index (slot spaces stay independent);
            # pre_keys is the validation pass's key list for these exact
            # slots — nothing stepped either group since
            dest_slots = dst_kvs.index.get_slots(pre_keys).astype(np.int64)
        elif dest_slots is None:
            dest_slots = slots
        # else: caller-placed dense slots (validated up front; slot i of
        # the archive — source slot lo + i — lands on dest_slots[i])
        rows32 = rows32.copy()
        mig_hi = -(2 + dst_rt.step_idx)  # migration uid namespace: hi <= -2
        rows32[:, fst.BANK_VAL] = dest_slots.astype(np.int32)
        rows32[:, fst.BANK_VAL + 1] = np.int32(mig_hi)
        uids = np.stack([dest_slots.astype(np.int32),
                         np.full(dest_slots.size, mig_hi, np.int32)], axis=1)
        if dst_kvs.heap is not None:
            # value heap (round-17): re-append the archived extents into
            # the DESTINATION's log and re-point the rows' ref words —
            # source refs name source granules and mean nothing here.
            # Appends before the flip are safe on the abort path: rows
            # that never become reachable leave dead extents the next
            # destination GC reclaims.
            heap_ext = snapshot_lib.read_range_heap(path)
            if heap_ext is None:
                raise RuntimeError(
                    "heap-mode migration needs a heap section in the "
                    "range archive (source saved without its facade?)")
            from hermes_tpu.heap import HeapFull

            _lens, extents = heap_ext
            newrefs = np.zeros(dest_slots.size, np.int32)
            # newrefs is a GC root WHILE the transfer is still staging: a
            # HeapFull mid-loop compacts the destination, and the refs
            # already appended here must survive it remapped
            with dst_kvs._heap_staging(newrefs):
                for i, ext in enumerate(extents):
                    if ext is not None:
                        try:
                            newrefs[i] = dst_kvs.heap.append(ext)
                        except HeapFull:
                            dst_kvs.heap_gc(reason="migrate")
                            newrefs[i] = dst_kvs.heap.append(ext)
            rows32[:, fst.BANK_VAL + 2] = newrefs
            summary["heap_extents"] = int(sum(
                1 for e in extents if e is not None))

        # -- restore: rows + version re-anchoring + history preload ---------
        snapshot_lib.write_rows(dst_rt, dest_slots, vpts, rows32)
        snapshot_lib.anchor_ver_base(dst_rt, dest_slots, ver_base)
        if dst_rt.recorder is not None:
            vers = (vpts.astype(np.int64) >> fst.PTS_FC_BITS) + ver_base
            fcs = vpts.astype(np.int64) & fst.FC_MASK
            dst_rt.recorder.record_migration(
                dest_slots, uids, vers, fcs, dst_rt.step_idx)

        # -- flip: atomic routing cutover -----------------------------------
        src_kvs.drill_phase = "flip"
        if router is not None:
            router.flip(lo, hi, dst_group)
        flipped = True
        src_rt._trace("migrate_out", lo=lo, hi=hi, rows=hi - lo,
                      salvaged=summary["salvaged"])
        dst_rt._trace("migrate_in", lo=lo, hi=hi, rows=hi - lo,
                      step=dst_rt.step_idx)
    except BaseException:
        # abort path: the keys STAY with the source — un-fence the range
        # and clear the router drain so it is not permanently unavailable.
        # Ops already salvaged are honestly lost (their maybe_w rows
        # stand); rows already restored into the destination are
        # unreachable (routing never flipped) — a retry must target a
        # fresh destination.
        if not flipped:
            src_kvs.release_slots(lo, hi)
            if router is not None:
                router.release(lo, hi)
        raise
    finally:
        src_kvs.drill_phase = None
        if tmp_dir is not None:
            # the transfer archive is a byproduct, not an artifact: remove
            # it on every exit path (a caller-supplied path is kept)
            shutil.rmtree(tmp_dir, ignore_errors=True)
    summary["dest_lo"] = int(dest_slots.min())
    summary["dest_hi"] = int(dest_slots.max()) + 1
    summary["dest_slots"] = dest_slots
    return summary
