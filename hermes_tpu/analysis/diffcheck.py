"""Differential sanitizer + the standalone kernel matrix (ISSUE 8).

The kernel sub-interpreter (analysis/pallas.py) is new code proving
soundness claims about other new code — a wrong transfer rule would
silently BLESS the very kernels the mega-round is about to trust.  This
module is the self-test that catches unsound rules before they do:

  * ``kernel_cells()`` registers every in-tree Pallas kernel at several
    shapes (single-block, multi-block, ragged padding — the grid-revisit
    accumulation path included) with declared abstract input bounds
    (analysis/seeds.py, fed by the same ``core.layouts`` tables the
    kernels build their outputs from);
  * ``analyze_kernel(cell)`` traces the kernel standalone and walks it
    with the full pass set — the kernel analogue of
    ``engines.analyze_program`` (the CI gate runs both, see
    scripts/check_analysis.py);
  * ``diff_check(cell)`` draws concrete inputs uniformly inside the
    declared bounds, runs the kernel for real (``interpret=True`` on
    CPU — the same path the test suite pins against pure jnp), and
    asserts every concrete output element lies inside the abstract
    interval (and possible-ones mask) the interpreter derived.  A rule
    that under-approximates — the unsoundness that would turn the
    analyzer into a rubber stamp — shows up as a concrete escape
    (red-tested in tests/test_pallas_analysis.py with a deliberately
    broken ``add`` rule).

Everything here is CPU-safe and deterministic (seeded generator);
``python -m hermes_tpu.analysis --kernels`` runs it standalone.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from hermes_tpu.analysis import interp as I
from hermes_tpu.analysis import seeds as seeds_lib
from hermes_tpu.analysis.domain import AbsVal
from hermes_tpu.analysis.passes import Finding, default_passes


@dataclasses.dataclass
class KernelCell:
    """One kernel x shape: the traced fn, its arg shapes, and the
    declared abstract input bounds (one AbsVal per positional arg)."""

    name: str
    fn: Callable
    shapes: Tuple
    in_avs: List[AbsVal]
    note: str = ""


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _stats_cell(name: str, R: int, S: int, note: str = "") -> KernelCell:
    import jax.numpy as jnp

    from hermes_tpu.core import kernels

    shapes = (_sds((), jnp.int32),) + tuple(
        _sds((R, S), dt) for dt in (jnp.int32, jnp.int32, jnp.bool_,
                                    jnp.bool_, jnp.bool_))
    return KernelCell(name=name, fn=kernels.stats_block, shapes=shapes,
                      in_avs=seeds_lib.seed_stats_block(), note=note)


def _scan_acc_cell() -> KernelCell:
    """Synthetic sentinel: a fori_loop accumulating into a ref — the
    loop-carried cell pattern the mega-round's per-message apply will
    use.  The sub-interpreter's scan fixpoint must widen the cell, not
    'converge' after one body evaluation (an under-approximation the
    sanitizer caught in review); keeping the pattern in the matrix
    keeps that soundness property red-tested."""
    import jax
    import jax.numpy as jnp

    from jax.experimental import pallas as pl

    M, W = 16, 8

    def _kern(x_ref, o_ref):
        o_ref[:] = jnp.zeros_like(o_ref)

        def body(i, _):
            o_ref[:] = o_ref[:] + x_ref[pl.dslice(i, 1), :]
            return 0

        jax.lax.fori_loop(0, M, body, 0)

    def fn(x):
        return pl.pallas_call(
            _kern,
            in_specs=[pl.BlockSpec((M, W), lambda: (0, 0))],
            out_specs=pl.BlockSpec((1, W), lambda: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, W), jnp.int32),
            interpret=True)(x)

    return KernelCell(name="synthetic/scan-accumulate", fn=fn,
                      shapes=(_sds((M, W), jnp.int32),),
                      in_avs=[seeds_lib.iv(0, 100)],
                      note="loop-carried ref accumulation sentinel")


def _mega_cfg(n_keys: int = 16):
    from hermes_tpu.config import HermesConfig

    return HermesConfig(n_replicas=2, n_keys=n_keys, n_sessions=4,
                        replay_slots=2, ops_per_session=4,
                        arb_mode="sort", mega_round=True)


def _mega_route_cell() -> KernelCell:
    import jax.numpy as jnp

    from hermes_tpu.core import megaround

    cfg = _mega_cfg()
    R, L = cfg.n_replicas, cfg.n_lanes
    shapes = tuple(_sds((R, L), jnp.int32) for _ in range(3))
    return KernelCell(
        name="mega_route/r2l6", fn=lambda si, w, sr:
        megaround.mega_route(cfg, si, w, sr), shapes=shapes,
        in_avs=seeds_lib.seed_mega_route(cfg),
        note="serial permutation route-back + slot region (round-15)")


def _mega_apply_cell() -> KernelCell:
    import jax.numpy as jnp

    from hermes_tpu.core import megaround

    cfg = _mega_cfg()
    N = 2 * cfg.n_lanes + 4  # slots + replay rows shape
    shapes = (_sds((cfg.n_keys,), jnp.int32), _sds((N,), jnp.int32),
              _sds((N,), jnp.int32), _sds((N,), jnp.int32))
    return KernelCell(
        name="mega_apply/k16n16", fn=lambda v, k, p, m:
        megaround.mega_apply(cfg, v, k, p, m), shapes=shapes,
        in_avs=seeds_lib.seed_mega_apply(cfg),
        note="two-phase scatter-max + verdict read-back; keys span the "
             "untrusted 29-bit wire field (drop/clamp exercised)")


def _mega_replay_cell(name: str, n_keys: int, block_bytes: int,
                      note: str) -> KernelCell:
    import jax.numpy as jnp

    from hermes_tpu.core import faststep as fst
    from hermes_tpu.core import megaround

    cfg = _mega_cfg(n_keys=n_keys)
    R, RS, V4 = cfg.n_replicas, cfg.replay_slots, 4 * cfg.value_words
    W4 = 4 * (2 + cfg.value_words)
    K = cfg.n_keys

    def fn(step, act, frozen, bank, vpts, key, pts, acks, val):
        rep = fst.FastReplay(active=act, key=key, pts=pts, val=val,
                             acks=acks)
        return megaround.mega_replay(cfg, step, frozen, vpts, bank, rep,
                                     block_bytes=block_bytes)

    shapes = (_sds((), jnp.int32), _sds((R, RS), jnp.bool_),
              _sds((R,), jnp.bool_), _sds((K, W4), jnp.int8),
              _sds((K,), jnp.int32), _sds((R, RS), jnp.int32),
              _sds((R, RS), jnp.int32), _sds((R, RS), jnp.int32),
              _sds((R, RS, V4), jnp.int8))
    return KernelCell(name=name, fn=fn, shapes=shapes,
                      in_avs=seeds_lib.seed_mega_replay(cfg), note=note)


def kernel_cells() -> List[KernelCell]:
    """The gate's kernel matrix: every in-tree Pallas kernel at the
    shapes that exercise its distinct code paths (the block-size
    formula in kernels.stats_block makes R drive the block cap, so a
    tall R forces the multi-block grid at small S; the mega_replay
    block override forces its multi-block grid + streaming scratch at
    toy shapes), plus the synthetic scan-accumulate sentinel."""
    return [
        _stats_cell("stats_block/r4s512", 4, 512,
                    note="single block, no padding"),
        _stats_cell("stats_block/r1024s600", 1024, 600,
                    note="multi-block grid (revisit accumulation) + "
                         "ragged neutral padding"),
        _stats_cell("stats_block/r512s2000", 512, 2000,
                    note="3-block grid, ragged"),
        _scan_acc_cell(),
        _mega_route_cell(),
        _mega_apply_cell(),
        _mega_replay_cell("mega_replay/k16b1", 16, 1 << 20,
                          note="single table block (round-15)"),
        _mega_replay_cell("mega_replay/k22b3", 22, 8 * 40,
                          note="multi-block RAGGED grid (3 blocks of 8 "
                               "over 22 rows): streaming candidate "
                               "cursor crosses block visits"),
    ]


def cell_by_name(name: str) -> KernelCell:
    for c in kernel_cells():
        if c.name == name:
            return c
    raise KeyError(name)


# --------------------------------------------------------------------------
# abstract side (the kernel analogue of engines.analyze_program)
# --------------------------------------------------------------------------


def trace_cell(cell: KernelCell):
    import jax

    return jax.make_jaxpr(cell.fn)(*cell.shapes)


def analyze_kernel(cell: KernelCell, passes=None) -> dict:
    """Walk one kernel cell with the pass set; report dict shaped like
    ``engines.analyze_program`` (findings engine-stamped
    ``kernel/<name>`` so the baseline currency composes)."""
    ps = passes if passes is not None else default_passes()
    jx = trace_cell(cell)
    ctx = I.Ctx(passes=ps, mesh_axes=None)
    outs = I.eval_jaxpr(jx.jaxpr, list(cell.in_avs), ctx,
                        consts=list(jx.consts))
    findings: List[Finding] = []
    proved = {}
    for p in ps:
        p.finalize(ctx)
        for f in p.results():
            f.engine = f"kernel/{cell.name}"
            findings.append(f)
        proved[p.name] = p.n_proved
    return dict(engine=f"kernel/{cell.name}", n_eqns=ctx.n_eqns,
                proved=proved, findings=findings, outs_abs=outs)


# --------------------------------------------------------------------------
# concrete side (the sanitizer)
# --------------------------------------------------------------------------


def _draw(rng, sds, av: AbsVal):
    """One concrete argument uniformly inside the declared bound."""
    dt = np.dtype(sds.dtype)
    if dt == np.bool_:
        lo, hi = max(0, av.lo), min(1, av.hi)
        return rng.integers(lo, hi + 1, size=sds.shape).astype(np.bool_)
    info = np.iinfo(dt)
    lo = max(av.lo, int(info.min))
    hi = min(av.hi, int(info.max))
    return rng.integers(lo, hi + 1, size=sds.shape, dtype=np.int64).astype(dt)


def diff_check(cell: KernelCell, n_draws: int = 3, seed: int = 0,
               outs_abs: Optional[list] = None) -> dict:
    """Run the kernel on ``n_draws`` seeded concrete inputs drawn from
    the declared bounds; every concrete output element must lie inside
    the abstract interval (and possible-ones mask) the interpreter
    derived.  Returns ``dict(cell, ok, n_draws, violations, seconds)``
    — a violation means an UNSOUND transfer rule, not a kernel bug."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    if outs_abs is None:
        jx = trace_cell(cell)
        ctx = I.Ctx(passes=[])
        outs_abs = I.eval_jaxpr(jx.jaxpr, list(cell.in_avs), ctx,
                                consts=list(jx.consts))
    rng = np.random.default_rng(seed)
    violations = []
    for d in range(n_draws):
        args = [_draw(rng, s, av) for s, av in zip(cell.shapes, cell.in_avs)]
        outs = cell.fn(*[jnp.asarray(a) for a in args])
        import jax

        leaves = jax.tree.leaves(outs)
        for i, (arr, av) in enumerate(zip(leaves, outs_abs)):
            a = np.asarray(arr)
            if a.size == 0:
                continue
            lo, hi = int(a.min()), int(a.max())
            if lo < av.lo or hi > av.hi:
                violations.append(dict(
                    draw=d, out=i, concrete=[lo, hi],
                    abstract=[int(av.lo), int(av.hi)],
                    kind="interval"))
            if (av.ones != -1 and lo >= 0
                    and np.issubdtype(a.dtype, np.integer)):
                bits = int(np.bitwise_or.reduce(
                    a.ravel().astype(np.int64)))
                if bits & ~av.ones:
                    violations.append(dict(
                        draw=d, out=i, kind="ones-mask",
                        concrete=hex(bits), abstract=hex(av.ones)))
    return dict(cell=cell.name, ok=not violations, n_draws=n_draws,
                violations=violations,
                seconds=round(time.perf_counter() - t0, 3))


def run_kernel_matrix(n_draws: int = 3, seed: int = 0,
                      passes_factory=default_passes) -> List[dict]:
    """Analyze + sanitize every registered kernel cell (the CLI's
    ``--kernels`` and the gate's kernel section share this driver).
    Each entry: the analyze_kernel report plus a ``sanitizer`` dict and
    per-cell wall time."""
    out = []
    for cell in kernel_cells():
        t0 = time.perf_counter()
        rep = analyze_kernel(cell, passes=passes_factory())
        rep["sanitizer"] = diff_check(cell, n_draws=n_draws, seed=seed,
                                      outs_abs=rep.pop("outs_abs"))
        rep["seconds"] = round(time.perf_counter() - t0, 3)
        rep["note"] = cell.note
        out.append(rep)
    return out
