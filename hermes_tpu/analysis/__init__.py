"""hermes_tpu.analysis — static jaxpr invariant analyzer (ISSUE 3).

The fast engines re-encode Hermes's model-checked invariants as
hand-packed int32 bitfields; this package proves, at TRACE time, that the
packing is sound under the config's declared bounds — before a round ever
runs, and long before the runtime linearizability checker could notice a
corrupted history.  It walks the closed jaxpr of a protocol round with an
abstract interval/bitwidth interpreter (interp.py, domain.py) seeded from
``HermesConfig`` + the declared field layouts (core/layouts.py), and runs
five passes (passes.py):

  bitpack   every shift/or pack overlap-free and int32-sign-safe
  dtype     no silent 64-bit/float upcasts; converts value-preserving
  scatter   set-scatters carry injectivity evidence; donation aliasable
  refhazard kernel Refs inside pallas_call bodies: stores in-bounds
            against the block shape, no read-before-init, BlockSpec
            index maps inside the operand, grid-revisit accumulators
            declared (audited); unmodeled kernels surface as
            pallas-skipped info findings, never a silent TOP
  sharding  collectives name real mesh axes with agreeing sizes

Since ISSUE 8 the interpreter descends INTO ``pallas_call`` bodies
(analysis/pallas.py) and a differential sanitizer (analysis/diffcheck.py)
cross-checks the abstract cells against seeded concrete interpret-mode
runs of every in-tree kernel — the self-test that keeps the new kernel
rules sound before the Pallas mega-round leans on them.

Findings export in the obs run-log JSONL schema (kind="analysis") and are
CI-gated by scripts/check_analysis.py against ANALYSIS_BASELINE.json —
the same measure-then-gate pattern as the op census.  CLI:

    python -m hermes_tpu.analysis [--engine both] [--split-sort] ...
    python -m hermes_tpu.analysis --kernels   # standalone kernel matrix
    python -m hermes_tpu.analysis --host      # host concurrency lint

Since ISSUE 18 the package also covers the HOST side of the round: a
static lock-discipline lint proving the threaded serving/transport tier
against the declarative guard registry (analysis/hostlint.py over
hermes_tpu/concurrency.py) and a dynamic lock-order sanitizer
(analysis/lockgraph.py: ObsLock + held-before graph), gated serially by
scripts/check_hostlint.py against a committed-empty HOSTLINT_BASELINE.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional

from hermes_tpu.analysis.domain import AbsVal, iv  # noqa: F401
from hermes_tpu.analysis.engines import (  # noqa: F401
    Program, analyze_config, analyze_program, trace_program)
from hermes_tpu.analysis.passes import (  # noqa: F401
    ERROR, INFO, WARN, Finding, RefHazardPass, default_passes)
from hermes_tpu.analysis.diffcheck import (  # noqa: F401
    KernelCell, analyze_kernel, diff_check, kernel_cells,
    run_kernel_matrix)
from hermes_tpu.analysis.hostlint import (  # noqa: F401
    lint_package, lint_source)
from hermes_tpu.analysis.lockgraph import (  # noqa: F401
    LockGraph, ObsLock)

GATING = (ERROR, WARN)  # severities that fail the CI gate


def findings_of(reports: Iterable[dict]) -> List[Finding]:
    out: List[Finding] = []
    for r in reports:
        out.extend(r["findings"])
    return out


def key_counts(findings: Iterable[Finding]) -> dict:
    """Stable multiset of gating finding keys (baseline currency).  The
    key leads with the finding's ``engine`` field — callers analyzing
    several configs stamp it ``"<config>:<engine>"`` first (as the gate
    script does), so a finding grandfathered at one shape cannot silently
    excuse the same site at another."""
    counts: dict = {}
    for f in findings:
        if f.severity not in GATING:
            continue
        counts[f.key] = counts.get(f.key, 0) + f.count
    return counts


def diff_baseline(measured: dict, baseline: dict) -> tuple:
    """(new, stale): keys exceeding their grandfathered count, and
    baseline keys the code no longer produces (stale entries are reported
    but do not fail the gate — ``--update`` prunes them)."""
    new = {k: c - baseline.get(k, 0) for k, c in measured.items()
           if c > baseline.get(k, 0)}
    stale = {k: c for k, c in baseline.items() if measured.get(k, 0) < c}
    return new, stale


def load_baseline(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return {}
    g = doc.get("grandfathered", {})
    return {k: (v["count"] if isinstance(v, dict) else int(v))
            for k, v in g.items()}


def export_findings(path_or_fp, reports: Iterable[dict],
                    extra: Optional[dict] = None) -> None:
    """Write analyzer output as obs run-log JSONL (kind="analysis"):
    one summary record per analyzed program, one record per finding —
    mergeable by scripts/obs_report.py like any other obs stream."""
    from hermes_tpu.obs.metrics import JsonlExporter

    own = isinstance(path_or_fp, str)
    fp = open(path_or_fp, "w") if own else path_or_fp
    try:
        exp = JsonlExporter(fp, stamp=True)
        for r in reports:
            head = dict(record="program", engine=r["engine"],
                        n_eqns=r["n_eqns"], proved=r["proved"],
                        n_findings=len(r["findings"]),
                        by_severity={s: sum(1 for f in r["findings"]
                                            if f.severity == s)
                                     for s in (ERROR, WARN, INFO)})
            if extra:
                head = {**extra, **head}
            exp.write(head, kind="analysis")
            for f in r["findings"]:
                rec = f.record()
                if extra:
                    rec = {**extra, **rec}
                exp.write(rec, kind="analysis")
    finally:
        if own:
            fp.close()
