"""Abstract interval/bitwidth domain for the jaxpr invariant analyzer.

One abstract value summarizes EVERY element of an array (the engines are
data-parallel: per-element precision buys nothing for the invariants we
prove, which are all "no element of this tensor can reach bit N").  An
``AbsVal`` carries two cooperating abstractions:

  * an inclusive integer interval ``[lo, hi]`` (unbounded Python ints
    while an op computes; clamped to the result dtype afterwards, with a
    ``wrapped`` flag when the raw range escapes the dtype — that flag IS
    the overflow theorem's negation);
  * a ``ones`` bitmask of bits that MAY be 1.  Intervals alone cannot
    prove ``(ver << 10) | fc`` overlap-free — ``[0, m << 10]`` contains
    odd values — but the mask knows a shifted value keeps its low bits
    clear.  ``ones == -1`` means "any bit, including sign" (the mask is
    only meaningful for provably non-negative values).

The classic trick pays for itself once: ``a + b`` with disjoint masks IS
``a | b``, so index arithmetic like ``replica * K + key`` keeps exact
bounds.  Floats get the interval only (``ones = -1``); bools are the
interval [0, 1].

Everything here is pure Python over dtypes-as-data — no jax import, so the
domain unit-tests (tests/test_analysis.py) run without tracing anything.
"""

from __future__ import annotations

import dataclasses

import numpy as np

_INT_INFO = {}


def _int_range(dtype) -> tuple:
    key = np.dtype(dtype).name
    if key not in _INT_INFO:
        ii = np.iinfo(np.dtype(dtype))
        _INT_INFO[key] = (int(ii.min), int(ii.max))
    return _INT_INFO[key]


def is_int(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.integer)


def is_bool(dtype) -> bool:
    return np.dtype(dtype) == np.bool_


def is_float(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.floating)


def dtype_bits(dtype) -> int:
    return np.dtype(dtype).itemsize * 8


def mask_for(lo: int, hi: int) -> int:
    """Bits that may be 1 for a value in [lo, hi]: everything below the
    top bit of hi for non-negative ranges, "all bits" (-1) otherwise."""
    if lo < 0:
        return -1
    return (1 << int(hi).bit_length()) - 1


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """Interval + possible-ones mask.  ``ones == -1`` = unconstrained."""

    lo: int
    hi: int
    ones: int = -1

    def __post_init__(self):
        if self.lo > self.hi:
            raise ValueError(f"empty interval [{self.lo}, {self.hi}]")
        if self.lo < 0:
            object.__setattr__(self, "ones", -1)
            return
        # non-negative: tighten the mask against the interval (a constant's
        # mask IS the constant — `1 << 20` has exactly one possible bit,
        # which is what makes `WIN_BIT | rank` provably disjoint)
        m = self.lo if self.lo == self.hi else mask_for(self.lo, self.hi)
        object.__setattr__(self, "ones",
                           m if self.ones == -1 else (self.ones & m))

    @property
    def nonneg(self) -> bool:
        return self.lo >= 0

    @property
    def is_const(self) -> bool:
        return self.lo == self.hi

    def __repr__(self):
        m = "" if self.ones == -1 else f" ones=0x{self.ones:x}"
        return f"[{self.lo}, {self.hi}]{m}"


def iv(lo, hi=None, ones: int = -1) -> AbsVal:
    """Interval constructor (``iv(3)`` = the constant 3)."""
    return AbsVal(int(lo), int(lo if hi is None else hi), ones)


def const(v) -> AbsVal:
    if isinstance(v, (bool, np.bool_)):
        v = int(v)
    if isinstance(v, (float, np.floating)):
        return AbsVal(int(np.floor(v)), int(np.ceil(v))) if np.isfinite(v) \
            else top(np.float32)
    return iv(int(v))


def top(dtype) -> AbsVal:
    """The dtype's full range (the "know nothing" element)."""
    d = np.dtype(dtype)
    if is_bool(d):
        return iv(0, 1)
    if is_int(d):
        lo, hi = _int_range(d)
        return AbsVal(lo, hi, -1 if lo < 0 else hi)
    # floats (and anything exotic): a huge sentinel interval
    return AbsVal(-(1 << 127), 1 << 127)


def is_top(av: AbsVal, dtype) -> bool:
    t = top(dtype)
    return av.lo <= t.lo and av.hi >= t.hi


def join(a: AbsVal, b: AbsVal) -> AbsVal:
    ones = -1 if (a.ones == -1 or b.ones == -1) else (a.ones | b.ones)
    return AbsVal(min(a.lo, b.lo), max(a.hi, b.hi), ones)


def join_all(avs) -> AbsVal:
    avs = list(avs)
    out = avs[0]
    for a in avs[1:]:
        out = join(out, a)
    return out


def clamp(av: AbsVal, dtype) -> tuple:
    """Fit a raw result into its dtype: returns ``(clamped, wrapped)``.
    A range escaping the dtype wraps (two's complement) — the clamped
    value is the dtype TOP and ``wrapped`` is True: the analyzer's passes
    decide whether that wrap is a finding (a pack site) or intended
    modular arithmetic (hash mixing)."""
    d = np.dtype(dtype)
    if is_bool(d):
        # widen, never narrow: an out-of-range abstract bool (e.g. the
        # raw int result of `not`) must become the unknown [0, 1], not a
        # false constant — narrowing here made every `~mask` proof vacuous
        if 0 <= av.lo and av.hi <= 1:
            return av, False
        return AbsVal(0, 1), False
    if not is_int(d):
        return av, False
    lo, hi = _int_range(d)
    if av.lo >= lo and av.hi <= hi:
        return av, False
    return top(d), True


def from_concrete(arr) -> AbsVal:
    """Abstract a concrete constant (jaxpr consts / literals)."""
    a = np.asarray(arr)
    if a.size == 0:
        return iv(0)
    if a.dtype == np.bool_:
        return iv(int(a.min()), int(a.max()))
    if np.issubdtype(a.dtype, np.floating):
        lo, hi = float(a.min()), float(a.max())
        if not (np.isfinite(lo) and np.isfinite(hi)):
            return top(a.dtype)
        return AbsVal(int(np.floor(lo)), int(np.ceil(hi)))
    return iv(int(a.min()), int(a.max()))


# --------------------------------------------------------------------------
# Transfer functions (raw — the interpreter clamps to the result dtype)
# --------------------------------------------------------------------------

MAX_SHIFT = 64  # abstract shift amounts are capped (real shifts are < 32)


def add(a: AbsVal, b: AbsVal) -> AbsVal:
    lo, hi = a.lo + b.lo, a.hi + b.hi
    if a.ones != -1 and b.ones != -1 and (a.ones & b.ones) == 0:
        # disjoint possible-ones: no carry anywhere, add == or
        return AbsVal(lo, hi, a.ones | b.ones)
    return AbsVal(lo, hi)


def sub(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(a.lo - b.hi, a.hi - b.lo)


def neg(a: AbsVal) -> AbsVal:
    return AbsVal(-a.hi, -a.lo)


def mul(a: AbsVal, b: AbsVal) -> AbsVal:
    cs = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
    return AbsVal(min(cs), max(cs))


def max_(a: AbsVal, b: AbsVal) -> AbsVal:
    ones = -1 if (a.ones == -1 or b.ones == -1) else (a.ones | b.ones)
    return AbsVal(max(a.lo, b.lo), max(a.hi, b.hi), ones)


def min_(a: AbsVal, b: AbsVal) -> AbsVal:
    ones = -1 if (a.ones == -1 or b.ones == -1) else (a.ones | b.ones)
    return AbsVal(min(a.lo, b.lo), min(a.hi, b.hi), ones)


def and_(a: AbsVal, b: AbsVal) -> AbsVal:
    if a.nonneg or b.nonneg:
        # AND against a non-negative mask bounds the result by that mask
        # (this is how `pkf & KEY_MASK` restores a proven bound from an
        # unknown wire word); sound because x & m is in [0, m] whenever
        # m >= 0, regardless of x's sign
        masks = [x.ones for x in (a, b) if x.nonneg]
        m = masks[0] if len(masks) == 1 else (a.ones & b.ones)
        return AbsVal(0, m, m)
    # both may be negative: AND can go BELOW both (-5 & -3 == -7) — know
    # nothing (the dtype clamp bounds it)
    return AbsVal(-(1 << 63), 1 << 63)


def or_(a: AbsVal, b: AbsVal) -> AbsVal:
    if a.nonneg and b.nonneg:
        m = a.ones | b.ones
        return AbsVal(max(a.lo, b.lo), m, m)
    # a negative-capable operand: OR can exceed both positive his
    # (-1 | x == -1; 10 | 5 == 15) — know nothing (dtype clamp bounds it)
    return AbsVal(-(1 << 63), 1 << 63)


def xor(a: AbsVal, b: AbsVal) -> AbsVal:
    if a.nonneg and b.nonneg:
        m = a.ones | b.ones
        return AbsVal(0, m, m)
    return AbsVal(min(a.lo, b.lo, -(1 << 63)), max(a.hi, b.hi, 1 << 63))


def not_(a: AbsVal) -> AbsVal:
    return AbsVal(-a.hi - 1, -a.lo - 1)


def _shift_range(s: AbsVal) -> range:
    lo = max(0, min(s.lo, MAX_SHIFT))
    hi = max(0, min(s.hi, MAX_SHIFT))
    return range(lo, hi + 1)


def shl(a: AbsVal, s: AbsVal) -> AbsVal:
    rng = _shift_range(s)
    lo = min(a.lo << k for k in rng)
    hi = max(a.hi << k for k in rng)
    if a.ones != -1:
        ones = 0
        for k in rng:
            ones |= a.ones << k
        return AbsVal(lo, hi, ones)
    return AbsVal(lo, hi)


def shr_arith(a: AbsVal, s: AbsVal) -> AbsVal:
    rng = _shift_range(s)
    lo = min(a.lo >> k for k in rng)
    hi = max(a.hi >> k for k in rng)
    if a.ones != -1:
        ones = 0
        for k in rng:
            ones |= a.ones >> k
        return AbsVal(lo, hi, ones)
    return AbsVal(lo, hi)


def shr_logical(a: AbsVal, s: AbsVal, nbits: int) -> AbsVal:
    rng = _shift_range(s)
    if a.nonneg:
        return AbsVal(min(a.lo >> k for k in rng),
                      max(a.hi >> k for k in rng))
    # negative inputs reinterpret as large unsigned values
    umax = (1 << nbits) - 1
    return AbsVal(0, max(umax >> k for k in rng))


def rem(a: AbsVal, b: AbsVal) -> AbsVal:
    """XLA/jax ``rem``: sign follows the DIVIDEND."""
    if b.lo <= 0 <= b.hi:
        # divisor may be 0 (result undefined) — know nothing useful
        return AbsVal(min(a.lo, -abs(a.lo)), max(a.hi, abs(a.hi)))
    m = max(abs(b.lo), abs(b.hi)) - 1
    lo = 0 if a.nonneg else -m
    hi = 0 if a.hi <= 0 else m
    # a tighter bound when the dividend already fits
    if a.nonneg:
        hi = min(hi, a.hi)
    return AbsVal(lo, hi)


def div(a: AbsVal, b: AbsVal) -> AbsVal:
    """Integer division toward zero."""
    if b.lo <= 0 <= b.hi:
        return AbsVal(-max(abs(a.lo), abs(a.hi)), max(abs(a.lo), abs(a.hi)))

    def q(x, y):
        return int(abs(x) // abs(y)) * (1 if (x >= 0) == (y > 0) else -1)

    cs = [q(a.lo, b.lo), q(a.lo, b.hi), q(a.hi, b.lo), q(a.hi, b.hi)]
    return AbsVal(min(cs), max(cs))


def abs_(a: AbsVal) -> AbsVal:
    if a.nonneg:
        return a
    if a.hi <= 0:
        return AbsVal(-a.hi, -a.lo)
    return AbsVal(0, max(-a.lo, a.hi))


def clamp3(lo_av: AbsVal, x: AbsVal, hi_av: AbsVal) -> AbsVal:
    lo = max(lo_av.lo, min(x.lo, hi_av.hi))
    hi = min(hi_av.hi, max(x.hi, lo_av.lo))
    if lo > hi:  # contradictory clamp operands — stay sound
        lo, hi = min(lo, hi), max(lo, hi)
    return AbsVal(lo, hi)


def sum_n(a: AbsVal, n: int) -> AbsVal:
    """Sum of n independent elements each in ``a``."""
    if n <= 0:
        return iv(0)
    return AbsVal(min(a.lo * n, a.lo), max(a.hi * n, a.hi))


def prefix_sums(a: AbsVal, n: int) -> AbsVal:
    """Any prefix sum of up to n elements of ``a`` (cumsum)."""
    if n <= 0:
        return iv(0)
    return AbsVal(min(a.lo, a.lo * n), max(a.hi, a.hi * n))
