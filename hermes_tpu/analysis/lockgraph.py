"""Dynamic lock-order sanitizer (round-20, the host analyzer's runtime half).

``ObsLock`` is a drop-in instrumented lock: every acquisition records the
acquiring thread's stack into a process-wide HELD-BEFORE graph (lock A ->
lock B whenever a thread acquires B while holding A), plus per-lock
hold-time / wait-time series and contention counters.  A cycle in the
graph is a potential deadlock even if no run has deadlocked yet — two
threads that ever take the same pair of locks in opposite orders only
need the right interleaving — and the finding carries BOTH acquisition
stacks as evidence.

Deployment is the ``HERMES_LOCKLINT=1`` env switch: the serving tier
mints its locks through ``concurrency.make_lock``, which swaps in
ObsLock under the switch, so every serving/chaos soak doubles as a
sanitizer run at zero production cost (a plain ``threading.Lock``
otherwise).  The static twin — the lexical nested-``with`` graph over
the whole package — is ``analysis/hostlint.py``; the CI gate
(``scripts/check_hostlint.py``, gate eleven) runs both.

Instrumentation-measuring-instrumentation rule: the metrics registry a
graph feeds (``attach_registry``) keeps a PLAIN lock, and the obs
overhead gate forces the switch off — lock hold-time series must never
ride the overhead gate's traced leg (scripts/check_obs_overhead.py).

Keeps stdlib-only imports at module level so ``concurrency.make_lock``
can pull it into the transport/serving processes without dragging the
analysis engines (jax) in; ``Finding`` objects are built lazily.
"""

from __future__ import annotations

import collections
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

#: registry metric-name prefix for everything ObsLock feeds — the obs
#: overhead gate excludes (and asserts the absence of) this prefix
LOCK_METRIC_PREFIX = "lock_"
HOLD_SERIES_FMT = LOCK_METRIC_PREFIX + "hold_us:{name}"
WAIT_SERIES_FMT = LOCK_METRIC_PREFIX + "wait_us:{name}"

_STACK_SKIP = 2   # drop the ObsLock/LockGraph frames from evidence
_STACK_KEEP = 8   # frames of evidence per acquisition
_HOLD_KEEP = 4096  # per-lock hold samples kept for percentiles


def _stack() -> str:
    frames = traceback.format_stack()[:-_STACK_SKIP]
    return "".join(frames[-_STACK_KEEP:])


class LockGraph:
    """One process-wide held-before graph + per-lock stats."""

    def __init__(self):
        self._graph_lock = threading.Lock()
        # (held_name, acquired_name) -> dict(count, held_stack,
        # acquire_stack): first-occurrence stacks are the evidence pair
        self._edges: Dict[Tuple[str, str], dict] = {}
        # name -> dict(acquires, contended, holds: deque, wait_us_max)
        self._stats: Dict[str, dict] = {}
        self._registry = None  # obs MetricsRegistry (optional sink)
        self._held = threading.local()  # per-thread acquisition stack

    # -- wiring --------------------------------------------------------------

    def attach_registry(self, registry) -> None:
        """Feed per-lock hold/wait series + counters into an obs
        ``MetricsRegistry`` (obs/series.py rings).  The registry's own
        lock must stay uninstrumented — see concurrency.REGISTRY."""
        with self._graph_lock:
            self._registry = registry

    def _held_list(self) -> list:
        ent = getattr(self._held, "stack", None)
        if ent is None:
            ent = self._held.stack = []
        return ent

    # -- ObsLock callbacks ---------------------------------------------------

    def note_acquire(self, name: str, wait_s: float) -> None:
        held = self._held_list()
        for ent in held:
            if ent["name"] == name:   # reentrant re-acquire: no new edge
                ent["depth"] += 1
                return
        stack = _stack()
        wait_us = wait_s * 1e6
        with self._graph_lock:
            st = self._stats.setdefault(
                name, dict(acquires=0, contended=0,
                           holds=collections.deque(maxlen=_HOLD_KEEP),
                           seq=0))
            st["acquires"] += 1
            if wait_s > 0:
                st["contended"] += 1
            for prior in held:
                edge = (prior["name"], name)
                ent = self._edges.get(edge)
                if ent is None:
                    self._edges[edge] = dict(count=1,
                                             held_stack=prior["stack"],
                                             acquire_stack=stack)
                else:
                    ent["count"] += 1
            if self._registry is not None:
                st["seq"] += 1
                if wait_s > 0:
                    self._registry.series(
                        WAIT_SERIES_FMT.format(name=name)).append(
                            st["seq"], wait_us)
                self._registry.counter(
                    LOCK_METRIC_PREFIX + "acquires:" + name).inc()
                if wait_s > 0:
                    self._registry.counter(
                        LOCK_METRIC_PREFIX + "contended:" + name).inc()
        held.append(dict(name=name, stack=stack, depth=1,
                         t0=time.perf_counter()))

    def note_release(self, name: str) -> None:
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i]["name"] != name:
                continue
            held[i]["depth"] -= 1
            if held[i]["depth"] > 0:
                return
            hold_us = (time.perf_counter() - held[i]["t0"]) * 1e6
            del held[i]
            with self._graph_lock:
                st = self._stats.get(name)
                if st is not None:
                    st["holds"].append(hold_us)
                    if self._registry is not None:
                        st["seq"] += 1
                        self._registry.series(
                            HOLD_SERIES_FMT.format(name=name)).append(
                                st["seq"], hold_us)
            return
        # release without a matching note_acquire: let the caller's
        # underlying lock.release() raise — nothing to unwind here

    # -- analysis ------------------------------------------------------------

    def edges(self) -> dict:
        with self._graph_lock:
            return {e: dict(v) for e, v in self._edges.items()}

    def cycles(self) -> List[List[str]]:
        """Every elementary cycle reachable in the held-before graph,
        as name lists (first node repeated implicitly).  The graph is
        tiny (locks, not ops), so a plain DFS is fine."""
        with self._graph_lock:
            adj: Dict[str, list] = {}
            for a, b in self._edges:
                adj.setdefault(a, []).append(b)
        out: List[List[str]] = []
        seen_cycles = set()

        def dfs(node, path, on_path):
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):]
                    canon = tuple(sorted(cyc))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(cyc))
                    continue
                path.append(nxt)
                on_path.add(nxt)
                dfs(nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

        for start in sorted(adj):
            dfs(start, [start], {start})
        return out

    def hold_p99_us(self, name: str) -> Optional[float]:
        with self._graph_lock:
            st = self._stats.get(name)
            holds = sorted(st["holds"]) if st else []
        if not holds:
            return None
        return holds[min(len(holds) - 1, int(0.99 * len(holds)))]

    def findings(self) -> list:
        """Cycle findings in the passes.py schema (ERROR, both stacks as
        evidence) — the currency scripts/check_hostlint.py gates on."""
        from hermes_tpu.analysis.passes import ERROR, Finding

        edges = self.edges()
        out = []
        for cyc in self.cycles():
            ring = cyc + cyc[:1]
            ev = []
            for a, b in zip(ring, ring[1:]):
                e = edges.get((a, b))
                if e:
                    ev.append(f"-- {a} held at:\n{e['held_stack']}"
                              f"-- then {b} acquired at:\n"
                              f"{e['acquire_stack']}")
            out.append(Finding(
                pass_name="lockgraph", code="lock-order-cycle",
                severity=ERROR, engine="host",
                file="<runtime>", fn="dynamic",
                op="->".join(cyc),
                message=("potential deadlock: locks acquired in "
                         f"conflicting orders ({' -> '.join(cyc)} -> "
                         f"{cyc[0]}); acquisition stacks:\n"
                         + "\n".join(ev))))
        return out

    def report(self) -> dict:
        """JSON-ready summary for CLI lines and the gate artifact."""
        with self._graph_lock:
            stats = {n: dict(acquires=st["acquires"],
                             contended=st["contended"],
                             holds=sorted(st["holds"]))
                     for n, st in self._stats.items()}
            n_edges = len(self._edges)
        locks = {}
        for n, st in sorted(stats.items()):
            holds = st.pop("holds")
            if holds:
                st["hold_p99_us"] = round(
                    holds[min(len(holds) - 1, int(0.99 * len(holds)))], 1)
                st["hold_max_us"] = round(holds[-1], 1)
            locks[n] = st
        return dict(locks=locks, n_edges=n_edges, cycles=self.cycles())


class ObsLock:
    """Drop-in instrumented lock.

    Wraps a ``threading.RLock`` (reentrant — a drop-in must never turn a
    legal re-acquire into a self-deadlock) and reports acquisitions /
    releases to a :class:`LockGraph`.  Reentrant re-acquires count depth
    only: no new edge, no new stack, and the hold interval runs from the
    OUTERMOST acquire to the matching release — context-manager
    semantics are exactly ``threading.Lock``'s otherwise."""

    def __init__(self, name: str, graph: Optional[LockGraph] = None):
        self.name = name
        self._graph = graph  # None -> follow the CURRENT global graph
        self._lk = threading.RLock()

    @property
    def graph(self) -> LockGraph:
        """Explicit graph if one was given, else the current GLOBAL —
        resolved per call, so ``reset_global()`` at a quiescent point
        (e.g. after a jit-warmup) retargets every default lock at once
        without re-minting them."""
        return self._graph if self._graph is not None else GLOBAL

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lk.acquire(False)
        wait_s = 0.0
        if not got:
            if not blocking:
                return False
            t0 = time.perf_counter()
            got = self._lk.acquire(True, timeout)
            wait_s = time.perf_counter() - t0
            if not got:
                return False
        self.graph.note_acquire(self.name, wait_s)
        return True

    def release(self) -> None:
        self.graph.note_release(self.name)
        self._lk.release()

    def __enter__(self) -> "ObsLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


#: process-wide default graph (what make_lock-minted ObsLocks join)
GLOBAL = LockGraph()


def global_graph() -> LockGraph:
    return GLOBAL


def reset_global() -> LockGraph:
    """Fresh process-wide graph (gates/tests).  Default-graph ObsLocks
    follow the swap on their next acquire (the ``graph`` property);
    only locks minted with an EXPLICIT graph keep the old one.  Call at
    a quiescent point — an acquisition spanning the swap records its
    acquire in the old graph and its release in the new one (both are
    tolerated, the sample is simply dropped)."""
    global GLOBAL
    GLOBAL = LockGraph()
    return GLOBAL
