"""Abstract sub-interpreter for ``pallas_call`` kernel bodies (ISSUE 8).

Before this module the analyzer SKIPPED kernel bodies: every
``pallas_call`` output was dtype-TOP and the one place the mega-round
plan moves the per-key state machine was the one place the PR-3
bitpack/dtype/scatter proofs could not see.  This module opens the box:

  * every kernel Ref (input block, output block, scratch) maps to an
    abstract **cell** — one ``AbsVal`` summarizing the block's content
    plus an init state (NO/MAYBE/YES) — keyed through the interpreter's
    alias chain so refs stay resolvable across ``cond``/``scan``/
    ``pjit`` nesting inside the kernel;
  * the state primitives get transfer rules: ``get`` reads the cell
    (flagging read-before-init), ``swap`` stores (strong update for a
    full-block store, weak join for a partial one; a ``DropVar`` result
    is a pure store and never counts as a read), ``addupdate``
    read-modify-writes; every dynamic index is bounds-checked against
    the block shape (``oob-block-store`` / ``oob-block-load``);
  * ``pl.when`` regions arrive as ``cond``: branch cell-states are
    joined as interval unions, and a predicate the domain proves
    constant (``blk == 0`` on the first visit) selects its branch
    path-sensitively;
  * ``pl.program_id``/``pl.num_programs`` are seeded from the grid, and
    every BlockSpec index map is evaluated abstractly over the full
    grid range and checked against the operand shape
    (``blockspec-oob``); a grid-invariant output index map means the
    block REVISITS across grid steps — the accumulator aliasing must be
    declared with a ``layouts.audited`` tag on the call site
    (``grid-revisit-accumulator``), the kernel analogue of PR-3's
    scatter discipline;
  * the body is evaluated in two phases: a **first visit** (program ids
    pinned to 0, output cells uninitialized) that checks the
    ``pl.when(blk == 0)`` init discipline exactly, then a **steady
    state** (program ids spanning the grid, revisited cells carried)
    run through a small widening loop.  ``fori_loop``-lowered scans get
    an induction-variable refinement (carry ``c' = c + k`` over a known
    length) so serial per-message kernels keep exact index bounds.

Soundness stance: anything the model cannot faithfully express —
scalar-prefetch grids, dynamic grid bounds, vmapped kernels, an
unknown primitive touching a Ref (DMA, semaphores), an indexer tree we
cannot parse — DEFEATS the sub-interpreter for that ``pallas_call``:
outputs fall back to dtype-TOP (the pre-ISSUE-8 behavior) and a
``pallas-skipped`` info finding names what defeated it, so the blind
spot is visible in the findings stream instead of silent.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from hermes_tpu.analysis import domain as D
from hermes_tpu.analysis import interp as I
from hermes_tpu.analysis.domain import AbsVal

# cell init lattice: join is min (a branch that may not store demotes YES)
NO, MAYBE, YES = 0, 1, 2

#: primitives with Ref operands the sub-interpreter models; anything
#: else touching a Ref defeats the kernel (see module doc)
_STATE_PRIMS = ("get", "swap", "addupdate")


class Defeated(Exception):
    """The kernel uses a feature outside the cell model; the caller
    falls back to dtype-TOP outputs + a pallas-skipped finding."""

    def __init__(self, what: str):
        super().__init__(what)
        self.what = what


def _is_ref(aval) -> bool:
    try:
        from jax._src.state.types import AbstractRef

        return isinstance(aval, AbstractRef)
    except Exception:
        return "Ref" in type(aval).__name__


def _drop_var(v) -> bool:
    return type(v).__name__ == "DropVar"


@dataclasses.dataclass
class RefCell:
    """One kernel Ref: block shape/dtype + summarized abstract content."""

    shape: Tuple[int, ...]
    dtype: object
    kind: str  # "in" | "out" | "scratch"
    origin: str
    av: Optional[AbsVal]  # None = nothing stored yet (bottom)
    init: int  # NO / MAYBE / YES
    revisit: bool = False  # out block grid-invariant (accumulator)

    def read(self) -> AbsVal:
        """Sound read value: the cell content, or dtype-TOP when the
        block may hold garbage (uninitialized memory)."""
        if self.init == YES and self.av is not None:
            return self.av
        top = D.top(self.dtype)
        return top if self.av is None else D.join(self.av, top)

    def out_value(self) -> AbsVal:
        """The value this block contributes to the pallas output after a
        visit (garbage-aware like read())."""
        return self.read()

    def snapshot(self) -> tuple:
        return (self.av, self.init)

    def restore(self, snap: tuple) -> None:
        self.av, self.init = snap


def _join_snaps(a: tuple, b: tuple) -> tuple:
    av_a, in_a = a
    av_b, in_b = b
    if av_a is None:
        av = av_b
    elif av_b is None:
        av = av_a
    else:
        av = D.join(av_a, av_b)
    return (av, min(in_a, in_b))


class KCtx:
    """Kernel-local interpreter state riding beside the shared Ctx."""

    def __init__(self, grid: Tuple[int, ...], hazard):
        self.grid = grid
        self.pid: List[AbsVal] = [D.iv(0) for _ in grid]
        self.cells: Dict = {}  # canonical ref Var -> RefCell
        self.hazard = hazard  # RefHazardPass or None

    def cell_of(self, ctx: I.Ctx, atom) -> RefCell:
        cell = self.cells.get(ctx.canon(atom))
        if cell is None:
            # a ref the call didn't bind (run_scoped views, transforms)
            raise Defeated("unmapped-ref")
        return cell

    def emit(self, eqn, code, severity, message) -> None:
        if self.hazard is not None:
            self.hazard.emit(eqn, code, severity, message)

    def proved(self) -> None:
        if self.hazard is not None:
            self.hazard.n_proved += 1


def _hazard_pass(ctx: I.Ctx):
    for p in ctx.passes:
        if getattr(p, "name", "") == "refhazard":
            return p
    return None


# --------------------------------------------------------------------------
# entry point (called from interp._eval_eqn for every pallas_call)
# --------------------------------------------------------------------------


def eval_pallas_call(eqn, ins: List[AbsVal], ctx: I.Ctx) -> List[AbsVal]:
    """Interpret one ``pallas_call`` equation.  Returns the output
    abstractions; on defeat emits ``pallas-skipped`` (info) and returns
    dtype-TOP for every output — the sound pre-ISSUE-8 behavior."""
    try:
        return _interpret_kernel(eqn, ins, ctx)
    except Defeated as d:
        hp = _hazard_pass(ctx)
        if hp is not None:
            hp.note_skipped(eqn, d.what)
        return [D.top(v.aval.dtype) for v in eqn.outvars]


def _interpret_kernel(eqn, ins: List[AbsVal], ctx: I.Ctx) -> List[AbsVal]:
    gm = eqn.params["grid_mapping"]
    jaxpr = eqn.params["jaxpr"]
    if getattr(gm, "num_dynamic_grid_bounds", 0):
        raise Defeated("dynamic-grid-bounds")
    if getattr(gm, "num_index_operands", 0):
        raise Defeated("scalar-prefetch")
    if getattr(gm, "mapped_dims", ()) or getattr(gm, "vmapped_dims", ()):
        raise Defeated("vmapped-pallas_call")
    if getattr(jaxpr, "constvars", ()):
        raise Defeated("kernel-constvars")
    try:
        grid = tuple(int(g) for g in gm.grid)
    except Exception:
        raise Defeated("symbolic-grid")
    n_in, n_out = int(gm.num_inputs), int(gm.num_outputs)
    bms = list(gm.block_mappings)
    if len(bms) != n_in + n_out or len(jaxpr.invars) < n_in + n_out:
        raise Defeated("block-mappings")
    if len(ins) < n_in:
        raise Defeated("operand-arity")

    hp = _hazard_pass(ctx)
    kctx = KCtx(grid, hp)
    total = 1
    for g in grid:
        total *= g

    # -- BlockSpec index maps: bounds vs operand shape + revisit detection
    revisit = [_check_block_mapping(eqn, bm, grid, kctx) and total > 1
               for bm in bms]

    # -- bind cells ---------------------------------------------------------
    io_alias = {int(o): int(i)
                for i, o in (eqn.params.get("input_output_aliases") or ())}
    kin = jaxpr.invars
    for i in range(n_in):
        aval = kin[i].aval
        kctx.cells[kin[i]] = RefCell(
            shape=tuple(aval.shape), dtype=np.dtype(aval.dtype), kind="in",
            origin=getattr(bms[i], "origin", f"in{i}"),
            av=D.clamp(ins[i], aval.dtype)[0], init=YES, revisit=revisit[i])
    for o in range(n_out):
        v = kin[n_in + o]
        aval = v.aval
        src = io_alias.get(o)
        seeded = src is not None and src < len(ins)
        kctx.cells[v] = RefCell(
            shape=tuple(aval.shape), dtype=np.dtype(aval.dtype), kind="out",
            origin=getattr(bms[n_in + o], "origin", f"out{o}"),
            av=D.clamp(ins[src], aval.dtype)[0] if seeded else None,
            init=YES if seeded else NO, revisit=revisit[n_in + o])
    for s in range(n_in + n_out, len(kin)):
        aval = kin[s].aval
        try:
            dt = np.dtype(aval.dtype)
        except Exception:
            dt = np.dtype(np.int32)  # semaphores: only DMA prims touch
            # them, and any such primitive defeats the kernel anyway
        kctx.cells[kin[s]] = RefCell(
            shape=tuple(getattr(aval, "shape", ())), dtype=dt,
            kind="scratch", origin=f"scratch{s - n_in - n_out}",
            av=None, init=NO)

    # -- grid-revisit accumulators must be declared (audited call site) ----
    for o in range(n_out):
        if revisit[n_in + o]:
            kctx.emit(
                eqn, "grid-revisit-accumulator", "warn",
                f"output block {kctx.cells[kin[n_in + o]].origin!r} has a "
                f"grid-invariant index map over grid {grid}: the block is "
                f"revisited and accumulated across grid steps — declare "
                f"the aliasing with layouts.audited(tag) on the "
                f"pallas_call site (the kernel analogue of the scatter "
                f"injectivity discipline)")

    out_cells = [kctx.cells[kin[n_in + o]] for o in range(n_out)]
    out_seed = [c.snapshot() for c in out_cells]
    out_acc: List[Optional[AbsVal]] = [None] * n_out

    def run_visit():
        _eval_jaxpr_k(jaxpr, None, ctx, kctx)
        for o, c in enumerate(out_cells):
            v = c.out_value()
            out_acc[o] = v if out_acc[o] is None else D.join(out_acc[o], v)

    # -- phase 1: the first visit, program ids pinned to 0 ------------------
    kctx.pid = [D.iv(0) for _ in grid]
    run_visit()

    # -- phase 2: steady state over the whole grid --------------------------
    if total > 1:
        kctx.pid = [D.iv(0, max(0, g - 1)) for g in grid]
        unstable: set = set()
        for it in range(4):
            pre = {v: c.snapshot() for v, c in kctx.cells.items()}
            for o, c in enumerate(out_cells):
                if not c.revisit:  # a fresh block every visit
                    c.restore(out_seed[o])
            if it == 3:
                # widen ONLY the cells the previous iteration showed
                # still moving: a blanket widen would discard the seeded
                # bounds of never-stored inputs (the SMEM step scalar)
                # and of accumulators that stabilized early — exactly
                # the bounds the bitpack pass needs inside the mega
                # kernels.  Monotone transfer rules propagate
                # instability, so stable cells are genuine fixpoints.
                _widen_cells(kctx, only=unstable)
            run_visit()
            stable = True
            unstable = set()
            for v, c in kctx.cells.items():
                joined = _join_snaps(pre[v], c.snapshot())
                if c.kind == "out" and not c.revisit:
                    # fresh-block cells don't carry between visits;
                    # out_acc already folded this visit's value
                    continue
                if joined != pre[v]:
                    stable = False
                    unstable.add(v)
                c.restore(joined)
            if stable:
                break
        if unstable:
            # soundness belt: something STILL moved after the widened
            # pass (a cell destabilized by a neighbor's widening, or a
            # chain the selective widen missed).  Escalate to the old
            # blanket behavior: every cell to dtype-TOP, one more visit
            # so the TOP-derived values PROPAGATE into out_acc and
            # dependent cells, then re-pin (a full-block store in that
            # visit must not un-widen a cell).
            _widen_cells(kctx)
            run_visit()
            _widen_cells(kctx)

    outs = []
    for o, v in enumerate(eqn.outvars):
        av = out_acc[o] if out_acc[o] is not None else D.top(v.aval.dtype)
        outs.append(D.clamp(av, v.aval.dtype)[0])
    return outs


# --------------------------------------------------------------------------
# BlockSpec index maps
# --------------------------------------------------------------------------


def _check_block_mapping(eqn, bm, grid, kctx) -> bool:
    """Evaluate one BlockSpec index map over the full grid range; check
    the produced block indices against the operand shape.  Returns True
    when the map is grid-invariant (the block revisits)."""
    imj = bm.index_map_jaxpr
    try:
        block_shape = tuple(int(b) for b in bm.block_shape)
        ashape = tuple(int(s) for s in bm.array_shape_dtype.shape)
    except Exception:
        raise Defeated("mapped-block-dims")
    in_avs = [D.iv(0, max(0, g - 1)) for g in grid]
    sub = I.Ctx()  # throwaway: index maps never carry findings
    try:
        outs = I.eval_jaxpr(imj.jaxpr, in_avs, sub, consts=list(imj.consts))
    except Exception:
        raise Defeated("index-map")
    if len(outs) != len(block_shape) or len(block_shape) != len(ashape):
        raise Defeated("index-map-arity")
    ok = True
    for d, (b_av, bs, asz) in enumerate(zip(outs, block_shape, ashape)):
        nblk = -(-asz // max(1, bs))
        if b_av.lo < 0 or b_av.hi > nblk - 1:
            ok = False
            kctx.emit(
                eqn, "blockspec-oob", "error",
                f"BlockSpec for {getattr(bm, 'origin', '?')!r} dim {d}: "
                f"index map yields block index {b_av} over grid {grid} "
                f"but the {asz}-wide operand has only {nblk} blocks of "
                f"{bs} — out-of-bounds slab")
    if ok:
        kctx.proved()
    return all(o.is_const for o in outs)


# --------------------------------------------------------------------------
# the kernel body walk (mirrors interp.eval_jaxpr + cell semantics)
# --------------------------------------------------------------------------


def _safe_aval(ctx, atom) -> AbsVal:
    try:
        return ctx.aval_of(atom)
    except Exception:
        return D.iv(0)  # Ref/semaphore placeholder, never used as a value


def _eval_jaxpr_k(jaxpr, in_avs: Optional[List[AbsVal]], ctx: I.Ctx,
                  kctx: KCtx, consts: Optional[list] = None) -> List[AbsVal]:
    env = ctx.env
    for v, c in zip(jaxpr.constvars, consts or []):
        env[v] = D.from_concrete(c)
    if in_avs is not None:
        for v, av in zip(jaxpr.invars, in_avs):
            env[v] = av
    for eqn in jaxpr.eqns:
        ctx.n_eqns += 1
        ins = [_safe_aval(ctx, a) for a in eqn.invars]
        outs, wrapped = _eval_eqn_k(eqn, ins, ctx, kctx)
        for p in ctx.passes:
            p.on_eqn(ctx, eqn, ins, outs, wrapped)
        for v, av in zip(eqn.outvars, outs):
            env[v] = av
            ctx.defs[v] = eqn
    return [_safe_aval(ctx, a) for a in jaxpr.outvars]


def _eval_eqn_k(eqn, ins, ctx, kctx):
    name = eqn.primitive.name
    if name == "program_id":
        return [kctx.pid[int(eqn.params.get("axis", 0))]], False
    if name == "num_programs":
        return [D.iv(kctx.grid[int(eqn.params.get("axis", 0))])], False
    if name in _STATE_PRIMS:
        return _eval_ref_op(eqn, ins, ctx, kctx), False
    if name == "cond":
        return _eval_cond_k(eqn, ins, ctx, kctx), False
    if name == "scan":
        return _eval_scan_k(eqn, ins, ctx, kctx), False
    if name == "while":
        return _eval_while_k(eqn, ins, ctx, kctx), False
    if name in I._CALL_JAXPR_PRIMS:
        inner = eqn.params.get(I._CALL_JAXPR_PRIMS[name])
        if inner is not None:
            j, consts = I._as_open(inner)
            for inner_v, outer_a in zip(j.invars, eqn.invars):
                ctx.aliases[inner_v] = outer_a
            outs = _eval_jaxpr_k(j, list(ins), ctx, kctx, consts)
            for outer_v, inner_a in zip(eqn.outvars, j.outvars):
                ctx.aliases[outer_v] = inner_a
            return I._refine_named_call(eqn, ins, outs, ctx), False
    if any(_is_ref(getattr(v, "aval", None)) for v in eqn.invars):
        # an effectful primitive outside the cell model (DMA, semaphore
        # signal, ref view): the cells can no longer be trusted
        raise Defeated(name)
    if name == "pallas_call":
        raise Defeated("nested-pallas_call")
    fn = I.RULES.get(name)
    if fn is None:
        return [D.top(v.aval.dtype) for v in eqn.outvars], False
    raw = fn(eqn, ins, ctx)
    outs, wrapped = [], False
    for v, av in zip(eqn.outvars, raw):
        c, w = D.clamp(av, v.aval.dtype)
        outs.append(c)
        wrapped = wrapped or w
    return outs, wrapped


# -- get / swap / addupdate -------------------------------------------------


def _parse_indexers(eqn, idx_atoms):
    """Unflatten the NDIndexer tree riding the eqn params; returns the
    indexer tuple or None when the tree shape is not what we model."""
    tree = eqn.params.get("tree")
    if tree is None:
        return None
    try:
        import jax

        indexers = jax.tree_util.tree_unflatten(tree, list(idx_atoms))
    except Exception:
        return None
    if not isinstance(indexers, tuple):
        return None
    return indexers


def _dim_bounds(ctx, idx, dim) -> Optional[Tuple[int, int, bool]]:
    """(lo, hi, is_full) index bounds one indexer element can reach in a
    dimension of size ``dim``; None = unparseable."""
    from jax._src.state import indexing

    if isinstance(idx, indexing.Slice):
        if not isinstance(idx.size, int) or not isinstance(idx.stride, int):
            return None
        span = (idx.size - 1) * idx.stride
        if isinstance(idx.start, int):
            full = (idx.start == 0 and idx.stride == 1 and idx.size == dim)
            return (idx.start, idx.start + span, full)
        av = ctx.aval_of(idx.start)
        return (av.lo, av.hi + span, False)
    if isinstance(idx, int):
        return (idx, idx, dim == 1 and idx == 0)
    if isinstance(idx, np.ndarray):
        return (int(idx.min()), int(idx.max()), False)
    av = ctx.aval_of(idx)  # scalar or advanced int-array index
    return (av.lo, av.hi, False)


def _indexer_info(ctx, indexers, shape):
    """(in_bounds, full_block, detail) over every indexer/dim pair."""
    full = True
    oob = None
    for nd in indexers:
        idxs = getattr(nd, "indices", None)
        if idxs is None:
            return None
        if len(idxs) != len(shape):
            return None
        for d, (ix, dim) in enumerate(zip(idxs, shape)):
            b = _dim_bounds(ctx, ix, dim)
            if b is None:
                return None
            lo, hi, f = b
            full = full and f
            if (lo < 0 or hi > dim - 1) and oob is None:
                oob = (d, lo, hi, dim)
    return (oob is None, full, oob)


def _eval_ref_op(eqn, ins, ctx, kctx):
    name = eqn.primitive.name
    cell = kctx.cell_of(ctx, eqn.invars[0])
    n_val = 0 if name == "get" else 1
    info = None
    indexers = _parse_indexers(eqn, eqn.invars[1 + n_val:])
    if indexers is not None:
        info = _indexer_info(ctx, indexers, cell.shape)
    if info is None:
        raise Defeated(f"{name}:indexer")
    in_bounds, full, oob = info

    if not in_bounds:
        d, lo, hi, dim = oob
        code = "oob-block-load" if name == "get" else "oob-block-store"
        kctx.emit(
            eqn, code, "error",
            f"{name} on {cell.origin!r} dim {d}: index range [{lo}, {hi}] "
            f"escapes the {dim}-wide block — out-of-bounds {name} inside "
            f"a kernel is undefined behavior on TPU; bound the index or "
            f"widen the block")
    else:
        kctx.proved()

    # does this op READ the block? (a swap whose old value is dropped is
    # a pure store; addupdate always reads)
    reads = (name == "get" or name == "addupdate"
             or (name == "swap" and not _drop_var(eqn.outvars[0])))
    if reads:
        if cell.init != YES:
            kctx.emit(
                eqn, "ref-read-before-init", "error",
                f"{name} on {cell.origin!r} may read uninitialized "
                f"{cell.kind} memory (init={('no', 'maybe', 'yes')[cell.init]}"
                f"): initialize the block first (e.g. a pl.when(pid == 0) "
                f"zero-fill for a revisit-accumulated block)")
        else:
            kctx.proved()

    old = cell.read()
    if name == "get":
        return [D.clamp(old, eqn.outvars[0].aval.dtype)[0]]

    val = D.clamp(ins[1], cell.dtype)[0]
    if name == "swap":
        if full:
            cell.av, cell.init = val, YES
        else:
            cell.av = val if cell.av is None else D.join(cell.av, val)
            cell.init = max(cell.init, MAYBE)
        return [D.clamp(old, eqn.outvars[0].aval.dtype)[0]]
    # addupdate: the block gains val somewhere (full: everywhere)
    new = D.clamp(D.add(old, val), cell.dtype)[0]
    cell.av = new if full else D.join(old, new)
    return []


# -- control flow with cell-state joins -------------------------------------


def _eval_cond_k(eqn, ins, ctx, kctx):
    branches = eqn.params["branches"]
    pred = ins[0]
    if pred.is_const:  # path-sensitive: pl.when(blk == 0) on visit 0
        sel = min(max(int(pred.lo), 0), len(branches) - 1)
        j, consts = I._as_open(branches[sel])
        for inner_v, outer_a in zip(j.invars, eqn.invars[1:]):
            ctx.aliases[inner_v] = outer_a
        return _eval_jaxpr_k(j, list(ins[1:]), ctx, kctx, consts)
    base = {v: c.snapshot() for v, c in kctx.cells.items()}
    outs = None
    joined = None
    for br in branches:
        for v, c in kctx.cells.items():
            c.restore(base[v])
        j, consts = I._as_open(br)
        for inner_v, outer_a in zip(j.invars, eqn.invars[1:]):
            ctx.aliases[inner_v] = outer_a
        o = _eval_jaxpr_k(j, list(ins[1:]), ctx, kctx, consts)
        outs = o if outs is None else [D.join(a, b) for a, b in zip(outs, o)]
        snap = {v: c.snapshot() for v, c in kctx.cells.items()}
        joined = snap if joined is None else {
            v: _join_snaps(joined[v], snap[v]) for v in snap}
    for v, c in kctx.cells.items():
        c.restore(joined[v])
    return outs


def _induction_bounds(j, nc, ncar, init, length):
    """Exact bounds for syntactic induction carries: a carry whose body
    transfer is ``c' = c + k`` (k a literal) or the identity spans
    ``[init, init + k*(length-1)]`` — what keeps a fori_loop message
    index provably inside its SMEM block."""
    from jax.extend.core import Literal

    if not isinstance(length, int) or length <= 0:
        return [None] * ncar
    defs = {}
    for e in j.eqns:
        for v in e.outvars:
            defs[v] = e
    out = []
    for c in range(ncar):
        carry_in, carry_out = j.invars[nc + c], j.outvars[c]
        if isinstance(carry_out, Literal):
            # the body returns a constant carry (fori_loop's dummy 0):
            # after the first iteration the carry IS that constant
            out.append(D.join(init[c], D.from_concrete(carry_out.val)))
            continue
        if carry_out is carry_in:
            out.append(init[c])
            continue
        e = defs.get(carry_out)
        k = None
        if e is not None and e.primitive.name == "add":
            a, b = e.invars
            if a is carry_in and isinstance(b, Literal):
                k = int(np.asarray(b.val))
            elif b is carry_in and isinstance(a, Literal):
                k = int(np.asarray(a.val))
        if k is None:
            out.append(None)
            continue
        span = k * (length - 1)
        out.append(AbsVal(init[c].lo + min(0, span),
                          init[c].hi + max(0, span)))
    return out


def _widen_cells(kctx, only=None) -> None:
    """Widening: a cell named in ``only`` (default: every cell) may hold
    ANY dtype value after more iterations (init states form a finite
    min-join lattice and converge on their own).  Callers pass the set
    of cells their fixpoint loop measured UNSTABLE so early-stabilized
    accumulators and never-stored inputs keep their seeded bounds —
    monotone transfer rules propagate instability, so a stable cell is a
    genuine fixpoint."""
    for v, c in kctx.cells.items():
        if only is not None and v not in only:
            continue
        if c.av is not None:
            c.av = D.top(c.dtype)


def _join_cells_pre(kctx, pre, unstable=None) -> bool:
    """Kleene step for loop-carried cell state: join each cell's
    post-body state into its pre-body state; True when stable.  Without
    this the loop fixpoint would check only SSA carries and a
    ``ref[...] += 1`` accumulation would 'converge' after one body
    evaluation — an under-approximation the differential sanitizer
    red-tests (scan-accumulate cell).  ``unstable`` (a set) collects the
    cells that moved, for the selective widening above."""
    stable = True
    for v, c in kctx.cells.items():
        joined = _join_snaps(pre[v], c.snapshot())
        if joined != pre[v]:
            stable = False
            if unstable is not None:
                unstable.add(v)
        c.restore(joined)
    return stable


def _eval_scan_k(eqn, ins, ctx, kctx):
    nc = eqn.params.get("num_consts", 0)
    ncar = eqn.params.get("num_carry", 0)
    length = eqn.params.get("length")
    j, jconsts = I._as_open(eqn.params["jaxpr"])
    for inner_v, outer_a in zip(j.invars[:nc], eqn.invars[:nc]):
        ctx.aliases[inner_v] = outer_a  # refs ride the consts
    consts, init, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]
    pinned = _induction_bounds(j, nc, ncar, init, length)

    carry = [p if p is not None else c for p, c in zip(pinned, init)]
    ys = [D.top(v.aval.dtype) for v in eqn.outvars[ncar:]]
    unstable: set = set()
    for it in range(5):
        if it == 4:
            carry = [p if p is not None else
                     (AbsVal(min(c.lo, -(1 << 63)), max(c.hi, 1 << 63))
                      if (c.lo, c.hi) != (i.lo, i.hi) else c)
                     for p, c, i in zip(pinned, carry, init)]
            _widen_cells(kctx, only=unstable)
        pre = {v: c.snapshot() for v, c in kctx.cells.items()}
        o = _eval_jaxpr_k(j, consts + carry + xs, ctx, kctx, jconsts)
        ys = o[ncar:]
        unstable = set()
        cells_stable = _join_cells_pre(kctx, pre, unstable)
        nxt = [p if p is not None else D.join(c, n)
               for p, c, n in zip(pinned, carry, o[:ncar])]
        if cells_stable and all(n.lo == c.lo and n.hi == c.hi
                                for n, c in zip(nxt, carry)):
            break
        carry = nxt
    if unstable:
        # soundness belt, with propagation: blanket-widen, re-evaluate
        # the body once so TOP reaches ys/carry and dependent cells,
        # then re-pin the widened cell state
        _widen_cells(kctx)
        o = _eval_jaxpr_k(j, consts + carry + xs, ctx, kctx, jconsts)
        ys = [D.join(a, b) for a, b in zip(ys, o[ncar:])]
        carry = [D.join(c, n) for c, n in zip(carry, o[:ncar])]
        _widen_cells(kctx)
    outs = carry + list(ys)
    return [D.clamp(a, v.aval.dtype)[0] for a, v in zip(outs, eqn.outvars)]


def _eval_while_k(eqn, ins, ctx, kctx):
    cn = eqn.params.get("cond_nconsts", 0)
    bn = eqn.params.get("body_nconsts", 0)
    bj, bconsts = I._as_open(eqn.params["body_jaxpr"])
    for inner_v, outer_a in zip(bj.invars[:bn], eqn.invars[cn:cn + bn]):
        ctx.aliases[inner_v] = outer_a
    bconsts_avs = ins[cn:cn + bn]
    init = ins[cn + bn:]
    carry = list(init)
    unstable: set = set()
    for it in range(5):
        if it == 4:
            carry = [AbsVal(min(c.lo, -(1 << 63)), max(c.hi, 1 << 63))
                     if (c.lo, c.hi) != (i.lo, i.hi) else c
                     for c, i in zip(carry, init)]
            _widen_cells(kctx, only=unstable)
        pre = {v: c.snapshot() for v, c in kctx.cells.items()}
        o = _eval_jaxpr_k(bj, bconsts_avs + carry, ctx, kctx, bconsts)
        unstable = set()
        cells_stable = _join_cells_pre(kctx, pre, unstable)
        nxt = [D.join(c, n) for c, n in zip(carry, o)]
        if cells_stable and all(n.lo == c.lo and n.hi == c.hi
                                for n, c in zip(nxt, carry)):
            break
        carry = nxt
    if unstable:
        # soundness belt, with propagation (see _eval_scan_k)
        _widen_cells(kctx)
        o = _eval_jaxpr_k(bj, bconsts_avs + carry, ctx, kctx, bconsts)
        carry = [D.join(c, n) for c, n in zip(carry, o)]
        _widen_cells(kctx)
    return [D.clamp(a, v.aval.dtype)[0] for a, v in zip(carry, eqn.outvars)]
