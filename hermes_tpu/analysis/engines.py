"""Trace the engine programs and run the analyzer over them.

``trace_program`` closes a protocol round — batched or sharded, fused or
split sort — over a config into a jaxpr (abstract: ``jax.eval_shape``
shapes in, nothing materialized, so a 2^29-key mutation config analyzes
fine on a laptop), pairs it with the config-seeded input bounds
(seeds.py) and the engine's declared mesh/donation facts, and
``analyze_program`` walks it with the passes.

``analyze_config`` is the driver the CLI and the CI gate share: both
engines x (fused + split when the config resolves the fused sort) at one
config."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from hermes_tpu.analysis import seeds as seeds_lib
from hermes_tpu.analysis.interp import Ctx, eval_jaxpr
from hermes_tpu.analysis.passes import Finding, ScatterHazardPass, \
    default_passes
from hermes_tpu.config import HermesConfig


@dataclasses.dataclass
class Program:
    """One traced engine program + the facts the passes need."""

    engine: str  # "batched" | "sharded"
    variant: str  # "fused" | "split" | "race"
    closed_jaxpr: object
    in_avs: list
    mesh_axes: Optional[dict]  # {} for batched (no collectives allowed)
    donated: frozenset  # invar indices donated by the scan builders
    cfg: HermesConfig

    @property
    def name(self) -> str:
        return f"{self.engine}/{self.variant}"


def _flat_seeds(cfg: HermesConfig, shapes, seed_tree) -> list:
    import jax

    want = jax.tree.structure(shapes)
    have = jax.tree.structure(seed_tree)
    if want != have:
        raise ValueError(
            "seed pytree no longer matches the engine state structure — "
            "a state field was added/renamed without declaring its bound "
            f"in analysis/seeds.py (engine {want}, seeds {have})")
    return jax.tree.leaves(seed_tree)


def variant_of(cfg: HermesConfig) -> str:
    if cfg.use_fused_sort:
        return "fused"
    return "split" if cfg.arb_mode == "sort" else "race"


def trace_program(cfg: HermesConfig, engine: str = "batched",
                  mesh=None) -> Program:
    import jax

    from hermes_tpu.core import compat
    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    if engine == "batched":
        n_local = None

        def fn(fs, stream, ctl):
            return fst.fast_round_batched(cfg, ctl, fs, stream)

        mesh_axes: Optional[dict] = {}
    elif engine == "sharded":
        from jax.sharding import Mesh
        import numpy as np

        if mesh is None:
            devs = jax.devices()
            if len(devs) < cfg.n_replicas:
                raise RuntimeError(
                    f"sharded analysis needs {cfg.n_replicas} devices, have "
                    f"{len(devs)} (force a CPU mesh with XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=N)")
            mesh = Mesh(np.array(devs[:cfg.n_replicas]), ("replica",))
        n_local = cfg.n_replicas
        mesh_axes = {name: int(size) for name, size in
                     dict(mesh.shape).items()}

        from jax.sharding import PartitionSpec as P

        rspec = P("replica")
        ctl_spec = fst.FastCtl(step=P(), my_cid=P(), epoch=rspec,
                               live_mask=rspec, frozen=rspec, quiesce=P())

        def shard_body(fs, stream, ctl):
            import jax.numpy as jnp

            my = jax.lax.axis_index("replica").astype(jnp.int32)
            lctl = fst.FastCtl(step=ctl.step, my_cid=my[None],
                               epoch=ctl.epoch, live_mask=ctl.live_mask,
                               frozen=ctl.frozen, quiesce=ctl.quiesce)
            return fst.fast_round_sharded(cfg, lctl, fs, stream)

        fn = compat.shard_map(shard_body, mesh=mesh,
                              in_specs=(rspec, rspec, ctl_spec),
                              out_specs=(rspec, rspec))
    else:
        raise ValueError(f"unknown engine {engine!r}")

    fs = jax.eval_shape(lambda: fst.init_fast_state(cfg, n_local=n_local))
    stream = jax.eval_shape(lambda: fst.prep_stream(ycsb.stub_stream(cfg)))
    ctl = jax.eval_shape(lambda: fst.make_fast_ctl(cfg, 0))
    closed = jax.make_jaxpr(fn)(fs, stream, ctl)

    seed_tree = seeds_lib.seed_round_args(cfg, has_uval=False)
    in_avs = _flat_seeds(cfg, (fs, stream, ctl), seed_tree)
    n_fs = len(jax.tree.leaves(fs))
    return Program(engine=engine, variant=variant_of(cfg),
                   closed_jaxpr=closed, in_avs=in_avs,
                   mesh_axes=mesh_axes,
                   # the scan builders donate the state pytree (leaves 0..n)
                   donated=frozenset(range(n_fs)), cfg=cfg)


def analyze_program(prog: Program, passes=None) -> dict:
    """Run the passes over one traced program.  Returns the report dict:
    findings (engine-stamped), proof counts, eqn count."""
    ps = passes if passes is not None else default_passes(
        allow_float=prog.cfg.device_stream)
    ctx = Ctx(cfg=prog.cfg, mesh_axes=prog.mesh_axes, passes=ps,
              donated=prog.donated)
    jaxpr = prog.closed_jaxpr.jaxpr
    eval_jaxpr(jaxpr, list(prog.in_avs), ctx,
               consts=list(prog.closed_jaxpr.consts))
    findings: List[Finding] = []
    proved = {}
    for p in ps:
        if isinstance(p, ScatterHazardPass):
            p.check_donation(ctx, jaxpr)
        p.finalize(ctx)
        for f in p.results():
            f.engine = prog.name
            findings.append(f)
        proved[p.name] = p.n_proved
    return dict(engine=prog.name, n_eqns=ctx.n_eqns, proved=proved,
                findings=findings)


def analyze_config(cfg: HermesConfig, engines=("batched", "sharded"),
                   variants: str = "both", mesh=None) -> List[dict]:
    """The shared driver: each engine x (as-configured + the split-sort
    A/B program when the config resolves the fused sort).  ``variants``:
    "both" | "as-is"."""
    cfgs = [cfg]
    if variants == "both" and cfg.use_fused_sort:
        # the split program is the A/B baseline for BOTH the fused sort
        # and the round-15 mega path, so the variant drops mega_round
        # too (a split mega config is not constructible — the mega route
        # consumes the fused sort's verdicts)
        cfgs.append(dataclasses.replace(cfg, fused_sort=False,
                                        mega_round=False))
    reports = []
    for engine in engines:
        for c in cfgs:
            prog = trace_program(c, engine, mesh=mesh)
            reports.append(analyze_program(prog))
    return reports
