"""Static host-concurrency lint (round-20): prove the threaded host tier
against the declarative guard registry in ``hermes_tpu/concurrency.py``.

One AST pass over the whole package (lexical, intra-procedural — the
same honesty contract as the jaxpr analyzer: what it cannot see it says
so about, in the rules below, rather than guessing):

  * **guarded-attr-unlocked** (error) — a read or write of a registry-
    guarded attribute outside ``with self.<lock>:`` in the declaring
    class (``__init__`` is exempt: pre-publication construction).
  * **blocking-under-lock** (error) — a blocking call (``sendall`` /
    ``recv`` / ``accept`` / ``fsync`` / ``sleep`` / ``Future.result`` /
    ``device_get`` / ``join`` / ``wait``) lexically inside a held-lock
    region — the PR-15 bug class (encode+send inside the frontend
    lock).  A ``BlockingAudit`` in the registry downgrades the one
    sanctioned site class to info, tag attached.
  * **lock-order-cycle** (error) — the nested-``with`` static held-
    before graph across ALL modules contains a cycle (the lexical twin
    of lockgraph.py's dynamic graph).
  * **undeclared-lock** / **unregistered-lock-class** (warn) — a bare
    ``threading.Lock()`` (or ``make_lock``/``RLock``) assigned on a
    class outside the registry, or a lock attribute the class's entry
    does not declare.
  * **daemon-thread-unowned** (warn) — a ``threading.Thread`` started
    from a class without a registered ``thread_owner`` + ``close()``
    deregistration, or from a function that never ``join``s it.
  * **undeclared-mutable-attr** (warn) — a registered class mutates an
    attribute outside ``__init__`` that is neither guarded nor audited
    (the registry must stay complete for the classes it covers).
  * **host-audited** (info) — every access under an ``audited(tag)``
    declaration: suppressions stay visible, never silent (the
    ``layouts.audited`` contract).

Lexical means: ``fe = self.fe`` aliasing and cross-function lock
threading are out of model; the registry documents those serialization
contracts as audited entries instead (Frontend's wildcard).

Findings reuse the passes.py schema/keys, export via the obs JSONL
schema, and gate via scripts/check_hostlint.py (HOSTLINT_BASELINE.json,
committed empty).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from hermes_tpu import concurrency as conc
from hermes_tpu.analysis.passes import ERROR, INFO, WARN, Finding

#: blocking callees (ISSUE-18 list + join/wait — same deadlock class)
BLOCKING_CALLS = frozenset({
    "sendall", "recv", "accept", "fsync", "sleep", "result",
    "device_get", "join", "wait"})

#: method names that mutate their receiver (list/dict/set/deque/queue)
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "clear", "add", "discard", "update", "setdefault",
    "put", "set", "sort"})

#: lock-constructor callees recognized by the bare-lock rule
LOCK_CTORS = frozenset({"Lock", "RLock", "make_lock", "ObsLock"})

CLOSERS = ("close", "stop", "shutdown")


def _split_fields(node) -> Tuple[list, list]:
    """Partition a statement's AST fields into (statement-bodies,
    expressions).  ``except``/``case`` wrappers are not ``ast.stmt``
    themselves but carry statement bodies — flattening them into the
    expression scan would lose ``with``-block tracking inside handlers
    (the pump loop's error path lives in one)."""
    body_fields: list = []
    exprs: list = []
    for _name, value in ast.iter_fields(node):
        if isinstance(value, list):
            for v in value:
                if isinstance(v, ast.stmt):
                    body_fields.append([v])
                elif isinstance(v, ast.ExceptHandler):
                    if v.type is not None:
                        exprs.append(v.type)
                    body_fields.append(v.body)
                elif v.__class__.__name__ == "match_case":
                    if v.guard is not None:
                        exprs.append(v.guard)
                    body_fields.append(v.body)
                elif isinstance(v, ast.AST):
                    exprs.append(v)
        elif isinstance(value, ast.AST):
            exprs.append(value)
    return body_fields, exprs


def _module_of(path: str, pkg_root: str) -> str:
    rel = os.path.relpath(path, os.path.dirname(pkg_root))
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _call_name(func) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _self_attr(node) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lockish(name: str) -> bool:
    return "lock" in name.lower()


class _Sink:
    """Finding aggregator: one record per stable key, counted."""

    def __init__(self):
        self._by_key: Dict[str, Finding] = {}
        self.n_with_sites = 0
        self.n_classes = 0
        self.n_threads = 0
        # static held-before graph: (a, b) -> first site "file:line in fn"
        self.edges: Dict[Tuple[str, str], str] = {}

    def add(self, f: Finding) -> None:
        have = self._by_key.get(f.key)
        if have is None:
            self._by_key[f.key] = f
        else:
            have.count += f.count

    def findings(self) -> List[Finding]:
        return sorted(self._by_key.values(),
                      key=lambda f: (f.file, f.line, f.code, f.op))


class _ClassLinter:
    def __init__(self, module: str, relfile: str,
                 entry: Optional[conc.ClassGuards], cls: ast.ClassDef,
                 sink: _Sink):
        self.module = module
        self.relfile = relfile
        self.entry = entry
        self.cls = cls
        self.sink = sink
        self.guard_of: Dict[str, str] = {}
        self.audit_of: Dict[str, str] = {}
        self.wildcard: Optional[str] = None
        if entry is not None:
            for g in entry.guards:
                for a in g.attrs:
                    self.guard_of[a] = g.lock
            for au in entry.audited:
                if au.attrs == ("*",):
                    self.wildcard = au.tag
                else:
                    for a in au.attrs:
                        self.audit_of[a] = au.tag
        self.methods = [n for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))]
        self.method_names = {m.name for m in self.methods}

    def _find(self, code: str, severity: str, message: str, *, fn: str,
              op: str, line: int, audit: Optional[str] = None,
              pass_name: str = "hostlint") -> None:
        self.sink.add(Finding(
            pass_name=pass_name, code=code, severity=severity,
            message=message, file=self.relfile, line=line, fn=fn, op=op,
            engine="host", audit=audit))

    # -- mutation discovery --------------------------------------------------

    def mutated_attrs(self) -> Dict[str, int]:
        """{attr: first line} mutated outside __init__ (assignment,
        aug-assign, subscript store, del, or a MUTATORS method call)."""
        out: Dict[str, int] = {}

        def note(attr, line):
            out.setdefault(attr, line)

        for m in self.methods:
            if m.name == "__init__":
                continue
            for node in ast.walk(m):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        self._note_target(tgt, note)
                elif isinstance(node, ast.AugAssign):
                    self._note_target(node.target, note)
                elif isinstance(node, ast.Delete):
                    for tgt in node.targets:
                        self._note_target(tgt, note)
                elif isinstance(node, ast.Call):
                    name = _call_name(node.func)
                    if (name in MUTATORS
                            and isinstance(node.func, ast.Attribute)):
                        attr = _self_attr(node.func.value)
                        if attr is not None:
                            note(attr, node.lineno)
        return out

    def _note_target(self, tgt, note) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._note_target(e, note)
            return
        if isinstance(tgt, (ast.Subscript, ast.Starred)):
            self._note_target(tgt.value, note)
            return
        attr = _self_attr(tgt)
        if attr is not None:
            note(attr, tgt.lineno)

    # -- the lexical walk ----------------------------------------------------

    def lock_id(self, expr) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None:
            if self.entry is not None and attr in self.entry.locks:
                return f"{self.cls.name}.{attr}"
            if _is_lockish(attr):
                return f"{self.cls.name}.{attr}"
            return None
        if isinstance(expr, ast.Name) and _is_lockish(expr.id):
            return f"{self.module}.{expr.id}"
        return None

    def run(self) -> None:
        self.sink.n_classes += 1
        mutated = self.mutated_attrs()
        for m in self.methods:
            self._walk_fn(m, held=())

        if self.entry is None:
            return
        # registry completeness over the class's mutable surface
        undeclared = {a: ln for a, ln in mutated.items()
                      if a not in self.guard_of and a not in self.audit_of}
        if self.wildcard is not None and undeclared:
            attrs = sorted(undeclared)
            self.sink.add(Finding(
                pass_name="hostlint", code="host-audited", severity=INFO,
                message=f"{len(attrs)} lock-free attribute(s) covered by "
                f"the class's wildcard audit: {', '.join(attrs)}",
                file=self.relfile, line=min(undeclared.values()),
                fn=self.cls.name, op="*", engine="host",
                audit=self.wildcard, count=len(attrs)))
        elif undeclared:
            for a, ln in sorted(undeclared.items()):
                self._find(
                    "undeclared-mutable-attr", WARN,
                    f"{self.cls.name}.{a} is mutated outside __init__ but "
                    f"the concurrency registry neither guards nor audits "
                    f"it — declare it in hermes_tpu/concurrency.py",
                    fn=self.cls.name, op=a, line=ln)

    def _walk_fn(self, fn, held: tuple) -> None:
        fn_label = f"{self.cls.name}.{fn.name}"
        in_init = fn.name == "__init__"
        self._walk_body(fn.body, held, fn_label, in_init)

    def _walk_body(self, stmts, held, fn_label, in_init) -> None:
        for node in stmts:
            self._walk_node(node, held, fn_label, in_init)

    def _walk_node(self, node, held, fn_label, in_init) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, possibly without the lock: fresh
            # lexical context
            self._walk_body(node.body, (), f"{fn_label}.{node.name}",
                            in_init)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = list(held)
            for item in node.items:
                lid = self.lock_id(item.context_expr)
                # the context expression itself evaluates UNLOCKED
                self._scan_exprs([item.context_expr], tuple(newly),
                                 fn_label, in_init, skip_lock=lid)
                if lid is not None:
                    self.sink.n_with_sites += 1
                    for h in newly:
                        if h != lid and (h, lid) not in self.sink.edges:
                            self.sink.edges[(h, lid)] = (
                                f"{self.relfile}:{node.lineno} in "
                                f"{fn_label}")
                    newly.append(lid)
            self._walk_body(node.body, tuple(newly), fn_label, in_init)
            return
        # compound statements: recurse into their bodies with the same
        # held set; scan their own expressions
        body_fields, exprs = _split_fields(node)
        self._scan_exprs(exprs, held, fn_label, in_init)
        for body in body_fields:
            self._walk_body(body, held, fn_label, in_init)

    def _scan_exprs(self, exprs, held, fn_label, in_init,
                    skip_lock=None) -> None:
        for root in exprs:
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    self._check_call(node, held, fn_label, in_init)
                attr = _self_attr(node)
                if attr is None:
                    continue
                if (self.entry is not None and attr in self.entry.locks
                        and f"{self.cls.name}.{attr}" == skip_lock):
                    continue
                self._check_access(attr, node.lineno, held, fn_label,
                                   in_init)

    def _check_access(self, attr, line, held, fn_label, in_init) -> None:
        if self.entry is None or in_init:
            return
        lock = self.guard_of.get(attr)
        if lock is not None:
            lid = f"{self.cls.name}.{lock}"
            if lid not in held:
                self._find(
                    "guarded-attr-unlocked", ERROR,
                    f"{self.cls.name}.{attr} is declared guarded by "
                    f"{lid} but accessed without it",
                    fn=fn_label, op=attr, line=line)
            return
        tag = self.audit_of.get(attr)
        if tag is not None:
            self._find(
                "host-audited", INFO,
                f"{self.cls.name}.{attr} accessed lock-free under an "
                f"audited declaration",
                fn=fn_label, op=attr, line=line, audit=tag)

    def _check_call(self, node, held, fn_label, in_init) -> None:
        name = _call_name(node.func)
        if name is None:
            return
        # thread-ownership rule
        if name == "Thread":
            self.sink.n_threads += 1
            owned = (self.entry is not None
                     and self.entry.thread_owner is not None
                     and any(c in self.method_names for c in CLOSERS))
            if not owned:
                self._find(
                    "daemon-thread-unowned", WARN,
                    f"{self.cls.name} starts threads but the registry "
                    f"declares no thread_owner (or the class has no "
                    f"{'/'.join(CLOSERS)} to deregister them)",
                    fn=fn_label, op="Thread", line=node.lineno,
                    pass_name="hostthreads")
        if not held:
            return
        if name in BLOCKING_CALLS:
            # sanctioned sites downgrade with the audit tag attached
            if self.entry is not None:
                for b in self.entry.blocking:
                    if (b.call == name
                            and f"{self.cls.name}.{b.lock}" in held):
                        self._find(
                            "blocking-under-lock-audited", INFO,
                            f"audited blocking call {name}() under "
                            f"{self.cls.name}.{b.lock}",
                            fn=fn_label, op=name, line=node.lineno,
                            audit=b.tag)
                        return
            self._find(
                "blocking-under-lock", ERROR,
                f"blocking call {name}() while holding "
                f"{', '.join(held)} — a stalled peer (or a slow device "
                f"sync) extends the critical section unboundedly",
                fn=fn_label, op=name, line=node.lineno)


def _lint_bare_locks(module, relfile, cls, entry, sink: _Sink) -> None:
    """threading.Lock() assigned on a class the registry doesn't cover
    (or to an attribute its entry doesn't declare) — warn."""
    for m in (n for n in cls.body
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))):
        for node in ast.walk(m):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            if _call_name(node.value.func) not in LOCK_CTORS:
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if entry is None:
                    sink.add(Finding(
                        pass_name="hostlint", code="unregistered-lock-class",
                        severity=WARN, engine="host", file=relfile,
                        line=node.lineno, fn=f"{cls.name}.{m.name}",
                        op=attr,
                        message=f"{cls.name} creates lock {attr!r} but "
                        f"has no entry in the concurrency registry "
                        f"(hermes_tpu/concurrency.py) — declare its "
                        f"guards or audit it"))
                elif attr not in entry.locks:
                    sink.add(Finding(
                        pass_name="hostlint", code="undeclared-lock",
                        severity=WARN, engine="host", file=relfile,
                        line=node.lineno, fn=f"{cls.name}.{m.name}",
                        op=attr,
                        message=f"{cls.name}.{attr} is a lock the "
                        f"registry entry does not declare in its "
                        f"``locks`` tuple"))


def _lint_function_threads(module, relfile, fn, sink: _Sink,
                           prefix: str = "") -> None:
    """Module-level function rule: a created Thread must be joined in
    the same function (lexically) or it leaks past its owner."""
    label = f"{prefix}{fn.name}"
    makes = [n for n in ast.walk(fn)
             if isinstance(n, ast.Call) and _call_name(n.func) == "Thread"]
    if not makes:
        return
    sink.n_threads += len(makes)
    joins = any(isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                for n in ast.walk(fn))
    if not joins:
        for n in makes:
            sink.add(Finding(
                pass_name="hostthreads", code="daemon-thread-unowned",
                severity=WARN, engine="host", file=relfile,
                line=n.lineno, fn=label, op="Thread",
                message=f"function {label} starts a thread it never "
                f"joins — the thread outlives its owner with no "
                f"deregistration path"))


def _lint_tree(tree: ast.AST, module: str, relfile: str,
               reg: dict, sink: _Sink, seen_classes: set) -> None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            entry = reg.get((module, node.name))
            if entry is not None:
                seen_classes.add((module, node.name))
            _ClassLinter(module, relfile, entry, node, sink).run()
            _lint_bare_locks(module, relfile, node, entry, sink)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _lint_function_threads(module, relfile, node, sink)
            # module-level functions may also nest with-locks
            _FnOrderScan(module, relfile, node, sink).run()


class _FnOrderScan:
    """Order-graph (+ blocking) scan for module-level functions — same
    lexical rules, no registry entry (self-less)."""

    def __init__(self, module, relfile, fn, sink: _Sink):
        self.module = module
        self.relfile = relfile
        self.fn = fn
        self.sink = sink

    def run(self) -> None:
        self._walk(self.fn.body, ())

    def _lock_id(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Name) and _is_lockish(expr.id):
            return f"{self.module}.{expr.id}"
        return None

    def _walk(self, stmts, held) -> None:
        for node in stmts:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                newly = list(held)
                for item in node.items:
                    lid = self._lock_id(item.context_expr)
                    if lid is not None:
                        self.sink.n_with_sites += 1
                        for h in newly:
                            if h != lid and (h, lid) not in self.sink.edges:
                                self.sink.edges[(h, lid)] = (
                                    f"{self.relfile}:{node.lineno} in "
                                    f"{self.fn.name}")
                        newly.append(lid)
                self._walk(node.body, tuple(newly))
                continue
            body_fields, exprs = _split_fields(node)
            if held:
                for root in exprs:
                    for sub in ast.walk(root):
                        if (isinstance(sub, ast.Call)
                                and _call_name(sub.func)
                                in BLOCKING_CALLS):
                            self.sink.add(Finding(
                                pass_name="hostlint",
                                code="blocking-under-lock",
                                severity=ERROR, engine="host",
                                file=self.relfile, line=sub.lineno,
                                fn=self.fn.name,
                                op=_call_name(sub.func),
                                message=f"blocking call "
                                f"{_call_name(sub.func)}() while "
                                f"holding {', '.join(held)}"))
            for body in body_fields:
                self._walk(body, held)


def _cycle_findings(sink: _Sink) -> List[Finding]:
    adj: Dict[str, list] = {}
    for a, b in sink.edges:
        adj.setdefault(a, []).append(b)
    out: List[Finding] = []
    seen = set()

    def dfs(node, path, on_path):
        for nxt in adj.get(node, ()):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                canon = tuple(sorted(cyc))
                if canon in seen:
                    continue
                seen.add(canon)
                ring = cyc + cyc[:1]
                sites = [sink.edges.get((x, y), "?")
                         for x, y in zip(ring, ring[1:])
                         if (x, y) in sink.edges]
                out.append(Finding(
                    pass_name="hostlint", code="lock-order-cycle",
                    severity=ERROR, engine="host",
                    file=sites[0].split(":")[0] if sites else "<unknown>",
                    fn="static", op="->".join(cyc),
                    message=f"static lock-order cycle "
                    f"{' -> '.join(cyc)} -> {cyc[0]} (acquisition "
                    f"sites: {'; '.join(sites)})"))
                continue
            path.append(nxt)
            on_path.add(nxt)
            dfs(nxt, path, on_path)
            on_path.discard(nxt)
            path.pop()

    for start in sorted(adj):
        dfs(start, [start], {start})
    return out


def lint_source(src: str, module: str, relfile: str = "<mem>",
                registry: Optional[tuple] = None) -> List[Finding]:
    """Lint one module's SOURCE (tests, gate red-mutations).  ``module``
    selects which registry entries apply."""
    reg = conc.by_class(registry if registry is not None
                        else conc.REGISTRY)
    sink = _Sink()
    _lint_tree(ast.parse(src), module, relfile, reg, sink, set())
    return sink.findings() + _cycle_findings(sink)


def lint_package(root: Optional[str] = None,
                 registry: Optional[tuple] = None) -> dict:
    """Lint every module under ``root`` (default: the installed
    hermes_tpu package).  Returns one report dict in the analyzer's
    reports currency (engine/n_eqns/proved/findings) so key_counts /
    diff_baseline / export_findings apply unchanged."""
    if root is None:
        import hermes_tpu

        root = os.path.dirname(os.path.abspath(hermes_tpu.__file__))
    reg = conc.by_class(registry if registry is not None
                        else conc.REGISTRY)
    sink = _Sink()
    seen_classes: set = set()
    n_files = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            relfile = os.path.relpath(path, os.path.dirname(root))
            module = _module_of(path, root)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=relfile)
            except SyntaxError as e:
                sink.add(Finding(
                    pass_name="hostlint", code="unparseable",
                    severity=ERROR, engine="host", file=relfile,
                    line=e.lineno or 0, fn="<module>", op="parse",
                    message=f"cannot parse: {e.msg}"))
                continue
            n_files += 1
            _lint_tree(tree, module, relfile, reg, sink, seen_classes)
    # registry completeness the other way: stale entries rot silently
    for (module, cls), _entry in sorted(reg.items()):
        if (module, cls) not in seen_classes and module.startswith(
                os.path.basename(root)):
            sink.add(Finding(
                pass_name="hostlint", code="registry-stale-entry",
                severity=WARN, engine="host", file="<registry>",
                fn=cls, op=module,
                message=f"concurrency registry entry {module}.{cls} "
                f"matches no class in the package (renamed or removed?)"))
    findings = sink.findings() + _cycle_findings(sink)
    return dict(
        engine="host",
        n_eqns=n_files,
        proved=dict(files=n_files, classes=sink.n_classes,
                    registered=len(seen_classes),
                    with_sites=sink.n_with_sites,
                    lock_edges=len(sink.edges),
                    threads=sink.n_threads),
        findings=findings,
    )
