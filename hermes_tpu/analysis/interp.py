"""Abstract interpreter over closed jaxprs (the analyzer's engine).

Walks a traced protocol round equation by equation, propagating one
``domain.AbsVal`` per array through ~50 primitive transfer rules, and
invokes the registered analysis passes on every equation with the
computed operand/result abstractions plus an ``wrapped`` overflow flag.
Higher-order primitives recurse: ``pjit``/``closed_call`` bodies inline,
``cond`` branches join, ``scan``/``while`` carries run a small widening
loop, ``shard_map`` pushes its mesh's axis sizes (for ``axis_index`` and
the sharding-consistency pass).  ``pallas_call`` bodies are interpreted
by the kernel sub-interpreter (analysis/pallas.py): Refs map to abstract
cells, the state primitives (get/swap/addupdate) get transfer rules, and
the Ref discipline is policed by ``RefHazardPass`` — a kernel the model
cannot express falls back to dtype-TOP outputs plus a ``pallas-skipped``
info finding (never a silent skip).

Unknown primitives are sound by construction: outputs default to the
dtype's full range.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

import numpy as np

from hermes_tpu.analysis import domain as D
from hermes_tpu.analysis.domain import AbsVal
from hermes_tpu.core.layouts import AUDIT_PREFIX

_AUDIT_RE = re.compile(re.escape(AUDIT_PREFIX) + r"\[([^\]]+)\]")


def _jaxpr_types():
    from jax.extend.core import ClosedJaxpr, Jaxpr

    return Jaxpr, ClosedJaxpr


def eqn_site(eqn) -> tuple:
    """(file, line, function) of the closest user frame — for the engines
    that is the hermes_tpu call site that built the op."""
    try:
        import jax._src.source_info_util as siu

        fr = siu.user_frame(eqn.source_info)
        if fr is None:
            return ("<unknown>", 0, "<unknown>")
        fname = fr.file_name
        for root in ("hermes_tpu/", "tests/", "scripts/"):
            i = fname.rfind(root)
            if i >= 0:
                fname = fname[i:]
                break
        else:
            fname = fname.rsplit("/", 1)[-1]
        return (fname, int(fr.start_line), fr.function_name)
    except Exception:
        return ("<unknown>", 0, "<unknown>")


def eqn_audit(eqn) -> Optional[str]:
    """The ``layouts.audited(tag)`` annotation covering this equation, if
    any (the tag rides the jaxpr name stack)."""
    try:
        m = _AUDIT_RE.search(str(eqn.source_info.name_stack))
        return m.group(1) if m else None
    except Exception:
        return None


class Ctx:
    """Interpreter context shared with the passes."""

    def __init__(self, cfg=None, mesh_axes=None, passes=(), donated=None):
        self.cfg = cfg
        #: declared mesh axes {name: size}; {} = batched (no collectives
        #: allowed); None = don't check
        self.mesh_axes = mesh_axes
        self.passes = list(passes)
        self.axis_sizes: Dict[str, int] = {}  # live axis env (shard_map)
        self.defs: Dict = {}  # Var -> defining eqn
        self.env: Dict = {}  # Var -> AbsVal (flat across nesting)
        #: Var -> Var/Literal across call boundaries (a pjit's outvar IS
        #: its body's outvar; a body invar IS the caller's operand) — what
        #: lets resolve() see the select_n inside a jnp.where wrapper
        self.aliases: Dict = {}
        self.donated = set(donated or ())
        self.n_eqns = 0

    # -- dataflow helpers for passes --------------------------------------
    def canon(self, atom):
        from jax.extend.core import Literal

        seen = 0
        while (not isinstance(atom, Literal) and atom in self.aliases
               and seen < 256):
            atom = self.aliases[atom]
            seen += 1
        return atom

    def aval_of(self, atom) -> AbsVal:
        from jax.extend.core import Literal

        if isinstance(atom, Literal):
            return D.from_concrete(atom.val)
        if atom in self.env:
            return self.env[atom]
        atom = self.canon(atom)
        if isinstance(atom, Literal):
            return D.from_concrete(atom.val)
        return self.env.get(atom, D.top(atom.aval.dtype))

    def def_of(self, atom):
        from jax.extend.core import Literal

        atom = self.canon(atom)
        if isinstance(atom, Literal):
            return None
        return self.defs.get(atom)

    _TRANSPARENT = ("broadcast_in_dim", "reshape", "squeeze", "transpose",
                    "copy", "rev", "convert_element_type", "stop_gradient")

    def resolve(self, atom):
        """Skip through shape/dtype-transparent defs to the value-defining
        equation (None for inputs/literals)."""
        seen = 0
        while True:
            e = self.def_of(atom)
            if e is None or e.primitive.name not in self._TRANSPARENT:
                return e
            atom = e.invars[0]
            seen += 1
            if seen > 64:
                return e

    def is_const_like(self, atom) -> bool:
        """Literal, constant abstract value, or a select over const-like
        cases (the ``where(flag, CONST, 0)`` pack idiom)."""
        av = self.aval_of(atom)
        if av.is_const:
            return True
        e = self.resolve(atom)
        if e is None:
            return self.def_of(atom) is None and av.is_const
        if e.primitive.name == "select_n":
            return all(self.is_const_like(a) for a in e.invars[1:])
        return False


# --------------------------------------------------------------------------
# Primitive transfer rules
# --------------------------------------------------------------------------

RULES: Dict[str, Callable] = {}


def rule(*names):
    def deco(fn):
        for n in names:
            RULES[n] = fn
        return fn

    return deco


def _bool_out(eqn, ins, ctx):
    return [D.iv(0, 1)]


for _n in ("reduce_or", "reduce_and", "is_finite"):
    RULES[_n] = _bool_out


def _cmp_rule(decide):
    """Comparisons refine to a constant when the intervals decide them
    — what makes ``pl.when(blk == 0)`` path-sensitive on the kernel's
    first visit (blk pinned to 0 ⇒ pred provably 1)."""

    def fn(eqn, ins, ctx):
        if len(ins) == 2:
            r = decide(ins[0], ins[1])
            if r is not None:
                return [D.iv(r)]
        return [D.iv(0, 1)]

    return fn


RULES["eq"] = _cmp_rule(
    lambda a, b: 1 if (a.is_const and b.is_const and a.lo == b.lo)
    else (0 if (a.hi < b.lo or b.hi < a.lo) else None))
RULES["ne"] = _cmp_rule(
    lambda a, b: 0 if (a.is_const and b.is_const and a.lo == b.lo)
    else (1 if (a.hi < b.lo or b.hi < a.lo) else None))
RULES["lt"] = _cmp_rule(
    lambda a, b: 1 if a.hi < b.lo else (0 if a.lo >= b.hi else None))
RULES["le"] = _cmp_rule(
    lambda a, b: 1 if a.hi <= b.lo else (0 if a.lo > b.hi else None))
RULES["gt"] = _cmp_rule(
    lambda a, b: 1 if a.lo > b.hi else (0 if a.hi <= b.lo else None))
RULES["ge"] = _cmp_rule(
    lambda a, b: 1 if a.lo >= b.hi else (0 if a.hi < b.lo else None))


@rule("add")
def _(eqn, ins, ctx):
    return [D.add(ins[0], ins[1])]


@rule("sub")
def _(eqn, ins, ctx):
    return [D.sub(ins[0], ins[1])]


@rule("mul")
def _(eqn, ins, ctx):
    return [D.mul(ins[0], ins[1])]


@rule("neg")
def _(eqn, ins, ctx):
    return [D.neg(ins[0])]


@rule("max")
def _(eqn, ins, ctx):
    return [D.max_(ins[0], ins[1])]


@rule("min")
def _(eqn, ins, ctx):
    return [D.min_(ins[0], ins[1])]


@rule("and")
def _(eqn, ins, ctx):
    return [D.and_(ins[0], ins[1])]


@rule("or")
def _(eqn, ins, ctx):
    return [D.or_(ins[0], ins[1])]


@rule("xor")
def _(eqn, ins, ctx):
    return [D.xor(ins[0], ins[1])]


@rule("not")
def _(eqn, ins, ctx):
    if D.is_bool(eqn.outvars[0].aval.dtype):
        a = ins[0]
        return [D.AbsVal(1 - min(a.hi, 1), 1 - max(a.lo, 0))]
    return [D.not_(ins[0])]


@rule("shift_left")
def _(eqn, ins, ctx):
    return [D.shl(ins[0], ins[1])]


@rule("shift_right_arithmetic")
def _(eqn, ins, ctx):
    return [D.shr_arith(ins[0], ins[1])]


@rule("shift_right_logical")
def _(eqn, ins, ctx):
    nbits = D.dtype_bits(eqn.invars[0].aval.dtype)
    return [D.shr_logical(ins[0], ins[1], nbits)]


@rule("rem")
def _(eqn, ins, ctx):
    return [D.rem(ins[0], ins[1])]


@rule("div")
def _(eqn, ins, ctx):
    if D.is_int(eqn.outvars[0].aval.dtype):
        return [D.div(ins[0], ins[1])]
    return [D.top(eqn.outvars[0].aval.dtype)]


@rule("abs")
def _(eqn, ins, ctx):
    return [D.abs_(ins[0])]


@rule("sign")
def _(eqn, ins, ctx):
    return [D.iv(-1, 1)]


@rule("clamp")
def _(eqn, ins, ctx):
    return [D.clamp3(ins[0], ins[1], ins[2])]


def _base_atom(ctx, atom):
    """Walk transparent defs (broadcast/reshape/convert/...) to the
    underlying canonical atom, for identity comparisons."""
    seen = 0
    while True:
        e = ctx.def_of(atom)
        if e is None or e.primitive.name not in Ctx._TRANSPARENT:
            return ctx.canon(atom)
        atom = e.invars[0]
        seen += 1
        if seen > 64:
            return ctx.canon(atom)


def _refine_neg_index_select(eqn, ins, ctx):
    """Path-sensitive refinement for jnp's negative-index normalization
    ``select(x < 0, x + N, x)``: the joined hull [x.lo, x.hi + N] would
    flag every basic-indexing gather as possibly OOB; splitting on the
    guard gives the exact [0, N) bound the idiom guarantees."""
    if len(eqn.invars) != 3:
        return None
    pred = ctx.resolve(eqn.invars[0])
    if pred is None or pred.primitive.name != "lt":
        return None
    zav = ctx.aval_of(pred.invars[1])
    if not (zav.is_const and zav.lo == 0):
        return None
    x_base = _base_atom(ctx, pred.invars[0])
    xav = ctx.aval_of(pred.invars[0])
    # false case must be x itself; true case must be x + const
    if _base_atom(ctx, eqn.invars[1]) is not x_base:
        return None
    t_eqn = ctx.resolve(eqn.invars[2])
    if t_eqn is None or t_eqn.primitive.name != "add":
        return None
    n_av = None
    for a, b in (t_eqn.invars, reversed(t_eqn.invars)):
        if _base_atom(ctx, a) is x_base and ctx.aval_of(b).is_const:
            n_av = ctx.aval_of(b)
            break
    if n_av is None:
        return None
    n = n_av.lo
    cases = []
    if xav.hi >= 0:  # pred-false branch feasible: x >= 0
        cases.append(AbsVal(max(0, xav.lo), xav.hi))
    if xav.lo < 0:  # pred-true branch feasible: x < 0, shifted by N
        cases.append(AbsVal(xav.lo + n, min(-1, xav.hi) + n))
    return D.join_all(cases) if cases else None


@rule("select_n")
def _(eqn, ins, ctx):
    refined = _refine_neg_index_select(eqn, ins, ctx)
    if refined is not None:
        return [refined]
    return [D.join_all(ins[1:])]


@rule("broadcast_in_dim", "reshape", "squeeze", "transpose", "copy", "rev",
      "stop_gradient", "reduce_precision", "slice", "dynamic_slice",
      "reduce_max", "reduce_min", "cummax", "cummin", "real",
      "optimization_barrier", "all_gather", "all_to_all", "pmax", "pmin",
      "ppermute", "expand_dims")
def _passthrough(eqn, ins, ctx):
    return [ins[0] for _ in eqn.outvars]


@rule("convert_element_type")
def _(eqn, ins, ctx):
    # raw value unchanged; the dtype clamp downstream decides wrap
    return [ins[0]]


@rule("bitcast_convert_type")
def _(eqn, ins, ctx):
    # explicit reinterpret: value-preserving when it happens to fit,
    # dtype-TOP otherwise — never reported as an implicit wrap
    out_dtype = eqn.outvars[0].aval.dtype
    av, wrapped = D.clamp(ins[0], out_dtype)
    return [av if not wrapped else D.top(out_dtype)]


@rule("iota")
def _(eqn, ins, ctx):
    shape = eqn.outvars[0].aval.shape
    dim = eqn.params.get("dimension", 0)
    n = shape[dim] if shape else 1
    return [D.iv(0, max(0, n - 1))]


@rule("concatenate")
def _(eqn, ins, ctx):
    return [D.join_all(ins)]


@rule("pad")
def _(eqn, ins, ctx):
    return [D.join(ins[0], ins[1])]


@rule("gather")
def _(eqn, ins, ctx):
    # OOB indices fill (default 0) or clamp — join keeps it sound
    return [D.join(ins[0], D.iv(0))]


@rule("scatter", "scatter-max", "scatter-min")
def _(eqn, ins, ctx):
    return [D.join(ins[0], ins[2] if len(ins) > 2 else ins[-1])]


@rule("scatter-add", "scatter-mul")
def _(eqn, ins, ctx):
    upd = ins[2] if len(ins) > 2 else ins[-1]
    n = max(1, int(np.prod(eqn.invars[-1].aval.shape or (1,))))
    return [D.join(ins[0], D.add(ins[0], D.sum_n(upd, n)))]


@rule("dynamic_update_slice")
def _(eqn, ins, ctx):
    return [D.join(ins[0], ins[1])]


@rule("reduce_sum")
def _(eqn, ins, ctx):
    axes = eqn.params.get("axes", ())
    shape = eqn.invars[0].aval.shape
    n = 1
    for a in axes:
        n *= shape[a]
    return [D.sum_n(ins[0], n)]


@rule("cumsum")
def _(eqn, ins, ctx):
    axis = eqn.params.get("axis", 0)
    n = eqn.invars[0].aval.shape[axis] if eqn.invars[0].aval.shape else 1
    return [D.prefix_sums(ins[0], n)]


@rule("argmax", "argmin")
def _(eqn, ins, ctx):
    axes = eqn.params.get("axes", (0,))
    shape = eqn.invars[0].aval.shape
    n = shape[axes[0]] if shape else 1
    return [D.iv(0, max(0, n - 1))]


@rule("sort")
def _(eqn, ins, ctx):
    # a joint sort permutes every operand identically: value sets (and
    # therefore bounds) are preserved per operand
    return list(ins)


@rule("top_k")
def _(eqn, ins, ctx):
    shape = eqn.invars[0].aval.shape
    n = shape[-1] if shape else 1
    return [ins[0], D.iv(0, max(0, n - 1))]


@rule("axis_index")
def _(eqn, ins, ctx):
    name = eqn.params.get("axis_name")
    size = ctx.axis_sizes.get(name)
    if size is None:
        return [D.top(eqn.outvars[0].aval.dtype)]
    return [D.iv(0, max(0, size - 1))]


@rule("psum", "psum2")
def _(eqn, ins, ctx):
    axes = eqn.params.get("axes", ())
    n = 1
    for a in axes:
        if isinstance(a, str):
            n *= ctx.axis_sizes.get(a, 1)
    return [D.sum_n(x, n) for x in ins]


# --------------------------------------------------------------------------
# The walk
# --------------------------------------------------------------------------

_CALL_JAXPR_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "named_call": "call_jaxpr",
    "remat2": "jaxpr",
    "checkpoint": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
}

def _as_open(j):
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    if isinstance(j, ClosedJaxpr):
        return j.jaxpr, list(j.consts)
    return j, []


def eval_jaxpr(jaxpr, in_avs: List[AbsVal], ctx: Ctx,
               consts: Optional[list] = None) -> List[AbsVal]:
    env = ctx.env
    for v, c in zip(jaxpr.constvars, consts or []):
        env[v] = D.from_concrete(c)
    for v, av in zip(jaxpr.invars, in_avs):
        env[v] = av
    for eqn in jaxpr.eqns:
        ctx.n_eqns += 1
        ins = [ctx.aval_of(a) for a in eqn.invars]
        outs, wrapped = _eval_eqn(eqn, ins, ctx)
        for p in ctx.passes:
            p.on_eqn(ctx, eqn, ins, outs, wrapped)
        for v, av in zip(eqn.outvars, outs):
            env[v] = av
            ctx.defs[v] = eqn
    return [ctx.aval_of(a) for a in jaxpr.outvars]


def _eval_eqn(eqn, ins, ctx):
    name = eqn.primitive.name
    if name == "pallas_call":
        from hermes_tpu.analysis import pallas as pallas_mod

        return pallas_mod.eval_pallas_call(eqn, ins, ctx), False
    if name == "shard_map":
        return _eval_shard_map(eqn, ins, ctx), False
    if name == "cond":
        return _eval_cond(eqn, ins, ctx), False
    if name == "while":
        return _eval_while(eqn, ins, ctx), False
    if name == "scan":
        return _eval_scan(eqn, ins, ctx), False
    if name in _CALL_JAXPR_PRIMS:
        inner = eqn.params.get(_CALL_JAXPR_PRIMS[name])
        if inner is not None:
            j, consts = _as_open(inner)
            for inner_v, outer_a in zip(j.invars, eqn.invars):
                ctx.aliases[inner_v] = outer_a
            outs = eval_jaxpr(j, list(ins), ctx, consts)
            for outer_v, inner_a in zip(eqn.outvars, j.outvars):
                ctx.aliases[outer_v] = inner_a
            return _refine_named_call(eqn, ins, outs, ctx), False

    fn = RULES.get(name)
    if fn is None:
        return [D.top(v.aval.dtype) for v in eqn.outvars], False
    raw = fn(eqn, ins, ctx)
    outs, wrapped = [], False
    for v, av in zip(eqn.outvars, raw):
        c, w = D.clamp(av, v.aval.dtype)
        outs.append(c)
        wrapped = wrapped or w
    return outs, wrapped


def _refine_named_call(eqn, ins, outs, ctx):
    """Contract-based refinement for jnp ops that lower as named pjit
    wrappers.  ``jnp.remainder``/``jnp.mod`` build floor-mod from
    trunc-rem plus a sign-fix select whose abstract join spans
    [-(y-1), 2y-1]; the OP's contract for a positive divisor is [0, y-1],
    which is what makes ``(key + rot) % n`` provably in-bounds."""
    if eqn.params.get("name") in ("remainder", "mod") and len(ins) == 2:
        b = ins[1]
        if b.lo > 0 and len(outs) == 1:
            m = b.hi - 1
            o = outs[0]
            return [AbsVal(max(0, min(o.lo, m)), max(0, min(o.hi, m)))]
    return outs


def _eval_shard_map(eqn, ins, ctx):
    mesh = eqn.params.get("mesh")
    saved = dict(ctx.axis_sizes)
    try:
        if mesh is not None:
            for name, size in dict(mesh.shape).items():
                ctx.axis_sizes[name] = int(size)
        j, consts = _as_open(eqn.params["jaxpr"])
        for inner_v, outer_a in zip(j.invars, eqn.invars):
            ctx.aliases[inner_v] = outer_a
        outs = eval_jaxpr(j, list(ins), ctx, consts)
        for outer_v, inner_a in zip(eqn.outvars, j.outvars):
            ctx.aliases[outer_v] = inner_a
        return outs
    finally:
        ctx.axis_sizes = saved


def _eval_cond(eqn, ins, ctx):
    outs = None
    for br in eqn.params["branches"]:
        j, consts = _as_open(br)
        o = eval_jaxpr(j, list(ins[1:]), ctx, consts)
        outs = o if outs is None else [D.join(a, b) for a, b in zip(outs, o)]
    return outs


def _widen_loop(body_fn, init: List[AbsVal], max_iter: int = 3):
    """Small widening loop for scan/while carries: join until stable,
    then give unstable elements dtype-free TOP-ish bounds via join."""
    carry = list(init)
    last = None
    for _ in range(max_iter):
        out = body_fn(carry)
        nxt = [D.join(c, o) for c, o in zip(carry, out)]
        if last is not None and all(
                n.lo == c.lo and n.hi == c.hi for n, c in zip(nxt, carry)):
            return nxt, out
        last = carry
        carry = nxt
    # not stabilized: widen hard
    widened = []
    for c, i in zip(carry, init):
        widened.append(AbsVal(min(c.lo, -(1 << 63)), max(c.hi, 1 << 63))
                       if not (c.lo == i.lo and c.hi == i.hi) else c)
    out = body_fn(widened)
    return widened, out


def _eval_scan(eqn, ins, ctx):
    nc = eqn.params.get("num_consts", 0)
    ncar = eqn.params.get("num_carry", 0)
    j, jconsts = _as_open(eqn.params["jaxpr"])
    consts, init, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]

    ys_box = []

    def body(carry):
        o = eval_jaxpr(j, consts + carry + xs, ctx, jconsts)
        ys_box[:] = o[ncar:]
        return o[:ncar]

    carry, _last = _widen_loop(body, list(init))
    outs = carry + list(ys_box)
    # clamp everything back to the declared out dtypes
    return [D.clamp(a, v.aval.dtype)[0] for a, v in zip(outs, eqn.outvars)]


def _eval_while(eqn, ins, ctx):
    cn = eqn.params.get("cond_nconsts", 0)
    bn = eqn.params.get("body_nconsts", 0)
    bj, bconsts = _as_open(eqn.params["body_jaxpr"])
    cconsts_avs = ins[:cn]
    bconsts_avs = ins[cn:cn + bn]
    init = ins[cn + bn:]

    def body(carry):
        return eval_jaxpr(bj, bconsts_avs + carry, ctx, bconsts)

    carry, _ = _widen_loop(body, list(init))
    del cconsts_avs
    return [D.clamp(a, v.aval.dtype)[0] for a, v in zip(carry, eqn.outvars)]
