"""The analyzer's pluggable passes and their finding records.

Five passes ship (ISSUE 3 + the ISSUE-8 kernel pass):

  * ``BitPackPass`` — every shift/or pack in the traced round must be
    overlap-free and sign-safe under the config-seeded bounds.  A pack
    site is an ``or`` whose operand is a shift result or a constant-like
    mask (the ``key | where(flag, BIT, 0)`` idiom); plain bitmap unions
    (ack aggregation) are not pack sites and are never flagged.
  * ``DtypePromotionPass`` — no silent 64-bit widening, no floats in an
    integer round, and every integer convert must be value-preserving
    under the seeded bounds (a wrapping convert must be an explicit
    same-width ``bitcast_convert_type`` — see faststep's byte codec).
  * ``ScatterHazardPass`` — set-scatters need injectivity evidence
    (``unique_indices=True``, or a ``layouts.audited`` justification for
    protocol-invariant uniqueness); commutative scatters (max/min) are
    exempt.  Donated buffers must have an aliasable output.
  * ``ShardingConsistencyPass`` — collectives name declared mesh axes
    with matching sizes, shard_map meshes agree with the engine's
    declaration, batched programs contain no collectives at all.
  * ``RefHazardPass`` — kernel Ref discipline inside ``pallas_call``
    bodies (populated by the sub-interpreter, analysis/pallas.py):
    every load/store in-bounds against the block shape, no
    read-before-init, BlockSpec index maps inside the operand,
    grid-revisit accumulators declared via ``layouts.audited``; a
    kernel the sub-interpreter cannot model emits ``pallas-skipped``
    (info) naming what defeated it instead of a silent TOP.

Severity contract (the CI gate, scripts/check_analysis.py):

  * ``error``  — a violation provable from config-seeded facts; fails the
    gate unless explicitly grandfathered in ANALYSIS_BASELINE.json.
  * ``warn``   — a structural hazard the analyzer cannot discharge; fails
    the gate unless baselined.
  * ``info``   — a discharged assumption (audited sites, annotation
    trusts): never gates, always listed, so suppressions stay visible.

Findings inside a ``layouts.audited(tag)`` scope are downgraded to info
and carry the tag — the audit is the documented proof obligation.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from hermes_tpu.analysis import domain as D
from hermes_tpu.analysis.interp import Ctx, eqn_audit, eqn_site

ERROR, WARN, INFO = "error", "warn", "info"
_SEV_RANK = {ERROR: 2, WARN: 1, INFO: 0}


@dataclasses.dataclass
class Finding:
    """One analyzer fact, keyed stably for baseline matching (the key
    excludes the line number so a pure-motion refactor does not churn
    ANALYSIS_BASELINE.json; ``--update`` handles intentional changes)."""

    pass_name: str
    code: str
    severity: str
    message: str
    file: str = "<unknown>"
    line: int = 0
    fn: str = "<unknown>"
    op: str = ""
    engine: str = ""
    audit: Optional[str] = None
    count: int = 1

    @property
    def key(self) -> str:
        return "|".join((self.engine, self.pass_name, self.code, self.file,
                         self.fn, self.op))

    @property
    def site(self) -> str:
        return f"{self.file}:{self.line}"

    def record(self) -> dict:
        """Obs run-log JSONL payload (kind="analysis")."""
        return dict(record="finding", pass_=self.pass_name, code=self.code,
                    severity=self.severity, engine=self.engine,
                    site=self.site, fn=self.fn, op=self.op, audit=self.audit,
                    count=self.count, message=self.message, key=self.key)


class Pass:
    """Base: dedups findings by (code, site, op) and counts proof sites."""

    name = "pass"

    def __init__(self):
        self.findings: Dict[tuple, Finding] = {}
        self.n_proved = 0

    def on_eqn(self, ctx: Ctx, eqn, ins, outs, wrapped) -> None:
        pass

    def finalize(self, ctx: Ctx) -> None:
        pass

    def emit(self, eqn, code: str, severity: str, message: str) -> None:
        file, line, fn = eqn_site(eqn)
        audit = eqn_audit(eqn)
        if audit is not None and severity != INFO:
            message = f"audited[{audit}]: {message}"
            severity = INFO
        k = (code, file, line, fn, eqn.primitive.name, audit)
        f = self.findings.get(k)
        if f is None:
            self.findings[k] = Finding(
                pass_name=self.name, code=code, severity=severity,
                message=message, file=file, line=line, fn=fn,
                op=eqn.primitive.name, audit=audit)
        else:
            f.count += 1

    def results(self) -> List[Finding]:
        return sorted(self.findings.values(),
                      key=lambda f: (-_SEV_RANK[f.severity], f.file, f.line))


# --------------------------------------------------------------------------
# 1. bit-pack interval analysis
# --------------------------------------------------------------------------


class BitPackPass(Pass):
    name = "bitpack"

    def _is_pack_operand(self, ctx: Ctx, atom) -> bool:
        e = ctx.resolve(atom)
        # a shift result, a previous pack (chained `a | b | c`), or a
        # constant-like mask makes the `or` a field pack
        if e is not None and e.primitive.name in ("shift_left", "or"):
            return True
        return ctx.is_const_like(atom)

    def on_eqn(self, ctx: Ctx, eqn, ins, outs, wrapped) -> None:
        name = eqn.primitive.name
        if name == "shift_left":
            if not D.is_int(eqn.outvars[0].aval.dtype):
                return
            if wrapped:
                a, s = ins
                self.emit(
                    eqn, "pack-shift-overflow", ERROR,
                    f"left shift can escape {eqn.outvars[0].aval.dtype}: "
                    f"operand {a} << {s} — the shifted field can reach the "
                    f"sign bit / wrap; widen the layout or bound the field")
            else:
                self.n_proved += 1
            return
        if name != "or" or D.is_bool(eqn.outvars[0].aval.dtype):
            return
        a_pack = self._is_pack_operand(ctx, eqn.invars[0])
        b_pack = self._is_pack_operand(ctx, eqn.invars[1])
        if not (a_pack or b_pack):
            return  # a bitmap union, not a field pack
        a, b = ins
        if a.lo < 0 or b.lo < 0:
            self.emit(
                eqn, "pack-negative-operand", ERROR,
                f"pack operand may be negative ({a} | {b}): a sign-extended "
                f"value sets every high bit and aliases all fields above it")
            return
        overlap = a.ones & b.ones
        if overlap:
            self.emit(
                eqn, "pack-overlap", ERROR,
                f"packed fields may overlap on mask 0x{overlap:x} "
                f"({a} | {b}): a field value can alias its neighbor's bits")
            return
        self.n_proved += 1


# --------------------------------------------------------------------------
# 2. dtype promotion lint
# --------------------------------------------------------------------------


class DtypePromotionPass(Pass):
    name = "dtype"

    def __init__(self, allow_float: bool = False):
        super().__init__()
        self.allow_float = allow_float

    def on_eqn(self, ctx: Ctx, eqn, ins, outs, wrapped) -> None:
        import numpy as np

        name = eqn.primitive.name
        for v in eqn.outvars:
            dt = np.dtype(getattr(v.aval, "dtype", np.int32))
            if dt.itemsize == 8 and dt.kind in "iuf":
                self.emit(eqn, "silent-64bit", ERROR,
                          f"{name} produces {dt}: a 64-bit value on the "
                          f"round chain (x64 should be off; an i64/f64 "
                          f"upcast doubles wire/HBM bytes silently)")
            elif (not self.allow_float and dt.kind == "f"
                  and name != "convert_element_type"):
                self.emit(eqn, "float-in-round", WARN,
                          f"{name} produces {dt} in an integer protocol "
                          f"round (only device_stream zipfian sampling may "
                          f"use floats)")
        if name != "convert_element_type":
            return
        src = np.dtype(eqn.invars[0].aval.dtype)
        dst = np.dtype(eqn.outvars[0].aval.dtype)
        if src.kind == "f" and dst.kind in "iu" and not self.allow_float:
            self.emit(eqn, "float-to-int", WARN,
                      f"float->int convert ({src}->{dst}) in an integer "
                      f"round")
            return
        if src.kind not in "iub" or dst.kind not in "iub":
            return
        if wrapped:
            self.emit(
                eqn, "implicit-wrap-convert", WARN,
                f"convert {src}->{dst} can change the value "
                f"(operand {ins[0]} escapes {dst}): a silent two's-"
                f"complement wrap — make the reinterpretation explicit "
                f"with a same-width lax.bitcast_convert_type, or mask "
                f"first (see faststep._bank_to_i32)")
        else:
            self.n_proved += 1


# --------------------------------------------------------------------------
# 3. scatter/gather hazard detector
# --------------------------------------------------------------------------


class ScatterHazardPass(Pass):
    name = "scatter"

    def on_eqn(self, ctx: Ctx, eqn, ins, outs, wrapped) -> None:
        name = eqn.primitive.name
        if name == "gather":
            self._check_bounds(ctx, eqn, ins, operand_idx=0, index_idx=1,
                               dims=eqn.params["dimension_numbers"]
                               .start_index_map)
            return
        if not name.startswith("scatter"):
            return
        dn = eqn.params["dimension_numbers"]
        self._check_bounds(ctx, eqn, ins, operand_idx=0, index_idx=1,
                           dims=dn.scatter_dims_to_operand_dims)
        if name != "scatter":
            self.n_proved += 1  # max/min/add: duplicate indices commute
            return
        if eqn.params.get("unique_indices"):
            self.emit(
                eqn, "scatter-unique-annotated", INFO,
                "set-scatter trusts its unique_indices=True annotation "
                "(XLA behavior is undefined if violated); covered by the "
                "analyzer only as an assumption")
            return
        self.emit(
            eqn, "scatter-set-not-injective", WARN,
            "set-scatter without injectivity evidence: duplicate indices "
            "make the written row unspecified (XLA picks one).  Prove it "
            "(unique_indices=True), or audit the protocol invariant that "
            "makes duplicates deterministic (layouts.audited)")

    def _check_bounds(self, ctx: Ctx, eqn, ins, operand_idx, index_idx,
                      dims) -> None:
        from jax.lax import GatherScatterMode

        mode = eqn.params.get("mode")
        if mode != GatherScatterMode.PROMISE_IN_BOUNDS:
            return  # FILL_OR_DROP / CLIP: OOB is defined (the mask idiom)
        idx = ins[index_idx]
        shape = eqn.invars[operand_idx].aval.shape
        cap = min((shape[d] for d in dims), default=None)
        if cap is None:
            return
        if idx.lo < 0 or idx.hi >= cap:
            self.emit(
                eqn, "oob-promised-index", ERROR,
                f"indices {idx} can leave [0, {cap}) but the op PROMISES "
                f"in-bounds: out-of-bounds behavior is undefined")
        else:
            self.n_proved += 1

    def check_donation(self, ctx: Ctx, jaxpr) -> None:
        """Donated-buffer aliasing: every donated input must have a
        shape/dtype-matched output XLA can alias it to, or the donation
        silently buys nothing (jax warns at RUN time; this is the static
        version, findable before a chip is involved)."""
        if not ctx.donated:
            return
        outs = {}
        for o in jaxpr.outvars:
            aval = getattr(o, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                k = (tuple(aval.shape), str(aval.dtype))
                outs[k] = outs.get(k, 0) + 1
        for i in sorted(ctx.donated):
            if i >= len(jaxpr.invars):
                continue
            v = jaxpr.invars[i]
            k = (tuple(v.aval.shape), str(v.aval.dtype))
            if outs.get(k, 0) > 0:
                outs[k] -= 1
                self.n_proved += 1
            else:
                self.findings[("donation-wasted", "<program>", 0, "<io>",
                               str(i))] = Finding(
                    pass_name=self.name, code="donation-wasted",
                    severity=WARN, file="<program>", fn="<io>", op=f"arg{i}",
                    message=f"donated argument {i} {k} has no shape/dtype-"
                            f"matched output to alias: the donation cannot "
                            f"be honored and XLA will copy")


# --------------------------------------------------------------------------
# 4. kernel ref hazards (pallas_call bodies)
# --------------------------------------------------------------------------


class RefHazardPass(Pass):
    """Kernel Ref/block discipline.  The pass itself is the findings
    channel: the pallas sub-interpreter (analysis/pallas.py) computes
    the hazards while walking kernel bodies and emits through this pass
    so the dedup/audit/severity machinery — and the baseline currency —
    stay identical to every other pass.  Codes:

      * ``oob-block-store`` / ``oob-block-load`` (error) — an index
        range can escape the block shape;
      * ``ref-read-before-init`` (error) — a get/swap/addupdate reads an
        output or scratch block no store has fully initialized;
      * ``blockspec-oob`` (error) — an index map yields a block index
        outside the operand;
      * ``grid-revisit-accumulator`` (warn) — an output block with a
        grid-invariant index map (revisit-accumulated, like
        stats_block's ctr/hist) lacks a ``layouts.audited`` declaration
        on the call site (with one it downgrades to info, tag carried);
      * ``pallas-skipped`` (info) — the sub-interpreter could not model
        the kernel; names the defeating primitive/feature.
    """

    name = "refhazard"

    def note_skipped(self, eqn, what: str) -> None:
        self.emit(
            eqn, "pallas-skipped", INFO,
            f"pallas_call body not interpreted: {what!r} defeated the "
            f"kernel sub-interpreter — outputs are dtype-TOP and "
            f"kernel-internal invariants are UNCHECKED for this call")


# --------------------------------------------------------------------------
# 5. sharding consistency
# --------------------------------------------------------------------------

_COLLECTIVES = ("all_gather", "all_to_all", "psum", "psum2", "pmax", "pmin",
                "ppermute", "all_reduce", "reduce_scatter", "pgather",
                "axis_index")


class ShardingConsistencyPass(Pass):
    name = "sharding"

    def _axis_names(self, eqn) -> list:
        names = eqn.params.get("axis_name",
                               eqn.params.get("axes",
                                              eqn.params.get("axis_names")))
        if names is None:
            return []
        if not isinstance(names, (tuple, list)):
            names = (names,)
        return [n for n in names if isinstance(n, str)]

    def on_eqn(self, ctx: Ctx, eqn, ins, outs, wrapped) -> None:
        declared = ctx.mesh_axes
        if declared is None:
            return
        name = eqn.primitive.name
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            if mesh is not None:
                for ax, size in dict(mesh.shape).items():
                    if ax not in declared:
                        self.emit(eqn, "unknown-mesh-axis", ERROR,
                                  f"shard_map mesh axis {ax!r} is not a "
                                  f"declared engine axis {sorted(declared)}")
                    elif declared[ax] != int(size):
                        self.emit(eqn, "axis-size-mismatch", ERROR,
                                  f"shard_map axis {ax!r} has size {size}, "
                                  f"engine declares {declared[ax]} "
                                  f"(per-replica shapes will disagree)")
                    else:
                        self.n_proved += 1
            return
        if name not in _COLLECTIVES:
            return
        if not declared:
            self.emit(eqn, "collective-in-batched-engine", ERROR,
                      f"{name} in the batched (single-chip) engine: the "
                      f"lockstep emulation must not contain wire ops")
            return
        ok = True
        for ax in self._axis_names(eqn):
            if ax not in declared:
                ok = False
                self.emit(eqn, "unknown-mesh-axis", ERROR,
                          f"{name} names mesh axis {ax!r}; declared axes "
                          f"are {sorted(declared)}")
        if name == "all_gather":
            sz = eqn.params.get("axis_size")
            axs = self._axis_names(eqn)
            want = 1
            for ax in axs:
                want *= declared.get(ax, 1)
            if sz is not None and axs and int(sz) != want:
                ok = False
                self.emit(eqn, "axis-size-mismatch", ERROR,
                          f"all_gather axis_size={sz} but the declared "
                          f"axes {axs} multiply to {want}")
        if name == "all_to_all":
            split = eqn.params.get("split_axis")
            axs = self._axis_names(eqn)
            size = 1
            for ax in axs:
                size *= declared.get(ax, 1)
            shape = eqn.invars[0].aval.shape
            if (split is not None and size > 1 and split < len(shape)
                    and shape[split] % size != 0):
                ok = False
                self.emit(eqn, "uneven-all-to-all", ERROR,
                          f"all_to_all splits dim {split} of {shape} by "
                          f"axis size {size}: not divisible — per-replica "
                          f"shapes disagree")
        if ok:
            self.n_proved += 1


def default_passes(allow_float: bool = False) -> list:
    return [BitPackPass(), DtypePromotionPass(allow_float=allow_float),
            ScatterHazardPass(), RefHazardPass(),
            ShardingConsistencyPass()]
