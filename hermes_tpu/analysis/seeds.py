"""Config-seeded abstract bounds for the fast engines' inputs.

The analyzer's theorems are only as strong as the facts it starts from.
This module turns a ``HermesConfig`` plus the declared field layouts
(core/layouts.py) into one ``AbsVal`` per input leaf of the round
programs — sess.key is in [0, n_keys), a packed ts fits the declared
ver budget, ctl.step fits the SST step field, op_idx fits the write-uid
budget the config validates, and so on.  Facts that are PROTOCOL
invariants rather than config facts (e.g. "a winner-row pts mirror holds
a watermark-bounded ts") are deliberately NOT seeded: the engine audits
those sites explicitly (layouts.audited) so the assumption shows up in
the findings stream instead of being silently assumed here.

The seed pytrees mirror the state containers field by field — a renamed
or added FastState field breaks the structure match loudly (by design:
new state must state its bounds)."""

from __future__ import annotations

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import layouts
from hermes_tpu.core import state as st
from hermes_tpu.analysis.domain import AbsVal, iv, top

import numpy as np

I32_TOP = top(np.int32)
I8_TOP = top(np.int8)
BOOL = iv(0, 1)
COUNTER = iv(0, (1 << 31) - 1)  # monotone device counters (non-negative)


def pts_seed(cfg: HermesConfig) -> AbsVal:
    """Any legally minted packed timestamp: ver within the declared budget
    (enforced by the Meta.max_pts watermark + auto-rebase), any fc."""
    return iv(0, (layouts.MAX_KEY_VERSIONS << layouts.PTS_FC_BITS)
              | layouts.FC_MASK)


def step_seed(cfg: HermesConfig) -> AbsVal:
    """The round counter, bounded by the declared SST step field (the
    packed state+age word is the binding constraint: 2^28 rounds)."""
    return iv(0, layouts.MAX_STEPS - 1)


def op_idx_seed(cfg: HermesConfig) -> AbsVal:
    """Per-session op counter.  Clip mode tops out at ops_per_session;
    wrap mode grows until the write-uid formula op_idx*S + s would leave
    int31 — the budget HermesConfig documents and validates."""
    if cfg.wrap_stream:
        return iv(0, max(cfg.ops_per_session,
                         (1 << 31) // max(1, cfg.n_sessions) - 1))
    return iv(0, cfg.ops_per_session)


def seed_fast_state(cfg: HermesConfig):
    from hermes_tpu.core import faststep as fst

    key = iv(0, cfg.n_keys - 1)
    pts = pts_seed(cfg)
    stp = step_seed(cfg)
    acks = iv(0, cfg.full_mask)
    meta = st.Meta(
        last_seen=stp, suspect_age=stp, n_read=COUNTER, n_write=COUNTER,
        n_rmw=COUNTER,
        n_abort=COUNTER, lat_sum=COUNTER, lat_cnt=COUNTER, lat_hist=COUNTER,
        max_pts=pts, n_inv=COUNTER, n_rebcast=COUNTER, n_nack=COUNTER,
        n_retry=COUNTER, replay_peak=iv(0, cfg.replay_slots),
        qwait_sum=COUNTER, qwait_hist=COUNTER,
    )
    return fst.FastState(
        table=fst.FastTable(vpts=pts, bank=I8_TOP),
        sess=fst.FastSess(
            status=iv(0, 4),  # types.S_IDLE..S_DONE
            op=iv(0, 3),  # types.OP_NOP..OP_RMW
            op_idx=op_idx_seed(cfg),
            key=key,
            val=I8_TOP,
            pts=pts,
            acks=acks,
            rd_val=I8_TOP,
            invoke_step=stp,
            retries=iv(0, max(1, cfg.rmw_retries)),
            issue_step=stp,
        ),
        replay=fst.FastReplay(active=BOOL, key=key, pts=pts, val=I8_TOP,
                              acks=acks),
        meta=meta,
    )


def seed_stream(cfg: HermesConfig, has_uval: bool = False):
    return st.OpStream(op=iv(0, 3), key=iv(0, cfg.n_keys - 1),
                       uval=I8_TOP if has_uval else None)


def seed_fast_ctl(cfg: HermesConfig):
    from hermes_tpu.core import faststep as fst

    return fst.FastCtl(
        step=step_seed(cfg),
        my_cid=iv(0, cfg.n_replicas - 1),
        epoch=iv(0, layouts.BLOCK_META.field("epoch").cap - 1),
        live_mask=iv(0, cfg.full_mask),
        frozen=BOOL,
        quiesce=BOOL,
    )


def seed_round_args(cfg: HermesConfig, has_uval: bool = False) -> tuple:
    """(fs, stream, ctl) seed pytrees, structure-matched to the round
    builders' arguments."""
    return (seed_fast_state(cfg), seed_stream(cfg, has_uval),
            seed_fast_ctl(cfg))


# --------------------------------------------------------------------------
# kernel argument seeds (the standalone kernel matrix, ISSUE 8)
# --------------------------------------------------------------------------


def seed_mega_route(cfg: HermesConfig) -> list:
    """Bounds for ``core.megaround.mega_route(si, word, srank)``: si is a
    lane permutation ([0, n_lanes)), word the packed per-lane verdict
    (layouts.LANE_WORD fields), srank the slot-rank bijection ([0,
    n_lanes) for live entries; the kernel clamps+guards, so the declared
    hull is the dense formula's)."""
    L = cfg.n_lanes
    word_hi = (layouts.LANE_WORD.field("taken").mask
               | layouts.LANE_WORD.field("issue").mask
               | layouts.LANE_WORD.field("chain_rank").mask)
    return [iv(0, L - 1), iv(0, word_hi), iv(0, 2 * L)]


def seed_mega_apply(cfg: HermesConfig) -> list:
    """Bounds for ``core.megaround.mega_apply(vpts, keys, pts, mask)``:
    keys deliberately span the full 29-bit WIRE field (the sharded path
    feeds untrusted inbound keys — the kernel must drop/clamp them, and
    the sanitizer draws them)."""
    return [pts_seed(cfg), iv(0, layouts.INV_PKF.field("key").cap - 1),
            pts_seed(cfg), iv(0, 1)]


def seed_mega_replay(cfg: HermesConfig) -> list:
    """Bounds for the mega_replay cell wrapper (step, replay fields,
    frozen, bank, vpts, key, pts, acks, val) — same sources as
    seed_fast_state's replay/table rows."""
    key = iv(0, cfg.n_keys - 1)
    return [step_seed(cfg), BOOL, BOOL, I8_TOP, pts_seed(cfg), key,
            pts_seed(cfg), iv(0, cfg.full_mask), I8_TOP]


def seed_heap_gather(cfg: HermesConfig, batch: int = 1024) -> list:
    """Bounds for ``hermes_tpu.heap.build_extent_gather(log, refs)``
    (round-17): the log bytes are opaque (I8_TOP) and the refs span the
    FULL declared HEAP_REF word — refs arrive from table rows a wire
    could have corrupted, so the kernel must clamp every derived byte
    index into the log; the analyzer proves the promised-in-bounds
    gather from exactly this hull (scripts/check_heap.py runs it)."""
    hi = layouts.HEAP_REF.field("gran").mask | layouts.HEAP_REF.field("len").mask
    return [I8_TOP, iv(0, hi)]


def seed_stats_block() -> list:
    """One AbsVal per ``core.kernels.stats_block`` argument (step,
    sess_op, invoke_step, commit, abort, read_done) — the same bounds
    the round analysis derives at the kernel's call site.  The step
    bounds come from the declared SST step field and the counter/
    histogram accumulators from ``layouts.STATS_CTR``/``state.LAT_BINS``
    — the one declared source the kernel itself builds its packed
    outputs from (no bare ``range(6)``)."""
    stp = iv(0, layouts.MAX_STEPS - 1)  # == step_seed(cfg) for any cfg
    return [stp, iv(0, 3), stp, BOOL, BOOL, BOOL]
