"""CLI: ``python -m hermes_tpu.analysis`` — analyze the fast engines.

Prints the findings (and the proof counts) for the chosen config/engines;
``--out`` additionally exports obs-schema JSONL.  Exit code 1 iff any
ERROR-severity finding exists (the CI gate with baseline support is
scripts/check_analysis.py).

CPU-safe at any shape: programs are traced abstractly, nothing is
materialized.  Set JAX_PLATFORMS=cpu (and
XLA_FLAGS=--xla_force_host_platform_device_count=8 for --engine
sharded/both) when running next to a TPU claim.
"""

from __future__ import annotations

import argparse
import json
import sys


def _named_cfg(name: str, args):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    if name == "default":
        return HermesConfig()
    if name == "bench":
        # the bench operating shape (bench._cfg YCSB-A, sort arbiter +
        # chaining + fused sort), kept importable without the bench script
        from hermes_tpu.obs.profile import _cli_cfg

        return _cli_cfg(args.sessions, args.lane_budget
                        or (3 * args.sessions) // 4,
                        arb_mode="sort", chain_writes=128,
                        fused_sort=True)
    if name == "rmw":
        from hermes_tpu.obs.profile import _cli_cfg

        cfg = _cli_cfg(args.sessions, args.lane_budget
                       or (3 * args.sessions) // 4,
                       arb_mode="sort", chain_writes=0, fused_sort=True)
        import dataclasses

        return dataclasses.replace(
            cfg, rmw_retries=16,
            workload=WorkloadConfig(read_frac=0.5, rmw_frac=1.0, seed=0))
    raise KeyError(name)


def _kernels_main(args) -> int:
    """``--kernels``: the standalone kernel matrix (analysis + the
    differential sanitizer), one JSON summary line, exit 1 on any
    gating finding or sanitizer violation."""
    from hermes_tpu import analysis as ana

    reports = ana.run_kernel_matrix(n_draws=args.draws)
    n_err = n_warn = n_info = 0
    ok = True
    cells = {}
    for r in reports:
        errs = [f for f in r["findings"] if f.severity == ana.ERROR]
        warns = [f for f in r["findings"] if f.severity == ana.WARN]
        infos = [f for f in r["findings"] if f.severity == ana.INFO]
        n_err += len(errs)
        n_warn += len(warns)
        n_info += len(infos)
        san = r["sanitizer"]
        ok = ok and san["ok"] and not errs and not warns
        cells[r["engine"]] = dict(
            seconds=r["seconds"], n_eqns=r["n_eqns"],
            errors=len(errs), warnings=len(warns), infos=len(infos),
            sanitizer_ok=san["ok"], draws=san["n_draws"])
        if not args.json:
            proved = " ".join(f"{k}={v}" for k, v in r["proved"].items())
            print(f"== {r['engine']}: {r['n_eqns']} eqns, proved "
                  f"[{proved}], {len(errs)} error / {len(warns)} warn / "
                  f"{len(infos)} info, sanitizer "
                  f"{'ok' if san['ok'] else 'VIOLATED'} "
                  f"({san['n_draws']} draws) in {r['seconds']}s",
                  file=sys.stderr)
            for f in r["findings"]:
                tag = f" (audit: {f.audit})" if f.audit else ""
                print(f"  [{f.severity:<5}] {f.pass_name}/{f.code} "
                      f"{f.site} in {f.fn} x{f.count}{tag}\n"
                      f"          {f.message}", file=sys.stderr)
            for v in san["violations"]:
                print(f"  [UNSOUND] out{v['out']} draw{v['draw']} "
                      f"{v['kind']}: concrete {v['concrete']} escapes "
                      f"abstract {v['abstract']}", file=sys.stderr)
    if args.out:
        ana.export_findings(args.out, reports, extra={"config": "kernels"})
    print(json.dumps(dict(config="kernels", ok=ok, errors=n_err,
                          warnings=n_warn, infos=n_info, cells=cells)))
    return 0 if ok else 1


def _host_main(args) -> int:
    """``--host``: the host concurrency lint (hostlint.py) — prove the
    threaded serving/transport tier against the guard registry.  One
    JSON summary line, exit 1 iff any ERROR finding; the baselined CI
    gate is scripts/check_hostlint.py."""
    from hermes_tpu import analysis as ana
    from hermes_tpu.analysis import hostlint

    rep = hostlint.lint_package()
    errs = [f for f in rep["findings"] if f.severity == ana.ERROR]
    warns = [f for f in rep["findings"] if f.severity == ana.WARN]
    infos = [f for f in rep["findings"] if f.severity == ana.INFO]
    if not args.json:
        proved = " ".join(f"{k}={v}" for k, v in rep["proved"].items())
        print(f"== host: {rep['n_eqns']} files, proved [{proved}], "
              f"{len(errs)} error / {len(warns)} warn / {len(infos)} "
              f"info", file=sys.stderr)
        for f in rep["findings"]:
            tag = f" (audit: {f.audit})" if f.audit else ""
            print(f"  [{f.severity:<5}] {f.pass_name}/{f.code} "
                  f"{f.site} in {f.fn} x{f.count}{tag}\n"
                  f"          {f.message}", file=sys.stderr)
    if args.out:
        ana.export_findings(args.out, [rep], extra={"config": "host"})
    print(json.dumps(dict(
        config="host", engines=["host"], files=rep["n_eqns"],
        errors=len(errs), warnings=len(warns), infos=len(infos))))
    return 1 if errs else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hermes_tpu.analysis",
        description="Static jaxpr invariant analyzer: prove the packed "
        "words (bit-pack intervals, dtype promotion, scatter hazards, "
        "sharding consistency) of the fast protocol round.")
    ap.add_argument("--config", choices=["default", "bench", "rmw"],
                    default="default")
    ap.add_argument("--sessions", type=int, default=16384,
                    help="bench/rmw config session count")
    ap.add_argument("--lane-budget", type=int, default=None)
    ap.add_argument("--engine", choices=["batched", "sharded", "both"],
                    default="batched")
    ap.add_argument("--split-sort", action="store_true",
                    help="analyze ONLY the split two-sort program")
    ap.add_argument("--no-variants", action="store_true",
                    help="analyze the config as-is (skip the split-sort "
                    "A/B program)")
    ap.add_argument("--out", default=None, metavar="FINDINGS_JSONL",
                    help="export findings as obs-schema JSONL "
                    "(kind=analysis)")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON summary line instead of the "
                    "human report")
    ap.add_argument("--kernels", action="store_true",
                    help="run ONLY the standalone kernel matrix: every "
                    "in-tree Pallas kernel analyzed through the "
                    "sub-interpreter + the differential sanitizer "
                    "(seeded interpret-mode runs vs abstract cells)")
    ap.add_argument("--draws", type=int, default=3,
                    help="sanitizer draws per kernel cell (--kernels)")
    ap.add_argument("--host", action="store_true",
                    help="run ONLY the host concurrency lint: the "
                    "whole package statically proved against the "
                    "guard registry (hermes_tpu/concurrency.py) — "
                    "guarded-attr, blocking-under-lock, lock-order "
                    "cycles, thread ownership")
    args = ap.parse_args(argv)

    from hermes_tpu import analysis as ana

    if args.host:
        return _host_main(args)
    if args.kernels:
        return _kernels_main(args)

    cfg = _named_cfg(args.config, args)
    if args.split_sort:
        import dataclasses

        cfg = dataclasses.replace(cfg, fused_sort=False)
    engines = (("batched", "sharded") if args.engine == "both"
               else (args.engine,))
    variants = "as-is" if (args.no_variants or args.split_sort) else "both"
    reports = ana.analyze_config(cfg, engines=engines, variants=variants)

    n_err = n_warn = 0
    for r in reports:
        errs = [f for f in r["findings"] if f.severity == ana.ERROR]
        warns = [f for f in r["findings"] if f.severity == ana.WARN]
        infos = [f for f in r["findings"] if f.severity == ana.INFO]
        n_err += len(errs)
        n_warn += len(warns)
        if not args.json:
            proved = " ".join(f"{k}={v}" for k, v in r["proved"].items())
            print(f"== {r['engine']} @ {args.config}: {r['n_eqns']} eqns, "
                  f"proved [{proved}], {len(errs)} error / {len(warns)} "
                  f"warn / {len(infos)} info", file=sys.stderr)
            for f in r["findings"]:
                tag = f" (audit: {f.audit})" if f.audit else ""
                print(f"  [{f.severity:<5}] {f.pass_name}/{f.code} "
                      f"{f.site} in {f.fn} x{f.count}{tag}\n"
                      f"          {f.message}", file=sys.stderr)
    if args.out:
        ana.export_findings(args.out, reports, extra={"config": args.config})
    print(json.dumps(dict(
        config=args.config, engines=list(engines),
        programs=[r["engine"] for r in reports],
        errors=n_err, warnings=n_warn,
        infos=sum(1 for r in reports for f in r["findings"]
                  if f.severity == ana.INFO))))
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
