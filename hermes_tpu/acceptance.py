"""The five BASELINE acceptance configurations as runnable scenarios.

BASELINE.json:7-11 names the runs the judge cares about:

  1. 3-replica single-process KVS, YCSB-A (50/50), 1M keys, uniform
  2. 5-replica write-heavy YCSB-F (read-modify-write), uniform
  3. 7-replica Zipfian-0.99 hotspot (contended-key INV conflict + Replay)
  4. 8-replica with injected replica stall -> Write->Replay recovery
  5. 8-replica membership reconfiguration (join/leave) mid-workload

``run_config(n, scale=...)`` executes scenario ``n`` on the fast runtime
with history recording and returns (counters, Verdict).  ``scale`` shrinks
keys/sessions/ops for CI (scale=1.0 is the full BASELINE shape — 1M keys —
sized for a real chip, not a laptop).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.membership import MembershipService
from hermes_tpu.runtime import FastRuntime


def _sz(base: int, scale: float, lo: int = 4) -> int:
    return max(lo, int(base * scale))


def _cfg(n: int, scale: float) -> HermesConfig:
    keys = _sz(1 << 20, scale, lo=64)
    sessions = _sz(1024, scale, lo=8)
    ops = _sz(128, min(1.0, scale * 4), lo=8)
    base = dict(
        n_keys=keys, n_sessions=sessions, replay_slots=max(8, sessions // 16),
        ops_per_session=ops, value_words=8, replay_age=8, replay_scan_every=4,
    )
    if n == 1:
        return HermesConfig(n_replicas=3, workload=WorkloadConfig(read_frac=0.5, seed=1), **base)
    if n in (2, "2r"):
        # 2 is the judged gate exactly as BASELINE.json:8 frames it (RMW
        # conflicts abort, reference semantics); "2r" is the SAME scenario
        # under round-5 retry-in-place (config.rmw_retries) — nacked RMWs
        # re-read and re-issue instead of surfacing aborts, so contention
        # work converts to commits.  Additional variant, not a replacement.
        retr = dict(rmw_retries=16) if n == "2r" else {}
        return HermesConfig(
            n_replicas=5, **retr,
            workload=WorkloadConfig(read_frac=0.3, rmw_frac=1.0, seed=2), **base,
        )
    if n in (3, "3c"):
        # 3 is the judged gate exactly as BASELINE.json:9 frames it
        # (contended-key INV conflict + Replay under the race arbiter);
        # "3c" is the SAME scenario under the round-3 hot-key mitigation
        # (sort + write chaining, BASELINE.md "Round-3 mitigation") — an
        # additional variant, not a replacement: total version burn per
        # key is unchanged (one ts per committed write), the hot-key
        # queue just drains in far fewer rounds.
        arb = dict(arb_mode="sort", chain_writes=64) if n == "3c" else {}
        return HermesConfig(
            n_replicas=7, **arb,
            workload=WorkloadConfig(read_frac=0.5, distribution="zipfian",
                                    zipf_theta=0.99, seed=3), **base,
        )
    if n in (4, 5):
        return HermesConfig(n_replicas=8, workload=WorkloadConfig(read_frac=0.5, seed=n), **base)
    raise ValueError(f"config {n} not in 1..5 / '2r' / '3c'")


def run_config(n: int, scale: float = 0.01, max_steps: int = 5000,
               backend: str = "batched", mesh=None, check: bool = True,
               check_keys: Optional[int] = 512,
               pipeline_depth: int = 1,
               log: Optional[Callable[[str], None]] = None) -> Tuple[Dict, object]:
    """Run acceptance scenario ``n``; returns (counters, Verdict|None).
    ``check_keys`` samples the checked key set (None = every touched key —
    the full-scale artifact's setting; 512 keeps CI fast).
    ``pipeline_depth >= 2`` runs the scenario through the round-8 harvest
    ring (async completion readback) — protocol outcomes and checker
    verdicts must be unchanged (cli --acceptance --pipeline-depth)."""
    from hermes_tpu.checker.fast import default_record

    say = log or (lambda s: None)
    cfg = _cfg(n, scale)
    if pipeline_depth != 1:
        cfg = dataclasses.replace(cfg, pipeline_depth=pipeline_depth)
    # columnar recorder + native witness (checker/fast.py): same verdicts
    # as the Python recorder (witness FAILs are confirmed by the exact
    # search) at a per-op cost that survives scale=1.0 histories; falls
    # back to the pure-Python recorder where no compiler exists.
    rt = FastRuntime(cfg, backend=backend, mesh=mesh,
                     record=default_record(check))
    say(f"config {n}: R={cfg.n_replicas} K={cfg.n_keys} S={cfg.n_sessions} "
        f"G={cfg.ops_per_session} wl={cfg.workload}")

    if n == 4:
        # injected replica stall mid-workload; lease-based detection removes
        # it (epoch bump), waiting writes re-evaluate their quorum, stuck
        # Invalid keys recover through Replay (SURVEY.md §3.4).
        svc = MembershipService(cfg)
        rt.attach_membership(svc)
        rt.run(6)
        rt.freeze(7)
        say("config 4: froze replica 7 (stall injection)")
        drained = rt.drain(max_steps)
        say(f"config 4: membership events: {[dataclasses.asdict(e) for e in svc.events]}")
        detected = any(e.kind == "remove" and e.replica == 7 for e in svc.events)
    elif n == 5:
        # membership reconfiguration mid-workload: remove replica 6, let the
        # workload make progress without it, then re-join it via state
        # transfer from a live donor.
        rt.run(5)
        rt.remove(6)
        say("config 5: removed replica 6")
        rt.run(10)
        rt.join(6, from_replica=0)
        say("config 5: re-joined replica 6 (state transfer from 0)")
        drained = rt.drain(max_steps)
    else:
        drained = rt.drain(max_steps)

    counters = {k: int(v) for k, v in rt.counters().items() if k.startswith("n_")}
    counters["drained"] = bool(drained)
    if n == 4:
        # acceptance criterion: the lease-based service must detect the stall
        counters["failure_detected"] = detected
        counters["drained"] = counters["drained"] and detected
    verdict = None
    if check:
        verdict = rt.check(max_keys=check_keys)
    return counters, verdict


def run_sparse_variant(scale: float = 0.01, ops: Optional[int] = None,
                       max_steps: int = 50_000,
                       check_keys: Optional[int] = None,
                       backend: str = "batched", mesh=None,
                       n_replicas: int = 3,
                       log: Optional[Callable[[str], None]] = None
                       ) -> Tuple[Dict, object]:
    """Config-1-shaped YCSB-A through the CLIENT KVS in sparse-key mode
    (round-2 verdict item 5's completion criterion): scale x 1M arbitrary
    64-bit client keys bulk-preloaded through the vectorized
    KeyIndex.get_slots, then a 50/50 get/put mix driven over (replica,
    session) future slots, history-recorded and linearizability-checked.
    Returns (counters, Verdict) like run_config."""
    import time

    import numpy as np

    from hermes_tpu.kvs import KVS, drive_mix

    say = log or (lambda s: None)
    keys = _sz(1 << 20, scale, lo=64)
    sessions = _sz(1024, scale, lo=8)
    cfg = HermesConfig(
        n_replicas=n_replicas, n_keys=keys, n_sessions=sessions,
        replay_slots=max(8, min(sessions // 2, 64)), value_words=8,
        workload=WorkloadConfig(read_frac=0.5, seed=1),
    )
    from hermes_tpu.checker.fast import default_record

    kvs = KVS(cfg, backend=backend, mesh=mesh, record=default_record(),
              sparse_keys=True)
    rng = np.random.default_rng(1)
    # odd-constant multiply mod 2^64 is a bijection: `keys` DISTINCT
    # arbitrary-looking 64-bit client ids.  The reserved all-ones bucket
    # sentinel, if it appears, is remapped to 0 — the image of x=0, which is
    # outside the image of {1..keys}, so the universe stays duplicate-free
    # (round-3 advisor: the old 12345 remap could collide with a real
    # universe element and the only guard was a -O-stripped assert).
    universe = (rng.permutation(np.arange(1, keys + 1, dtype=np.uint64))
                * np.uint64(0x9E3779B97F4A7C15))
    universe[universe == np.uint64(0xFFFFFFFFFFFFFFFF)] = np.uint64(0)
    t0 = time.perf_counter()
    kvs.index.get_slots(universe)  # vectorized bulk preload
    preload_s = time.perf_counter() - t0
    if len(kvs.index) != keys:
        raise RuntimeError(
            f"sparse preload invariant broken: index holds "
            f"{len(kvs.index)} slots for {keys} distinct client keys")
    say(f"sparse variant: preloaded {keys} 64-bit keys in {preload_s:.2f}s")

    n_ops = ops if ops is not None else 4 * cfg.n_replicas * sessions
    is_get = rng.random(n_ops) < 0.5
    op_keys = universe[rng.integers(0, keys, n_ops)]
    bf, drained, enq_s, run_s = drive_mix(
        kvs, op_keys, is_get, lambda i: [i & 0x7FFF], max_steps=max_steps)
    drive_s = enq_s + run_s  # keep the artifact's historical rate meaning
    completed = bf.done_count()
    counters = {k: int(v) for k, v in kvs.counters().items()
                if k.startswith("n_")}
    counters.update(
        drained=bool(drained) and completed == n_ops,
        preload_keys=keys, preload_s=round(preload_s, 3),
        client_ops=n_ops, completed=completed,
        client_ops_per_s=round(completed / drive_s, 1),
    )
    verdict = kvs.rt.check(max_keys=check_keys)
    return counters, verdict


def run_all(scale: float = 0.01, log=None):
    """All five scenarios; returns {n: (counters, verdict)}."""
    return {n: run_config(n, scale=scale, log=log) for n in range(1, 6)}
