"""Arbitrary-key hash index: sparse 64-bit client keys -> dense table slots
(SURVEY.md §1 L2 / §2 "KVS store" — the MICA-style index of the reference's
store, rebuilt for this architecture).

Where it sits (and why host-side): the reference's MICA-derived hash index
lives in the data plane because clients address the store by arbitrary key
bytes directly.  In this rebuild the data plane is the dense SoA key-state
table stepped on-device (core/faststep.py) — dense slot ids are what make
the protocol a scatter/gather program, and keeping the index out of the
round costs nothing because the client API path (hermes_tpu/kvs.py) is
host-mediated per round anyway: ops are injected into the device stream by
the host, which is exactly where a sparse key must become a slot.  A
device-side probe loop would add serial sparse gathers (~1.5-2 ms each,
measured) to every round for work the host does in nanoseconds per op.

Structure: open addressing with linear probing over a power-of-two bucket
array (capacity >= 2x n_keys, load factor <= 0.5 against the dense-slot
budget), splitmix64 hash.  Unlike MICA's lossy index (which may evict
under pressure and re-fetch from the log), this index is EXACT: the dense
slots are the store, so eviction would lose data.

Collision / full policy (documented contract):
  * hash collisions probe linearly; a lookup stops at the first empty
    bucket (keys are never deleted — the KVS API has no delete op, so no
    tombstones exist and probes cannot be broken by removal);
  * inserting beyond ``n_keys`` distinct keys raises ``KeyspaceFull`` —
    the dense table is exactly the key budget; callers size ``n_keys`` to
    their working set the same way the reference sizes its store.
"""

from __future__ import annotations

import numpy as np

_EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)  # reserved bucket sentinel


class KeyspaceFull(RuntimeError):
    """More distinct keys inserted than the dense table has slots."""


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — the 64-bit analog of the stream hash's
    avalanche; vectorized over uint64 arrays (wraparound intended)."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


class KeyIndex:
    """Exact sparse->dense key index (open addressing, linear probing).

    ``get_slots(keys, insert=...)`` is numpy-vectorized end to end: lookups
    run as probe *rounds* over the still-unresolved elements (each round is
    one gather + compares over the whole pending set), and inserts place all
    new keys via first-wins claim rounds — so bulk-loading ~1M keys takes
    seconds, not minutes, and sparse-key mode can back stream-scale runs
    (round-2 verdict item 5).  Slots are allocated densely in
    first-occurrence order (0, 1, 2, ...), so the device table never sees a
    hole and batch semantics match one-at-a-time insertion.

    Bulk-insert atomicity: if a batch would exceed ``n_keys`` distinct keys,
    ``KeyspaceFull`` is raised *before* any mutation (no partial insert) —
    stricter than one-at-a-time calls, which insert up to the budget first.
    """

    def __init__(self, n_keys: int):
        self.n_keys = n_keys
        cap = 1
        while cap < 2 * n_keys:
            cap *= 2
        self._cap = cap
        self._mask = np.uint64(cap - 1)
        self._bucket_key = np.full(cap, _EMPTY, np.uint64)
        self._bucket_slot = np.zeros(cap, np.int32)
        self._rev = np.zeros(n_keys, np.uint64)  # slot -> client key
        self.n_used = 0

    # -- vectorized probe ---------------------------------------------------

    def _lookup(self, flat: np.ndarray):
        """Vectorized lookup of ``flat`` (1-D uint64): returns (slots int32
        with -1 for absent, absent_idx int64 positions into ``flat``).
        Probe rounds: each iteration gathers the current bucket of every
        still-pending element and resolves hits (key match) and misses
        (empty bucket); the rest advance one bucket.  Buckets never empty
        out (no delete), so a miss is definitive."""
        out = np.full(flat.shape[0], -1, np.int32)
        idx = np.arange(flat.shape[0], dtype=np.int64)
        pos = (_splitmix64(flat) & self._mask).astype(np.int64)
        absent = []
        while idx.size:
            k = self._bucket_key[pos]
            hit = k == flat[idx]
            empty = k == _EMPTY
            if hit.any():
                out[idx[hit]] = self._bucket_slot[pos[hit]]
            if empty.any():
                absent.append(idx[empty])
            cont = ~(hit | empty)
            idx = idx[cont]
            pos = (pos[cont] + 1) & np.int64(self._mask)
        absent_idx = (np.concatenate(absent) if absent
                      else np.empty(0, np.int64))
        return out, absent_idx

    def _insert_new(self, new_keys: np.ndarray, new_slots: np.ndarray):
        """Place distinct absent ``new_keys`` (pre-assigned ``new_slots``)
        into buckets via first-wins claim rounds.  A key claims the first
        empty bucket on its probe path; when several keys target the same
        empty bucket in one round, the lowest-indexed wins and the rest
        advance.  Every bucket a key passes was occupied when passed (wins
        happen before losers advance), so the linear-probing reachability
        invariant — no empty gap between a key's home and its bucket —
        holds exactly as it does for sequential insertion."""
        pend = np.arange(new_keys.shape[0], dtype=np.int64)
        pos = (_splitmix64(new_keys) & self._mask).astype(np.int64)
        while pend.size:
            empty = self._bucket_key[pos] == _EMPTY
            claimed = np.zeros(pend.size, bool)
            if empty.any():
                cand = np.flatnonzero(empty)
                _, first = np.unique(pos[cand], return_index=True)
                w = cand[first]  # first-wins per target bucket
                self._bucket_key[pos[w]] = new_keys[pend[w]]
                self._bucket_slot[pos[w]] = new_slots[pend[w]]
                claimed[w] = True
            cont = ~claimed
            pend = pend[cont]
            pos = (pos[cont] + 1) & np.int64(self._mask)

    # -- public API ---------------------------------------------------------

    def get_slots(self, keys, insert: bool = True) -> np.ndarray:
        """Dense slots for a batch of 64-bit client keys (int32 array,
        -1 marks absent keys when ``insert=False``)."""
        shape = np.shape(keys)
        flat = np.atleast_1d(np.asarray(keys, np.uint64)).ravel()
        if flat.size and (flat == _EMPTY).any():
            raise ValueError("key 0xFFFF...FF is reserved")
        out, absent_idx = self._lookup(flat)
        if insert and absent_idx.size:
            ak = flat[absent_idx]
            uk, inv = np.unique(ak, return_inverse=True)
            # first-occurrence order in the batch defines slot order (the
            # same slots one-at-a-time insertion would hand out)
            first_pos = np.full(uk.shape[0], flat.shape[0], np.int64)
            np.minimum.at(first_pos, inv, absent_idx)
            order = np.argsort(first_pos, kind="stable")
            rank = np.empty_like(order)
            rank[order] = np.arange(order.shape[0])
            if self.n_used + uk.shape[0] > self.n_keys:
                raise KeyspaceFull(
                    f"{self.n_used} distinct keys present + "
                    f"{uk.shape[0]} new in batch; dense table holds "
                    f"n_keys={self.n_keys} — size n_keys to the working "
                    f"set (the index is exact, not lossy; nothing from "
                    f"this batch was inserted)"
                )
            uslots = (self.n_used + rank).astype(np.int32)
            self._rev[uslots] = uk
            self._insert_new(uk, uslots)
            self.n_used += int(uk.shape[0])
            out[absent_idx] = uslots[inv]
        return out.reshape(shape) if shape else out[0]

    def slot(self, key: int, insert: bool = True) -> int:
        return int(self.get_slots(np.uint64(key), insert=insert))

    def key_of(self, slot: int) -> int:
        """Client key stored at a dense slot (inverse mapping)."""
        if not (0 <= slot < self.n_used):
            raise KeyError(f"slot {slot} unallocated")
        return int(self._rev[slot])

    def __len__(self) -> int:
        return self.n_used

    def __contains__(self, key: int) -> bool:
        return self.slot(key, insert=False) >= 0


class RangeRouter:
    """Key-range -> group routing table with an atomic flip (round-10
    elastic operations, hermes_tpu/elastic).

    Routes the dense slot space ``[0, n_keys)`` to group ids.  A live
    key-range migration drives it through three states per range:

      1. ``begin_drain(lo, hi)`` — the range still belongs to its owner but
         accepts no NEW ops (the owning KVS rejects them loudly,
         kind='rejected'); in-flight ops drain;
      2. ``flip(lo, hi, new_group)`` — ownership moves and the drain clears
         in ONE host-side state update, so no lookup can ever observe the
         half-flipped state (new owner while still draining, or old owner
         already released);
      3. (abort path) ``release(lo, hi)`` — clear the drain without moving
         ownership.

    Lookups are exact at range boundaries by construction: ``owner``/
    ``draining`` index a dense per-slot array, so ``lo`` is in the range
    and ``hi`` is not — there is no interval arithmetic to get off by one.
    """

    def __init__(self, n_keys: int, default_group: int = 0):
        self.n_keys = n_keys
        self._owner = np.full(n_keys, default_group, np.int32)
        self._drain = np.zeros(n_keys, bool)

    def _check_range(self, lo: int, hi: int) -> None:
        if not (0 <= lo < hi <= self.n_keys):
            raise ValueError(
                f"range [{lo}, {hi}) outside the slot space "
                f"[0, {self.n_keys})")

    # -- lookups (vectorized; scalars accepted) -----------------------------

    def owner(self, slot) -> np.ndarray:
        """Group id owning each slot (int32, shape of ``slot``)."""
        return self._owner[np.asarray(slot)]

    def draining(self, slot) -> np.ndarray:
        """True where a migration has fenced the slot (reject-new)."""
        return self._drain[np.asarray(slot)]

    def routable(self, slot, group: int) -> np.ndarray:
        """True where ``group`` may accept a new op for the slot: it owns
        the slot AND no drain is in progress."""
        s = np.asarray(slot)
        return (self._owner[s] == group) & ~self._drain[s]

    def owned_ranges(self):
        """The routing table as maximal contiguous ``(lo, hi, group)``
        runs — the human/report form of the dense per-slot array (fleet
        summaries, boundary tests).  Exact by construction: derived from
        the same array lookups consult."""
        out = []
        if self.n_keys == 0:
            return out
        edges = np.flatnonzero(np.diff(self._owner)) + 1
        lo = 0
        for hi in list(edges) + [self.n_keys]:
            out.append((int(lo), int(hi), int(self._owner[lo])))
            lo = hi
        return out

    # -- migration state machine --------------------------------------------

    def assign(self, lo: int, hi: int, group: int) -> None:
        """Initial ownership assignment (fleet construction): like
        ``flip`` but refuses to touch a draining slot — assignment is a
        build-time act, never a way around an in-flight migration."""
        self._check_range(lo, hi)
        if self._drain[lo:hi].any():
            raise RuntimeError(
                f"assign [{lo}, {hi}) overlaps a draining range; finish "
                "or release the migration first")
        self._owner[lo:hi] = group

    def begin_drain(self, lo: int, hi: int) -> None:
        self._check_range(lo, hi)
        self._drain[lo:hi] = True

    def flip(self, lo: int, hi: int, new_group: int) -> None:
        """Atomic cutover: ownership and drain state change in one host
        update — the migration's linearization point for routing."""
        self._check_range(lo, hi)
        self._owner[lo:hi] = new_group
        self._drain[lo:hi] = False

    def release(self, lo: int, hi: int) -> None:
        """Abort a drain: the range stays with its current owner."""
        self._check_range(lo, hi)
        self._drain[lo:hi] = False
