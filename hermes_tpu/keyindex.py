"""Arbitrary-key hash index: sparse 64-bit client keys -> dense table slots
(SURVEY.md §1 L2 / §2 "KVS store" — the MICA-style index of the reference's
store, rebuilt for this architecture).

Where it sits (and why host-side): the reference's MICA-derived hash index
lives in the data plane because clients address the store by arbitrary key
bytes directly.  In this rebuild the data plane is the dense SoA key-state
table stepped on-device (core/faststep.py) — dense slot ids are what make
the protocol a scatter/gather program, and keeping the index out of the
round costs nothing because the client API path (hermes_tpu/kvs.py) is
host-mediated per round anyway: ops are injected into the device stream by
the host, which is exactly where a sparse key must become a slot.  A
device-side probe loop would add serial sparse gathers (~1.5-2 ms each,
measured) to every round for work the host does in nanoseconds per op.

Structure: open addressing with linear probing over a power-of-two bucket
array (capacity >= 2x n_keys, load factor <= 0.5 against the dense-slot
budget), splitmix64 hash.  Unlike MICA's lossy index (which may evict
under pressure and re-fetch from the log), this index is EXACT: the dense
slots are the store, so eviction would lose data.

Collision / full policy (documented contract):
  * hash collisions probe linearly; a lookup stops at the first empty
    bucket (keys are never deleted — the KVS API has no delete op, so no
    tombstones exist and probes cannot be broken by removal);
  * inserting beyond ``n_keys`` distinct keys raises ``KeyspaceFull`` —
    the dense table is exactly the key budget; callers size ``n_keys`` to
    their working set the same way the reference sizes its store.
"""

from __future__ import annotations

import numpy as np

_EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)  # reserved bucket sentinel


class KeyspaceFull(RuntimeError):
    """More distinct keys inserted than the dense table has slots."""


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — the 64-bit analog of the stream hash's
    avalanche; vectorized over uint64 arrays (wraparound intended)."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


class KeyIndex:
    """Exact sparse->dense key index (open addressing, linear probing).

    ``get_slots(keys, insert=...)`` accepts batches as a convenience (the
    probe itself runs per element in Python — fine for the KVS API path,
    which injects a handful of ops per round; a stream-scale bulk loader
    would want a numpy-probed batch insert).  Slots are allocated densely
    in insertion order (0, 1, 2, ...), so the device table never sees a
    hole."""

    def __init__(self, n_keys: int):
        self.n_keys = n_keys
        cap = 1
        while cap < 2 * n_keys:
            cap *= 2
        self._cap = cap
        self._mask = np.uint64(cap - 1)
        self._bucket_key = np.full(cap, _EMPTY, np.uint64)
        self._bucket_slot = np.zeros(cap, np.int32)
        self._rev = np.zeros(n_keys, np.uint64)  # slot -> client key
        self.n_used = 0

    # -- core probe ---------------------------------------------------------

    def _probe_one(self, key: np.uint64, insert: bool) -> int:
        """Slot of ``key``; -1 if absent and not inserting."""
        if key == _EMPTY:
            raise ValueError("key 0xFFFF...FF is reserved")
        b = int(_splitmix64(np.uint64(key)) & self._mask)
        while True:
            k = self._bucket_key[b]
            if k == key:
                return int(self._bucket_slot[b])
            if k == _EMPTY:
                if not insert:
                    return -1
                if self.n_used >= self.n_keys:
                    raise KeyspaceFull(
                        f"{self.n_used} distinct keys inserted; dense table "
                        f"holds n_keys={self.n_keys} — size n_keys to the "
                        f"working set (the index is exact, not lossy)"
                    )
                slot = self.n_used
                self._bucket_key[b] = key
                self._bucket_slot[b] = slot
                self._rev[slot] = key
                self.n_used += 1
                return slot
            b = (b + 1) & int(self._mask)

    # -- public API ---------------------------------------------------------

    def get_slots(self, keys, insert: bool = True) -> np.ndarray:
        """Dense slots for a batch of 64-bit client keys (int32 array,
        -1 marks absent keys when ``insert=False``)."""
        flat = np.atleast_1d(np.asarray(keys, np.uint64))
        out = np.empty(flat.shape, np.int32)
        for i, k in enumerate(flat.ravel()):
            out.ravel()[i] = self._probe_one(k, insert)
        return out.reshape(np.shape(keys)) if np.shape(keys) else out[0]

    def slot(self, key: int, insert: bool = True) -> int:
        return int(self.get_slots(np.uint64(key), insert=insert))

    def key_of(self, slot: int) -> int:
        """Client key stored at a dense slot (inverse mapping)."""
        if not (0 <= slot < self.n_used):
            raise KeyError(f"slot {slot} unallocated")
        return int(self._rev[slot])

    def __len__(self) -> int:
        return self.n_used

    def __contains__(self, key: int) -> bool:
        return self.slot(key, insert=False) >= 0
