"""Overload-hardened serving front-end (round-14).

The networked RPC path between clients and the replicated store:
CRC-framed request/response wire (serving/wire.py) over real sockets
(serving/rpc.py TcpRpcServer/RpcClient) or the byte-honest in-process
loopback, admission control + deadlines + backpressure + the load-shed
ladder (serving/server.py Frontend over kvs.KVS or fleet.Fleet), and
deterministic open-loop soaks (serving/soak.py with
workload.openloop's seeded Poisson arrivals).

Round-19 adds the COLUMNAR data plane: whole request batches decode
into column arrays in one numpy pass (wire.ReqBatch/RspBatch), admit
through the ladder in O(1)-per-batch vectorized judgments
(admission.admit_batch), resolve through a preallocated completion
ring instead of per-request futures (server.ColumnarFrontend), and
drain as one framed encode per connection per pump
(rpc.ColumnarLoopback / ColumnarTcpServer, with SO_REUSEPORT accept
sharding across worker processes via launch.start_serve_workers).

Round-21 adds the shared-memory columnar IPC plane (serving/ipc.py
over transport/shm.py): N front-end worker PROCESSES doing accept +
frame decode on their own GILs, each feeding ONE device-owning store
process through zero-copy SPSC columnar shm rings — one merged
submit_batch + pump per round at full lane occupancy
(ipc.OneStoreServer, launch.start_one_store, ``--one-store``).
"""

from hermes_tpu.serving import wire
from hermes_tpu.serving.admission import AdmissionControl, TokenBucket
from hermes_tpu.serving.ipc import (OneStoreServer, ShmWorker,
                                    StoreOwner, run_shm_soak)
from hermes_tpu.serving.rpc import (ColumnarClient, ColumnarLoopback,
                                    ColumnarTcpServer, LoopbackServer,
                                    RpcClient, TcpRpcServer)
from hermes_tpu.serving.server import (ColumnarFrontend, Frontend,
                                       ServingConfig, VirtualClock,
                                       verify_columnar, verify_serving)
from hermes_tpu.serving.soak import (committed_uids, measure_capacity,
                                     run_open_loop)

__all__ = [
    "wire", "AdmissionControl", "TokenBucket", "LoopbackServer",
    "RpcClient", "TcpRpcServer", "ColumnarClient", "ColumnarLoopback",
    "ColumnarTcpServer", "ColumnarFrontend", "Frontend", "ServingConfig",
    "VirtualClock", "verify_columnar", "verify_serving", "committed_uids",
    "measure_capacity", "run_open_loop", "OneStoreServer", "ShmWorker",
    "StoreOwner", "run_shm_soak",
]
