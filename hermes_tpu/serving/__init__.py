"""Overload-hardened serving front-end (round-14).

The networked RPC path between clients and the replicated store:
CRC-framed request/response wire (serving/wire.py) over real sockets
(serving/rpc.py TcpRpcServer/RpcClient) or the byte-honest in-process
loopback, admission control + deadlines + backpressure + the load-shed
ladder (serving/server.py Frontend over kvs.KVS or fleet.Fleet), and
deterministic open-loop soaks (serving/soak.py with
workload.openloop's seeded Poisson arrivals).
"""

from hermes_tpu.serving import wire
from hermes_tpu.serving.admission import AdmissionControl, TokenBucket
from hermes_tpu.serving.rpc import LoopbackServer, RpcClient, TcpRpcServer
from hermes_tpu.serving.server import (Frontend, ServingConfig, VirtualClock,
                                       verify_serving)
from hermes_tpu.serving.soak import (committed_uids, measure_capacity,
                                     run_open_loop)

__all__ = [
    "wire", "AdmissionControl", "TokenBucket", "LoopbackServer",
    "RpcClient", "TcpRpcServer", "Frontend", "ServingConfig",
    "VirtualClock", "verify_serving", "committed_uids",
    "measure_capacity", "run_open_loop",
]
