"""Serving-path benchmark cells (round-14, BENCH_LATENCY.json).

End-to-end honesty: every latency here is measured AT THE CLIENT SOCKET
(t_send just before the framed request hits the socket, t_recv when the
framed response decodes) through a real localhost ``TcpRpcServer`` —
not a dispatch-loop estimate.  Two operating points:

  * ``latency`` — small dispatches at ``pipeline_depth >= 2`` with
    donated state (the round-8 serving pipeline's latency end), open
    loop at moderate rate: what one op costs the client wall-to-wall.
    The acceptance bar: its p50 must beat the 28 ms dispatch-loop
    figure (BENCH_r05's rounds_per_dispatch=50 p50 commit) on the host
    backend.
  * ``throughput`` — windowed closed loop (W ops in flight), larger
    session count: the serving rate the socket path sustains, with the
    same client-side percentiles.

The scenario matrix runs the latency point over the uniform / zipfian /
hot-key mixes (seed anchored to CHECKED_ZIPFIAN.json).  Host cells run
reduced shapes and carry a ``tpu_pending`` note naming the on-chip
rerun — the PIPELINE_COMPARE / CHAOS_BENCH / FUSED_COMPARE / BENCH_FLEET
protocol.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from hermes_tpu.serving import wire
from hermes_tpu.serving.rpc import RpcClient, TcpRpcServer
from hermes_tpu.serving.server import Frontend, ServingConfig
from hermes_tpu.workload.openloop import (MixSpec, make_mix, poisson_arrivals,
                                          scenario_matrix, scenario_seed)


# the BENCH_r05 rounds_per_dispatch=50 p50 commit figure the latency
# operating point is gated against — the ONE source for every drive
# (run_serve_bench here, cli --bench-latency, bench.py --serve)
DISPATCH_LOOP_P50_MS = 28.0


def improves_dispatch_loop(p50_us: Optional[float]) -> bool:
    return p50_us is not None and p50_us < DISPATCH_LOOP_P50_MS * 1e3


def host_cfg(mode: str, on_tpu: bool = False):
    """Operating-point store shapes.  Host cells are reduced (the full
    bench shape is hours of CPU); on a TPU the throughput point should
    use the bench shape (run there for the artifact refresh)."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    kw = dict(value_words=8, replay_slots=16, ops_per_session=64,
              pipeline_depth=2, op_timeout_rounds=64,
              workload=WorkloadConfig(read_frac=0.5, seed=0))
    if mode == "latency":
        kw.update(n_replicas=8 if on_tpu else 4, n_keys=1 << 10,
                  n_sessions=8)
    else:
        kw.update(n_replicas=8 if on_tpu else 4,
                  n_keys=1 << (20 if on_tpu else 12),
                  n_sessions=4096 if on_tpu else 64)
    return HermesConfig(**kw)


def _pctl(sorted_vals: List[float], q: float) -> Optional[float]:
    from hermes_tpu.stats import percentile_nearest_rank

    return percentile_nearest_rank(sorted_vals, q)


def _mk_reqs(client: RpcClient, mix: dict, n: int,
             deadline_us: int) -> List[wire.Request]:
    return [wire.Request(
        kind=("get", "put", "rmw")[int(mix["kind"][i])],
        req_id=client.next_id(), tenant=int(mix["tenant"][i]),
        key=int(mix["key"][i]), deadline_us=deadline_us,
        value=mix["value"][i].tolist()) for i in range(n)]


def run_socket_cell(cfg, scfg: ServingConfig, spec: MixSpec, n: int,
                    mode: str, rate_per_s: float = 0.0, window: int = 16,
                    deadline_us: int = 0, seed: int = 14,
                    warmup: int = 16) -> dict:
    """One measured socket cell: spin a TcpRpcServer over a fresh KVS,
    drive ``n`` ops (open-loop at ``rate_per_s``, or closed-loop with
    ``window`` in flight), return client-socket percentiles."""
    from hermes_tpu.kvs import KVS

    kvs = KVS(cfg)
    fe = Frontend(kvs, scfg)
    server = TcpRpcServer(fe)
    lat_by_status: Dict[str, List[float]] = {}
    statuses: Dict[str, int] = {}
    try:
        client = RpcClient(server.addr, fe.u)
        warm_mix = make_mix(spec, fe.n_keys, warmup, seed ^ 0xBEEF,
                            value_words=fe.u)
        for req in _mk_reqs(client, warm_mix, warmup, 0):
            client.send(req)
            client.recv_next()
        mix = make_mix(spec, fe.n_keys, n, seed, value_words=fe.u)
        reqs = _mk_reqs(client, mix, n, deadline_us)
        t_send: Dict[int, float] = {}
        t_recv: Dict[int, float] = {}
        rsp_of: Dict[int, wire.Response] = {}

        def recv_loop():
            # daemon thread: the socket may be closed under it when the
            # main thread gives up (join timeout on a slow host) — exit
            # quietly and let the cell report partial counts
            try:
                while len(t_recv) < n:
                    rsp = client.recv_next()
                    if rsp is None:
                        return
                    rsp_of[rsp.req_id] = rsp
                    t_recv[rsp.req_id] = time.perf_counter()
            except OSError:
                return

        t0 = time.perf_counter()
        if mode == "open":
            arr = poisson_arrivals(rate_per_s, n, seed)
            rx = threading.Thread(target=recv_loop, daemon=True)
            rx.start()
            for i, req in enumerate(reqs):
                lead = t0 + arr[i] - time.perf_counter()
                if lead > 0:
                    time.sleep(lead)
                t_send[req.req_id] = time.perf_counter()
                try:
                    client.send(req)
                except OSError:
                    break  # stream died: the error field reports the loss
            rx.join(timeout=60.0)
        else:  # closed loop, window in flight
            inflight = 0
            cursor = 0
            try:
                while len(t_recv) < n:
                    while inflight < window and cursor < n:
                        req = reqs[cursor]
                        cursor += 1
                        t_send[req.req_id] = time.perf_counter()
                        client.send(req)
                        inflight += 1
                    rsp = client.recv_next()
                    if rsp is None:
                        break
                    t_recv[rsp.req_id] = time.perf_counter()
                    rsp_of[rsp.req_id] = rsp
                    inflight -= 1
            except OSError:
                pass  # timeout / reset mid-run: report the partial cell
                # through the error field instead of crashing the bench
        wall = time.perf_counter() - t0
        client.close()
    finally:
        server.close()
    # a cell that lost its server mid-run must say so — percentiles over
    # an answered prefix would otherwise pass for a clean measurement
    err = None
    if server.pump_error is not None:
        err = f"server pump died: {server.pump_error!r}"
    elif len(t_recv) < n:
        err = f"answered {len(t_recv)}/{n} ops (stream died or client gave up)"
    for rid, t1 in list(t_recv.items()):
        rsp = rsp_of[rid]
        statuses[rsp.status_name] = statuses.get(rsp.status_name, 0) + 1
        lat_by_status.setdefault(rsp.status_name, []).append(
            (t1 - t_send[rid]) * 1e6)
    served = sorted(lat_by_status.get("ok", [])
                    + lat_by_status.get("rmw_abort", []))
    every = sorted(x for v in lat_by_status.values() for x in v)
    return dict(
        mode=mode, scenario=spec.name, ops=n, answered=len(t_recv),
        wall_s=round(wall, 4),
        ops_per_sec=round(len(t_recv) / max(wall, 1e-9), 1),
        statuses=statuses,
        p50_us=None if not served else round(_pctl(served, 0.5), 1),
        p99_us=None if not served else round(_pctl(served, 0.99), 1),
        p50_all_us=None if not every else round(_pctl(every, 0.5), 1),
        p99_all_us=None if not every else round(_pctl(every, 0.99), 1),
        rate_per_s=rate_per_s if mode == "open" else None,
        window=window if mode != "open" else None,
        pipeline_depth=cfg.pipeline_depth,
        error=err,
    )


def measure_decode_rate(n: int = 4096, u: int = 6, reps: int = 20,
                        seed: int = 14) -> dict:
    """Columnar wire-decode bandwidth (round-19): one drained-buffer
    request stream of ``n`` records decoded into columns per rep,
    best-of-``reps`` wall time -> MB/s.  The number the tentpole's
    one-numpy-pass claim is accountable to."""
    import numpy as np

    rng = np.random.default_rng(seed)
    b = wire.ReqBatch(
        kind=rng.choice([wire.K_GET, wire.K_PUT, wire.K_RMW], n)
            .astype(np.uint8),
        req_id=np.arange(1, n + 1, dtype=np.uint32),
        tenant=rng.integers(0, 8, n).astype(np.uint16),
        trace=np.zeros(n, np.uint16),
        deadline_us=np.zeros(n, np.uint32),
        key=rng.integers(0, 1 << 10, n).astype(np.int64),
        value=rng.integers(-99, 99, (n, u)).astype(np.int32))
    raw = wire.encode_request_batch(b, u)
    wire.decode_request_batch(raw, u)  # warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        wire.decode_request_batch(raw, u)
        best = min(best, time.perf_counter() - t0)
    return dict(records=n, bytes=len(raw),
                decode_us=round(best * 1e6, 1),
                mb_per_s=round(len(raw) / best / 1e6, 1),
                records_per_s=round(n / best, 1))


def run_columnar_worker_cell(n_workers: int, n_ops: int = 4096,
                             batch: int = 256, seed: int = 14) -> dict:
    """Closed-loop columnar ops/s through ``n_workers`` accept-sharded
    worker PROCESSES (SO_REUSEPORT, launch.start_serve_workers): one
    client thread per worker, each driving framed columnar batches over
    its own connection.  Error-field honesty: a cell that lost workers
    or clients mid-run says so instead of reporting a partial rate."""
    import numpy as np

    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.launch import start_serve_workers
    from hermes_tpu.serving.rpc import ColumnarClient
    from hermes_tpu.workload.openloop import make_mix

    cfg = HermesConfig(
        n_replicas=4, n_keys=64, n_sessions=64, value_words=8,
        pipeline_depth=2, workload=WorkloadConfig(read_frac=0.5, seed=seed))
    scfg = ServingConfig(tenant_rate_per_s=1e9, tenant_burst=1e9,
                         tenant_quota=4 * batch, queue_cap=4 * batch)
    u = cfg.value_words - 2
    spec = MixSpec(read_frac=0.5, rmw_frac=0.1, tenants=4)
    per_client = n_ops // n_workers
    err: List[str] = []
    answered = [0] * n_workers
    try:
        fleet = start_serve_workers(n_workers, cfg=cfg, scfg=scfg)
    except Exception as e:  # noqa: BLE001 — no SO_REUSEPORT, boot fail
        return dict(workers=n_workers, ops=n_ops, answered=0,
                    ops_per_sec=None, error=f"worker boot failed: {e!r}")
    # warmup happens OUTSIDE the timed wall: each client warms its own
    # worker's jit cache (one batch through its own connection), then
    # everyone meets at the barrier and the clock starts — otherwise a
    # host cell is mostly measuring n_workers XLA compiles
    gate = threading.Barrier(n_workers + 1, timeout=180.0)
    try:
        def client_loop(w: int) -> None:
            try:
                cl = ColumnarClient(fleet.addr, u)
                mix = make_mix(spec, cfg.n_keys, per_client,
                               seed + 101 * w, value_words=u)
                kind = (np.asarray(mix["kind"], np.uint8) + 1)
                key = np.asarray(mix["key"], np.int64)
                ten = np.asarray(mix["tenant"], np.uint16)
                val = np.asarray(mix["value"], np.int32
                                 ).reshape(per_client, u)

                def shoot(lo: int, hi: int) -> int:
                    k = hi - lo
                    b = wire.ReqBatch(
                        kind=kind[lo:hi], req_id=cl.next_ids(k),
                        tenant=ten[lo:hi], trace=np.zeros(k, np.uint16),
                        deadline_us=np.zeros(k, np.uint32),
                        key=key[lo:hi], value=val[lo:hi])
                    return len(cl.call_batch(b))

                shoot(0, min(batch, per_client))  # warm, untimed
                gate.wait()
                for lo in range(0, per_client, batch):
                    answered[w] += shoot(lo, min(lo + batch, per_client))
                cl.close()
            except Exception as e:  # noqa: BLE001
                err.append(f"client {w}: {e!r}")
                try:
                    gate.abort()
                except Exception:  # noqa: BLE001
                    pass

        threads = [threading.Thread(target=client_loop, args=(w,),
                                    daemon=True) for w in range(n_workers)]
        for t in threads:
            t.start()
        try:
            gate.wait()
        except threading.BrokenBarrierError:
            pass  # a client died warming up; its err entry says why
        t0 = time.perf_counter()
        for t in threads:
            t.join(timeout=120.0)
        wall = time.perf_counter() - t0
        if any(t.is_alive() for t in threads):
            err.append("client thread(s) still running at join timeout")
        if fleet.alive() < n_workers:
            err.append(f"only {fleet.alive()}/{n_workers} workers alive "
                       "at the end of the run")
    finally:
        fleet.stop()
    total = sum(answered)
    if total < n_workers * per_client:
        err.append(f"answered {total}/{n_workers * per_client} ops")
    return dict(
        workers=n_workers, ops=n_workers * per_client, answered=total,
        batch=batch, wall_s=round(wall, 4),
        ops_per_sec=None if err else round(total / max(wall, 1e-9), 1),
        error="; ".join(err) if err else None)


def run_serve_bench(n: Optional[int] = None, seed: Optional[int] = None,
                    scenarios: bool = True) -> dict:
    """The BENCH_LATENCY.json payload: latency + throughput operating
    points (client-socket truth) and the scenario matrix on the latency
    point."""
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    seed = scenario_seed() if seed is None else seed
    n = (400 if on_tpu else 200) if n is None else n
    scfg = ServingConfig(tenant_rate_per_s=1e6, tenant_burst=1e5,
                         tenant_quota=64, queue_cap=256)
    lat_cfg = host_cfg("latency", on_tpu)
    thr_cfg = host_cfg("throughput", on_tpu)
    # moderate open-loop rate for the latency point: well under the
    # closed-loop capacity so queueing delay does not pollute the
    # service-latency number (overload truth lives in the serving gate)
    cells = {}
    probe = run_socket_cell(lat_cfg, scfg, MixSpec(name="uniform"),
                            max(32, n // 4), mode="closed", window=8,
                            seed=seed)
    cap = probe["ops_per_sec"]
    cells["latency"] = run_socket_cell(
        lat_cfg, scfg, MixSpec(name="uniform"), n, mode="open",
        rate_per_s=max(10.0, 0.2 * cap), seed=seed)
    cells["throughput"] = run_socket_cell(
        thr_cfg, scfg, MixSpec(name="uniform"), 2 * n, mode="closed",
        window=64, seed=seed)
    # round-19 columnar cells: wire-decode bandwidth, the in-process
    # loopback floor, and accept-sharded worker scaling at 1/2/4
    # workers — each quoted against the scalar throughput cell above
    scalar_ops = cells["throughput"]["ops_per_sec"]
    cells["columnar_decode"] = measure_decode_rate(seed=seed)
    try:
        from hermes_tpu.serving.soak import measure_columnar_floor

        fl = measure_columnar_floor(seed=seed)
        fl["speedup_vs_scalar"] = round(
            fl["ops_per_sec"] / max(scalar_ops, 1e-9), 1)
        fl["scalar_ops_per_sec"] = scalar_ops
        cells["columnar_loopback"] = fl
    except Exception as e:  # noqa: BLE001 — honesty over silence
        cells["columnar_loopback"] = dict(ops_per_sec=None,
                                          error=f"floor failed: {e!r}")
    for w in (1, 2, 4):
        c = run_columnar_worker_cell(w, seed=seed)
        if c["ops_per_sec"] is not None:
            c["speedup_vs_scalar"] = round(
                c["ops_per_sec"] / max(scalar_ops, 1e-9), 1)
        cells[f"columnar_workers_{w}"] = c
    out = dict(
        cells=cells, capacity_probe=probe,
        dispatch_loop_p50_ms=DISPATCH_LOOP_P50_MS,
        latency_p50_improves=improves_dispatch_loop(
            cells["latency"]["p50_us"]),
        platform=jax.devices()[0].platform,
        device=getattr(jax.devices()[0], "device_kind", "?"),
        seed=seed,
        note="p50/p99 measured from the client socket (framed RPC over "
             "localhost TCP), NOT dispatch-loop estimates; "
             "dispatch_loop_p50_ms is the BENCH_r05 rounds_per_dispatch="
             "50 figure the latency point is gated against",
    )
    if scenarios:
        mat = {}
        for spec in scenario_matrix():
            mat[spec.name] = run_socket_cell(
                lat_cfg, scfg, spec, max(64, n // 2), mode="open",
                rate_per_s=max(10.0, 0.2 * cap), seed=seed)
        out["scenarios"] = mat
    bad = {name: c["error"]
           for name, c in [("capacity_probe", probe), *cells.items(),
                           *out.get("scenarios", {}).items()]
           if c.get("error")}
    if bad:
        out["errors"] = bad
    if not on_tpu:
        out["tpu_pending"] = (
            "host-backend stand-in at reduced shapes — rerun bench.py "
            "--serve on the chip (throughput point at the bench shape) "
            "alongside the carried-over PIPELINE_COMPARE.json / "
            "CHAOS_BENCH.json / FUSED_COMPARE.json / BENCH_FLEET.json "
            "artifacts")
    return out
