"""Serving-path benchmark cells (round-14, BENCH_LATENCY.json).

End-to-end honesty: every latency here is measured AT THE CLIENT SOCKET
(t_send just before the framed request hits the socket, t_recv when the
framed response decodes) through a real localhost ``TcpRpcServer`` —
not a dispatch-loop estimate.  Two operating points:

  * ``latency`` — small dispatches at ``pipeline_depth >= 2`` with
    donated state (the round-8 serving pipeline's latency end), open
    loop at moderate rate: what one op costs the client wall-to-wall.
    The acceptance bar: its p50 must beat the 28 ms dispatch-loop
    figure (BENCH_r05's rounds_per_dispatch=50 p50 commit) on the host
    backend.
  * ``throughput`` — windowed closed loop (W ops in flight), larger
    session count: the serving rate the socket path sustains, with the
    same client-side percentiles.

The scenario matrix runs the latency point over the uniform / zipfian /
hot-key mixes (seed anchored to CHECKED_ZIPFIAN.json).  Host cells run
reduced shapes and carry a ``tpu_pending`` note naming the on-chip
rerun — the PIPELINE_COMPARE / CHAOS_BENCH / FUSED_COMPARE / BENCH_FLEET
protocol.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from hermes_tpu.serving import wire
from hermes_tpu.serving.rpc import RpcClient, TcpRpcServer
from hermes_tpu.serving.server import Frontend, ServingConfig
from hermes_tpu.workload.openloop import (MixSpec, make_mix, poisson_arrivals,
                                          scenario_matrix, scenario_seed)


# the BENCH_r05 rounds_per_dispatch=50 p50 commit figure the latency
# operating point is gated against — the ONE source for every drive
# (run_serve_bench here, cli --bench-latency, bench.py --serve)
DISPATCH_LOOP_P50_MS = 28.0


def improves_dispatch_loop(p50_us: Optional[float]) -> bool:
    return p50_us is not None and p50_us < DISPATCH_LOOP_P50_MS * 1e3


def host_cfg(mode: str, on_tpu: bool = False):
    """Operating-point store shapes.  Host cells are reduced (the full
    bench shape is hours of CPU); on a TPU the throughput point should
    use the bench shape (run there for the artifact refresh)."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    kw = dict(value_words=8, replay_slots=16, ops_per_session=64,
              pipeline_depth=2, op_timeout_rounds=64,
              workload=WorkloadConfig(read_frac=0.5, seed=0))
    if mode == "latency":
        kw.update(n_replicas=8 if on_tpu else 4, n_keys=1 << 10,
                  n_sessions=8)
    else:
        kw.update(n_replicas=8 if on_tpu else 4,
                  n_keys=1 << (20 if on_tpu else 12),
                  n_sessions=4096 if on_tpu else 64)
    return HermesConfig(**kw)


def _pctl(sorted_vals: List[float], q: float) -> Optional[float]:
    from hermes_tpu.stats import percentile_nearest_rank

    return percentile_nearest_rank(sorted_vals, q)


def _mk_reqs(client: RpcClient, mix: dict, n: int,
             deadline_us: int) -> List[wire.Request]:
    return [wire.Request(
        kind=("get", "put", "rmw")[int(mix["kind"][i])],
        req_id=client.next_id(), tenant=int(mix["tenant"][i]),
        key=int(mix["key"][i]), deadline_us=deadline_us,
        value=mix["value"][i].tolist()) for i in range(n)]


def run_socket_cell(cfg, scfg: ServingConfig, spec: MixSpec, n: int,
                    mode: str, rate_per_s: float = 0.0, window: int = 16,
                    deadline_us: int = 0, seed: int = 14,
                    warmup: int = 16) -> dict:
    """One measured socket cell: spin a TcpRpcServer over a fresh KVS,
    drive ``n`` ops (open-loop at ``rate_per_s``, or closed-loop with
    ``window`` in flight), return client-socket percentiles."""
    from hermes_tpu.kvs import KVS

    kvs = KVS(cfg)
    fe = Frontend(kvs, scfg)
    server = TcpRpcServer(fe)
    lat_by_status: Dict[str, List[float]] = {}
    statuses: Dict[str, int] = {}
    try:
        client = RpcClient(server.addr, fe.u)
        warm_mix = make_mix(spec, fe.n_keys, warmup, seed ^ 0xBEEF,
                            value_words=fe.u)
        for req in _mk_reqs(client, warm_mix, warmup, 0):
            client.send(req)
            client.recv_next()
        mix = make_mix(spec, fe.n_keys, n, seed, value_words=fe.u)
        reqs = _mk_reqs(client, mix, n, deadline_us)
        t_send: Dict[int, float] = {}
        t_recv: Dict[int, float] = {}
        rsp_of: Dict[int, wire.Response] = {}

        def recv_loop():
            # daemon thread: the socket may be closed under it when the
            # main thread gives up (join timeout on a slow host) — exit
            # quietly and let the cell report partial counts
            try:
                while len(t_recv) < n:
                    rsp = client.recv_next()
                    if rsp is None:
                        return
                    rsp_of[rsp.req_id] = rsp
                    t_recv[rsp.req_id] = time.perf_counter()
            except OSError:
                return

        t0 = time.perf_counter()
        if mode == "open":
            arr = poisson_arrivals(rate_per_s, n, seed)
            rx = threading.Thread(target=recv_loop, daemon=True)
            rx.start()
            for i, req in enumerate(reqs):
                lead = t0 + arr[i] - time.perf_counter()
                if lead > 0:
                    time.sleep(lead)
                t_send[req.req_id] = time.perf_counter()
                try:
                    client.send(req)
                except OSError:
                    break  # stream died: the error field reports the loss
            rx.join(timeout=60.0)
        else:  # closed loop, window in flight
            inflight = 0
            cursor = 0
            try:
                while len(t_recv) < n:
                    while inflight < window and cursor < n:
                        req = reqs[cursor]
                        cursor += 1
                        t_send[req.req_id] = time.perf_counter()
                        client.send(req)
                        inflight += 1
                    rsp = client.recv_next()
                    if rsp is None:
                        break
                    t_recv[rsp.req_id] = time.perf_counter()
                    rsp_of[rsp.req_id] = rsp
                    inflight -= 1
            except OSError:
                pass  # timeout / reset mid-run: report the partial cell
                # through the error field instead of crashing the bench
        wall = time.perf_counter() - t0
        client.close()
    finally:
        server.close()
    # a cell that lost its server mid-run must say so — percentiles over
    # an answered prefix would otherwise pass for a clean measurement
    err = None
    if server.pump_error is not None:
        err = f"server pump died: {server.pump_error!r}"
    elif len(t_recv) < n:
        err = f"answered {len(t_recv)}/{n} ops (stream died or client gave up)"
    for rid, t1 in list(t_recv.items()):
        rsp = rsp_of[rid]
        statuses[rsp.status_name] = statuses.get(rsp.status_name, 0) + 1
        lat_by_status.setdefault(rsp.status_name, []).append(
            (t1 - t_send[rid]) * 1e6)
    served = sorted(lat_by_status.get("ok", [])
                    + lat_by_status.get("rmw_abort", []))
    every = sorted(x for v in lat_by_status.values() for x in v)
    return dict(
        mode=mode, scenario=spec.name, ops=n, answered=len(t_recv),
        wall_s=round(wall, 4),
        ops_per_sec=round(len(t_recv) / max(wall, 1e-9), 1),
        statuses=statuses,
        p50_us=None if not served else round(_pctl(served, 0.5), 1),
        p99_us=None if not served else round(_pctl(served, 0.99), 1),
        p50_all_us=None if not every else round(_pctl(every, 0.5), 1),
        p99_all_us=None if not every else round(_pctl(every, 0.99), 1),
        rate_per_s=rate_per_s if mode == "open" else None,
        window=window if mode != "open" else None,
        pipeline_depth=cfg.pipeline_depth,
        error=err,
    )


def measure_decode_rate(n: int = 4096, u: int = 6, reps: int = 20,
                        seed: int = 14) -> dict:
    """Columnar wire-decode bandwidth (round-19): one drained-buffer
    request stream of ``n`` records decoded into columns per rep,
    best-of-``reps`` wall time -> MB/s.  The number the tentpole's
    one-numpy-pass claim is accountable to."""
    import numpy as np

    rng = np.random.default_rng(seed)
    b = wire.ReqBatch(
        kind=rng.choice([wire.K_GET, wire.K_PUT, wire.K_RMW], n)
            .astype(np.uint8),
        req_id=np.arange(1, n + 1, dtype=np.uint32),
        tenant=rng.integers(0, 8, n).astype(np.uint16),
        trace=np.zeros(n, np.uint16),
        deadline_us=np.zeros(n, np.uint32),
        key=rng.integers(0, 1 << 10, n).astype(np.int64),
        value=rng.integers(-99, 99, (n, u)).astype(np.int32))
    raw = wire.encode_request_batch(b, u)
    wire.decode_request_batch(raw, u)  # warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        wire.decode_request_batch(raw, u)
        best = min(best, time.perf_counter() - t0)
    return dict(records=n, bytes=len(raw),
                decode_us=round(best * 1e6, 1),
                mb_per_s=round(len(raw) / best / 1e6, 1),
                records_per_s=round(n / best, 1))


def run_columnar_worker_cell(n_workers: int, n_ops: int = 4096,
                             batch: int = 256, seed: int = 14) -> dict:
    """Closed-loop columnar ops/s through ``n_workers`` accept-sharded
    worker PROCESSES (SO_REUSEPORT, launch.start_serve_workers): one
    client thread per worker, each driving framed columnar batches over
    its own connection.  Error-field honesty: a cell that lost workers
    or clients mid-run says so instead of reporting a partial rate."""
    import numpy as np

    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.launch import start_serve_workers
    from hermes_tpu.serving.rpc import ColumnarClient
    from hermes_tpu.workload.openloop import make_mix

    cfg = HermesConfig(
        n_replicas=4, n_keys=64, n_sessions=64, value_words=8,
        pipeline_depth=2, workload=WorkloadConfig(read_frac=0.5, seed=seed))
    scfg = ServingConfig(tenant_rate_per_s=1e9, tenant_burst=1e9,
                         tenant_quota=4 * batch, queue_cap=4 * batch)
    u = cfg.value_words - 2
    spec = MixSpec(read_frac=0.5, rmw_frac=0.1, tenants=4)
    per_client = n_ops // n_workers
    err: List[str] = []
    answered = [0] * n_workers
    try:
        fleet = start_serve_workers(n_workers, cfg=cfg, scfg=scfg)
    except Exception as e:  # noqa: BLE001 — no SO_REUSEPORT, boot fail
        return dict(workers=n_workers, ops=n_ops, answered=0,
                    ops_per_sec=None, error=f"worker boot failed: {e!r}")
    # warmup happens OUTSIDE the timed wall: each client warms its own
    # worker's jit cache (one batch through its own connection), then
    # everyone meets at the barrier and the clock starts — otherwise a
    # host cell is mostly measuring n_workers XLA compiles
    gate = threading.Barrier(n_workers + 1, timeout=180.0)
    try:
        def client_loop(w: int) -> None:
            try:
                cl = ColumnarClient(fleet.addr, u)
                mix = make_mix(spec, cfg.n_keys, per_client,
                               seed + 101 * w, value_words=u)
                kind = (np.asarray(mix["kind"], np.uint8) + 1)
                key = np.asarray(mix["key"], np.int64)
                ten = np.asarray(mix["tenant"], np.uint16)
                val = np.asarray(mix["value"], np.int32
                                 ).reshape(per_client, u)

                def shoot(lo: int, hi: int) -> int:
                    k = hi - lo
                    b = wire.ReqBatch(
                        kind=kind[lo:hi], req_id=cl.next_ids(k),
                        tenant=ten[lo:hi], trace=np.zeros(k, np.uint16),
                        deadline_us=np.zeros(k, np.uint32),
                        key=key[lo:hi], value=val[lo:hi])
                    return len(cl.call_batch(b))

                shoot(0, min(batch, per_client))  # warm, untimed
                gate.wait()
                for lo in range(0, per_client, batch):
                    answered[w] += shoot(lo, min(lo + batch, per_client))
                cl.close()
            except Exception as e:  # noqa: BLE001
                err.append(f"client {w}: {e!r}")
                try:
                    gate.abort()
                except Exception:  # noqa: BLE001
                    pass

        threads = [threading.Thread(target=client_loop, args=(w,),
                                    daemon=True) for w in range(n_workers)]
        for t in threads:
            t.start()
        try:
            gate.wait()
        except threading.BrokenBarrierError:
            pass  # a client died warming up; its err entry says why
        t0 = time.perf_counter()
        for t in threads:
            t.join(timeout=120.0)
        wall = time.perf_counter() - t0
        if any(t.is_alive() for t in threads):
            err.append("client thread(s) still running at join timeout")
        if fleet.alive() < n_workers:
            err.append(f"only {fleet.alive()}/{n_workers} workers alive "
                       "at the end of the run")
    finally:
        fleet.stop()
    total = sum(answered)
    if total < n_workers * per_client:
        err.append(f"answered {total}/{n_workers * per_client} ops")
    return dict(
        workers=n_workers, ops=n_workers * per_client, answered=total,
        batch=batch, wall_s=round(wall, 4),
        ops_per_sec=None if err else round(total / max(wall, 1e-9), 1),
        # honesty label (round-21): these cells scale because every
        # worker owns a PRIVATE store — N device programs, not one.
        # The shared-store numbers live in the one_store_workers_N
        # cells (run_one_store_cell).
        topology="private-store-per-worker",
        error="; ".join(err) if err else None)


def _one_store_client_main(w: int, addr, u: int, n_keys: int,
                           per_client: int, batch: int, seed: int,
                           ready_q, go_ev, out_q) -> None:
    """One closed-loop bench client PROCESS for the one-store cell
    (module-level so ``spawn`` can import it).  Client processes — not
    threads — keep the parent's GIL free for the owner pump, so the
    cell measures the shm plane, not client-side encode contention."""
    import numpy as np

    from hermes_tpu.serving.rpc import ColumnarClient
    from hermes_tpu.workload.openloop import MixSpec, make_mix

    try:
        cl = ColumnarClient(addr, u)
        spec = MixSpec(read_frac=0.5, rmw_frac=0.1, tenants=4)
        n_mix = per_client + batch  # one extra untimed warmup batch
        mix = make_mix(spec, n_keys, n_mix, seed + 101 * w,
                       value_words=u)
        kind = (np.asarray(mix["kind"], np.uint8) + 1)
        key = np.asarray(mix["key"], np.int64)
        ten = np.asarray(mix["tenant"], np.uint16)
        val = np.asarray(mix["value"], np.int32).reshape(n_mix, u)

        def _encode(lo: int, hi: int) -> bytes:
            k = hi - lo
            return wire.encode_request_batch(wire.ReqBatch(
                kind=kind[lo:hi], req_id=cl.next_ids(k),
                tenant=ten[lo:hi], trace=np.zeros(k, np.uint16),
                deadline_us=np.zeros(k, np.uint32),
                key=key[lo:hi], value=val[lo:hi]), u)

        # pre-encode every frame OUTSIDE the timed window, and stay
        # columnar on the receive side (row counts off RspBatch, no
        # per-row Response objects): on a small host the clients share
        # cores with the owner pump, so client-side per-op Python is
        # time STOLEN from the store
        frames = [(_encode(lo, min(lo + batch, n_mix)),
                   min(lo + batch, n_mix) - lo)
                  for lo in range(0, n_mix, batch)]
        warm_raw, warm_rows = frames[0]
        cl.fsock.send(warm_raw)
        got = 0
        while got < warm_rows:
            rb = cl.recv_batch()
            if rb is None:
                raise ConnectionError("server closed during warmup")
            got += len(rb)
        statuses = np.zeros(256, np.int64)
        ready_q.put(w)
        go_ev.wait()
        # closed loop at window 2: one batch resolving while the next
        # is already on the wire, so the owner's merge never starves
        # between a client's batches
        t0 = time.perf_counter()
        n = 0
        outstanding = 0
        cursor = 1  # frame 0 was the warmup
        total = sum(rows for _, rows in frames[1:])
        while n < total:
            while cursor < len(frames) and outstanding < 2 * batch:
                raw, rows = frames[cursor]
                cl.fsock.send(raw)
                outstanding += rows
                cursor += 1
            rb = cl.recv_batch()
            if rb is None:
                raise ConnectionError("server closed mid-run")
            k = len(rb)
            n += k
            outstanding -= k
            statuses += np.bincount(rb.status, minlength=256)
        wall = time.perf_counter() - t0
        st = {wire.STATUS_NAMES.get(i, str(i)): int(c)
              for i, c in enumerate(statuses) if c}
        out_q.put((w, n, wall, None, st))
        cl.close()
    except Exception as e:  # noqa: BLE001 — the cell reports it
        out_q.put((w, 0, 0.0, repr(e), {}))


def run_one_store_cell(n_workers: int, n_clients: Optional[int] = None,
                       n_ops: int = 131072, batch: int = 2048,
                       n_sessions: int = 2048, n_keys: int = 2048,
                       seed: int = 14) -> dict:
    """Closed-loop columnar ops/s through ``n_workers`` shm front-end
    processes feeding ONE store (serving/ipc.py, round-21) — the
    shared-store counterpart of ``run_columnar_worker_cell``'s
    private-store scale-out, and the BENCH_LATENCY cell the shm gate's
    floor compares against ``columnar_loopback``.  Client PROCESSES
    drive framed columnar batches over SO_REUSEPORT-sharded sockets;
    the parent runs only the owner pump.  The store is the scale-out
    shape (``n_sessions`` lanes): the whole point of the plane is that
    one process's socket work cannot feed a large store — the loopback
    floor's 128-session shape would cap the cell at the client edge,
    not the store.  Error-field honesty as everywhere: lost workers,
    short counts, or a pump error make the cell say so instead of
    quoting a partial rate."""
    import multiprocessing as mp
    import queue as _queue

    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.serving.ipc import OneStoreServer

    n_clients = n_clients or 2 * n_workers
    per_client = n_ops // n_clients
    cfg = HermesConfig(
        n_replicas=4, n_keys=n_keys, n_sessions=n_sessions,
        value_words=8, pipeline_depth=2,
        workload=WorkloadConfig(read_frac=0.5, seed=seed))
    scfg = ServingConfig(tenant_rate_per_s=1e9, tenant_burst=1e9,
                         tenant_quota=1 << 20,
                         queue_cap=4 * batch * n_clients)
    err: List[str] = []
    store = KVS(cfg)
    try:
        srv = OneStoreServer(store, scfg, n_workers=n_workers,
                             nslots=8, slot_rows=batch)
    except Exception as e:  # noqa: BLE001 — no SO_REUSEPORT, boot fail
        return dict(workers=n_workers, clients=n_clients, ops=n_ops,
                    answered=0, ops_per_sec=None, topology="one-store",
                    error=f"one-store boot failed: {e!r}")
    ctx = mp.get_context("spawn")
    ready_q, out_q, go_ev = ctx.Queue(), ctx.Queue(), ctx.Event()
    clients = [ctx.Process(
        target=_one_store_client_main,
        args=(c, srv.addr, srv.fe.u, cfg.n_keys, per_client, batch,
              seed, ready_q, go_ev, out_q),
        daemon=True) for c in range(n_clients)]
    answered = 0
    walls: List[float] = []
    try:
        for p in clients:
            p.start()
        ready = 0
        while ready < n_clients:
            try:
                ready_q.get(timeout=180.0)
                ready += 1
            except _queue.Empty:
                err.append(f"only {ready}/{n_clients} clients warmed up")
                break
        go_ev.set()
        t0 = time.perf_counter()
        statuses: Dict[str, int] = {}
        for _ in range(ready):
            try:
                _w, n, wall, e, st = out_q.get(timeout=300.0)
            except _queue.Empty:
                err.append("client result(s) missing at timeout")
                break
            answered += n
            walls.append(wall)
            for name, c in st.items():
                statuses[name] = statuses.get(name, 0) + c
            if e is not None:
                err.append(f"client {_w}: {e}")
        parent_wall = time.perf_counter() - t0
        for p in clients:
            p.join(timeout=10.0)
        if srv.alive() < n_workers:
            err.append(f"only {srv.alive()}/{n_workers} workers alive "
                       "at the end of the run")
        if srv.pump_error is not None:
            err.append(f"owner pump died: {srv.pump_error!r}")
    finally:
        for p in clients:
            if p.is_alive():
                p.terminate()
        srv.close()
    if answered < n_clients * per_client:
        err.append(f"answered {answered}/{n_clients * per_client} ops")
    # rate over the slowest client's closed-loop wall: every client ran
    # the whole window, so total/max(wall) is the sustained aggregate
    wall = max(walls) if walls else parent_wall
    ipc = srv.owner.counters()
    return dict(
        workers=n_workers, clients=n_clients,
        ops=n_clients * per_client, answered=answered, batch=batch,
        n_sessions=n_sessions, n_keys=n_keys, wall_s=round(wall, 4),
        ops_per_sec=None if err else round(answered / max(wall, 1e-9), 1),
        topology="one-store", statuses=statuses, ipc=ipc,
        error="; ".join(err) if err else None)


def run_serve_bench(n: Optional[int] = None, seed: Optional[int] = None,
                    scenarios: bool = True) -> dict:
    """The BENCH_LATENCY.json payload: latency + throughput operating
    points (client-socket truth) and the scenario matrix on the latency
    point."""
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    seed = scenario_seed() if seed is None else seed
    n = (400 if on_tpu else 200) if n is None else n
    scfg = ServingConfig(tenant_rate_per_s=1e6, tenant_burst=1e5,
                         tenant_quota=64, queue_cap=256)
    lat_cfg = host_cfg("latency", on_tpu)
    thr_cfg = host_cfg("throughput", on_tpu)
    # moderate open-loop rate for the latency point: well under the
    # closed-loop capacity so queueing delay does not pollute the
    # service-latency number (overload truth lives in the serving gate)
    cells = {}
    probe = run_socket_cell(lat_cfg, scfg, MixSpec(name="uniform"),
                            max(32, n // 4), mode="closed", window=8,
                            seed=seed)
    cap = probe["ops_per_sec"]
    cells["latency"] = run_socket_cell(
        lat_cfg, scfg, MixSpec(name="uniform"), n, mode="open",
        rate_per_s=max(10.0, 0.2 * cap), seed=seed)
    cells["throughput"] = run_socket_cell(
        thr_cfg, scfg, MixSpec(name="uniform"), 2 * n, mode="closed",
        window=64, seed=seed)
    # round-19 columnar cells: wire-decode bandwidth, the in-process
    # loopback floor, and accept-sharded worker scaling at 1/2/4
    # workers — each quoted against the scalar throughput cell above
    scalar_ops = cells["throughput"]["ops_per_sec"]
    cells["columnar_decode"] = measure_decode_rate(seed=seed)
    try:
        from hermes_tpu.serving.soak import measure_columnar_floor

        fl = measure_columnar_floor(seed=seed)
        fl["speedup_vs_scalar"] = round(
            fl["ops_per_sec"] / max(scalar_ops, 1e-9), 1)
        fl["scalar_ops_per_sec"] = scalar_ops
        cells["columnar_loopback"] = fl
    except Exception as e:  # noqa: BLE001 — honesty over silence
        cells["columnar_loopback"] = dict(ops_per_sec=None,
                                          error=f"floor failed: {e!r}")
    for w in (1, 2, 4):
        c = run_columnar_worker_cell(w, seed=seed)
        if c["ops_per_sec"] is not None:
            c["speedup_vs_scalar"] = round(
                c["ops_per_sec"] / max(scalar_ops, 1e-9), 1)
        cells[f"columnar_workers_{w}"] = c
    # round-21 one-store cells: N shm front-end processes feeding ONE
    # store (the shared-store truth the private-store cells above are
    # not) — quoted against the loopback floor, the single-process
    # ceiling the plane exists to beat
    floor_ops = cells["columnar_loopback"].get("ops_per_sec") or 0.0
    for w in (2, 4):
        c = run_one_store_cell(w, seed=seed)
        if c["ops_per_sec"] is not None:
            c["speedup_vs_scalar"] = round(
                c["ops_per_sec"] / max(scalar_ops, 1e-9), 1)
            c["speedup_vs_loopback"] = round(
                c["ops_per_sec"] / max(floor_ops, 1e-9), 2)
            c["loopback_ops_per_sec"] = floor_ops
        cells[f"one_store_workers_{w}"] = c
    out = dict(
        cells=cells, capacity_probe=probe,
        dispatch_loop_p50_ms=DISPATCH_LOOP_P50_MS,
        latency_p50_improves=improves_dispatch_loop(
            cells["latency"]["p50_us"]),
        platform=jax.devices()[0].platform,
        device=getattr(jax.devices()[0], "device_kind", "?"),
        seed=seed,
        note="p50/p99 measured from the client socket (framed RPC over "
             "localhost TCP), NOT dispatch-loop estimates; "
             "dispatch_loop_p50_ms is the BENCH_r05 rounds_per_dispatch="
             "50 figure the latency point is gated against",
    )
    if scenarios:
        mat = {}
        for spec in scenario_matrix():
            mat[spec.name] = run_socket_cell(
                lat_cfg, scfg, spec, max(64, n // 2), mode="open",
                rate_per_s=max(10.0, 0.2 * cap), seed=seed)
        out["scenarios"] = mat
    bad = {name: c["error"]
           for name, c in [("capacity_probe", probe), *cells.items(),
                           *out.get("scenarios", {}).items()]
           if c.get("error")}
    if bad:
        out["errors"] = bad
    if not on_tpu:
        out["tpu_pending"] = (
            "host-backend stand-in at reduced shapes — rerun bench.py "
            "--serve on the chip (throughput point at the bench shape) "
            "alongside the carried-over PIPELINE_COMPARE.json / "
            "CHAOS_BENCH.json / FUSED_COMPARE.json / BENCH_FLEET.json "
            "artifacts")
    return out
