"""Admission control of the serving front-end (round-14).

Three rungs stand between a request and the intake queue, each refusing
LOUDLY (wire.S_RETRY_AFTER with a reason + retry hint) instead of
buffering silently:

  1. the overload ladder — a queue-occupancy staircase that composes
     with the store's quorum-loss degraded mode: rung 1 sheds NEW
     writes (reads still serve — exactly the round-11
     ``min_healthy_for_writes`` policy pulled forward to the front
     door, where refusing is cheaper than admitting a doomed op), rung
     2 additionally sheds non-hot-key reads (the hot set keeps serving:
     under a zipfian storm that preserves the bulk of the offered read
     value at a fraction of the lane cost);
  2. the per-tenant session quota — a cap on client-visible in-flight
     ops, the serving analogue of the reference's per-worker session
     arrays (SURVEY.md §1 L5) — and the bounded intake queue
     (R_QUEUE_FULL);
  3. the per-tenant token bucket — sustained rate + burst, refilled on
     the SERVING clock (virtual in deterministic soaks, monotonic wall
     time on sockets), so one tenant cannot starve the rest.  Charged
     LAST: a quota/queue refusal never burns the tenant's rate budget.
All state is plain floats/ints driven by a caller-supplied ``now``:
given the same arrival schedule the whole admission path replays
byte-identically (the chaos-schedule discipline applied to overload).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from hermes_tpu.serving import wire


class TokenBucket:
    """Deterministic token bucket on a caller-supplied clock."""

    def __init__(self, rate_per_s: float, burst: float):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError("rate_per_s and burst must be > 0")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t_last: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._t_last is not None and now > self._t_last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t_last) * self.rate)
        self._t_last = now if self._t_last is None else max(self._t_last, now)

    def take(self, now: float) -> bool:
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def wait_s(self, now: float) -> float:
        """Seconds until one token accrues (the retry_after hint)."""
        self._refill(now)
        if self.tokens >= 1.0:
            return 0.0
        return (1.0 - self.tokens) / self.rate


@dataclasses.dataclass
class TenantState:
    """Per-tenant admission + accounting state."""

    bucket: TokenBucket
    inflight: int = 0       # client-visible in-flight ops (quota unit)
    admitted: int = 0
    completed: int = 0      # S_OK + S_RMW_ABORT
    retry_after: int = 0    # all front-door refusals
    shed: int = 0           # refusals by the overload ladder specifically
    deadline: int = 0
    rejected: int = 0       # store-level definitive rejects
    lost: int = 0

    def counters(self) -> dict:
        return dict(admitted=self.admitted, completed=self.completed,
                    retry_after=self.retry_after, shed=self.shed,
                    deadline=self.deadline, rejected=self.rejected,
                    lost=self.lost, inflight=self.inflight)


class AdmissionControl:
    """The front door: ladder + bucket + quota + queue bound.

    ``admit`` returns ``(reason, retry_after_s)`` — reason ``R_NONE``
    means admitted (the caller enqueues and calls ``note_admitted``).
    """

    def __init__(self, scfg):
        self.scfg = scfg
        self.tenants: Dict[int, TenantState] = {}

    def tenant(self, t: int) -> TenantState:
        ts = self.tenants.get(t)
        if ts is None:
            ts = self.tenants[t] = TenantState(TokenBucket(
                self.scfg.tenant_rate_per_s, self.scfg.tenant_burst))
        return ts

    # -- the overload ladder -------------------------------------------------

    def ladder_level(self, queue_len: int, degraded: bool) -> int:
        """Rung for the CURRENT pressure: 2 past the read watermark, 1
        past the write watermark OR while the store is in quorum-loss
        degraded mode (writes cannot commit — refuse at the door rather
        than admit a doomed op), else 0."""
        cap = self.scfg.queue_cap
        if queue_len >= int(cap * self.scfg.shed_read_frac):
            return 2
        if degraded or queue_len >= int(cap * self.scfg.shed_write_frac):
            return 1
        return 0

    def admit(self, kind: str, key: int, tenant: int, now: float,
              queue_len: int, degraded: bool) -> Tuple[int, float]:
        level = self.ladder_level(queue_len, degraded)
        ts = self.tenant(tenant)
        retry_s = self.scfg.retry_after_floor_s
        if level >= 1 and kind != "get":
            ts.shed += 1
            ts.retry_after += 1
            return wire.R_SHED_WRITE, retry_s
        if level >= 2 and kind == "get" \
                and key not in self.scfg.hot_key_set:
            ts.shed += 1
            ts.retry_after += 1
            return wire.R_SHED_READ, retry_s
        if ts.inflight >= self.scfg.tenant_quota:
            ts.retry_after += 1
            return wire.R_QUOTA, retry_s
        if queue_len >= self.scfg.queue_cap:
            ts.retry_after += 1
            return wire.R_QUEUE_FULL, retry_s
        # the bucket is charged LAST: a quota/queue refusal must not also
        # burn the tenant's rate budget, or a backed-up tenant re-emerges
        # from the jam rate-starved by its own refused retries
        if not ts.bucket.take(now):
            ts.retry_after += 1
            return wire.R_RATE, max(retry_s, ts.bucket.wait_s(now))
        return wire.R_NONE, 0.0

    def note_admitted(self, tenant: int) -> None:
        ts = self.tenant(tenant)
        ts.admitted += 1
        ts.inflight += 1

    def note_resolved(self, tenant: int, status: int) -> None:
        ts = self.tenant(tenant)
        ts.inflight -= 1
        assert ts.inflight >= 0, "tenant inflight went negative"
        if status in (wire.S_OK, wire.S_RMW_ABORT):
            ts.completed += 1
        elif status == wire.S_DEADLINE:
            ts.deadline += 1
        elif status == wire.S_REJECTED:
            ts.rejected += 1
        elif status == wire.S_LOST:
            ts.lost += 1

    def counters(self) -> dict:
        return {t: ts.counters() for t, ts in sorted(self.tenants.items())}
