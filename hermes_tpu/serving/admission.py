"""Admission control of the serving front-end (round-14).

Three rungs stand between a request and the intake queue, each refusing
LOUDLY (wire.S_RETRY_AFTER with a reason + retry hint) instead of
buffering silently:

  1. the overload ladder — a queue-occupancy staircase that composes
     with the store's quorum-loss degraded mode: rung 1 sheds NEW
     writes (reads still serve — exactly the round-11
     ``min_healthy_for_writes`` policy pulled forward to the front
     door, where refusing is cheaper than admitting a doomed op), rung
     2 additionally sheds non-hot-key reads (the hot set keeps serving:
     under a zipfian storm that preserves the bulk of the offered read
     value at a fraction of the lane cost);
  2. the per-tenant session quota — a cap on client-visible in-flight
     ops, the serving analogue of the reference's per-worker session
     arrays (SURVEY.md §1 L5) — and the bounded intake queue
     (R_QUEUE_FULL);
  3. the per-tenant token bucket — sustained rate + burst, refilled on
     the SERVING clock (virtual in deterministic soaks, monotonic wall
     time on sockets), so one tenant cannot starve the rest.  Charged
     LAST: a quota/queue refusal never burns the tenant's rate budget.
All state is plain floats/ints driven by a caller-supplied ``now``:
given the same arrival schedule the whole admission path replays
byte-identically (the chaos-schedule discipline applied to overload).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from hermes_tpu.serving import wire


class TokenBucket:
    """Deterministic token bucket on a caller-supplied clock."""

    def __init__(self, rate_per_s: float, burst: float):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError("rate_per_s and burst must be > 0")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t_last: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._t_last is not None and now > self._t_last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t_last) * self.rate)
        self._t_last = now if self._t_last is None else max(self._t_last, now)

    def take(self, now: float) -> bool:
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def wait_s(self, now: float) -> float:
        """Seconds until one token accrues (the retry_after hint)."""
        self._refill(now)
        if self.tokens >= 1.0:
            return 0.0
        return (1.0 - self.tokens) / self.rate


@dataclasses.dataclass
class TenantState:
    """Per-tenant admission + accounting state."""

    bucket: TokenBucket
    inflight: int = 0       # client-visible in-flight ops (quota unit)
    admitted: int = 0
    completed: int = 0      # S_OK + S_RMW_ABORT
    retry_after: int = 0    # all front-door refusals
    shed: int = 0           # refusals by the overload ladder specifically
    deadline: int = 0
    rejected: int = 0       # store-level definitive rejects
    lost: int = 0

    def counters(self) -> dict:
        return dict(admitted=self.admitted, completed=self.completed,
                    retry_after=self.retry_after, shed=self.shed,
                    deadline=self.deadline, rejected=self.rejected,
                    lost=self.lost, inflight=self.inflight)


class AdmissionControl:
    """The front door: ladder + bucket + quota + queue bound.

    ``admit`` returns ``(reason, retry_after_s)`` — reason ``R_NONE``
    means admitted (the caller enqueues and calls ``note_admitted``).
    """

    def __init__(self, scfg):
        self.scfg = scfg
        self.tenants: Dict[int, TenantState] = {}
        hot = getattr(scfg, "hot_key_set", frozenset()) or frozenset()
        # sorted array mirror of the hot set for the batch ladder's
        # vectorized membership test (np.isin wants a sorted haystack)
        self._hot_arr = np.sort(np.fromiter(hot, np.int64, len(hot)))

    def tenant(self, t: int) -> TenantState:
        ts = self.tenants.get(t)
        if ts is None:
            ts = self.tenants[t] = TenantState(TokenBucket(
                self.scfg.tenant_rate_per_s, self.scfg.tenant_burst))
        return ts

    # -- the overload ladder -------------------------------------------------

    def ladder_level(self, queue_len: int, degraded: bool) -> int:
        """Rung for the CURRENT pressure: 2 past the read watermark, 1
        past the write watermark OR while the store is in quorum-loss
        degraded mode (writes cannot commit — refuse at the door rather
        than admit a doomed op), else 0."""
        cap = self.scfg.queue_cap
        if queue_len >= int(cap * self.scfg.shed_read_frac):
            return 2
        if degraded or queue_len >= int(cap * self.scfg.shed_write_frac):
            return 1
        return 0

    def admit(self, kind: str, key: int, tenant: int, now: float,
              queue_len: int, degraded: bool) -> Tuple[int, float]:
        level = self.ladder_level(queue_len, degraded)
        ts = self.tenant(tenant)
        retry_s = self.scfg.retry_after_floor_s
        if level >= 1 and kind != "get":
            ts.shed += 1
            ts.retry_after += 1
            return wire.R_SHED_WRITE, retry_s
        if level >= 2 and kind == "get" \
                and key not in self.scfg.hot_key_set:
            ts.shed += 1
            ts.retry_after += 1
            return wire.R_SHED_READ, retry_s
        if ts.inflight >= self.scfg.tenant_quota:
            ts.retry_after += 1
            return wire.R_QUOTA, retry_s
        if queue_len >= self.scfg.queue_cap:
            ts.retry_after += 1
            return wire.R_QUEUE_FULL, retry_s
        # the bucket is charged LAST: a quota/queue refusal must not also
        # burn the tenant's rate budget, or a backed-up tenant re-emerges
        # from the jam rate-starved by its own refused retries
        if not ts.bucket.take(now):
            ts.retry_after += 1
            return wire.R_RATE, max(retry_s, ts.bucket.wait_s(now))
        return wire.R_NONE, 0.0

    def admit_batch(self, writes: np.ndarray, keys: np.ndarray,
                    tenants: np.ndarray, now: float, queue_len: int,
                    degraded: bool) -> Tuple[np.ndarray, np.ndarray]:
        """Judge a whole columnar batch through the ladder, row-for-row
        EQUIVALENT to calling ``admit`` sequentially over the rows —
        same reasons, same retry hints, same counter and bucket state
        afterwards — in O(segments) numpy passes instead of O(rows)
        Python (round-19).

        Returns ``(reasons u8, retry_after_s f64)``; reason ``R_NONE``
        means admitted, and ``note_admitted`` is FOLDED IN for admitted
        rows (the scalar path's separate call) — the caller only
        enqueues them.

        Why segments: within a batch the queue only grows, so the
        ladder level and the queue-full verdict are monotone in the row
        index.  Each iteration judges the remaining rows against the
        CURRENT (level, queue) and commits only the prefix whose
        judgments that state actually covers — the first row whose
        admitted-prefix pushes the queue across the next threshold
        (write watermark, read watermark, or cap) starts a new segment.
        Per tenant the scalar order is preserved exactly: shed ->
        quota -> queue-full -> token bucket charged LAST, with the
        first ``min(quota_room, whole_tokens)`` candidate rows
        admitting and every later row refusing with the same reason
        and hint the scalar loop would give (refused takes consume
        nothing, so one shared hint is exact)."""
        writes = np.asarray(writes, bool)
        keys = np.asarray(keys, np.int64)
        tenants = np.asarray(tenants)
        n = int(writes.shape[0])
        reasons = np.zeros(n, np.uint8)
        waits = np.zeros(n, np.float64)
        if n == 0:
            return reasons, waits
        scfg = self.scfg
        floor = scfg.retry_after_floor_s
        cap = scfg.queue_cap
        wmark = int(cap * scfg.shed_write_frac)
        rmark = int(cap * scfg.shed_read_frac)
        is_hot = (np.isin(keys, self._hot_arr) if self._hot_arr.size
                  else np.zeros(n, bool))

        def peek(bucket) -> float:
            # the refilled token count WITHOUT mutating the bucket: the
            # scalar path only refills when a row actually reaches
            # take(), so the batch must judge on a peek and commit the
            # refill only for tenants whose committed rows got there —
            # or the post-batch bucket state drifts from the scalar's
            if bucket._t_last is not None and now > bucket._t_last:
                return min(bucket.burst,
                           bucket.tokens + (now - bucket._t_last)
                           * bucket.rate)
            return bucket.tokens

        q = int(queue_len)
        i = 0
        while i < n:
            m = n - i
            level = self.ladder_level(q, degraded)
            w = writes[i:n]
            t_seg = tenants[i:n]
            shed_w = w if level >= 1 else np.zeros(m, bool)
            shed_r = (((~w) & ~is_hot[i:n]) if level >= 2
                      else np.zeros(m, bool))
            rsn = np.zeros(m, np.uint8)
            wt = np.zeros(m, np.float64)
            rsn[shed_w] = wire.R_SHED_WRITE
            rsn[shed_r] = wire.R_SHED_READ
            wt[shed_w | shed_r] = floor
            cand = ~(shed_w | shed_r)
            admit = np.zeros(m, bool)
            quota_rooms: Dict[int, int] = {}  # tenants whose rows reach take()
            if q >= cap:
                # terminal segment: nothing can admit, so the queue (and
                # level) are frozen — judge every remaining row now.
                # Scalar order: quota refuses BEFORE queue-full.
                for tt in np.unique(t_seg[cand]).tolist():
                    ts = self.tenant(int(tt))
                    rows = np.nonzero(cand & (t_seg == tt))[0]
                    rsn[rows] = (wire.R_QUOTA
                                 if ts.inflight >= scfg.tenant_quota
                                 else wire.R_QUEUE_FULL)
                    wt[rows] = floor
                cut = m
            else:
                thr = cap
                if level < 2:
                    thr = min(thr, rmark)
                if level < 1:
                    thr = min(thr, wmark)
                for tt in np.unique(t_seg[cand]).tolist():
                    ts = self.tenant(int(tt))
                    rows = np.nonzero(cand & (t_seg == tt))[0]
                    quota_room = max(0, scfg.tenant_quota - ts.inflight)
                    quota_rooms[int(tt)] = quota_room
                    tokens = peek(ts.bucket)
                    rate_room = int(tokens) if tokens >= 1.0 else 0
                    adm = min(quota_room, rate_room)
                    admit[rows[:adm]] = True
                    over = rows[adm:]
                    if over.size:
                        if quota_room <= rate_room:
                            rsn[over] = wire.R_QUOTA
                            wt[over] = floor
                        else:
                            rsn[over] = wire.R_RATE
                            left = tokens - float(rate_room)
                            wt[over] = max(floor,
                                           (1.0 - left) / ts.bucket.rate)
                # commit only the prefix whose judgments saw this queue:
                # cut at the first row whose admitted-prefix crosses thr
                pre = q + np.concatenate(([0], np.cumsum(admit)[:-1]))
                crossed = np.nonzero(pre >= thr)[0]
                cut = int(crossed[0]) if crossed.size else m
            adm_c = admit[:cut]
            rsn_c = rsn[:cut]
            cand_c = cand[:cut]
            for tt in np.unique(t_seg[:cut]).tolist():
                ts = self.tenant(int(tt))
                trows = t_seg[:cut] == tt
                if (cand_c & trows).any() and quota_rooms.get(int(tt), 0):
                    # at least one committed row of this tenant reached
                    # take(): the refill the judgment peeked becomes real
                    ts.bucket._refill(now)
                a = int((adm_c & trows).sum())
                if a:
                    # one exact float subtraction == a sequential takes
                    ts.bucket.tokens -= float(a)
                    ts.admitted += a
                    ts.inflight += a
                r = int((~adm_c & trows).sum())
                ts.retry_after += r
                ts.shed += int((((rsn_c == wire.R_SHED_WRITE)
                                 | (rsn_c == wire.R_SHED_READ))
                                & trows).sum())
            reasons[i: i + cut] = rsn_c
            waits[i: i + cut] = wt[:cut]
            q += int(adm_c.sum())
            i += cut
        return reasons, waits

    def note_admitted(self, tenant: int) -> None:
        ts = self.tenant(tenant)
        ts.admitted += 1
        ts.inflight += 1

    def note_resolved(self, tenant: int, status: int) -> None:
        ts = self.tenant(tenant)
        ts.inflight -= 1
        assert ts.inflight >= 0, "tenant inflight went negative"
        if status in (wire.S_OK, wire.S_RMW_ABORT):
            ts.completed += 1
        elif status == wire.S_DEADLINE:
            ts.deadline += 1
        elif status == wire.S_REJECTED:
            ts.rejected += 1
        elif status == wire.S_LOST:
            ts.lost += 1

    def note_resolved_batch(self, tenants: np.ndarray,
                            statuses: np.ndarray) -> None:
        """Column form of ``note_resolved``: one pass over a pump's
        resolutions, grouped by (tenant, status) — O(unique pairs), not
        O(rows) (round-19)."""
        pairs = (np.asarray(tenants, np.int64) * 8
                 + np.asarray(statuses, np.int64))  # statuses are < 8
        uniq, cnt = np.unique(pairs, return_counts=True)
        for p, c in zip(uniq.tolist(), cnt.tolist()):
            t, st = p >> 3, p & 7
            ts = self.tenant(t)
            ts.inflight -= c
            assert ts.inflight >= 0, "tenant inflight went negative"
            if st in (wire.S_OK, wire.S_RMW_ABORT):
                ts.completed += c
            elif st == wire.S_DEADLINE:
                ts.deadline += c
            elif st == wire.S_REJECTED:
                ts.rejected += c
            elif st == wire.S_LOST:
                ts.lost += c

    def counters(self) -> dict:
        return {t: ts.counters() for t, ts in sorted(self.tenants.items())}
