"""The networked RPC path of the serving front-end (round-14).

Two servers share one ``Frontend``:

  * ``LoopbackServer`` — in-process, byte-honest: every request and
    response round-trips through the full wire codec (encode -> CRC
    frame -> unframe -> decode), but no socket or thread exists, so
    soaks are single-threaded and byte-identically replayable on a
    ``VirtualClock`` (the CI gate / test path).
  * ``TcpRpcServer`` — real localhost sockets: one accept thread, one
    reader thread per connection feeding a locked intake, and one pump
    thread driving ``Frontend.pump`` — the honest end-to-end path
    ``bench.py --serve`` measures client-socket p50/p99 on.  Frames ride
    ``transport.tcp.FramedSocket`` (the round-11 CRC frame layer over a
    stream socket).

``RpcClient`` is the matching blocking client: ``call`` for one op,
``send``/``recv_next`` for open-loop pacing (requests in flight while
more are sent — the Poisson load shape needs a non-lockstep client).
"""

from __future__ import annotations

import select
import socket
import threading
from typing import Dict, List, Optional

import numpy as np

from hermes_tpu.concurrency import make_lock
from hermes_tpu.serving import wire
from hermes_tpu.serving.server import Frontend


class LoopbackServer:
    """Byte-honest in-process server: the deterministic soak path."""

    def __init__(self, frontend: Frontend):
        self.fe = frontend
        self.u = frontend.u
        self.vbytes = frontend.vbytes
        self.wire_rx = 0
        self.wire_tx = 0
        self._out: List[bytes] = []

    def _roundtrip_req(self, req):
        from hermes_tpu.transport import codec

        raw = codec.frame_unpack(codec.frame_pack(np.frombuffer(
            wire.encode_any_request(req, self.u, self.vbytes),
            np.uint8))).tobytes()
        self.wire_rx += len(raw) + codec.FRAME_OVERHEAD
        return wire.decode_any_request(raw, self.u, self.vbytes)

    def submit(self, req) -> Optional[object]:
        """One client request (single-op Request or round-16 batched
        ReadRequest) through the wire codec + admission.  Immediate
        refusals come back decoded; admitted ops resolve via ``pump``."""
        rsp = self.fe.submit(self._roundtrip_req(req))
        if rsp is None:
            return None
        return self._encode_out([rsp])[0]

    def pump(self) -> List[wire.Response]:
        return self._encode_out(self.fe.pump())

    def drain(self, max_rounds: int = 10_000) -> bool:
        """Pump until the frontend envelope is empty, keeping every
        response in the byte log (``Frontend.drain`` queues them for
        ``pop_responses``; this encodes them in emission order)."""
        ok = self.fe.drain(max_rounds)
        self._encode_out(self.fe.pop_responses())
        return ok

    def _encode_out(self, rsps) -> List[object]:
        out = []
        for rsp in rsps:
            raw = wire.encode_any_response(rsp, self.u, self.vbytes)
            self.wire_tx += len(raw)
            self._out.append(raw)
            out.append(wire.decode_any_response(raw, self.u,
                                                 self.vbytes))
        return out

    def response_log(self) -> bytes:
        """Concatenated response bytes in emission order — the
        determinism witness (same seed + config => byte-identical)."""
        return b"".join(self._out)


class TcpRpcServer:
    """Threaded localhost RPC server over CRC-framed sockets."""

    def __init__(self, frontend: Frontend, host: str = "127.0.0.1",
                 port: int = 0, pump_sleep_s: float = 0.0002):
        from hermes_tpu.transport.tcp import FramedSocket

        self.fe = frontend
        self.u = frontend.u
        self.vbytes = frontend.vbytes
        self._FramedSocket = FramedSocket
        # minted via make_lock: HERMES_LOCKLINT=1 swaps in the
        # instrumented ObsLock (analysis/lockgraph.py) so soaks double
        # as lock-order sanitizer runs; plain threading.Lock otherwise
        self._lock = make_lock("TcpRpcServer._lock")
        # round-19 lock-fairness split: ``_lock`` guards the Frontend
        # itself (submit/pump — held for a full store round at a time);
        # ``_map_lock`` guards only the iid<->connection bookkeeping, so
        # the pump's per-response map pops and the readers' iid minting
        # never extend the frontend critical section
        self._map_lock = make_lock("TcpRpcServer._map_lock")
        # client req_ids are only unique PER CONNECTION (wire.py): the
        # server re-mints each into a globally unique internal id before
        # submit, and maps it back on send — two connections using the
        # same req_id can never collide in the frontend's pending map or
        # steal each other's responses
        self._next_iid = 1
        self._conn_of: Dict[int, tuple] = {}  # iid -> (FramedSocket, rid)
        self.undecodable = 0  # frame-valid requests refused undecoded
        self._stop = threading.Event()
        self.pump_error: Optional[BaseException] = None
        self._pump_sleep = pump_sleep_s
        self._threads: List[threading.Thread] = []
        self._conns: List = []
        self._listener = socket.create_server((host, port))
        self.addr = self._listener.getsockname()
        # register BOTH threads before starting either: the accept loop
        # prunes/extends _threads (under _map_lock), so a start-then-
        # append would race the pump thread's registration away
        accept_t = threading.Thread(target=self._accept_loop, daemon=True)
        pump_t = threading.Thread(target=self._pump_loop, daemon=True)
        self._threads.extend((accept_t, pump_t))
        accept_t.start()
        pump_t.start()

    # -- server side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                sock, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # bound SENDS only (SO_SNDTIMEO, not settimeout — the reader
            # thread must keep blocking on recv indefinitely): a client
            # that stops reading fills its kernel buffer, and an
            # unbounded sendall would wedge the pump thread's send pass.
            # Sends happen OUTSIDE the frontend lock, so a stalled send
            # never blocks intake or other connections' submits; it can
            # still delay the pump's send pass by up to this bound once,
            # after which the send raises and the slow client's stream
            # dies — server-wide service survives one non-reading client.
            import struct as _struct
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            _struct.pack("ll", 1, 0))
            # CRC failures on implausible frame lengths tear the stream
            # down instead of desyncing it; plausible = the fixed
            # single-op size OR a round-16 variable read-request size
            # (a corrupted-but-plausible frame is skipped + counted)
            fsock = self._FramedSocket(
                sock, expect_lens=wire.plausible_request_len(self.u,
                                                         self.vbytes))
            t = threading.Thread(target=self._reader_loop, args=(fsock,),
                                 daemon=True)
            # register conn + thread (and prune finished readers so a
            # long-lived server's list doesn't grow with every
            # connection ever made) BEFORE start, under _map_lock:
            # close() snapshots both lists under the same lock
            with self._map_lock:
                self._conns.append(fsock)
                self._threads = [th for th in self._threads
                                 if th.is_alive()]
                self._threads.append(t)
            t.start()

    def _reader_loop(self, fsock) -> None:
        try:
            self._reader_body(fsock)
        finally:
            fsock.close()
            with self._map_lock:
                try:
                    self._conns.remove(fsock)
                except ValueError:
                    pass

    def _reader_body(self, fsock) -> None:
        while not self._stop.is_set():
            # batch intake: one blocking recv, then drain everything the
            # socket already buffered, and submit the whole batch under
            # ONE lock acquisition — the pump thread holds the lock for a
            # full store round at a time, so per-message locking would
            # throttle intake to ~1 request per round
            try:
                raw = fsock.recv()
            except Exception:
                return
            if raw is None:
                return
            raws = [raw]
            while select.select([fsock.sock], [], [], 0)[0]:
                try:
                    more = fsock.recv()
                except Exception:
                    more = None
                if more is None:
                    break
                raws.append(more)
            reqs = []
            for raw in raws:
                try:
                    reqs.append(wire.decode_any_request(raw, self.u,
                                                        self.vbytes))
                except ValueError:
                    # frame-valid but undecodable (payload-width/magic
                    # mismatch): refuse LOUDLY when the header still
                    # yields a req_id — never leave the client to time
                    # out on silence.  No lock needed: FramedSocket.send
                    # serializes itself, so the pump thread's concurrent
                    # sends on this socket can't splice frames.
                    rid = wire.peek_req_id(raw)
                    with self._map_lock:
                        self.undecodable += 1
                    if rid is not None:
                        try:
                            fsock.send(wire.encode_response(
                                wire.Response(
                                    status=wire.S_REJECTED, req_id=rid,
                                    found=False), self.u, self.vbytes))
                        except OSError:
                            fsock.close()
                            return
            # mint iids + record the return map OUTSIDE the frontend
            # lock (the map has its own lock): the frontend critical
            # section is exactly the submit calls, nothing else
            with self._map_lock:
                for req in reqs:
                    iid, self._next_iid = self._next_iid, self._next_iid + 1
                    self._conn_of[iid] = (fsock, req.req_id)
                    req.req_id = iid
            refusals = []
            with self._lock:
                for req in reqs:
                    rsp = self.fe.submit(req)
                    if rsp is not None:  # immediate refusal
                        refusals.append(rsp)
            outs = [out for out in map(self._resolve, refusals) if out]
            # send OUTSIDE the lock: a non-reading client stalls only
            # its own reader thread here, never the frontend
            for conn, rsp in outs:
                self._send_out(conn, rsp)

    def _resolve(self, rsp: wire.Response):
        """Swap the internal id back for the client's req_id; returns
        ``(fsock, rsp)`` ready to send, or None for an unknown (already
        torn down) connection.  Takes ``_map_lock`` itself — callers
        must NOT hold the frontend lock (that coupling was the round-14
        fairness bug: per-response dict work inside the pump's critical
        section)."""
        with self._map_lock:
            ent = self._conn_of.pop(rsp.req_id, None)
        if ent is None:
            return None
        fsock, client_rid = ent
        rsp.req_id = client_rid
        return fsock, rsp

    def _send_out(self, fsock, rsp) -> None:
        try:
            fsock.send(wire.encode_any_response(rsp, self.u, self.vbytes))
        except OSError:
            # send timed out or failed mid-frame: the stream boundary is
            # gone, so the connection is unusable — tear it down
            fsock.close()

    def _pump_loop(self) -> None:
        import time as _time

        fe = self.fe
        while not self._stop.is_set():
            with self._lock:
                busy = bool(fe._intake or fe._pending or fe._abandoned)
            if not busy:
                _time.sleep(0.001)  # idle: don't spin the store
                continue
            try:
                with self._lock:
                    rsps = fe.pump()
                # publish completions OUTSIDE the frontend lock (the
                # round-19 fairness fix): the map swap is _map_lock-only,
                # so readers can submit while this pass runs
                outs = [out for out in map(self._resolve, rsps) if out]
            except Exception as e:  # noqa: BLE001 — store died (e.g.
                # StuckOpError): a silently dead pump thread would leave
                # every connected client hanging on its socket timeout.
                # Fail LOUDLY instead: record, stop, and close every
                # stream so clients see EOF now.
                self.pump_error = e
                self._stop.set()
                with self._map_lock:
                    conns = list(self._conns)
                for fsock in conns:
                    fsock.close()
                raise
            # sends OUTSIDE the lock: a stalled client blocks this send
            # pass (bounded by SO_SNDTIMEO) but never the reader
            # threads' intake path
            for fsock, rsp in outs:
                self._send_out(fsock, rsp)
            # ALWAYS yield between pumps: Python locks are unfair, and a
            # tight re-acquire starves the reader threads' submit path —
            # requests would sit unsubmitted for whole pump generations
            _time.sleep(self._pump_sleep)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # snapshot under _map_lock, close/join OUTSIDE it: joining a
        # reader while holding the lock its exit path needs would
        # deadlock close() against the threads it is waiting out
        with self._map_lock:
            conns = list(self._conns)
            threads = list(self._threads)
        # close every accepted connection: reader threads blocked in
        # fsock.recv() only exit when their socket dies
        for fsock in conns:
            fsock.close()
        for t in threads:
            t.join(timeout=2.0)


# -- round-19: the columnar RPC path -----------------------------------------


class ColumnarLoopback:
    """Byte-honest in-process COLUMNAR server: every request batch and
    response batch round-trips the full columnar wire codec (encode ->
    CRC frame -> unframe -> decode) with no socket or thread, so
    columnar soaks replay byte-identically on a ``VirtualClock`` — the
    columnar twin of ``LoopbackServer`` and the serving gate's floor
    path.  The response byte log is record-for-record walkable by
    ``wire.response_extent`` (the columnar stream is byte-identical to
    the per-struct one), so ``soak.committed_uids`` works unchanged."""

    def __init__(self, frontend):
        self.fe = frontend
        self.u = frontend.u
        self.vbytes = frontend.vbytes
        self.wire_rx = 0
        self.wire_tx = 0
        self._out: List[bytes] = []

    def submit_batch(self, batch: wire.ReqBatch,
                     conn: int = 0) -> wire.RspBatch:
        """One client batch through the wire + admission; returns the
        decoded immediate-refusal batch (possibly empty)."""
        from hermes_tpu.transport import codec

        raw = wire.encode_request_batch(batch, self.u, self.vbytes)
        raw = codec.frame_unpack(codec.frame_pack(
            np.frombuffer(raw, np.uint8))).tobytes()
        self.wire_rx += len(raw) + codec.FRAME_OVERHEAD
        b = wire.decode_request_batch(raw, self.u, self.vbytes)
        return self._encode_out(self.fe.submit_batch(b, conn=conn))

    def pump(self) -> Dict[int, wire.RspBatch]:
        return {cid: self._encode_out(rb)
                for cid, rb in self.fe.pump().items()}

    def drain(self, max_rounds: int = 10_000) -> bool:
        """Pump until the envelope drains, keeping every response batch
        in the byte log in emission order."""
        drained, emitted = self.fe.drain(max_rounds)
        for d in emitted:
            for cid in sorted(d):
                self._encode_out(d[cid])
        return drained

    def _encode_out(self, rb: wire.RspBatch) -> wire.RspBatch:
        if len(rb) == 0:
            return rb
        raw = wire.encode_response_batch(rb, self.u, self.vbytes)
        self.wire_tx += len(raw)
        self._out.append(raw)
        return wire.decode_response_batch(raw, self.u, self.vbytes)

    def response_log(self) -> bytes:
        """Concatenated response bytes in emission order — the
        determinism witness (same seed + config => byte-identical)."""
        return b"".join(self._out)


class ColumnarTcpServer:
    """Threaded localhost COLUMNAR RPC server: every inbound frame
    carries a whole request batch, and the pump sends ONE framed
    response batch per connection per round (the one-encode-per-
    connection-per-pump drain the ring plane was built for).

    ``reuseport=True`` binds the listener with SO_REUSEPORT so N worker
    PROCESSES shard accepts on one port (``launch.start_serve_workers``):
    the kernel load-balances new connections across workers, and each
    worker owns its own store, frontend, and GIL — the GIL stops being
    the admission ladder."""

    def __init__(self, frontend, host: str = "127.0.0.1", port: int = 0,
                 pump_sleep_s: float = 0.0002, reuseport: bool = False):
        from hermes_tpu.transport.tcp import FramedSocket, serving_listener

        self.fe = frontend
        self.u = frontend.u
        self.vbytes = frontend.vbytes
        self._FramedSocket = FramedSocket
        # make_lock: ObsLock under HERMES_LOCKLINT=1, plain Lock otherwise
        self._lock = make_lock("ColumnarTcpServer._lock")
        self._map_lock = make_lock("ColumnarTcpServer._map_lock")
        self._next_cid = 1
        self._sock_of: Dict[int, object] = {}
        self.undecodable = 0
        self._stop = threading.Event()
        self.pump_error: Optional[BaseException] = None
        self._pump_sleep = pump_sleep_s
        self._threads: List[threading.Thread] = []
        self._conns: List = []
        self._listener = serving_listener(host, port, reuseport=reuseport)
        self.addr = self._listener.getsockname()
        # register both threads before starting either (see TcpRpcServer)
        accept_t = threading.Thread(target=self._accept_loop, daemon=True)
        pump_t = threading.Thread(target=self._pump_loop, daemon=True)
        self._threads.extend((accept_t, pump_t))
        accept_t.start()
        pump_t.start()

    def _accept_loop(self) -> None:
        import struct as _struct

        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                sock, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # bound sends only — same rationale as TcpRpcServer: a
            # non-reading client must stall only its own stream
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            _struct.pack("ll", 1, 0))
            # columnar frames are variable-length (k * record strides),
            # so no plausible-length set: a CRC failure skips the frame
            fsock = self._FramedSocket(sock)
            with self._map_lock:
                cid, self._next_cid = self._next_cid, self._next_cid + 1
                self._sock_of[cid] = fsock
                self._conns.append(fsock)
            t = threading.Thread(target=self._reader_loop,
                                 args=(fsock, cid), daemon=True)
            # registered before start (see TcpRpcServer._accept_loop)
            with self._map_lock:
                self._threads = [th for th in self._threads
                                 if th.is_alive()]
                self._threads.append(t)
            t.start()

    def _reader_loop(self, fsock, cid: int) -> None:
        try:
            self._reader_body(fsock, cid)
        finally:
            fsock.close()
            with self._map_lock:
                self._sock_of.pop(cid, None)
                try:
                    self._conns.remove(fsock)
                except ValueError:
                    pass

    def _reader_body(self, fsock, cid: int) -> None:
        while not self._stop.is_set():
            # batch intake, batch-of-batches drain: one blocking recv,
            # then everything the socket already buffered, submitted
            # under ONE frontend lock acquisition
            try:
                raw = fsock.recv()
            except Exception:
                return
            if raw is None:
                return
            raws = [raw]
            while select.select([fsock.sock], [], [], 0)[0]:
                try:
                    more = fsock.recv()
                except Exception:
                    more = None
                if more is None:
                    break
                raws.append(more)
            batches = []
            for raw in raws:
                try:
                    batches.append(wire.decode_request_batch(
                        raw, self.u, self.vbytes))
                except ValueError:
                    # a CRC-valid frame that doesn't parse as a batch
                    # (torn record stream, width mismatch) means the
                    # sender's batch framing itself is broken — there is
                    # no per-row identity to refuse on, so tear the
                    # stream down LOUDLY (client sees EOF now, not a
                    # timeout later)
                    with self._map_lock:
                        self.undecodable += 1
                    return
            refusals = []
            with self._lock:
                for b in batches:
                    rb = self.fe.submit_batch(b, conn=cid)
                    if len(rb):
                        refusals.append(rb)
            for rb in refusals:  # send outside the lock
                self._send_out(fsock, rb)

    def _send_out(self, fsock, rb: wire.RspBatch) -> None:
        try:
            fsock.send(wire.encode_response_batch(rb, self.u,
                                                  self.vbytes))
        except OSError:
            fsock.close()

    def _pump_loop(self) -> None:
        import time as _time

        fe = self.fe
        while not self._stop.is_set():
            with self._lock:
                busy = not fe.idle()
            if not busy:
                _time.sleep(0.001)
                continue
            try:
                with self._lock:
                    rsps = fe.pump()
            except Exception as e:  # noqa: BLE001 — store died: fail
                # loudly, close every stream so clients see EOF now
                self.pump_error = e
                self._stop.set()
                with self._map_lock:
                    conns = list(self._conns)
                for fsock in conns:
                    fsock.close()
                raise
            # publish OUTSIDE the frontend lock: one encode + one send
            # per connection per round
            for cid in sorted(rsps):
                with self._map_lock:
                    fsock = self._sock_of.get(cid)
                if fsock is not None:
                    self._send_out(fsock, rsps[cid])
            _time.sleep(self._pump_sleep)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # snapshot under _map_lock, close/join outside it (see
        # TcpRpcServer.close)
        with self._map_lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for fsock in conns:
            fsock.close()
        for t in threads:
            t.join(timeout=2.0)


class ColumnarClient:
    """Blocking columnar client: one framed request BATCH per send;
    a batch's rows may resolve across several server pump rounds, so
    ``call_batch`` collects response batches until every req_id has
    answered."""

    def __init__(self, addr, u: int, vbytes: int = 0,
                 timeout_s: float = 30.0):
        from hermes_tpu.transport.tcp import FramedSocket

        sock = socket.create_connection(addr, timeout=timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.fsock = FramedSocket(sock)
        self.u = u
        self.vbytes = vbytes
        self._next_id = 1

    def next_ids(self, k: int) -> np.ndarray:
        ids = np.arange(self._next_id, self._next_id + k, dtype=np.uint32)
        self._next_id += k
        return ids

    def send_batch(self, batch: wire.ReqBatch) -> None:
        self.fsock.send(wire.encode_request_batch(batch, self.u,
                                                  self.vbytes))

    def recv_batch(self) -> Optional[wire.RspBatch]:
        raw = self.fsock.recv()
        if raw is None:
            return None
        return wire.decode_response_batch(raw, self.u, self.vbytes)

    def call_batch(self, batch: wire.ReqBatch) -> Dict[int, wire.Response]:
        """Send one batch and block until every row has a response;
        returns {req_id: Response}."""
        want = set(int(r) for r in batch.req_id.tolist())
        self.send_batch(batch)
        out: Dict[int, wire.Response] = {}
        while want:
            rb = self.recv_batch()
            if rb is None:
                raise ConnectionError("server closed mid-batch")
            for r in rb.to_responses():
                out[r.req_id] = r
                want.discard(r.req_id)
        return out

    def close(self) -> None:
        self.fsock.close()


def serve_worker_main(worker_id: int, host: str, port: int, cfg, scfg,
                      ready_q, stop_ev) -> None:
    """One accept-sharding worker process (module-level so the
    ``spawn`` start method can import it): own KVS, own
    ColumnarFrontend, own ColumnarTcpServer bound SO_REUSEPORT on the
    shared port.  Reports ``(worker_id, port)`` on ``ready_q`` once
    accepting, then serves until ``stop_ev`` fires."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from hermes_tpu.kvs import KVS
    from hermes_tpu.serving.server import ColumnarFrontend

    store = KVS(cfg)
    fe = ColumnarFrontend(store, scfg)
    srv = ColumnarTcpServer(fe, host=host, port=port, reuseport=True)
    ready_q.put((worker_id, srv.addr[1]))
    stop_ev.wait()
    srv.close()


class RpcClient:
    """Blocking client over one CRC-framed socket."""

    def __init__(self, addr, u: int, timeout_s: float = 30.0,
                 vbytes: int = 0):
        from hermes_tpu.transport.tcp import FramedSocket

        sock = socket.create_connection(addr, timeout=timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.fsock = FramedSocket(
            sock, expect_lens=wire.plausible_response_len(u, vbytes))
        self.u = u
        self.vbytes = vbytes
        self._next_id = 1

    def next_id(self) -> int:
        rid, self._next_id = self._next_id, self._next_id + 1
        return rid

    def send(self, req) -> None:
        self.fsock.send(wire.encode_any_request(req, self.u, self.vbytes))

    def recv_next(self) -> Optional[object]:
        raw = self.fsock.recv()
        if raw is None:
            return None
        return wire.decode_any_response(raw, self.u, self.vbytes)

    def call(self, kind: str, key: int, value=None, tenant: int = 0,
             deadline_us: int = 0, data=None) -> wire.Response:
        req = wire.Request(kind=kind, req_id=self.next_id(), tenant=tenant,
                           key=key, deadline_us=deadline_us, value=value,
                           data=data)
        self.send(req)
        rsp = self.recv_next()
        if rsp is None:
            raise ConnectionError("server closed mid-call")
        return rsp

    def call_mget(self, keys, tenant: int = 0,
                  deadline_us: int = 0) -> wire.ReadResponse:
        """One batched K_MGET round trip (round-16)."""
        req = wire.ReadRequest(kind="mget", req_id=self.next_id(),
                               tenant=tenant, keys=list(keys),
                               deadline_us=deadline_us)
        self.send(req)
        rsp = self.recv_next()
        if rsp is None:
            raise ConnectionError("server closed mid-call")
        return rsp

    def call_scan(self, lo: int, hi: int, tenant: int = 0,
                  deadline_us: int = 0) -> wire.ReadResponse:
        """One K_SCAN round trip over keys [lo, hi)."""
        req = wire.ReadRequest(kind="scan", req_id=self.next_id(),
                               tenant=tenant, lo=lo, hi=hi,
                               deadline_us=deadline_us)
        self.send(req)
        rsp = self.recv_next()
        if rsp is None:
            raise ConnectionError("server closed mid-call")
        return rsp

    def close(self) -> None:
        self.fsock.close()
