"""Deterministic open-loop serving soaks (round-14).

One driver for the CI gate, the CLI quickstart, and the tests: a seeded
Poisson arrival schedule (optionally shaped by chaos ``overload``
windows) drives a byte-honest ``LoopbackServer`` on a ``VirtualClock``
that advances ``scfg.round_us`` per pump — so a soak is a pure function
of (store config, serving config, mix spec, rate, seed): the executed
response byte log replays IDENTICALLY, the chaos-schedule determinism
contract applied to overload.

Capacity measurement (``measure_capacity``) is closed-loop: every store
lane kept full, throughput service-bound — the honest denominator for
"soak at >= 2x capacity".
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from hermes_tpu.serving import wire
from hermes_tpu.serving.rpc import LoopbackServer
from hermes_tpu.stats import percentile_nearest_rank
from hermes_tpu.serving.server import (Frontend, ServingConfig, VirtualClock,
                                       verify_serving)
from hermes_tpu.workload.openloop import (ClosedLoop, MixSpec, ShapedArrivals,
                                          make_mix)


def measure_capacity(store, scfg: ServingConfig, spec: MixSpec, n: int,
                     seed: int) -> dict:
    """Closed-loop service rate through the full serving path: ``n`` ops
    offered as fast as admission refills, every refusal retried next
    round (closed-loop clients wait, they don't walk away).  Returns
    ops/virtual-second + ops/round."""
    clock = VirtualClock()
    fe = Frontend(store, scfg, clock=clock)
    lb = LoopbackServer(fe)
    cl = ClosedLoop(spec, fe.n_keys, n, seed, value_words=fe.u)
    round_s = scfg.round_us * 1e-6
    resolved, retry = 0, []
    rounds = 0
    next_rid = 1
    while resolved < n and rounds < 100_000:
        # closed-loop offer: retries first, then fresh ops, until the
        # front door refuses (rate/quota/queue) or the mix runs dry
        offer = retry
        retry = []
        while True:
            if offer:
                req = offer.pop(0)
            else:
                op = cl.next_op()
                if op is None:
                    break
                req = wire.Request(
                    kind=op["kind"], req_id=next_rid, tenant=op["tenant"],
                    key=op["key"], value=op["value"])
                next_rid += 1
            rsp = lb.submit(req)
            if rsp is not None:
                if rsp.status == wire.S_RETRY_AFTER:
                    # the door is closed this round: stash this op AND
                    # everything still waiting behind it for the next one
                    retry.append(req)
                    retry.extend(offer)
                    break
                resolved += 1
        resolved += len(lb.pump())
        clock.advance(round_s)
        rounds += 1
    lb.drain()
    done = fe.counters()["totals"]
    served = (done.get("completed", 0) + done.get("deadline", 0)
              + done.get("rejected", 0) + done.get("lost", 0))
    ops_per_round = served / max(1, rounds)
    return dict(ops=served, rounds=rounds,
                ops_per_round=round(ops_per_round, 3),
                ops_per_vs=round(ops_per_round / round_s, 1))


def run_open_loop(store, scfg: ServingConfig, spec: MixSpec,
                  rate_per_s: float, n: int, seed: int, deadline_us: int,
                  chaos_runner=None, arrivals: Optional[ShapedArrivals] = None,
                  max_rounds: int = 200_000) -> dict:
    """The open-loop Poisson soak: arrivals fire on THEIR schedule (the
    client does not wait for the server), every request resolves loudly,
    and the whole run replays byte-identically from the seed.

    ``chaos_runner``: an already-constructed ``chaos.ChaosRunner`` over
    ``store`` (its ``load=`` may be the arrival schedule for overload
    verbs); it is TICKED each round — the frontend pump is what steps
    the store.  Returns the summary dict (responses stay on the
    LoopbackServer for byte-log comparison).
    """
    clock = VirtualClock()
    fe = Frontend(store, scfg, clock=clock)
    lb = LoopbackServer(fe)
    if chaos_runner is not None and chaos_runner.load is not None:
        # the runner's shaper and the soak's arrival schedule must be ONE
        # object, or the overload verbs shape a schedule nobody consumes
        # (the silent-skip failure mode the net-fault routability rule
        # exists to prevent)
        if arrivals is None:
            arrivals = chaos_runner.load
        elif arrivals is not chaos_runner.load:
            raise ValueError("chaos_runner.load and arrivals= are "
                             "different objects: the overload storm would "
                             "shape a schedule this soak never consumes")
    if arrivals is None:
        arrivals = ShapedArrivals(rate_per_s, n, seed)
    if chaos_runner is not None and chaos_runner.load is None \
            and any(e.kind.startswith("overload")
                    for e in chaos_runner.schedule):
        raise ValueError("chaos schedule has overload verbs: construct "
                         "ChaosRunner(..., load=arrivals) and pass the "
                         "same arrivals here")
    mix = make_mix(spec, fe.n_keys, n, seed, value_words=fe.u)
    round_s = scfg.round_us * 1e-6
    sent = 0
    rounds = 0
    # flight recorder (round-18): an obs-attached soak dumps its black
    # box on an operator SIGTERM and on envelope-invariant failure — a
    # long soak that dies must leave a post-mortem (no-op unless a dump
    # dir is configured; obs/flightrec.py)
    obs = fe._rt().obs
    restore_sigterm = None
    if obs is not None:
        from hermes_tpu.obs.flightrec import install_sigterm

        restore_sigterm = install_sigterm(
            obs.flight, extra=dict(where="serving_soak", seed=seed))
    try:
        while rounds < max_rounds:
            if chaos_runner is not None:
                chaos_runner.tick(rounds)
            k = arrivals.due(clock.t)
            for _ in range(k):
                if sent >= n:
                    break
                i = sent
                req = wire.Request(
                    kind=("get", "put", "rmw")[int(mix["kind"][i])],
                    req_id=i + 1, tenant=int(mix["tenant"][i]),
                    key=int(mix["key"][i]), deadline_us=deadline_us,
                    value=mix["value"][i].tolist())
                sent += 1
                lb.submit(req)
            lb.pump()
            clock.advance(round_s)
            rounds += 1
            if sent >= n and not (fe._intake or fe._pending
                                  or fe._abandoned):
                break
        lb.drain()
        # one authoritative status census off the response meta (covers
        # both submit()-time refusals and pump()-time resolutions)
        statuses: dict = {}
        for _t, st, _lat in fe._resp_meta:
            name = wire.STATUS_NAMES[st]
            statuses[name] = statuses.get(name, 0) + 1
        lat = sorted(fe.latencies())
        pctl = lambda q: percentile_nearest_rank(lat, q)
        try:
            ev = verify_serving(fe)
        except AssertionError:
            if obs is not None:
                obs.flight_dump("verify_serving_failed",
                                extra=dict(seed=seed, rounds=rounds))
            raise
    finally:
        if restore_sigterm is not None:
            restore_sigterm()
    totals = fe.counters()["totals"]
    return dict(
        ops_offered=n, sent=sent, rounds=rounds,
        statuses=statuses, admitted=ev["admitted"],
        retry_after=ev["retry_after"], shed=ev["shed"],
        deadline=ev["deadline"], lost=ev["lost"],
        completed=ev["completed"], rejected=ev["rejected"],
        p50_latency_us=(None if pctl(0.5) is None
                        else round(pctl(0.5) * 1e6, 1)),
        p99_latency_us=(None if pctl(0.99) is None
                        else round(pctl(0.99) * 1e6, 1)),
        deadline_us=deadline_us,
        virtual_seconds=round(clock.t, 6),
        response_log_sha=_sha(lb.response_log()),
        tenants=fe.counters()["tenants"],
        _frontend=fe, _server=lb,
    )


def run_columnar_soak(store, scfg: ServingConfig, spec: MixSpec,
                      rate_per_s: float, n: int, seed: int,
                      deadline_us: int,
                      arrivals: Optional[ShapedArrivals] = None,
                      max_rounds: int = 200_000) -> dict:
    """The open-loop soak on the COLUMNAR data plane (round-19): the
    same seeded Poisson schedule and op mix as ``run_open_loop``, but
    each round's due arrivals go through the wire as ONE columnar batch
    and responses drain as one encode per pump — still a pure function
    of (store config, serving config, mix spec, rate, seed), so the
    response byte log replays identically."""
    from hermes_tpu.serving.rpc import ColumnarLoopback
    from hermes_tpu.serving.server import ColumnarFrontend, verify_columnar

    clock = VirtualClock()
    fe = ColumnarFrontend(store, scfg, clock=clock)
    lb = ColumnarLoopback(fe)
    if fe.vbytes:
        raise ValueError(
            "the columnar soak drives fixed-width stores (the open-loop "
            "mix generator mints word values); heap-mode coverage lives "
            "in the codec property tests and the frontend unit tests")
    if arrivals is None:
        arrivals = ShapedArrivals(rate_per_s, n, seed)
    mix = make_mix(spec, fe.n_keys, n, seed, value_words=fe.u)
    # columnize the whole mix ONCE; each round slices a view
    kind_col = (np.asarray(mix["kind"], np.uint8) + 1)  # 0/1/2 -> K_*
    rid_col = np.arange(1, n + 1, dtype=np.uint32)
    tenant_col = np.asarray(mix["tenant"], np.uint16)
    trace_col = np.zeros(n, np.uint16)  # server-minted sampling
    dl_col = np.full(n, deadline_us, np.uint32)
    key_col = np.asarray(mix["key"], np.int64)
    val_col = np.asarray(mix["value"], np.int32).reshape(n, fe.u)
    round_s = scfg.round_us * 1e-6
    sent = 0
    rounds = 0
    obs = fe._rt().obs
    restore_sigterm = None
    if obs is not None:
        from hermes_tpu.obs.flightrec import install_sigterm

        restore_sigterm = install_sigterm(
            obs.flight, extra=dict(where="columnar_soak", seed=seed))
    try:
        while rounds < max_rounds:
            k = min(arrivals.due(clock.t), n - sent)
            if k:
                b = wire.ReqBatch(
                    kind=kind_col[sent:sent + k],
                    req_id=rid_col[sent:sent + k],
                    tenant=tenant_col[sent:sent + k],
                    trace=trace_col[sent:sent + k],
                    deadline_us=dl_col[sent:sent + k],
                    key=key_col[sent:sent + k],
                    value=val_col[sent:sent + k])
                lb.submit_batch(b, conn=0)
                sent += k
            lb.pump()
            clock.advance(round_s)
            rounds += 1
            if sent >= n and fe.idle():
                break
        lb.drain()
        statuses: dict = {}
        for _t, st, _lat in fe._resp_meta:
            name = wire.STATUS_NAMES[st]
            statuses[name] = statuses.get(name, 0) + 1
        lat = sorted(fe.latencies())
        pctl = lambda q: percentile_nearest_rank(lat, q)
        try:
            ev = verify_columnar(fe)
        except AssertionError:
            if obs is not None:
                obs.flight_dump("verify_columnar_failed",
                                extra=dict(seed=seed, rounds=rounds))
            raise
    finally:
        if restore_sigterm is not None:
            restore_sigterm()
    return dict(
        ops_offered=n, sent=sent, rounds=rounds,
        statuses=statuses, admitted=ev["admitted"],
        retry_after=ev["retry_after"], shed=ev["shed"],
        deadline=ev["deadline"], lost=ev["lost"],
        completed=ev["completed"], rejected=ev["rejected"],
        p50_latency_us=(None if pctl(0.5) is None
                        else round(pctl(0.5) * 1e6, 1)),
        p99_latency_us=(None if pctl(0.99) is None
                        else round(pctl(0.99) * 1e6, 1)),
        deadline_us=deadline_us,
        virtual_seconds=round(clock.t, 6),
        response_log_sha=_sha(lb.response_log()),
        tenants=fe.counters()["tenants"],
        _frontend=fe, _server=lb,
    )


def measure_columnar_floor(n_ops: int = 8192, batch: int = 1024,
                           seed: int = 14, store=None,
                           scfg: Optional[ServingConfig] = None) -> dict:
    """WALL-CLOCK closed-loop throughput of the columnar loopback path
    — the serving-throughput floor leg (scripts/check_serving.py): the
    full byte-honest pipeline (columnar encode -> CRC frame -> decode ->
    batch admission -> ring -> store -> columnar response encode) on the
    real clock.  Every op resolves; refusals would be S_RETRY_AFTER rows
    and are RETRIED (closed-loop clients wait) — with the generous
    default envelope none occur, and the count is reported loudly."""
    import time as _time

    from hermes_tpu.serving.rpc import ColumnarLoopback
    from hermes_tpu.serving.server import ColumnarFrontend, verify_columnar

    if store is None:
        from hermes_tpu.config import HermesConfig, WorkloadConfig
        from hermes_tpu.kvs import KVS

        store = KVS(HermesConfig(
            n_replicas=4, n_keys=64, n_sessions=128, value_words=8,
            pipeline_depth=2,
            workload=WorkloadConfig(read_frac=0.5, seed=seed)))
    scfg = scfg or ServingConfig(
        tenant_rate_per_s=1e9, tenant_burst=1e9,
        tenant_quota=4 * batch, queue_cap=4 * batch)
    fe = ColumnarFrontend(store, scfg)  # real clock: wall-honest floor
    lb = ColumnarLoopback(fe)
    spec = MixSpec(read_frac=0.5, rmw_frac=0.1, tenants=4)
    mix = make_mix(spec, fe.n_keys, n_ops, seed, value_words=fe.u)
    kind_col = (np.asarray(mix["kind"], np.uint8) + 1)
    rid_col = np.arange(1, n_ops + 1, dtype=np.uint32)
    tenant_col = np.asarray(mix["tenant"], np.uint16)
    zeros16 = np.zeros(n_ops, np.uint16)
    zeros32 = np.zeros(n_ops, np.uint32)
    key_col = np.asarray(mix["key"], np.int64)
    val_col = np.asarray(mix["value"], np.int32).reshape(n_ops, fe.u)

    def _slice(lo, hi):
        return wire.ReqBatch(
            kind=kind_col[lo:hi], req_id=rid_col[lo:hi],
            tenant=tenant_col[lo:hi], trace=zeros16[lo:hi],
            deadline_us=zeros32[lo:hi], key=key_col[lo:hi],
            value=val_col[lo:hi])

    # warm the store's jit cache on a throwaway prefix so the floor
    # measures the data plane, not XLA compile time
    warm = min(batch, n_ops)
    lb.submit_batch(_slice(0, warm), conn=0)
    while not fe.idle():
        lb.pump()
    retried = 0
    sent = warm
    retry_q: List[wire.ReqBatch] = []

    def _offer(b):
        nonlocal retried
        rb = lb.submit_batch(b, conn=0)
        if len(rb):  # closed-loop: refused rows go around again
            idx = np.nonzero(rb.status == wire.S_RETRY_AFTER)[0]
            if idx.size:
                retried += int(idx.size)
                retry_q.append(b.select(idx))

    t0 = _time.perf_counter()
    while sent < n_ops or retry_q or not fe.idle():
        inflight = fe.requests - fe.responses
        if retry_q:
            _offer(retry_q.pop(0))
        elif sent < n_ops and inflight < batch:
            k = min(batch, n_ops - sent)
            _offer(_slice(sent, sent + k))
            sent += k
        lb.pump()
    seconds = _time.perf_counter() - t0
    verify_columnar(fe)
    measured = n_ops - warm
    return dict(ops=measured, seconds=round(seconds, 6),
                ops_per_sec=round(measured / seconds, 1),
                batch=batch, retried=retried,
                wire_rx_bytes=lb.wire_rx, wire_tx_bytes=lb.wire_tx,
                n_replicas=store.cfg.n_replicas,
                n_sessions=store.cfg.n_sessions)


def _sha(b: bytes) -> str:
    import hashlib

    return hashlib.sha256(b).hexdigest()


def committed_uids(fe: Frontend, lb: LoopbackServer) -> List[tuple]:
    """Write uids the CLIENT saw commit (S_OK puts/rmws) — the
    ``committed_write_lost`` witness set.  The byte log interleaves
    fixed-size single-op responses with variable-size round-16 read
    responses; each record's extent comes from its magic + count."""
    import struct

    out = []
    u, vbytes = lb.u, lb.vbytes
    off = 0
    raw = lb.response_log()
    while off + 2 <= len(raw):
        # records are variable even for single ops in heap mode: each
        # record's extent comes from its own magic/count/length prefix
        # (wire.response_extent — the one walker primitive)
        step = wire.response_extent(raw, off, u, vbytes)
        (magic,) = struct.unpack_from("<H", raw, off)
        if magic == wire.RRSP_MAGIC:
            # batched read response: reads never mint uids — skip it
            off += step
            continue
        rsp = wire.decode_response(raw[off: off + step], u, vbytes)
        off += step
        if rsp.status == wire.S_OK and rsp.uid is not None:
            out.append(rsp.uid)
    return out
