"""The serving front-end (round-14): the robustness envelope between
clients and the replicated store.

``Frontend`` owns one ``kvs.KVS`` (single group) or ``fleet.Fleet``
(key-routed groups — the fleet-aware serving front-end of ROADMAP item
2) and drives client RPCs through it:

  * ADMISSION (serving/admission.py): overload ladder -> per-tenant
    session quota -> bounded intake queue -> per-tenant token bucket
    (charged last — refusals never burn rate budget).
    Every refusal is a loud ``S_RETRY_AFTER`` with a reason and a retry
    hint — queue-full is an explicit wire signal, never silent
    buffering.
  * DEADLINES: the client's relative deadline is stamped absolute at
    intake; an op that expires in the intake queue resolves
    ``S_DEADLINE`` WITHOUT being injected, and an admitted op that
    out-ages its deadline resolves ``S_DEADLINE`` at the completion
    scan (for updates a deadline is a MAYBE — the broadcast may still
    commit, exactly the crash-'lost' semantics; the abandoned future is
    kept until the store resolves it so quota accounting stays exact).
  * SHED LADDER: rung transitions land on the obs timeline as
    ``shed``/``shed_clear`` events and per-tenant counters; rung 1
    composes with the store's ``min_healthy_for_writes`` degraded mode
    (degraded => writes shed at the front door).
  * WATCHDOG TAGS: the round-9 stuck-op diagnostics (and
    ``StuckOpError``) carry the op's tenant id and remaining deadline
    budget through ``kvs.diag_hook`` — the ``drill=``/``net_phase``
    pattern, per op.

The clock is caller-supplied: ``VirtualClock`` for deterministic soaks
(the driver advances it by ``scfg.round_us`` per pump — same-seed runs
replay byte-identically, the chaos-schedule discipline applied to
serving), ``time.monotonic`` under real sockets (serving/rpc.py).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from hermes_tpu.serving import wire
from hermes_tpu.serving.admission import AdmissionControl
from hermes_tpu.transport import codec as _codec


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Front-end envelope knobs (one frozen dataclass, config.py style)."""

    tenant_rate_per_s: float = 4000.0   # sustained per-tenant admission rate
    tenant_burst: float = 64.0          # token-bucket burst
    tenant_quota: int = 32              # client-visible in-flight cap/tenant
    queue_cap: int = 128                # bounded intake queue
    shed_write_frac: float = 0.6        # ladder rung 1 at this queue fill
    shed_read_frac: float = 0.9         # ladder rung 2 at this queue fill
    hot_keys: Tuple[int, ...] = ()      # reads on these survive rung 2
    default_deadline_us: int = 0        # applied when a request carries 0
    round_us: int = 1000                # virtual microseconds per pump
    retry_after_floor_s: float = 0.001  # minimum retry hint
    store_inflight_cap: Optional[int] = None  # ops handed to the store at
    # once (None = one per store session lane); the intake queue holds the
    # rest — THAT bound is what makes backpressure observable
    resp_meta_cap: int = 1 << 17  # per-response (tenant, status, latency)
    # retention ring: exact for the finite soak/bench drivers (which size
    # well under it), bounded for a long-lived TCP server — the always-on
    # exact accounting is AdmissionControl's counters, not this ring
    trace_sample: int = 0  # per-op tracing (round-18, obs/tracing.py):
    # 0 = off, N = mint a trace id for ~1 in N submitted ops (seeded,
    # deterministic — same ops trace on every replay).  A request already
    # carrying a nonzero wire trace id is ALWAYS traced (the client
    # sampled it); the id rides the formerly-pad u16 of wire._REQ.
    trace_seed: int = 0

    def __post_init__(self) -> None:
        if self.tenant_quota < 1 or self.queue_cap < 1:
            raise ValueError("tenant_quota and queue_cap must be >= 1")
        if self.trace_sample < 0:
            raise ValueError("trace_sample must be >= 0 (0 disables)")
        if not (0.0 < self.shed_write_frac <= self.shed_read_frac <= 1.0):
            raise ValueError(
                "want 0 < shed_write_frac <= shed_read_frac <= 1 (writes "
                "shed first, then non-hot reads)")
        if self.round_us <= 0:
            raise ValueError("round_us must be > 0")
        if self.resp_meta_cap < 1:
            raise ValueError("resp_meta_cap must be >= 1")
        object.__setattr__(self, "hot_key_set", frozenset(
            int(k) for k in self.hot_keys))


class VirtualClock:
    """Deterministic serving clock: the soak driver advances it."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, ds: float) -> None:
        self.t += ds


class RespMetaRing:
    """Bounded columnar response-meta history — the round-21 twin of
    the old per-row ``deque`` of (tenant, status, latency) tuples: a
    fixed-capacity numpy ring the hot paths append COLUMNS into
    (``extend`` is one fancy-index write per batch; the scalar
    ``append`` stays for the row-at-a-time Frontend).  Latency NaN
    encodes a refusal's absent measurement; iteration yields the exact
    (tenant, status, latency-or-None) tuples the soak census loops
    always consumed, oldest first."""

    def __init__(self, cap: int):
        self.cap = int(cap)
        self.tenant = np.zeros(self.cap, np.int32)
        self.status = np.zeros(self.cap, np.uint8)
        self.lat = np.full(self.cap, np.nan)
        self.n = 0  # total rows ever appended (monotone)

    def append(self, tenant: int, status: int,
               latency_s: Optional[float]) -> None:
        i = self.n % self.cap
        self.tenant[i] = tenant
        self.status[i] = status
        self.lat[i] = np.nan if latency_s is None else latency_s
        self.n += 1

    def extend(self, tenants, statuses, lats=None) -> None:
        """Column append: ``lats=None`` records NaN for every row (the
        immediate-refusal shape).  Batches larger than the capacity
        keep their LAST ``cap`` rows — same semantics as appending row
        by row into a maxlen deque."""
        k = int(np.asarray(statuses).shape[0])
        if not k:
            return
        drop = max(0, k - self.cap)
        idx = (self.n + drop + np.arange(k - drop)) % self.cap
        self.tenant[idx] = np.asarray(tenants)[drop:]
        self.status[idx] = np.asarray(statuses)[drop:]
        self.lat[idx] = (np.nan if lats is None
                         else np.asarray(lats, float)[drop:])
        self.n += k

    def _window(self) -> np.ndarray:
        held = min(self.n, self.cap)
        return (self.n - held + np.arange(held)) % self.cap

    def __len__(self) -> int:
        return min(self.n, self.cap)

    def __iter__(self):
        idx = self._window()
        for t, s, lc in zip(self.tenant[idx].tolist(),
                            self.status[idx].tolist(),
                            self.lat[idx].tolist()):
            yield (t, s, None if math.isnan(lc) else lc)

    def latencies(self, statuses) -> List[float]:
        """Measured latencies of rows whose status is in ``statuses``
        (one vectorized mask, the hot-path replacement for the old
        list comprehension over tuples)."""
        idx = self._window()
        lat = self.lat[idx]
        m = (np.isin(self.status[idx], np.asarray(list(statuses)))
             & ~np.isnan(lat))
        return lat[m].tolist()


class _ReadFuture:
    """Future-shaped adapter over a MultiGetResult/FleetReads: done when
    every key answered (locally or via the round-path fallback the pump's
    store.step() drives)."""

    def __init__(self, res):
        self.res = res

    def done(self) -> bool:
        return self.res.all_done()


class Frontend:
    """One serving front-end over a KVS or Fleet facade."""

    def __init__(self, store, scfg: Optional[ServingConfig] = None,
                 clock=None):
        self.store = store
        self.scfg = scfg or ServingConfig()
        self.is_fleet = hasattr(store, "router") and hasattr(store, "groups")
        base = store.cfg.base if self.is_fleet else store.cfg
        self.u = base.value_words - 2
        # value heap (round-17): > 0 switches the wire to length-prefixed
        # byte payloads (both ends derive it from the shared config, like
        # ``u``) and the issue path to store byte puts
        self.vbytes = base.max_value_bytes
        if self.u < 1:
            raise ValueError("serving needs value_words >= 3 (the store "
                             "carries write uids in words 0-1)")
        self.n_keys = (store.cfg.total_keys if self.is_fleet
                       else base.n_keys)
        self.clock = clock if clock is not None else time.monotonic
        self.adm = AdmissionControl(self.scfg)
        self._intake: collections.deque = collections.deque()
        self._pending: Dict[int, dict] = {}   # req_id -> entry (admit order)
        self._abandoned: List[dict] = []      # RPC resolved, store op open
        self._responses: List[wire.Response] = []
        self._resp_meta = RespMetaRing(self.scfg.resp_meta_cap)
        self._lane_seq: Dict[int, int] = collections.defaultdict(int)
        self.requests = 0
        self.responses = 0
        self.shed_level = 0
        self._fleet_deg: Optional[bool] = None  # any-group scan, per round
        self._lanes: List[tuple] = []
        if self.is_fleet:
            cap = sum(g.cfg.n_replicas * g.cfg.n_sessions
                      for g in store.groups)
            for g in store.groups:
                g.kvs.diag_hook = (
                    lambda r, s, _g=g.gid: self._diag_for(_g, r, s))
        else:
            cfg = store.cfg
            cap = cfg.n_replicas * cfg.n_sessions
            self._lanes = [(r, s) for r in range(cfg.n_replicas)
                           for s in range(cfg.n_sessions)]
            store.diag_hook = lambda r, s: self._diag_for(None, r, s)
        self._store_cap = (self.scfg.store_inflight_cap
                           if self.scfg.store_inflight_cap is not None
                           else cap)
        self._store_inflight = 0
        # per-op tracing (round-18): front-door sampler + span writer.
        # Single-op requests only — the batched-read header has no free
        # u16 (count occupies it), so K_MGET/K_SCAN stay untraced.
        if self.scfg.trace_sample:
            from hermes_tpu.obs.tracing import TraceSampler

            self._sampler = TraceSampler(self.scfg.trace_sample,
                                         seed=self.scfg.trace_seed)
        else:
            self._sampler = None
        self._op_tracer_cache = None
        self._round_key_ops: dict = {}  # key -> admitted ops this round

    # -- plumbing ------------------------------------------------------------

    def _rt(self):
        return (self.store.groups[0].rt if self.is_fleet else self.store.rt)

    def _trace(self, name: str, **fields) -> None:
        rt = self._rt()
        rt._trace(name, **fields)
        if rt.obs is not None:
            rt.obs.registry.counter(f"serving_{name}").inc()

    def _count(self, name: str, n: int = 1) -> None:
        rt = self._rt()
        if rt.obs is not None:
            rt.obs.registry.counter(f"serving_{name}").inc(n)

    def _op_tracer(self):
        """Span writer bound to the store runtime's current obs context
        (None while none is attached)."""
        rt = self._rt()
        if rt.obs is None:
            return None
        c = self._op_tracer_cache
        if c is None or c.obs is not rt.obs:
            from hermes_tpu.obs.tracing import OpTracer

            c = self._op_tracer_cache = OpTracer(rt.obs)
        return c

    def _trace_resolve(self, entry: dict, status: int, now: float) -> None:
        """Close a sampled op's end-to-end span at RPC resolution:
        admission round -> resolution round, with the terminal status
        (the critical-path denominator obs/report.py breaks down)."""
        trace = entry.get("trace", 0)
        if not trace:
            return
        tr = self._op_tracer()
        if tr is None:
            return
        req = entry["req"]
        tags = dict(tenant=req.tenant, op=req.kind, key=req.key,
                    status=int(status))
        lane = entry.get("lane")
        if lane is not None and lane[0] is not None:
            tags["group"] = lane[0]
        tr.span("fe_resolve", trace, r0=entry["r_admit"],
                r1=self._rt().step_idx,
                dur_s=now - entry["t_admit"], **tags)

    def _degraded_for_key(self, key: int) -> bool:
        if self.is_fleet:
            return self.store.degraded(key)
        return self.store.degraded()

    def _diag_for(self, group, r, s) -> Optional[dict]:
        """Watchdog tag lookup: the oldest un-resolved op on lane
        (group, r, s) names its tenant + remaining deadline budget.
        Abandoned entries (RPC already resolved S_DEADLINE, store op
        still open) are scanned too — a long-stuck op has usually
        out-aged its deadline by the time the watchdog fires."""
        now = self.clock()
        for entry in list(self._pending.values()) + self._abandoned:
            if entry.get("lane") == (group, r, s):
                d = dict(tenant=entry["req"].tenant)
                if entry["deadline"] is not None:
                    d["deadline_left_us"] = int(
                        round((entry["deadline"] - now) * 1e6))
                return d
        return None

    def _update_level(self, degraded: Optional[bool] = None,
                      fresh: bool = True) -> None:
        # non-fleet degradation is key-independent, so submit can hand us
        # the value it already computed; fleet ladder pressure is the
        # any-group scan regardless of the op's key — and that scan can
        # only change when membership does (once per store round), so the
        # per-request path (fresh=False) reuses the last pump's scan
        # instead of walking every group's healthy set per request
        if self.is_fleet:
            if fresh or self._fleet_deg is None:
                self._fleet_deg = any(g.kvs.degraded()
                                      for g in self.store.groups)
            degraded = self._fleet_deg
        elif degraded is None:
            degraded = self._degraded_for_key(0)
        level = self.adm.ladder_level(len(self._intake), degraded)
        if level != self.shed_level:
            if level > 0:
                self._trace("shed", level=level, queue=len(self._intake))
            else:
                self._trace("shed_clear", queue=len(self._intake))
            self.shed_level = level

    def _respond(self, rsp: wire.Response, tenant: int,
                 latency_s: Optional[float] = None,
                 queue: bool = True) -> wire.Response:
        # queue=False: an immediate refusal submit() hands straight back
        # to its caller — accounted here, but NOT queued for pump(), or
        # the transport would deliver it a second time (and on the TCP
        # path the re-send would carry the restored CLIENT req_id, which
        # can collide with another connection's pending internal id)
        if queue:
            self._responses.append(rsp)
        self._resp_meta.append(tenant, rsp.status, latency_s)
        self.responses += 1
        return rsp

    def pop_responses(self) -> List[wire.Response]:
        out, self._responses = self._responses, []
        return out

    # -- intake --------------------------------------------------------------

    def submit(self, req) -> Optional[object]:
        """Run one request through admission.  Returns an immediate
        refusal Response, or None when admitted (the resolution arrives
        from a later ``pump``).  Accepts the single-op ``wire.Request``
        and the round-16 batched ``wire.ReadRequest`` (K_MGET/K_SCAN)."""
        if isinstance(req, wire.ReadRequest):
            return self._submit_read(req)
        now = self.clock()
        self.requests += 1
        if req.kind not in ("get", "put", "rmw") \
                or not (0 <= req.key < self.n_keys):
            return self._respond(wire.Response(
                status=wire.S_REJECTED, req_id=req.req_id), req.tenant,
                queue=False)
        if self.vbytes and req.kind != "get" and (
                req.data is None or len(req.data) > self.vbytes):
            # heap mode: an update must carry a byte payload the store
            # can hold — refused loudly at the door, never a deep error
            return self._respond(wire.Response(
                status=wire.S_REJECTED, req_id=req.req_id), req.tenant,
                queue=False)
        degraded = self._degraded_for_key(req.key)
        self._update_level(degraded, fresh=False)
        reason, wait = self.adm.admit(req.kind, req.key, req.tenant, now,
                                      len(self._intake), degraded)
        if reason != wire.R_NONE:
            self._count("retry_after")
            return self._respond(wire.Response(
                status=wire.S_RETRY_AFTER, req_id=req.req_id, reason=reason,
                retry_after_us=int(math.ceil(wait * 1e6))), req.tenant,
                queue=False)
        self.adm.note_admitted(req.tenant)
        # key-heat tally (round-18, obs/series.py): admitted ops per key
        # this serving round, harvested into the heat series at pump time
        self._round_key_ops[req.key] = \
            self._round_key_ops.get(req.key, 0) + 1
        # trace mint (round-18): adopt a client-sampled wire id, else
        # sample on the monotone request sequence; the id follows the
        # entry through issue and resolution (and is staged into the
        # store so the KVS-level spans share it)
        trace = int(getattr(req, "trace", 0) or 0)
        if not trace and self._sampler is not None:
            trace = self._sampler.sample(self.requests - 1)
        dl_us = req.deadline_us or self.scfg.default_deadline_us
        self._intake.append(dict(
            req=req, t_admit=now, trace=trace,
            r_admit=self._rt().step_idx,
            deadline=(now + dl_us * 1e-6) if dl_us else None))
        return None

    def _read_probe_key(self, req: wire.ReadRequest) -> int:
        """The key the admission ladder judges a batched read by: its
        first NON-hot key, so rung 2 sheds the batch unless EVERY key is
        hot — reads shed at rung 2 exactly as today, and a batch cannot
        smuggle cold keys past the ladder behind one hot one.  A scan
        range wider than the hot set provably CONTAINS a cold key
        (len(hot)+1 distinct keys cannot all be hot), so probing that
        many from lo always finds one — never judge a range by its
        endpoints, which may both be hot over a cold interior."""
        hot = self.scfg.hot_key_set
        if req.kind == "mget":
            keys = req.keys
        else:
            keys = range(req.lo, min(req.hi, req.lo + len(hot) + 1))
        for k in keys:
            if k not in hot:
                return int(k)
        return int(next(iter(keys)))

    def _submit_read(self, req: wire.ReadRequest):
        """Admission for one batched read RPC (ONE admission unit: one
        quota slot, one queue entry, one rate token — the batch is one
        client-visible op)."""
        now = self.clock()
        self.requests += 1
        bad = (req.kind not in ("mget", "scan")
               or (req.kind == "mget" and not (
                   req.keys and len(req.keys) <= wire.MGET_MAX_KEYS
                   and all(0 <= k < self.n_keys for k in req.keys)))
               or (req.kind == "scan"
                   and not (0 <= req.lo < req.hi <= self.n_keys)))
        if bad:
            return self._respond(wire.ReadResponse(
                status=wire.S_REJECTED, req_id=req.req_id), req.tenant,
                queue=False)
        self._update_level(None, fresh=False)
        # degraded mode never sheds reads (rung 1 is write-only), so the
        # ladder decision for a read depends on queue pressure alone —
        # and the probe key only matters at rung 2, so the O(batch) cold
        # hunt is skipped entirely while the queue is below that mark
        probe = (self._read_probe_key(req)
                 if self.adm.ladder_level(len(self._intake), False) >= 2
                 else (req.keys[0] if req.kind == "mget" else req.lo))
        reason, wait = self.adm.admit(
            "get", probe, req.tenant, now, len(self._intake), False)
        if reason != wire.R_NONE:
            self._count("retry_after")
            return self._respond(wire.ReadResponse(
                status=wire.S_RETRY_AFTER, req_id=req.req_id, reason=reason,
                retry_after_us=int(math.ceil(wait * 1e6))), req.tenant,
                queue=False)
        self.adm.note_admitted(req.tenant)
        dl_us = req.deadline_us or self.scfg.default_deadline_us
        self._intake.append(dict(
            req=req, t_admit=now,
            deadline=(now + dl_us * 1e-6) if dl_us else None))
        return None

    # -- the pump ------------------------------------------------------------

    def _issue(self, entry: dict) -> None:
        """Hand one admitted op to the store on a deterministic lane."""
        req = entry["req"]
        seq = self._lane_seq[req.tenant]
        self._lane_seq[req.tenant] = seq + 1
        if isinstance(req, wire.ReadRequest):
            # batched read (round-16): issued straight to the store's
            # local-read fast path; only Invalid keys ride round-path
            # fallback slots, which the pump's store.step() drives.
            # Read-your-writes is TENANT-scoped here: the frontend pins a
            # per-tenant fence token on every commit it delivers
            # (_result_response -> store.pin_read_fence), and the read
            # carries the same token — lane rotation on the write path
            # cannot defeat it.
            args = dict(session=("tenant", req.tenant), wait=False)
            res = (self.store.multi_get(req.keys, **args)
                   if req.kind == "mget"
                   else self.store.scan(req.lo, req.hi, **args))
            entry["fut"] = _ReadFuture(res)
            self._pending[req.req_id] = entry
            self._store_inflight += 1
            return
        value = None
        if req.kind != "get":
            # heap mode stores the request's byte payload verbatim (the
            # KVS appends the extent and rounds only the packed ref)
            value = bytes(req.data) if self.vbytes else req.value
        trace = entry.get("trace", 0)
        if self.is_fleet:
            session = req.tenant * 7919 + seq
            fut, lane = self.store.route_op(req.kind, session, req.key,
                                            value)
            entry["lane"] = lane
        else:
            r, s = self._lanes[(req.tenant * 7919 + seq) % len(self._lanes)]
            entry["lane"] = (None, r, s)
            if trace:
                # hand the minted id to the KVS so its op_queue/op_rounds
                # spans carry the SAME trace (consumed by the next
                # _enqueue; the fleet path keeps frontend spans only —
                # route_op picks the group internally)
                self.store._staged_trace = trace
            fut = getattr(self.store, req.kind)(r, s, req.key, *(
                (value,) if value is not None else ()))
        entry["fut"] = fut
        self._pending[req.req_id] = entry
        self._store_inflight += 1
        if trace:
            tr = self._op_tracer()
            if tr is not None:
                # intake-queue wait: admission round -> store-issue round
                tags = dict(tenant=req.tenant, op=req.kind, key=req.key)
                lane = entry.get("lane")
                if lane is not None and lane[0] is not None:
                    tags["group"] = lane[0]
                tr.span("fe_queue", trace, r0=entry["r_admit"],
                        r1=self._rt().step_idx,
                        dur_s=self.clock() - entry["t_admit"], **tags)

    _STATUS = {"get": wire.S_OK, "put": wire.S_OK, "rmw": wire.S_OK,
               "rmw_abort": wire.S_RMW_ABORT, "lost": wire.S_LOST,
               "rejected": wire.S_REJECTED}

    def _deadline_rsp(self, req):
        """The S_DEADLINE refusal in the request's own response layout."""
        if isinstance(req, wire.ReadRequest):
            return wire.ReadResponse(status=wire.S_DEADLINE,
                                     req_id=req.req_id)
        return wire.Response(status=wire.S_DEADLINE, req_id=req.req_id,
                             found=False)

    def _result_response(self, entry: dict):
        req = entry["req"]
        if isinstance(req, wire.ReadRequest):
            import numpy as np

            from hermes_tpu.kvs import C_REJECTED
            from hermes_tpu.core import types as t

            res = entry["fut"].res
            res._pull()
            served = res.code == t.C_READ
            rrsp = wire.ReadResponse(
                status=wire.S_OK, req_id=req.req_id,
                step=int(res.step.max()) if len(res) else -1,
                found=(np.asarray(res.found) & served).tolist(),
                local=np.asarray(res.local).tolist(),
                codes=np.where(res.code == C_REJECTED, wire.RK_REJECTED,
                               wire.RK_OK).tolist(),
                values=np.asarray(res.value).tolist())
            if self.vbytes:
                rrsp.data = list(res.data)
            return rrsp
        c = entry["fut"].result()
        rsp = wire.Response(status=self._STATUS[c.kind], req_id=req.req_id,
                            found=c.found, step=c.step)
        if c.value is not None:
            rsp.value = c.value
            rsp.data = c.data
        if c.uid is not None:
            rsp.uid = c.uid
            if c.ts is not None:
                # the tenant just SAW this write commit: pin its fence
                # token so the tenant's later K_MGET/K_SCAN reads must
                # observe this timestamp or take the round path (RYW
                # through the serving front-end, per tenant)
                self.store.pin_read_fence(("tenant", req.tenant),
                                          req.key, c.ts)
        return rsp

    def pump(self) -> List[wire.Response]:
        """One serving round: issue from the intake queue (deadline-
        checked), run one store round, harvest completions and expired
        deadlines.  Returns the responses produced this round."""
        now = self.clock()
        # intake expiry FIRST, over the whole queue — an op stuck behind a
        # full store must still resolve S_DEADLINE on time, not wait for
        # its pop turn
        if self._intake:
            keep = collections.deque()
            for entry in self._intake:
                req = entry["req"]
                if entry["deadline"] is not None and now > entry["deadline"]:
                    self.adm.note_resolved(req.tenant, wire.S_DEADLINE)
                    self._count("deadline")
                    self._respond(self._deadline_rsp(req), req.tenant,
                                  now - entry["t_admit"])
                    self._trace_resolve(entry, wire.S_DEADLINE, now)
                else:
                    keep.append(entry)
            self._intake = keep
        # intake -> store (expired ops were resolved above, never injected)
        while self._intake and self._store_inflight < self._store_cap:
            self._issue(self._intake.popleft())
        self.store.step()
        now = self.clock()
        # harvest completions + completion-side deadline enforcement
        done_ids = []
        for rid, entry in self._pending.items():
            fut = entry["fut"]
            late = (entry["deadline"] is not None
                    and now > entry["deadline"])
            if fut.done():
                rsp = (self._deadline_rsp(entry["req"]) if late
                       else self._result_response(entry))
                if late:
                    self._count("deadline")
                self.adm.note_resolved(entry["req"].tenant, rsp.status)
                self._respond(rsp, entry["req"].tenant,
                              now - entry["t_admit"])
                self._trace_resolve(entry, rsp.status, now)
                self._store_inflight -= 1
                done_ids.append(rid)
            elif late:
                # the RPC resolves NOW; the store op stays abandoned until
                # the protocol finishes it (quota freed, lane not yet)
                self.adm.note_resolved(entry["req"].tenant, wire.S_DEADLINE)
                self._count("deadline")
                self._respond(self._deadline_rsp(entry["req"]),
                              entry["req"].tenant, now - entry["t_admit"])
                self._trace_resolve(entry, wire.S_DEADLINE, now)
                self._abandoned.append(entry)
                done_ids.append(rid)
        for rid in done_ids:
            del self._pending[rid]
        still = []
        for entry in self._abandoned:
            if entry["fut"].done():
                self._store_inflight -= 1
            else:
                still.append(entry)
        self._abandoned = still
        self._update_level()
        rt = self._rt()
        if rt.obs is not None:
            # ladder history (round-18, obs/series.py): intake depth and
            # shed rung per serving round, keyed by the store's round
            # index — the backpressure trend a controller steers on
            reg = rt.obs.registry
            reg.series("intake_depth_series").append(
                rt.step_idx, len(self._intake))
            reg.series("shed_level_series").append(
                rt.step_idx, self.shed_level)
            # per-range key heat (ROADMAP item 6's controller input):
            # the round's hottest single key's op count and its distinct
            # key spread — the skew trend shed rung 2 would steer on
            reg.series("key_heat_max_series").append(
                rt.step_idx, max(self._round_key_ops.values(), default=0))
            reg.series("key_distinct_series").append(
                rt.step_idx, len(self._round_key_ops))
        self._round_key_ops.clear()
        return self.pop_responses()

    def flush(self) -> List[wire.Response]:
        """Force the store's deferred (pipelined) completions out and
        harvest them."""
        if self.is_fleet:
            self.store.flush()
        else:
            self.store.flush()
            self.store.rt.flush_pipeline()
        return self.pump()

    def drain(self, max_rounds: int = 10_000) -> bool:
        """Pump until every admitted op (including abandoned deadline
        maybes) resolves.  True when fully drained.  The responses
        produced while draining stay queued for ``pop_responses`` — a
        drained op resolved loudly, so its Response must remain
        observable, not vanish into the drain loop."""
        kept: List[wire.Response] = []
        done = False
        for _ in range(max_rounds):
            if not (self._intake or self._pending or self._abandoned):
                # a drained envelope is the ladder's floor: re-evaluate so
                # a pressure-driven rung emits its shed_clear even when no
                # further request arrives to observe it
                self._update_level()
                done = True
                break
            kept.extend(self.pump())
        if not done:
            kept.extend(self.flush())
        self._responses = kept + self._responses
        return not (self._intake or self._pending or self._abandoned)

    # -- accounting ----------------------------------------------------------

    def latencies(self, statuses=(wire.S_OK, wire.S_RMW_ABORT,
                                  wire.S_DEADLINE, wire.S_REJECTED,
                                  wire.S_LOST)) -> List[float]:
        """Admission-to-resolution latency (serving clock, seconds) of
        every ADMITTED op whose terminal status is in ``statuses``."""
        return self._resp_meta.latencies(statuses)

    def counters(self) -> dict:
        per = self.adm.counters()
        agg: Dict[str, int] = {}
        for row in per.values():
            for k, v in row.items():
                agg[k] = agg.get(k, 0) + v
        return dict(requests=self.requests, responses=self.responses,
                    shed_level=self.shed_level, queue=len(self._intake),
                    store_inflight=self._store_inflight,
                    tenants=per, fleet=self.is_fleet, totals=agg)


# -- round-19: the columnar data plane ---------------------------------------
#
# ``ColumnarFrontend`` is the batch twin of ``Frontend``: whole columnar
# request batches (wire.ReqBatch) run the admission ladder in O(1)
# numpy passes per batch (admission.admit_batch — proven row-for-row
# equivalent to the scalar ladder), admitted rows live in a
# preallocated ``CompletionRing`` instead of per-request Future/dict
# objects, the pump resolves a round's completions as COLUMN writes off
# ``kvs.BatchFutures``, and responses drain as one ``RspBatch`` per
# connection per pump (one encode per connection on the transport).
# Single-op verbs only (get/put/rmw); the batched-read verbs
# (K_MGET/K_SCAN) and fleet routing stay on the scalar Frontend — and
# because the columnar plane serves no reads-with-fences, it does not
# pin per-tenant read fences on commit (the scalar path's RYW
# plumbing).  KVS-level op spans are also scalar-only (submit_batch has
# no per-op trace staging); the columnar plane closes fe_resolve spans
# for sampled rows so traced soaks still cover the front-end phase.

_RING_OPEN = 0xFF  # status column sentinel: slot allocated, unresolved


class CompletionRing:
    """The preallocated completion plane: an admitted op's identity is a
    SLOT INDEX into these columns (conn + client req_id restore the wire
    identity at emit time), allocated from a free stack and recycled the
    pump after the response is built.  No per-op Python objects exist
    between admission and emit."""

    def __init__(self, cap: int, u: int, vbytes: int):
        size = 1 << max(4, int(cap - 1).bit_length())
        self.cap = size
        self.u = u
        self.vbytes = vbytes
        # free stack: pop from the end, push back on release
        self.free = np.arange(size - 1, -1, -1, np.int32)
        self.n_free = size
        # request-side columns (written at admission)
        self.conn = np.zeros(size, np.int32)
        self.client_rid = np.zeros(size, np.uint32)
        self.tenant = np.zeros(size, np.int32)
        self.kind = np.zeros(size, np.uint8)      # wire K_* codes
        self.key = np.zeros(size, np.int64)
        self.trace = np.zeros(size, np.uint16)
        self.deadline = np.full(size, np.inf)     # absolute; inf = none
        self.t_admit = np.zeros(size)
        self.r_admit = np.zeros(size, np.int32)
        # resolution columns (written by the pump's harvest)
        self.status = np.full(size, _RING_OPEN, np.uint8)
        self.reason = np.zeros(size, np.uint8)
        self.found = np.zeros(size, bool)
        self.has_uid = np.zeros(size, bool)
        self.step = np.full(size, -1, np.int32)
        self.retry_us = np.zeros(size, np.uint32)
        self.uid = np.zeros((size, 2), np.int32)
        # payload: fixed word matrix, or (heap mode) a preallocated byte
        # ARENA — one (size, vbytes) row per slot plus a length column
        # (-1 = no payload), so the emit path can assemble a response
        # blob with codec.ragged_gather instead of a per-row Python
        # join (round-21; the old per-slot ``bytes`` list is gone)
        self.value = (np.zeros((size, u), np.int32) if not vbytes else None)
        self.heap = (np.zeros((size, vbytes), np.uint8) if vbytes
                     else None)
        self.dlen = (np.full(size, -1, np.int64) if vbytes else None)

    def set_data(self, s: int, b: Optional[bytes]) -> None:
        """Write one slot's heap payload (None clears)."""
        if b is None:
            self.dlen[s] = -1
            return
        n = len(b)
        self.heap[s, :n] = np.frombuffer(b, np.uint8)
        self.dlen[s] = n

    def get_data(self, s: int) -> Optional[bytes]:
        n = int(self.dlen[s])
        return None if n < 0 else self.heap[s, :n].tobytes()

    def alloc(self, k: int) -> np.ndarray:
        if k > self.n_free:
            raise RuntimeError(
                f"completion ring exhausted: want {k} slots, {self.n_free} "
                f"free of {self.cap} — the ring is sized for queue_cap + "
                "store_inflight_cap, so this is an accounting bug, not "
                "backpressure")
        out = self.free[self.n_free - k: self.n_free].copy()
        self.n_free -= k
        return out

    def release(self, slots: np.ndarray) -> None:
        k = int(slots.size)
        if not k:
            return
        self.free[self.n_free: self.n_free + k] = slots
        self.n_free += k
        self.status[slots] = _RING_OPEN
        if self.vbytes:
            self.dlen[slots] = -1

    def in_use(self) -> int:
        return self.cap - self.n_free


class ColumnarFrontend:
    """The columnar serving data plane over one ``kvs.KVS`` (round-19).

    Same envelope semantics as ``Frontend`` — refusal reasons and
    retry hints row-for-row identical to the scalar ladder, deadlines
    enforced at intake and completion, loud statuses everywhere — at
    columnar throughput: admission, issue, harvest, and emit each touch
    a whole batch per numpy pass."""

    def __init__(self, store, scfg: Optional[ServingConfig] = None,
                 clock=None, ring_slack: int = 64):
        if hasattr(store, "router") and hasattr(store, "groups"):
            raise ValueError(
                "the columnar plane serves a single KVS; fleet routing "
                "(and the batched-read verbs) stay on the scalar Frontend")
        from hermes_tpu.core import types as t
        from hermes_tpu.kvs import C_LOST, C_REJECTED

        # the wire op codes ARE the store op codes (K_GET==OP_READ, ...):
        # the issue path relies on passing the kind column through verbatim
        assert (wire.K_GET, wire.K_PUT, wire.K_RMW) == (
            t.OP_READ, t.OP_WRITE, t.OP_RMW)
        self._C_READ, self._C_WRITE = t.C_READ, t.C_WRITE
        self._C_RMW, self._C_RMW_ABORT = t.C_RMW, t.C_RMW_ABORT
        self._C_LOST, self._C_REJECTED = C_LOST, C_REJECTED
        # completion code -> wire status, indexed by code + 3
        lut = np.zeros(8, np.uint8)
        lut[C_LOST + 3] = wire.S_LOST
        lut[C_REJECTED + 3] = wire.S_REJECTED
        lut[t.C_READ + 3] = wire.S_OK
        lut[t.C_WRITE + 3] = wire.S_OK
        lut[t.C_RMW + 3] = wire.S_OK
        lut[t.C_RMW_ABORT + 3] = wire.S_RMW_ABORT
        self._code_lut = lut

        self.store = store
        self.scfg = scfg or ServingConfig()
        self.u = store.cfg.value_words - 2
        self.vbytes = store.cfg.max_value_bytes
        if self.u < 1:
            raise ValueError("serving needs value_words >= 3 (the store "
                             "carries write uids in words 0-1)")
        self.n_keys = store.cfg.n_keys
        self.clock = clock if clock is not None else time.monotonic
        self.adm = AdmissionControl(self.scfg)
        cap = store.cfg.n_replicas * store.cfg.n_sessions
        self._store_cap = (self.scfg.store_inflight_cap
                           if self.scfg.store_inflight_cap is not None
                           else cap)
        self.ring = CompletionRing(
            self.scfg.queue_cap + self._store_cap + ring_slack,
            self.u, self.vbytes)
        self._intake: List[np.ndarray] = []   # FIFO of slot-id arrays
        self._intake_len = 0
        # open store batches: bf + slots + per-row resolved/harvested/
        # released masks (a row may resolve S_DEADLINE while its store op
        # is still open — the slot is held until the store finishes it,
        # the batch twin of the scalar _abandoned list)
        self._open: List[dict] = []
        self._store_inflight = 0
        self._resp_meta = RespMetaRing(self.scfg.resp_meta_cap)
        self.requests = 0
        self.responses = 0
        self.shed_level = 0
        if self.scfg.trace_sample:
            from hermes_tpu.obs.tracing import TraceSampler

            self._sampler = TraceSampler(self.scfg.trace_sample,
                                         seed=self.scfg.trace_seed)
        else:
            self._sampler = None
        self._op_tracer_cache = None
        self._round_key_ops: dict = {}

    # -- plumbing ------------------------------------------------------------

    def _rt(self):
        return self.store.rt

    def _trace(self, name: str, **fields) -> None:
        rt = self._rt()
        rt._trace(name, **fields)
        if rt.obs is not None:
            rt.obs.registry.counter(f"serving_{name}").inc()

    def _count(self, name: str, n: int = 1) -> None:
        rt = self._rt()
        if rt.obs is not None and n:
            rt.obs.registry.counter(f"serving_{name}").inc(n)

    def _op_tracer(self):
        rt = self._rt()
        if rt.obs is None:
            return None
        c = self._op_tracer_cache
        if c is None or c.obs is not rt.obs:
            from hermes_tpu.obs.tracing import OpTracer

            c = self._op_tracer_cache = OpTracer(rt.obs)
        return c

    def _update_level(self, degraded: Optional[bool] = None) -> None:
        if degraded is None:
            degraded = self.store.degraded()
        level = self.adm.ladder_level(self._intake_len, degraded)
        if level != self.shed_level:
            if level > 0:
                self._trace("shed", level=level, queue=self._intake_len)
            else:
                self._trace("shed_clear", queue=self._intake_len)
            self.shed_level = level

    # -- intake --------------------------------------------------------------

    def submit_batch(self, batch: wire.ReqBatch, conn=0):
        """Run a whole request batch through admission in one pass.
        Returns the IMMEDIATE refusals (S_REJECTED validity failures and
        loud S_RETRY_AFTER rows) — possibly empty; admitted rows resolve
        through later ``pump`` calls.  ``conn`` tags admitted rows so
        the pump can emit one response batch per connection: a scalar
        tags the whole batch (one transport connection, the round-19
        contract — refusals return as an RspBatch in batch row order),
        while an int ndarray tags PER ROW (the round-21 shm merge path,
        where one owner batch carries every worker's connections —
        refusals return as {conn: RspBatch}, the same shape ``pump``
        emits)."""
        vec_conn = isinstance(conn, np.ndarray)
        now = self.clock()
        k = len(batch)
        self.requests += k
        if k == 0:
            return _empty_rsp_batch(self.u, self.vbytes)
        status = np.full(k, _RING_OPEN, np.uint8)
        reason = np.zeros(k, np.uint8)
        retry_us = np.zeros(k, np.uint32)
        kind = np.asarray(batch.kind, np.uint8)
        key = np.asarray(batch.key, np.int64)
        # validity (the scalar path's pre-admission S_REJECTED checks):
        # unknown kind, key out of range, heap update without a payload
        valid = (np.isin(kind, (wire.K_GET, wire.K_PUT, wire.K_RMW))
                 & (key >= 0) & (key < self.n_keys))
        writes = kind != wire.K_GET
        if self.vbytes:
            vlen = (np.asarray(batch.vlen, np.int64)
                    if batch.vlen is not None else np.full(k, -1, np.int64))
            valid &= ~writes | ((vlen >= 0) & (vlen <= self.vbytes))
        status[~valid] = wire.S_REJECTED
        vi = np.nonzero(valid)[0]
        degraded = self.store.degraded()
        self._update_level(degraded)
        reasons, waits = self.adm.admit_batch(
            writes[vi], key[vi], batch.tenant[vi], now,
            self._intake_len, degraded)
        refused = reasons != wire.R_NONE
        ri = vi[refused]
        status[ri] = wire.S_RETRY_AFTER
        reason[ri] = reasons[refused]
        retry_us[ri] = np.ceil(waits[refused] * 1e6).astype(np.uint32)
        self._count("retry_after", int(ri.size))
        ai = vi[~refused]
        if ai.size:
            # trace mint: adopt client-sampled wire ids, else sample on
            # the monotone request index (one vectorized splitmix64
            # pass, bit-exact with the old per-row loop)
            trace = np.asarray(batch.trace[ai], np.uint16).copy()
            if self._sampler is not None:
                base = self.requests - k
                z = np.nonzero(trace == 0)[0]
                if z.size:
                    trace[z] = self._sampler.sample_array(
                        (base + ai[z]).astype(np.uint64))
            rg = self.ring
            slots = rg.alloc(int(ai.size))
            rg.conn[slots] = conn[ai] if vec_conn else conn
            rg.client_rid[slots] = batch.req_id[ai]
            rg.tenant[slots] = batch.tenant[ai]
            rg.kind[slots] = kind[ai]
            rg.key[slots] = key[ai]
            rg.trace[slots] = trace
            dl = batch.deadline_us[ai].astype(np.int64)
            if self.scfg.default_deadline_us:
                dl = np.where(dl == 0, self.scfg.default_deadline_us, dl)
            rg.deadline[slots] = np.where(dl > 0, now + dl * 1e-6, np.inf)
            rg.t_admit[slots] = now
            rg.r_admit[slots] = self._rt().step_idx
            rg.status[slots] = _RING_OPEN
            if self.vbytes:
                # payload tails land in the arena in one ragged pass
                # (blob extents -> slot rows); gets carry vlen=-1 by
                # the wire codec's rule, matching old row_data(None)
                vl = (np.asarray(batch.vlen, np.int64)[ai]
                      if batch.vlen is not None
                      else np.full(ai.size, -1, np.int64))
                vo = (np.asarray(batch.voff, np.int64)[ai]
                      if batch.voff is not None
                      else np.zeros(ai.size, np.int64))
                # clamp defensively: the wire decoder already refuses
                # dlen > vbytes, but a hand-built batch must not be
                # able to scatter past its arena row
                vl = np.minimum(vl, self.vbytes)
                pl = np.maximum(vl, 0)
                src = _codec.ragged_gather(
                    np.frombuffer(batch.blob, np.uint8), vo, pl)
                _codec.ragged_scatter(
                    rg.heap.reshape(-1),
                    slots.astype(np.int64) * self.vbytes, pl, src)
                rg.dlen[slots] = vl
            else:
                rg.value[slots] = (batch.value[ai]
                                   if batch.value is not None
                                   else 0)
            self._intake.append(slots)
            self._intake_len += int(slots.size)
            ku, kc = np.unique(key[ai], return_counts=True)
            for kk, cc in zip(ku.tolist(), kc.tolist()):
                self._round_key_ops[kk] = \
                    self._round_key_ops.get(kk, 0) + cc
        # immediate refusals (in batch row order)
        done = status != _RING_OPEN
        di = np.nonzero(done)[0]
        nd = int(di.size)
        self.responses += nd
        self._resp_meta.extend(batch.tenant[di], status[di])
        rb = wire.RspBatch(
            status=status[di], reason=reason[di],
            req_id=np.asarray(batch.req_id)[di].astype(np.uint32),
            found=np.ones(nd, bool),  # refusal Responses default found=True
            has_uid=np.zeros(nd, bool), step=np.full(nd, -1, np.int32),
            retry_after_us=retry_us[di],
            uid=np.zeros((nd, 2), np.int32))
        if self.vbytes:
            rb.vlen = np.full(nd, -1, np.int64)
        else:
            rb.value = np.zeros((nd, self.u), np.int32)
        if not vec_conn:
            return rb
        out: Dict[int, wire.RspBatch] = {}
        cdi = np.asarray(conn)[di]
        for cid in np.unique(cdi).tolist():
            out[int(cid)] = rb.select(np.nonzero(cdi == cid)[0])
        return out

    # -- resolution helpers --------------------------------------------------

    def _mark_deadline(self, slots: np.ndarray) -> None:
        """Write the S_DEADLINE resolution columns (found=False, no
        result payload — the scalar ``_deadline_rsp`` shape)."""
        rg = self.ring
        rg.status[slots] = wire.S_DEADLINE
        rg.reason[slots] = wire.R_NONE
        rg.found[slots] = False
        rg.has_uid[slots] = False
        rg.step[slots] = -1
        rg.retry_us[slots] = 0
        rg.uid[slots] = 0
        if rg.value is not None:
            rg.value[slots] = 0
        else:
            rg.dlen[slots] = -1

    def _finish(self, slots: np.ndarray, now: float,
                emit: List[np.ndarray]) -> None:
        """Account + meta + spans for freshly-resolved slots, and queue
        them for this pump's per-connection emit."""
        rg = self.ring
        sts = rg.status[slots]
        self.adm.note_resolved_batch(rg.tenant[slots], sts)
        self._count("deadline", int((sts == wire.S_DEADLINE).sum()))
        lats = now - rg.t_admit[slots]
        self._resp_meta.extend(rg.tenant[slots], sts, lats)
        self.responses += int(slots.size)
        traced = np.nonzero(rg.trace[slots] != 0)[0]
        if traced.size:
            tr = self._op_tracer()
            if tr is not None:
                r1 = self._rt().step_idx
                for j in traced.tolist():
                    s = int(slots[j])
                    tr.span(
                        "fe_resolve", int(rg.trace[s]),
                        r0=int(rg.r_admit[s]), r1=r1,
                        dur_s=now - float(rg.t_admit[s]),
                        tenant=int(rg.tenant[s]),
                        op=wire._KIND_NAMES[int(rg.kind[s])],
                        key=int(rg.key[s]), status=int(rg.status[s]))
        emit.append(slots)

    def _rsp_batch(self, slots: np.ndarray) -> wire.RspBatch:
        rg = self.ring
        rb = wire.RspBatch(
            status=rg.status[slots], reason=rg.reason[slots],
            req_id=rg.client_rid[slots], found=rg.found[slots],
            has_uid=rg.has_uid[slots], step=rg.step[slots],
            retry_after_us=rg.retry_us[slots], uid=rg.uid[slots])
        if self.vbytes:
            # one ragged gather straight off the slot arena replaces the
            # per-row blob join (round-21): only S_OK rows with a
            # payload contribute extents, same as the old loop
            have = ((rg.status[slots] == wire.S_OK)
                    & (rg.dlen[slots] >= 0))
            vlen = np.where(have, rg.dlen[slots], -1)
            plen = np.maximum(vlen, 0)
            voff = np.concatenate(
                ([0], np.cumsum(plen)[:-1])) if slots.size \
                else np.zeros(0, np.int64)
            blob = _codec.ragged_gather(
                rg.heap.reshape(-1),
                slots.astype(np.int64) * self.vbytes, plen)
            rb.vlen, rb.voff, rb.blob = vlen, voff, blob.tobytes()
        else:
            rb.value = rg.value[slots]
        return rb

    # -- the pump ------------------------------------------------------------

    def pump(self) -> Dict[int, wire.RspBatch]:
        """One serving round, all columns: intake expiry -> issue (ONE
        store.submit_batch) -> store.step() -> harvest (column writes
        off BatchFutures) + completion-side deadlines -> one RspBatch
        per connection.  Returns {conn: RspBatch} for this round's
        resolutions."""
        now = self.clock()
        rg = self.ring
        emit: List[np.ndarray] = []
        expired_free: List[np.ndarray] = []
        # 1. intake expiry FIRST, over the whole queue (scalar rule: an
        # op stuck behind a full store still resolves S_DEADLINE on time)
        if self._intake_len:
            kept: List[np.ndarray] = []
            n_left = 0
            for slots in self._intake:
                late = now > rg.deadline[slots]
                if late.any():
                    ds = slots[late]
                    self._mark_deadline(ds)
                    self._finish(ds, now, emit)
                    expired_free.append(ds)
                    slots = slots[~late]
                if slots.size:
                    kept.append(slots)
                    n_left += int(slots.size)
            self._intake = kept
            self._intake_len = n_left
        # 2. issue: fill the store's free depth with the intake prefix,
        # one submit_batch for the whole round
        room = self._store_cap - self._store_inflight
        if room > 0 and self._intake_len:
            take: List[np.ndarray] = []
            while self._intake and room > 0:
                s = self._intake[0]
                if s.size <= room:
                    take.append(s)
                    self._intake.pop(0)
                    room -= int(s.size)
                else:
                    take.append(s[:room])
                    self._intake[0] = s[room:]
                    room = 0
            slots = (np.concatenate(take) if len(take) > 1 else take[0])
            self._intake_len -= int(slots.size)
            if self.vbytes:
                vals = [rg.get_data(s) for s in slots.tolist()]
            else:
                vals = rg.value[slots]
            bf = self.store.submit_batch(
                rg.kind[slots].astype(np.int32), rg.key[slots], vals)
            n = int(slots.size)
            self._open.append(dict(
                bf=bf, slots=slots,
                resolved=np.zeros(n, bool),
                harvested=np.zeros(n, bool),
                released=np.zeros(n, bool)))
            self._store_inflight += n
            traced = np.nonzero(rg.trace[slots] != 0)[0]
            if traced.size:
                tr = self._op_tracer()
                if tr is not None:
                    r1 = self._rt().step_idx
                    for j in traced.tolist():
                        s = int(slots[j])
                        tr.span(
                            "fe_queue", int(rg.trace[s]),
                            r0=int(rg.r_admit[s]), r1=r1,
                            dur_s=now - float(rg.t_admit[s]),
                            tenant=int(rg.tenant[s]),
                            op=wire._KIND_NAMES[int(rg.kind[s])],
                            key=int(rg.key[s]))
        # 3. one store round
        self.store.step()
        now = self.clock()
        # 4. harvest completions + completion-side deadline enforcement,
        # in issue order (deterministic)
        for ob in self._open:
            bf, slots = ob["bf"], ob["slots"]
            code = np.asarray(bf.code)
            done = code != 0
            newly_done = done & ~ob["harvested"]
            if newly_done.any():
                self._store_inflight -= int(newly_done.sum())
                ob["harvested"] |= newly_done
            res = done & ~ob["resolved"]
            if res.any():
                ds = slots[res]
                c = code[res]
                late = now > rg.deadline[ds]
                st = self._code_lut[c + 3]
                rg.status[ds] = np.where(late, wire.S_DEADLINE, st)
                maybe = (c == self._C_LOST) | (c == self._C_REJECTED)
                fnd = np.asarray(bf.found)[res] & ~maybe
                rg.found[ds] = np.where(late, False, fnd)
                rg.reason[ds] = wire.R_NONE
                rg.retry_us[ds] = 0
                rg.step[ds] = np.where(late, -1, np.asarray(bf.step)[res])
                hu = (((c == self._C_WRITE) | (c == self._C_RMW))
                      & ~late)
                rg.has_uid[ds] = hu
                rg.uid[ds] = np.where(hu[:, None],
                                      np.asarray(bf.uid)[res], 0)
                readable = (((c == self._C_READ) | (c == self._C_RMW))
                            & fnd & ~late)
                if rg.value is not None:
                    rg.value[ds] = np.where(readable[:, None],
                                            np.asarray(bf.value)[res], 0)
                else:
                    ridx = np.nonzero(res)[0]
                    for j, s, keep in zip(ridx.tolist(), ds.tolist(),
                                          readable.tolist()):
                        rg.set_data(s, bf.data[j] if keep else None)
                ob["resolved"] |= res
                self._finish(ds, now, emit)
            # completion-side deadline on rows the store still holds:
            # the RPC resolves NOW, the slot stays until the store
            # finishes the op (the scalar _abandoned semantics)
            pend = ~done & ~ob["resolved"]
            if pend.any():
                ds_all = slots[pend]
                late = now > rg.deadline[ds_all]
                if late.any():
                    ds = ds_all[late]
                    self._mark_deadline(ds)
                    idx = np.nonzero(pend)[0][late]
                    ob["resolved"][idx] = True
                    self._finish(ds, now, emit)
        self._update_level()
        rt = self._rt()
        if rt.obs is not None:
            reg = rt.obs.registry
            reg.series("intake_depth_series").append(
                rt.step_idx, self._intake_len)
            reg.series("shed_level_series").append(
                rt.step_idx, self.shed_level)
            reg.series("key_heat_max_series").append(
                rt.step_idx, max(self._round_key_ops.values(), default=0))
            reg.series("key_distinct_series").append(
                rt.step_idx, len(self._round_key_ops))
        self._round_key_ops.clear()
        # 5. emit: one response batch per connection, then recycle slots
        out: Dict[int, wire.RspBatch] = {}
        if emit:
            all_slots = np.concatenate(emit)
            conns = rg.conn[all_slots]
            for cid in np.unique(conns).tolist():
                out[cid] = self._rsp_batch(all_slots[conns == cid])
        for ds in expired_free:
            rg.release(ds)
        still: List[dict] = []
        for ob in self._open:
            freeable = ob["resolved"] & ob["harvested"] & ~ob["released"]
            if freeable.any():
                rg.release(ob["slots"][freeable])
                ob["released"] |= freeable
            if not ob["released"].all():
                still.append(ob)
        self._open = still
        return out

    def idle(self) -> bool:
        return not self._intake and not self._open

    def flush(self) -> Dict[int, wire.RspBatch]:
        """Force the store's deferred (pipelined) completions out and
        harvest them."""
        self.store.flush()
        self.store.rt.flush_pipeline()
        return self.pump()

    def drain(self, max_rounds: int = 10_000
              ) -> Tuple[bool, List[Dict[int, wire.RspBatch]]]:
        """Pump until every admitted op resolves; returns (drained,
        per-pump emit dicts) — drained responses stay observable."""
        emitted: List[Dict[int, wire.RspBatch]] = []
        for _ in range(max_rounds):
            if self.idle():
                self._update_level()
                return True, emitted
            emitted.append(self.pump())
        emitted.append(self.flush())
        return self.idle(), emitted

    # -- accounting ----------------------------------------------------------

    def latencies(self, statuses=(wire.S_OK, wire.S_RMW_ABORT,
                                  wire.S_DEADLINE, wire.S_REJECTED,
                                  wire.S_LOST)) -> List[float]:
        return self._resp_meta.latencies(statuses)

    def counters(self) -> dict:
        per = self.adm.counters()
        agg: Dict[str, int] = {}
        for row in per.values():
            for k, v in row.items():
                agg[k] = agg.get(k, 0) + v
        return dict(requests=self.requests, responses=self.responses,
                    shed_level=self.shed_level, queue=self._intake_len,
                    store_inflight=self._store_inflight,
                    ring_in_use=self.ring.in_use(),
                    tenants=per, fleet=False, totals=agg)


def _empty_rsp_batch(u: int, vbytes: int) -> wire.RspBatch:
    rb = wire.RspBatch(
        status=np.zeros(0, np.uint8), reason=np.zeros(0, np.uint8),
        req_id=np.zeros(0, np.uint32), found=np.zeros(0, bool),
        has_uid=np.zeros(0, bool), step=np.zeros(0, np.int32),
        retry_after_us=np.zeros(0, np.uint32),
        uid=np.zeros((0, 2), np.int32))
    if vbytes:
        rb.vlen = np.zeros(0, np.int64)
    else:
        rb.value = np.zeros((0, u), np.int32)
    return rb


def verify_columnar(fe: ColumnarFrontend) -> dict:
    """The serving envelope invariants, ring edition:

      1. response conservation — every batched request produced exactly
         one response row;
      2. admission accounting exactness per tenant, in-flight zero;
      3. the envelope is empty — intake, open store batches, and the
         completion ring all drained (every slot back on the free
         stack).
    """
    assert fe.requests == fe.responses, (
        f"response conservation broken: {fe.requests} requests but "
        f"{fe.responses} responses")
    for t, row in fe.adm.counters().items():
        assert row["inflight"] == 0, (
            f"tenant {t} still shows {row['inflight']} in flight")
        resolved = (row["completed"] + row["deadline"] + row["rejected"]
                    + row["lost"])
        assert row["admitted"] == resolved, (
            f"tenant {t} admission accounting broken: "
            f"admitted={row['admitted']} != resolved={resolved} ({row})")
    assert not fe._intake and not fe._open, (
        "columnar envelope not empty after drain")
    assert fe.ring.in_use() == 0, (
        f"completion ring leaked {fe.ring.in_use()} slots")
    agg = fe.counters()["totals"]
    return dict(requests=fe.requests, responses=fe.responses,
                admitted=agg.get("admitted", 0),
                completed=agg.get("completed", 0),
                deadline=agg.get("deadline", 0),
                retry_after=agg.get("retry_after", 0),
                shed=agg.get("shed", 0),
                rejected=agg.get("rejected", 0), lost=agg.get("lost", 0))


def verify_serving(fe: Frontend) -> dict:
    """Serving envelope invariants (run after a drained soak):

      1. response conservation — every request produced exactly ONE
         response (refusal or resolution; nothing silently buffered or
         dropped);
      2. admission accounting exactness — per tenant,
         admitted == completed + deadline + rejected + lost and the
         in-flight count is back to zero;
      3. the envelope is empty — intake queue, pending map, and
         abandoned list all drained.

    Raises AssertionError on the first violation; returns evidence.
    """
    assert fe.requests == fe.responses, (
        f"response conservation broken: {fe.requests} requests but "
        f"{fe.responses} responses")
    for t, row in fe.adm.counters().items():
        assert row["inflight"] == 0, (
            f"tenant {t} still shows {row['inflight']} in flight")
        resolved = (row["completed"] + row["deadline"] + row["rejected"]
                    + row["lost"])
        assert row["admitted"] == resolved, (
            f"tenant {t} admission accounting broken: "
            f"admitted={row['admitted']} != resolved={resolved} ({row})")
    assert not fe._intake and not fe._pending and not fe._abandoned, (
        "serving envelope not empty after drain")
    agg = fe.counters()["totals"]
    return dict(requests=fe.requests, responses=fe.responses,
                admitted=agg.get("admitted", 0),
                completed=agg.get("completed", 0),
                deadline=agg.get("deadline", 0),
                retry_after=agg.get("retry_after", 0),
                shed=agg.get("shed", 0),
                rejected=agg.get("rejected", 0), lost=agg.get("lost", 0))
