"""The serving front-end (round-14): the robustness envelope between
clients and the replicated store.

``Frontend`` owns one ``kvs.KVS`` (single group) or ``fleet.Fleet``
(key-routed groups — the fleet-aware serving front-end of ROADMAP item
2) and drives client RPCs through it:

  * ADMISSION (serving/admission.py): overload ladder -> per-tenant
    session quota -> bounded intake queue -> per-tenant token bucket
    (charged last — refusals never burn rate budget).
    Every refusal is a loud ``S_RETRY_AFTER`` with a reason and a retry
    hint — queue-full is an explicit wire signal, never silent
    buffering.
  * DEADLINES: the client's relative deadline is stamped absolute at
    intake; an op that expires in the intake queue resolves
    ``S_DEADLINE`` WITHOUT being injected, and an admitted op that
    out-ages its deadline resolves ``S_DEADLINE`` at the completion
    scan (for updates a deadline is a MAYBE — the broadcast may still
    commit, exactly the crash-'lost' semantics; the abandoned future is
    kept until the store resolves it so quota accounting stays exact).
  * SHED LADDER: rung transitions land on the obs timeline as
    ``shed``/``shed_clear`` events and per-tenant counters; rung 1
    composes with the store's ``min_healthy_for_writes`` degraded mode
    (degraded => writes shed at the front door).
  * WATCHDOG TAGS: the round-9 stuck-op diagnostics (and
    ``StuckOpError``) carry the op's tenant id and remaining deadline
    budget through ``kvs.diag_hook`` — the ``drill=``/``net_phase``
    pattern, per op.

The clock is caller-supplied: ``VirtualClock`` for deterministic soaks
(the driver advances it by ``scfg.round_us`` per pump — same-seed runs
replay byte-identically, the chaos-schedule discipline applied to
serving), ``time.monotonic`` under real sockets (serving/rpc.py).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Dict, List, Optional, Tuple

from hermes_tpu.serving import wire
from hermes_tpu.serving.admission import AdmissionControl


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Front-end envelope knobs (one frozen dataclass, config.py style)."""

    tenant_rate_per_s: float = 4000.0   # sustained per-tenant admission rate
    tenant_burst: float = 64.0          # token-bucket burst
    tenant_quota: int = 32              # client-visible in-flight cap/tenant
    queue_cap: int = 128                # bounded intake queue
    shed_write_frac: float = 0.6        # ladder rung 1 at this queue fill
    shed_read_frac: float = 0.9         # ladder rung 2 at this queue fill
    hot_keys: Tuple[int, ...] = ()      # reads on these survive rung 2
    default_deadline_us: int = 0        # applied when a request carries 0
    round_us: int = 1000                # virtual microseconds per pump
    retry_after_floor_s: float = 0.001  # minimum retry hint
    store_inflight_cap: Optional[int] = None  # ops handed to the store at
    # once (None = one per store session lane); the intake queue holds the
    # rest — THAT bound is what makes backpressure observable
    resp_meta_cap: int = 1 << 17  # per-response (tenant, status, latency)
    # retention ring: exact for the finite soak/bench drivers (which size
    # well under it), bounded for a long-lived TCP server — the always-on
    # exact accounting is AdmissionControl's counters, not this ring
    trace_sample: int = 0  # per-op tracing (round-18, obs/tracing.py):
    # 0 = off, N = mint a trace id for ~1 in N submitted ops (seeded,
    # deterministic — same ops trace on every replay).  A request already
    # carrying a nonzero wire trace id is ALWAYS traced (the client
    # sampled it); the id rides the formerly-pad u16 of wire._REQ.
    trace_seed: int = 0

    def __post_init__(self) -> None:
        if self.tenant_quota < 1 or self.queue_cap < 1:
            raise ValueError("tenant_quota and queue_cap must be >= 1")
        if self.trace_sample < 0:
            raise ValueError("trace_sample must be >= 0 (0 disables)")
        if not (0.0 < self.shed_write_frac <= self.shed_read_frac <= 1.0):
            raise ValueError(
                "want 0 < shed_write_frac <= shed_read_frac <= 1 (writes "
                "shed first, then non-hot reads)")
        if self.round_us <= 0:
            raise ValueError("round_us must be > 0")
        if self.resp_meta_cap < 1:
            raise ValueError("resp_meta_cap must be >= 1")
        object.__setattr__(self, "hot_key_set", frozenset(
            int(k) for k in self.hot_keys))


class VirtualClock:
    """Deterministic serving clock: the soak driver advances it."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, ds: float) -> None:
        self.t += ds


class _ReadFuture:
    """Future-shaped adapter over a MultiGetResult/FleetReads: done when
    every key answered (locally or via the round-path fallback the pump's
    store.step() drives)."""

    def __init__(self, res):
        self.res = res

    def done(self) -> bool:
        return self.res.all_done()


class Frontend:
    """One serving front-end over a KVS or Fleet facade."""

    def __init__(self, store, scfg: Optional[ServingConfig] = None,
                 clock=None):
        self.store = store
        self.scfg = scfg or ServingConfig()
        self.is_fleet = hasattr(store, "router") and hasattr(store, "groups")
        base = store.cfg.base if self.is_fleet else store.cfg
        self.u = base.value_words - 2
        # value heap (round-17): > 0 switches the wire to length-prefixed
        # byte payloads (both ends derive it from the shared config, like
        # ``u``) and the issue path to store byte puts
        self.vbytes = base.max_value_bytes
        if self.u < 1:
            raise ValueError("serving needs value_words >= 3 (the store "
                             "carries write uids in words 0-1)")
        self.n_keys = (store.cfg.total_keys if self.is_fleet
                       else base.n_keys)
        self.clock = clock if clock is not None else time.monotonic
        self.adm = AdmissionControl(self.scfg)
        self._intake: collections.deque = collections.deque()
        self._pending: Dict[int, dict] = {}   # req_id -> entry (admit order)
        self._abandoned: List[dict] = []      # RPC resolved, store op open
        self._responses: List[wire.Response] = []
        self._resp_meta: collections.deque = collections.deque(
            maxlen=self.scfg.resp_meta_cap)   # (tenant, status, latency_s)
        self._lane_seq: Dict[int, int] = collections.defaultdict(int)
        self.requests = 0
        self.responses = 0
        self.shed_level = 0
        self._fleet_deg: Optional[bool] = None  # any-group scan, per round
        self._lanes: List[tuple] = []
        if self.is_fleet:
            cap = sum(g.cfg.n_replicas * g.cfg.n_sessions
                      for g in store.groups)
            for g in store.groups:
                g.kvs.diag_hook = (
                    lambda r, s, _g=g.gid: self._diag_for(_g, r, s))
        else:
            cfg = store.cfg
            cap = cfg.n_replicas * cfg.n_sessions
            self._lanes = [(r, s) for r in range(cfg.n_replicas)
                           for s in range(cfg.n_sessions)]
            store.diag_hook = lambda r, s: self._diag_for(None, r, s)
        self._store_cap = (self.scfg.store_inflight_cap
                           if self.scfg.store_inflight_cap is not None
                           else cap)
        self._store_inflight = 0
        # per-op tracing (round-18): front-door sampler + span writer.
        # Single-op requests only — the batched-read header has no free
        # u16 (count occupies it), so K_MGET/K_SCAN stay untraced.
        if self.scfg.trace_sample:
            from hermes_tpu.obs.tracing import TraceSampler

            self._sampler = TraceSampler(self.scfg.trace_sample,
                                         seed=self.scfg.trace_seed)
        else:
            self._sampler = None
        self._op_tracer_cache = None
        self._round_key_ops: dict = {}  # key -> admitted ops this round

    # -- plumbing ------------------------------------------------------------

    def _rt(self):
        return (self.store.groups[0].rt if self.is_fleet else self.store.rt)

    def _trace(self, name: str, **fields) -> None:
        rt = self._rt()
        rt._trace(name, **fields)
        if rt.obs is not None:
            rt.obs.registry.counter(f"serving_{name}").inc()

    def _count(self, name: str, n: int = 1) -> None:
        rt = self._rt()
        if rt.obs is not None:
            rt.obs.registry.counter(f"serving_{name}").inc(n)

    def _op_tracer(self):
        """Span writer bound to the store runtime's current obs context
        (None while none is attached)."""
        rt = self._rt()
        if rt.obs is None:
            return None
        c = self._op_tracer_cache
        if c is None or c.obs is not rt.obs:
            from hermes_tpu.obs.tracing import OpTracer

            c = self._op_tracer_cache = OpTracer(rt.obs)
        return c

    def _trace_resolve(self, entry: dict, status: int, now: float) -> None:
        """Close a sampled op's end-to-end span at RPC resolution:
        admission round -> resolution round, with the terminal status
        (the critical-path denominator obs/report.py breaks down)."""
        trace = entry.get("trace", 0)
        if not trace:
            return
        tr = self._op_tracer()
        if tr is None:
            return
        req = entry["req"]
        tags = dict(tenant=req.tenant, op=req.kind, key=req.key,
                    status=int(status))
        lane = entry.get("lane")
        if lane is not None and lane[0] is not None:
            tags["group"] = lane[0]
        tr.span("fe_resolve", trace, r0=entry["r_admit"],
                r1=self._rt().step_idx,
                dur_s=now - entry["t_admit"], **tags)

    def _degraded_for_key(self, key: int) -> bool:
        if self.is_fleet:
            return self.store.degraded(key)
        return self.store.degraded()

    def _diag_for(self, group, r, s) -> Optional[dict]:
        """Watchdog tag lookup: the oldest un-resolved op on lane
        (group, r, s) names its tenant + remaining deadline budget.
        Abandoned entries (RPC already resolved S_DEADLINE, store op
        still open) are scanned too — a long-stuck op has usually
        out-aged its deadline by the time the watchdog fires."""
        now = self.clock()
        for entry in list(self._pending.values()) + self._abandoned:
            if entry.get("lane") == (group, r, s):
                d = dict(tenant=entry["req"].tenant)
                if entry["deadline"] is not None:
                    d["deadline_left_us"] = int(
                        round((entry["deadline"] - now) * 1e6))
                return d
        return None

    def _update_level(self, degraded: Optional[bool] = None,
                      fresh: bool = True) -> None:
        # non-fleet degradation is key-independent, so submit can hand us
        # the value it already computed; fleet ladder pressure is the
        # any-group scan regardless of the op's key — and that scan can
        # only change when membership does (once per store round), so the
        # per-request path (fresh=False) reuses the last pump's scan
        # instead of walking every group's healthy set per request
        if self.is_fleet:
            if fresh or self._fleet_deg is None:
                self._fleet_deg = any(g.kvs.degraded()
                                      for g in self.store.groups)
            degraded = self._fleet_deg
        elif degraded is None:
            degraded = self._degraded_for_key(0)
        level = self.adm.ladder_level(len(self._intake), degraded)
        if level != self.shed_level:
            if level > 0:
                self._trace("shed", level=level, queue=len(self._intake))
            else:
                self._trace("shed_clear", queue=len(self._intake))
            self.shed_level = level

    def _respond(self, rsp: wire.Response, tenant: int,
                 latency_s: Optional[float] = None,
                 queue: bool = True) -> wire.Response:
        # queue=False: an immediate refusal submit() hands straight back
        # to its caller — accounted here, but NOT queued for pump(), or
        # the transport would deliver it a second time (and on the TCP
        # path the re-send would carry the restored CLIENT req_id, which
        # can collide with another connection's pending internal id)
        if queue:
            self._responses.append(rsp)
        self._resp_meta.append((tenant, rsp.status, latency_s))
        self.responses += 1
        return rsp

    def pop_responses(self) -> List[wire.Response]:
        out, self._responses = self._responses, []
        return out

    # -- intake --------------------------------------------------------------

    def submit(self, req) -> Optional[object]:
        """Run one request through admission.  Returns an immediate
        refusal Response, or None when admitted (the resolution arrives
        from a later ``pump``).  Accepts the single-op ``wire.Request``
        and the round-16 batched ``wire.ReadRequest`` (K_MGET/K_SCAN)."""
        if isinstance(req, wire.ReadRequest):
            return self._submit_read(req)
        now = self.clock()
        self.requests += 1
        if req.kind not in ("get", "put", "rmw") \
                or not (0 <= req.key < self.n_keys):
            return self._respond(wire.Response(
                status=wire.S_REJECTED, req_id=req.req_id), req.tenant,
                queue=False)
        if self.vbytes and req.kind != "get" and (
                req.data is None or len(req.data) > self.vbytes):
            # heap mode: an update must carry a byte payload the store
            # can hold — refused loudly at the door, never a deep error
            return self._respond(wire.Response(
                status=wire.S_REJECTED, req_id=req.req_id), req.tenant,
                queue=False)
        degraded = self._degraded_for_key(req.key)
        self._update_level(degraded, fresh=False)
        reason, wait = self.adm.admit(req.kind, req.key, req.tenant, now,
                                      len(self._intake), degraded)
        if reason != wire.R_NONE:
            self._count("retry_after")
            return self._respond(wire.Response(
                status=wire.S_RETRY_AFTER, req_id=req.req_id, reason=reason,
                retry_after_us=int(math.ceil(wait * 1e6))), req.tenant,
                queue=False)
        self.adm.note_admitted(req.tenant)
        # key-heat tally (round-18, obs/series.py): admitted ops per key
        # this serving round, harvested into the heat series at pump time
        self._round_key_ops[req.key] = \
            self._round_key_ops.get(req.key, 0) + 1
        # trace mint (round-18): adopt a client-sampled wire id, else
        # sample on the monotone request sequence; the id follows the
        # entry through issue and resolution (and is staged into the
        # store so the KVS-level spans share it)
        trace = int(getattr(req, "trace", 0) or 0)
        if not trace and self._sampler is not None:
            trace = self._sampler.sample(self.requests - 1)
        dl_us = req.deadline_us or self.scfg.default_deadline_us
        self._intake.append(dict(
            req=req, t_admit=now, trace=trace,
            r_admit=self._rt().step_idx,
            deadline=(now + dl_us * 1e-6) if dl_us else None))
        return None

    def _read_probe_key(self, req: wire.ReadRequest) -> int:
        """The key the admission ladder judges a batched read by: its
        first NON-hot key, so rung 2 sheds the batch unless EVERY key is
        hot — reads shed at rung 2 exactly as today, and a batch cannot
        smuggle cold keys past the ladder behind one hot one.  A scan
        range wider than the hot set provably CONTAINS a cold key
        (len(hot)+1 distinct keys cannot all be hot), so probing that
        many from lo always finds one — never judge a range by its
        endpoints, which may both be hot over a cold interior."""
        hot = self.scfg.hot_key_set
        if req.kind == "mget":
            keys = req.keys
        else:
            keys = range(req.lo, min(req.hi, req.lo + len(hot) + 1))
        for k in keys:
            if k not in hot:
                return int(k)
        return int(next(iter(keys)))

    def _submit_read(self, req: wire.ReadRequest):
        """Admission for one batched read RPC (ONE admission unit: one
        quota slot, one queue entry, one rate token — the batch is one
        client-visible op)."""
        now = self.clock()
        self.requests += 1
        bad = (req.kind not in ("mget", "scan")
               or (req.kind == "mget" and not (
                   req.keys and len(req.keys) <= wire.MGET_MAX_KEYS
                   and all(0 <= k < self.n_keys for k in req.keys)))
               or (req.kind == "scan"
                   and not (0 <= req.lo < req.hi <= self.n_keys)))
        if bad:
            return self._respond(wire.ReadResponse(
                status=wire.S_REJECTED, req_id=req.req_id), req.tenant,
                queue=False)
        self._update_level(None, fresh=False)
        # degraded mode never sheds reads (rung 1 is write-only), so the
        # ladder decision for a read depends on queue pressure alone —
        # and the probe key only matters at rung 2, so the O(batch) cold
        # hunt is skipped entirely while the queue is below that mark
        probe = (self._read_probe_key(req)
                 if self.adm.ladder_level(len(self._intake), False) >= 2
                 else (req.keys[0] if req.kind == "mget" else req.lo))
        reason, wait = self.adm.admit(
            "get", probe, req.tenant, now, len(self._intake), False)
        if reason != wire.R_NONE:
            self._count("retry_after")
            return self._respond(wire.ReadResponse(
                status=wire.S_RETRY_AFTER, req_id=req.req_id, reason=reason,
                retry_after_us=int(math.ceil(wait * 1e6))), req.tenant,
                queue=False)
        self.adm.note_admitted(req.tenant)
        dl_us = req.deadline_us or self.scfg.default_deadline_us
        self._intake.append(dict(
            req=req, t_admit=now,
            deadline=(now + dl_us * 1e-6) if dl_us else None))
        return None

    # -- the pump ------------------------------------------------------------

    def _issue(self, entry: dict) -> None:
        """Hand one admitted op to the store on a deterministic lane."""
        req = entry["req"]
        seq = self._lane_seq[req.tenant]
        self._lane_seq[req.tenant] = seq + 1
        if isinstance(req, wire.ReadRequest):
            # batched read (round-16): issued straight to the store's
            # local-read fast path; only Invalid keys ride round-path
            # fallback slots, which the pump's store.step() drives.
            # Read-your-writes is TENANT-scoped here: the frontend pins a
            # per-tenant fence token on every commit it delivers
            # (_result_response -> store.pin_read_fence), and the read
            # carries the same token — lane rotation on the write path
            # cannot defeat it.
            args = dict(session=("tenant", req.tenant), wait=False)
            res = (self.store.multi_get(req.keys, **args)
                   if req.kind == "mget"
                   else self.store.scan(req.lo, req.hi, **args))
            entry["fut"] = _ReadFuture(res)
            self._pending[req.req_id] = entry
            self._store_inflight += 1
            return
        value = None
        if req.kind != "get":
            # heap mode stores the request's byte payload verbatim (the
            # KVS appends the extent and rounds only the packed ref)
            value = bytes(req.data) if self.vbytes else req.value
        trace = entry.get("trace", 0)
        if self.is_fleet:
            session = req.tenant * 7919 + seq
            fut, lane = self.store.route_op(req.kind, session, req.key,
                                            value)
            entry["lane"] = lane
        else:
            r, s = self._lanes[(req.tenant * 7919 + seq) % len(self._lanes)]
            entry["lane"] = (None, r, s)
            if trace:
                # hand the minted id to the KVS so its op_queue/op_rounds
                # spans carry the SAME trace (consumed by the next
                # _enqueue; the fleet path keeps frontend spans only —
                # route_op picks the group internally)
                self.store._staged_trace = trace
            fut = getattr(self.store, req.kind)(r, s, req.key, *(
                (value,) if value is not None else ()))
        entry["fut"] = fut
        self._pending[req.req_id] = entry
        self._store_inflight += 1
        if trace:
            tr = self._op_tracer()
            if tr is not None:
                # intake-queue wait: admission round -> store-issue round
                tags = dict(tenant=req.tenant, op=req.kind, key=req.key)
                lane = entry.get("lane")
                if lane is not None and lane[0] is not None:
                    tags["group"] = lane[0]
                tr.span("fe_queue", trace, r0=entry["r_admit"],
                        r1=self._rt().step_idx,
                        dur_s=self.clock() - entry["t_admit"], **tags)

    _STATUS = {"get": wire.S_OK, "put": wire.S_OK, "rmw": wire.S_OK,
               "rmw_abort": wire.S_RMW_ABORT, "lost": wire.S_LOST,
               "rejected": wire.S_REJECTED}

    def _deadline_rsp(self, req):
        """The S_DEADLINE refusal in the request's own response layout."""
        if isinstance(req, wire.ReadRequest):
            return wire.ReadResponse(status=wire.S_DEADLINE,
                                     req_id=req.req_id)
        return wire.Response(status=wire.S_DEADLINE, req_id=req.req_id,
                             found=False)

    def _result_response(self, entry: dict):
        req = entry["req"]
        if isinstance(req, wire.ReadRequest):
            import numpy as np

            from hermes_tpu.kvs import C_REJECTED
            from hermes_tpu.core import types as t

            res = entry["fut"].res
            res._pull()
            served = res.code == t.C_READ
            rrsp = wire.ReadResponse(
                status=wire.S_OK, req_id=req.req_id,
                step=int(res.step.max()) if len(res) else -1,
                found=(np.asarray(res.found) & served).tolist(),
                local=np.asarray(res.local).tolist(),
                codes=np.where(res.code == C_REJECTED, wire.RK_REJECTED,
                               wire.RK_OK).tolist(),
                values=np.asarray(res.value).tolist())
            if self.vbytes:
                rrsp.data = list(res.data)
            return rrsp
        c = entry["fut"].result()
        rsp = wire.Response(status=self._STATUS[c.kind], req_id=req.req_id,
                            found=c.found, step=c.step)
        if c.value is not None:
            rsp.value = c.value
            rsp.data = c.data
        if c.uid is not None:
            rsp.uid = c.uid
            if c.ts is not None:
                # the tenant just SAW this write commit: pin its fence
                # token so the tenant's later K_MGET/K_SCAN reads must
                # observe this timestamp or take the round path (RYW
                # through the serving front-end, per tenant)
                self.store.pin_read_fence(("tenant", req.tenant),
                                          req.key, c.ts)
        return rsp

    def pump(self) -> List[wire.Response]:
        """One serving round: issue from the intake queue (deadline-
        checked), run one store round, harvest completions and expired
        deadlines.  Returns the responses produced this round."""
        now = self.clock()
        # intake expiry FIRST, over the whole queue — an op stuck behind a
        # full store must still resolve S_DEADLINE on time, not wait for
        # its pop turn
        if self._intake:
            keep = collections.deque()
            for entry in self._intake:
                req = entry["req"]
                if entry["deadline"] is not None and now > entry["deadline"]:
                    self.adm.note_resolved(req.tenant, wire.S_DEADLINE)
                    self._count("deadline")
                    self._respond(self._deadline_rsp(req), req.tenant,
                                  now - entry["t_admit"])
                    self._trace_resolve(entry, wire.S_DEADLINE, now)
                else:
                    keep.append(entry)
            self._intake = keep
        # intake -> store (expired ops were resolved above, never injected)
        while self._intake and self._store_inflight < self._store_cap:
            self._issue(self._intake.popleft())
        self.store.step()
        now = self.clock()
        # harvest completions + completion-side deadline enforcement
        done_ids = []
        for rid, entry in self._pending.items():
            fut = entry["fut"]
            late = (entry["deadline"] is not None
                    and now > entry["deadline"])
            if fut.done():
                rsp = (self._deadline_rsp(entry["req"]) if late
                       else self._result_response(entry))
                if late:
                    self._count("deadline")
                self.adm.note_resolved(entry["req"].tenant, rsp.status)
                self._respond(rsp, entry["req"].tenant,
                              now - entry["t_admit"])
                self._trace_resolve(entry, rsp.status, now)
                self._store_inflight -= 1
                done_ids.append(rid)
            elif late:
                # the RPC resolves NOW; the store op stays abandoned until
                # the protocol finishes it (quota freed, lane not yet)
                self.adm.note_resolved(entry["req"].tenant, wire.S_DEADLINE)
                self._count("deadline")
                self._respond(self._deadline_rsp(entry["req"]),
                              entry["req"].tenant, now - entry["t_admit"])
                self._trace_resolve(entry, wire.S_DEADLINE, now)
                self._abandoned.append(entry)
                done_ids.append(rid)
        for rid in done_ids:
            del self._pending[rid]
        still = []
        for entry in self._abandoned:
            if entry["fut"].done():
                self._store_inflight -= 1
            else:
                still.append(entry)
        self._abandoned = still
        self._update_level()
        rt = self._rt()
        if rt.obs is not None:
            # ladder history (round-18, obs/series.py): intake depth and
            # shed rung per serving round, keyed by the store's round
            # index — the backpressure trend a controller steers on
            reg = rt.obs.registry
            reg.series("intake_depth_series").append(
                rt.step_idx, len(self._intake))
            reg.series("shed_level_series").append(
                rt.step_idx, self.shed_level)
            # per-range key heat (ROADMAP item 6's controller input):
            # the round's hottest single key's op count and its distinct
            # key spread — the skew trend shed rung 2 would steer on
            reg.series("key_heat_max_series").append(
                rt.step_idx, max(self._round_key_ops.values(), default=0))
            reg.series("key_distinct_series").append(
                rt.step_idx, len(self._round_key_ops))
        self._round_key_ops.clear()
        return self.pop_responses()

    def flush(self) -> List[wire.Response]:
        """Force the store's deferred (pipelined) completions out and
        harvest them."""
        if self.is_fleet:
            self.store.flush()
        else:
            self.store.flush()
            self.store.rt.flush_pipeline()
        return self.pump()

    def drain(self, max_rounds: int = 10_000) -> bool:
        """Pump until every admitted op (including abandoned deadline
        maybes) resolves.  True when fully drained.  The responses
        produced while draining stay queued for ``pop_responses`` — a
        drained op resolved loudly, so its Response must remain
        observable, not vanish into the drain loop."""
        kept: List[wire.Response] = []
        done = False
        for _ in range(max_rounds):
            if not (self._intake or self._pending or self._abandoned):
                # a drained envelope is the ladder's floor: re-evaluate so
                # a pressure-driven rung emits its shed_clear even when no
                # further request arrives to observe it
                self._update_level()
                done = True
                break
            kept.extend(self.pump())
        if not done:
            kept.extend(self.flush())
        self._responses = kept + self._responses
        return not (self._intake or self._pending or self._abandoned)

    # -- accounting ----------------------------------------------------------

    def latencies(self, statuses=(wire.S_OK, wire.S_RMW_ABORT,
                                  wire.S_DEADLINE, wire.S_REJECTED,
                                  wire.S_LOST)) -> List[float]:
        """Admission-to-resolution latency (serving clock, seconds) of
        every ADMITTED op whose terminal status is in ``statuses``."""
        return [lat for _t, st, lat in self._resp_meta
                if st in statuses and lat is not None]

    def counters(self) -> dict:
        per = self.adm.counters()
        agg: Dict[str, int] = {}
        for row in per.values():
            for k, v in row.items():
                agg[k] = agg.get(k, 0) + v
        return dict(requests=self.requests, responses=self.responses,
                    shed_level=self.shed_level, queue=len(self._intake),
                    store_inflight=self._store_inflight,
                    tenants=per, fleet=self.is_fleet, totals=agg)


def verify_serving(fe: Frontend) -> dict:
    """Serving envelope invariants (run after a drained soak):

      1. response conservation — every request produced exactly ONE
         response (refusal or resolution; nothing silently buffered or
         dropped);
      2. admission accounting exactness — per tenant,
         admitted == completed + deadline + rejected + lost and the
         in-flight count is back to zero;
      3. the envelope is empty — intake queue, pending map, and
         abandoned list all drained.

    Raises AssertionError on the first violation; returns evidence.
    """
    assert fe.requests == fe.responses, (
        f"response conservation broken: {fe.requests} requests but "
        f"{fe.responses} responses")
    for t, row in fe.adm.counters().items():
        assert row["inflight"] == 0, (
            f"tenant {t} still shows {row['inflight']} in flight")
        resolved = (row["completed"] + row["deadline"] + row["rejected"]
                    + row["lost"])
        assert row["admitted"] == resolved, (
            f"tenant {t} admission accounting broken: "
            f"admitted={row['admitted']} != resolved={resolved} ({row})")
    assert not fe._intake and not fe._pending and not fe._abandoned, (
        "serving envelope not empty after drain")
    agg = fe.counters()["totals"]
    return dict(requests=fe.requests, responses=fe.responses,
                admitted=agg.get("admitted", 0),
                completed=agg.get("completed", 0),
                deadline=agg.get("deadline", 0),
                retry_after=agg.get("retry_after", 0),
                shed=agg.get("shed", 0),
                rejected=agg.get("rejected", 0), lost=agg.get("lost", 0))
