"""Shared-memory columnar IPC plane (round-21): N front-end worker
PROCESSES feeding ONE device-owning store process.

Round-19's accept sharding scaled the socket path by giving every
worker its own store — N private KVS instances, N device programs.
This round keeps the worker processes (own GIL, own accept queue, own
socket syscalls) but funnels every request into a SINGLE
``ColumnarFrontend`` owned by one process, over the
``transport.shm.SpscColumnRing`` pairs — so the device round stays ONE
program at full lane occupancy while the Python-side socket work scales
out across processes.

Topology (``OneStoreServer``)::

    client --tcp--> ShmWorker 0 --req ring 0--\
    client --tcp--> ShmWorker 1 --req ring 1---> StoreOwner -> ONE
        ...                                       ColumnarFrontend
    client <--tcp-- ShmWorker w <--rsp ring w--/  (merge + pump +
                                                   scatter per round)

Zero-copy discipline: a worker's reader thread validates an inbound
frame with ``wire.check_request_matrix`` and copies the raw record
matrix STRAIGHT into request-ring slot columns (one vectorized
assignment — the frame bytes are never re-encoded, re-framed, or
pickled).  The owner concatenates the ready slot views (the one
mandatory copy out of shared memory), decodes the merged matrix ONCE
with ``wire.decode_request_matrix``, and runs ONE ``submit_batch`` +
``pump`` for the whole fleet per round.  Resolutions scatter back as
decoded response columns; the worker encodes one wire batch per
connection per slot.

Connection identity across the boundary: worker-local connection ids
pack into the frontend's int32 ``conn`` column as
``(worker_id << CONN_BITS) | local_cid`` — the owner's pump emissions
arrive already grouped per packed id, and ``conn_worker``/``conn_local``
split them back.

Backpressure (the never-drop / never-silently-block rule):

  * request ring full past the worker's deadline -> the worker refuses
    the overflow rows ON THE WIRE (S_RETRY_AFTER / R_QUEUE_FULL, retry
    hint attached) — loud, bounded, no drops;
  * response ring full past the owner's deadline -> ``ShmBackpressure``
    propagates out of the owner pump (a live worker that stopped
    draining is a deployment fault, not a steady state);
  * dead worker (crashed process) -> the owner stops consuming its
    request ring (a torn slot is its tombstone), keeps pumping the
    store (admission conservation holds — every admitted op still
    resolves), and counts the undeliverable response rows LOUDLY
    (``ipc_dead_drop_rows``); its clients see EOF from the broken
    socket, and MAYBE-committed writes surface through the store's
    normal S_LOST/S_DEADLINE contract.

``run_shm_soak`` is the deterministic witness: real rings, simulated
workers, a VirtualClock, worker-id-order merge — same seed + config =>
byte-identical per-worker response logs (scripts/check_serving.py
replays it twice and compares digests).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from hermes_tpu.concurrency import make_lock
from hermes_tpu.serving import wire
from hermes_tpu.transport.shm import (RingSpec, ShmBackpressure,
                                      SpscColumnRing)

#: Worker-local connection ids occupy the low CONN_BITS of the packed
#: int32 ``conn`` column; the worker id rides above them.  22 bits of
#: local ids x up to 512 workers fits int32 with the sign bit clear.
CONN_BITS = 22
CONN_MASK = (1 << CONN_BITS) - 1
MAX_WORKERS = 1 << (31 - CONN_BITS)


def pack_conn(worker_id: int, local_cid: int) -> int:
    return (worker_id << CONN_BITS) | local_cid


def conn_worker(conn: int) -> int:
    return conn >> CONN_BITS


def conn_local(conn: int) -> int:
    return conn & CONN_MASK


def req_ring_fields(u: int) -> Tuple:
    """Request-ring slot columns: the RAW wire record matrix (rows ARE
    the columnar request records — decode happens once, owner-side)
    plus the worker-local connection id per row."""
    return (("conn", "<i4", 0), ("raw", "u1", wire.req_nbytes(u)))


def rsp_ring_fields(u: int) -> Tuple:
    """Response-ring slot columns: DECODED response columns (the owner
    already has them as arrays off the completion ring; the worker
    encodes wire bytes per connection at the socket edge)."""
    return (("conn", "<i4", 0), ("req_id", "<u4", 0),
            ("status", "u1", 0), ("reason", "u1", 0),
            ("found", "u1", 0), ("has_uid", "u1", 0),
            ("step", "<i4", 0), ("retry_after_us", "<u4", 0),
            ("uid", "<i4", 2), ("value", "<i4", u))


def create_ring_pair(u: int, nslots: int, slot_rows: int,
                     worker_id: int) -> Tuple[SpscColumnRing,
                                              SpscColumnRing]:
    """One worker's (request, response) ring pair, creator side."""
    req = SpscColumnRing.create(nslots, slot_rows, req_ring_fields(u),
                                name_hint=f"hermes_req{worker_id}")
    rsp = SpscColumnRing.create(nslots, slot_rows, rsp_ring_fields(u),
                                name_hint=f"hermes_rsp{worker_id}")
    return req, rsp


# -- the worker process edge --------------------------------------------------


class ShmWorker:
    """One front-end worker: TCP accept + frame decode on its own GIL,
    requests forwarded through its request ring, responses drained from
    its response ring.  Thread shape mirrors ``ColumnarTcpServer`` (one
    accept thread, one reader per connection, one response-drain thread
    in place of the pump); the reader threads serialize on
    ``_ring_lock`` so the request ring sees ONE producer."""

    def __init__(self, worker_id: int, req_ring: SpscColumnRing,
                 rsp_ring: SpscColumnRing, u: int,
                 host: str = "127.0.0.1", port: int = 0,
                 reuseport: bool = False,
                 push_timeout_s: float = 2.0,
                 retry_after_us: int = 2000):
        from hermes_tpu.transport.tcp import FramedSocket, serving_listener

        self.worker_id = worker_id
        self.req_ring = req_ring
        self.rsp_ring = rsp_ring
        self.u = u
        self.stride = wire.req_nbytes(u)
        self.push_timeout_s = push_timeout_s
        self.retry_after_us = retry_after_us
        self._FramedSocket = FramedSocket
        # make_lock: ObsLock under HERMES_LOCKLINT=1, plain Lock otherwise.
        # _ring_lock serializes the reader threads on the request ring
        # (collectively one producer); _map_lock guards conn bookkeeping.
        self._ring_lock = make_lock("ShmWorker._ring_lock")
        self._map_lock = make_lock("ShmWorker._map_lock")
        self._next_cid = 1
        self._sock_of: Dict[int, object] = {}
        self.undecodable = 0     # CRC-valid frames that fail record triage
        self.backpressured = 0   # rows refused S_RETRY_AFTER on a full ring
        self.rows_in = 0         # rows committed into the request ring
        self.rows_out = 0        # rows drained from the response ring
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List = []
        self._listener = serving_listener(host, port, reuseport=reuseport)
        self.addr = self._listener.getsockname()
        accept_t = threading.Thread(target=self._accept_loop, daemon=True)
        self._rsp_t = threading.Thread(target=self._rsp_loop, daemon=True)
        # registered before starting either (see ColumnarTcpServer)
        self._threads.extend((accept_t, self._rsp_t))
        accept_t.start()
        self._rsp_t.start()

    # -- accept / read -------------------------------------------------------

    def _accept_loop(self) -> None:
        import socket as _socket
        import struct as _struct

        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                sock, _peer = self._listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                return
            # bound sends only — a non-reading client must stall only
            # its own stream (the ColumnarTcpServer rationale)
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDTIMEO,
                            _struct.pack("ll", 1, 0))
            fsock = self._FramedSocket(sock)
            with self._map_lock:
                cid, self._next_cid = self._next_cid, self._next_cid + 1
                if cid > CONN_MASK:
                    fsock.close()
                    raise RuntimeError(
                        f"worker {self.worker_id} exhausted its "
                        f"{CONN_MASK} connection ids")
                self._sock_of[cid] = fsock
                self._conns.append(fsock)
            t = threading.Thread(target=self._reader_loop,
                                 args=(fsock, cid), daemon=True)
            with self._map_lock:
                self._threads = [th for th in self._threads
                                 if th.is_alive()]
                self._threads.append(t)
            t.start()

    def _reader_loop(self, fsock, cid: int) -> None:
        try:
            self._reader_body(fsock, cid)
        finally:
            fsock.close()
            with self._map_lock:
                self._sock_of.pop(cid, None)
                try:
                    self._conns.remove(fsock)
                except ValueError:
                    pass

    def _reader_body(self, fsock, cid: int) -> None:
        import select

        while not self._stop.is_set():
            try:
                raw = fsock.recv()
            except Exception:
                return
            if raw is None:
                return
            raws = [raw]
            while select.select([fsock.sock], [], [], 0)[0]:
                try:
                    more = fsock.recv()
                except Exception:
                    more = None
                if more is None:
                    break
                raws.append(more)
            for raw in raws:
                if len(raw) == 0 or len(raw) % self.stride:
                    # torn record stream: no per-row identity to refuse
                    # on — tear the stream down LOUDLY (client sees EOF
                    # now, not a timeout later)
                    with self._map_lock:
                        self.undecodable += 1
                    return
                M = np.frombuffer(raw, np.uint8).reshape(-1, self.stride)
                try:
                    wire.check_request_matrix(M)
                except ValueError:
                    with self._map_lock:
                        self.undecodable += 1
                    return
                if not self._push(M, cid, fsock):
                    return

    def _push(self, M: np.ndarray, cid: int, fsock) -> bool:
        """Forward validated raw records into the request ring: claim a
        slot under ``_ring_lock``, ONE vectorized copy of up to
        slot_rows records, commit.  A ring full past the deadline
        refuses the REMAINING rows on the wire (never drops, never
        blocks unbounded).  Returns False only if the worker stopped."""
        rows = self.req_ring.spec.slot_rows
        k = M.shape[0]
        done = 0
        deadline = time.monotonic() + self.push_timeout_s
        while done < k:
            if self._stop.is_set():
                return False
            claimed = 0
            with self._ring_lock:
                slot = self.req_ring.try_claim()
                if slot is not None:
                    n = min(k - done, rows)
                    slot.cols["raw"][:n] = M[done: done + n]
                    slot.cols["conn"][:n] = cid
                    self.req_ring.commit(n)
                    self.rows_in += n
                    claimed = n
            if claimed:
                done += claimed
                deadline = time.monotonic() + self.push_timeout_s
                continue
            if time.monotonic() >= deadline:
                # loud backpressure: the owner stalled — surface
                # S_RETRY_AFTER / R_QUEUE_FULL for the overflow rows
                with self._map_lock:
                    self.backpressured += k - done
                self._refuse(M[done:], fsock)
                return True
            time.sleep(50e-6)
        return True

    def _refuse(self, M: np.ndarray, fsock) -> None:
        k = M.shape[0]
        rb = wire.RspBatch(
            status=np.full(k, wire.S_RETRY_AFTER, np.uint8),
            reason=np.full(k, wire.R_QUEUE_FULL, np.uint8),
            req_id=wire._get_col(M, 4, "<u4"),
            found=np.ones(k, bool), has_uid=np.zeros(k, bool),
            step=np.full(k, -1, np.int32),
            retry_after_us=np.full(k, self.retry_after_us, np.uint32),
            uid=np.zeros((k, 2), np.int32),
            value=np.zeros((k, self.u), np.int32))
        self._send_out(fsock, rb)

    # -- response drain ------------------------------------------------------

    def _rsp_loop(self) -> None:
        while True:
            slot = self.rsp_ring.poll()
            if slot is None:
                if self._stop.is_set() and self.rsp_ring.ready() == 0:
                    return
                time.sleep(0.0002)
                continue
            n = slot.count
            if n:
                self._deliver(slot)
                self.rows_out += n
            self.rsp_ring.ack()

    def _deliver(self, slot) -> None:
        """One ready response slot -> one encoded wire batch per
        connection (fancy-indexed column copies leave shared memory
        BEFORE the ack releases the slot)."""
        n = slot.count
        c = slot.cols
        conns = np.asarray(c["conn"][:n])
        for cid in np.unique(conns).tolist():
            idx = np.nonzero(conns == cid)[0]
            rb = wire.RspBatch(
                status=c["status"][:n][idx],
                reason=c["reason"][:n][idx],
                req_id=c["req_id"][:n][idx],
                found=c["found"][:n][idx] != 0,
                has_uid=c["has_uid"][:n][idx] != 0,
                step=c["step"][:n][idx],
                retry_after_us=c["retry_after_us"][:n][idx],
                uid=c["uid"][:n][idx],
                value=c["value"][:n][idx])
            with self._map_lock:
                fsock = self._sock_of.get(int(cid))
            if fsock is not None:
                self._send_out(fsock, rb)

    def _send_out(self, fsock, rb: wire.RspBatch) -> None:
        try:
            fsock.send(wire.encode_response_batch(rb, self.u))
        except OSError:
            fsock.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # the rsp drain thread flushes remaining ready slots before
        # exiting — join it FIRST, while client sockets are still open,
        # so in-flight resolutions reach their clients
        self._rsp_t.join(timeout=5.0)
        # now cut the streams (reader threads block in recv until their
        # socket closes), then join everything
        with self._map_lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for fsock in conns:
            fsock.close()
        for t in threads:
            t.join(timeout=2.0)


def shm_worker_main(worker_id: int, req_spec: RingSpec,
                    rsp_spec: RingSpec, u: int, host: str, port: int,
                    ready_q, push_timeout_s: float = 2.0) -> None:
    """One shm front-end worker process (module-level so ``spawn`` can
    import it): attaches the ring pair by name, binds SO_REUSEPORT on
    the shared port, reports ``(worker_id, port)`` once accepting, and
    serves until the parent's SIGTERM.  Deliberately jax-free: the
    import chain (wire/tcp/shm/concurrency) never touches the device
    runtime, so worker boot is milliseconds, not a jax init.

    Shutdown rides SIGTERM + a process-local Event, NOT a shared
    ``multiprocessing.Event``: mp's Event is a condition variable whose
    ``set()`` blocks until every sleeper CONFIRMS wake-up — a worker
    killed with SIGKILL while waiting on it would deadlock the parent's
    ``set()`` forever (the crashed sleeper can never confirm).  Signals
    have no such handshake, so the crash path the kill soak gates stays
    deadlock-free."""
    import signal

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    req_ring = SpscColumnRing.attach(req_spec)
    rsp_ring = SpscColumnRing.attach(rsp_spec)
    srv = ShmWorker(worker_id, req_ring, rsp_ring, u, host=host,
                    port=port, reuseport=True,
                    push_timeout_s=push_timeout_s)
    ready_q.put((worker_id, srv.addr[1]))
    done.wait()
    srv.close()
    req_ring.close()
    rsp_ring.close()


# -- the store-owner side -----------------------------------------------------


class StoreOwner:
    """The single device-owning merge/pump/scatter engine: polls every
    live worker's request ring in worker-id order, decodes the merged
    record matrix ONCE, runs ONE ``submit_batch`` + ``pump`` against
    the shared ``ColumnarFrontend`` per round, and scatters refusals
    and resolutions back to the owning worker's response ring.

    Single-threaded by contract (the caller — ``OneStoreServer``'s pump
    thread or the soak driver — is the only entrant), so the frontend
    needs no lock here."""

    def __init__(self, fe, rings: List[Tuple[SpscColumnRing,
                                             SpscColumnRing]],
                 alive: Optional[Callable[[int], bool]] = None,
                 push_timeout_s: float = 5.0):
        if fe.vbytes:
            raise ValueError(
                "the shm IPC plane is fixed-value mode only (the ring "
                "slot layout preallocates (rows, u) int32 value "
                "columns; heap stores stay on the socket planes)")
        if len(rings) > MAX_WORKERS:
            raise ValueError(f"at most {MAX_WORKERS} workers fit the "
                             f"packed conn id ({CONN_BITS} local bits)")
        self.fe = fe
        self.rings = rings
        self.alive = alive if alive is not None else (lambda w: True)
        self.push_timeout_s = push_timeout_s
        self.u = fe.u
        self.stride = wire.req_nbytes(fe.u)
        self.dead = [False] * len(rings)
        self.rows_in = 0          # rows merged out of request rings
        self.rows_out = 0         # rows scattered into response rings
        self.dead_drop_rows = 0   # response rows for a dead worker
        self.torn_slots = 0       # dead producers' tombstone slots
        self.rsp_stalls = 0       # response-ring claim waits

    # -- liveness ------------------------------------------------------------

    def _mark_dead(self, w: int) -> None:
        if self.dead[w]:
            return
        self.dead[w] = True
        req_ring, _ = self.rings[w]
        if req_ring.torn():
            # the crashed producer's half-written slot: count the
            # tombstone, never read past it
            self.torn_slots += 1
        self.fe._count("ipc_worker_dead")

    def live_workers(self) -> List[int]:
        return [w for w in range(len(self.rings)) if not self.dead[w]]

    # -- merge (request rings -> ONE submit_batch) ---------------------------

    def intake(self) -> Dict[int, wire.RspBatch]:
        """Drain every live request ring (worker-id order — the
        deterministic merge), decode the concatenated record matrix
        once, submit as ONE batch with per-row packed conn tags.
        Returns the immediate refusals, grouped {packed_conn:
        RspBatch} like ``pump``'s emissions."""
        mats: List[np.ndarray] = []
        conns: List[np.ndarray] = []
        polled: List[SpscColumnRing] = []
        for w, (req_ring, _) in enumerate(self.rings):
            if self.dead[w]:
                continue
            if not self.alive(w):
                self._mark_dead(w)
                continue
            while True:
                slot = req_ring.poll()
                if slot is None:
                    break
                polled.append(req_ring)
                n = slot.count
                if n:
                    mats.append(slot.cols["raw"][:n])
                    conns.append(slot.cols["conn"][:n].astype(np.int32)
                                 + np.int32(w << CONN_BITS))
        if not mats:
            for req_ring in polled:
                req_ring.ack()
            return {}
        # the one mandatory copy out of shared memory: concatenate the
        # slot views into the round's merged matrix, then release slots
        M = np.concatenate(mats) if len(mats) > 1 else mats[0].copy()
        conn = (np.concatenate(conns) if len(conns) > 1
                else conns[0])  # astype above already copied
        for req_ring in polled:
            req_ring.ack()
        self.rows_in += M.shape[0]
        batch = wire.decode_request_matrix(M, self.u)
        return self.fe.submit_batch(batch, conn=conn)

    # -- scatter (resolutions -> response rings) -----------------------------

    def scatter(self, rsps: Dict[int, wire.RspBatch]) -> None:
        """Route {packed_conn: RspBatch} back to the owning workers'
        response rings: one concatenated column set per worker per
        call, chunked to slot_rows.  Dead workers' rows are dropped
        LOUDLY (counted); a live worker that stops draining raises
        ``ShmBackpressure`` out of the pump."""
        by_w: Dict[int, List[int]] = {}
        for cid in sorted(rsps):
            by_w.setdefault(conn_worker(cid), []).append(cid)
        for w, cids in sorted(by_w.items()):
            n_rows = sum(len(rsps[c]) for c in cids)
            if self.dead[w] or not self.alive(w):
                self._mark_dead(w)
                self.dead_drop_rows += n_rows
                self.fe._count("ipc_dead_drop_rows", n_rows)
                continue
            parts = [rsps[c] for c in cids]
            cols = dict(
                conn=np.concatenate([np.full(len(rsps[c]),
                                             conn_local(c), np.int32)
                                     for c in cids]),
                req_id=np.concatenate([np.asarray(p.req_id, np.uint32)
                                       for p in parts]),
                status=np.concatenate([np.asarray(p.status, np.uint8)
                                       for p in parts]),
                reason=np.concatenate([np.asarray(p.reason, np.uint8)
                                       for p in parts]),
                found=np.concatenate([np.asarray(p.found, np.uint8)
                                      for p in parts]),
                has_uid=np.concatenate([np.asarray(p.has_uid, np.uint8)
                                        for p in parts]),
                step=np.concatenate([np.asarray(p.step, np.int32)
                                     for p in parts]),
                retry_after_us=np.concatenate(
                    [np.asarray(p.retry_after_us, np.uint32)
                     for p in parts]),
                uid=np.concatenate([np.asarray(p.uid, np.int32)
                                    .reshape(-1, 2) for p in parts]),
                value=np.concatenate(
                    [np.asarray(p.value, np.int32).reshape(-1, self.u)
                     for p in parts]))
            self._push_rows(w, n_rows, cols)

    def _push_rows(self, w: int, total: int,
                   cols: Dict[str, np.ndarray]) -> None:
        _, rsp_ring = self.rings[w]
        rows = rsp_ring.spec.slot_rows
        done = 0
        deadline = time.monotonic() + self.push_timeout_s
        while done < total:
            slot = rsp_ring.try_claim()
            if slot is None:
                if not self.alive(w):
                    self._mark_dead(w)
                    dropped = total - done
                    self.dead_drop_rows += dropped
                    self.fe._count("ipc_dead_drop_rows", dropped)
                    return
                if time.monotonic() >= deadline:
                    raise ShmBackpressure(
                        f"worker {w} response ring full for "
                        f"{self.push_timeout_s:.3f}s with the worker "
                        "alive: its drain thread is wedged — failing "
                        "the pump loudly instead of blocking")
                self.rsp_stalls += 1
                time.sleep(50e-6)
                continue
            n = min(total - done, rows)
            sl = slice(done, done + n)
            for name, arr in cols.items():
                slot.cols[name][:n] = arr[sl]
            rsp_ring.commit(n)
            self.rows_out += n
            done += n
            deadline = time.monotonic() + self.push_timeout_s

    # -- one owner round -----------------------------------------------------

    def step(self) -> int:
        """One merge + pump + scatter round.  Returns the number of
        rows moved (0 = nothing to do; the caller may sleep)."""
        before = self.rows_in + self.rows_out
        refusals = self.intake()
        if refusals:
            self.scatter(refusals)
        if not self.fe.idle():
            out = self.fe.pump()
            if out:
                self.scatter(out)
            self._series()
        return self.rows_in + self.rows_out - before

    def _series(self) -> None:
        rt = self.fe._rt()
        if rt.obs is None:
            return
        reg = rt.obs.registry
        live = self.live_workers()
        depth = sum(self.rings[w][0].ready() for w in live)
        free = min((self.rings[w][1].free_slots() for w in live),
                   default=0)
        reg.series("ipc_req_depth_series").append(rt.step_idx, depth)
        reg.series("ipc_rsp_free_series").append(rt.step_idx, free)
        reg.series("ipc_rsp_stall_series").append(rt.step_idx,
                                                  self.rsp_stalls)

    def counters(self) -> dict:
        return dict(rows_in=self.rows_in, rows_out=self.rows_out,
                    dead_drop_rows=self.dead_drop_rows,
                    torn_slots=self.torn_slots,
                    rsp_stalls=self.rsp_stalls,
                    dead_workers=[w for w, d in enumerate(self.dead)
                                  if d])


# -- the one-store topology ---------------------------------------------------


class OneStoreServer:
    """Round-21 topology: ``n_workers`` shm front-end PROCESSES sharding
    TCP accepts on one SO_REUSEPORT port, all feeding THIS process's
    single store through the ring pairs; one owner pump thread runs the
    merge/pump/scatter rounds.  Counterpart of round-19's
    ``launch.start_serve_workers`` (which gives every worker a PRIVATE
    store) — here the device program stays one store at full lane
    occupancy and only the socket work scales out."""

    def __init__(self, store, scfg=None, host: str = "127.0.0.1",
                 port: int = 0, n_workers: int = 2, nslots: int = 8,
                 slot_rows: int = 512, pump_sleep_s: float = 0.0002,
                 push_timeout_s: float = 5.0,
                 worker_push_timeout_s: float = 2.0,
                 ready_timeout_s: float = 120.0):
        import multiprocessing as mp
        import queue as _queue
        import socket as _socket

        from hermes_tpu.serving.server import ColumnarFrontend

        if n_workers < 1:
            raise ValueError("need at least one shm worker")
        self.fe = ColumnarFrontend(store, scfg)
        u = self.fe.u
        self.rings = [create_ring_pair(u, nslots, slot_rows, w)
                      for w in range(n_workers)]
        if port == 0:
            # claim a concrete port up front: every worker must bind
            # the SAME number for SO_REUSEPORT accept sharding
            probe = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            probe.bind((host, 0))
            port = probe.getsockname()[1]
            probe.close()
        self.addr = (host, port)
        ctx = mp.get_context("spawn")
        self._ready_q = ctx.Queue()
        self.procs = []
        for w in range(n_workers):
            req_ring, rsp_ring = self.rings[w]
            p = ctx.Process(
                target=shm_worker_main,
                args=(w, req_ring.spec, rsp_ring.spec, u, host, port,
                      self._ready_q, worker_push_timeout_s),
                daemon=True)
            p.start()
            self.procs.append(p)
        ready = set()
        while len(ready) < n_workers:
            try:
                wid, _port = self._ready_q.get(timeout=ready_timeout_s)
            except _queue.Empty:
                self._teardown_procs()
                self._close_rings()
                raise RuntimeError(
                    f"shm workers failed to come up: {sorted(ready)} "
                    f"of {n_workers} ready within {ready_timeout_s}s")
            ready.add(wid)
            if sum(p.is_alive() for p in self.procs) < n_workers:
                self._teardown_procs()
                self._close_rings()
                raise RuntimeError(
                    "a shm worker died during boot — check its stderr")
        self.owner = StoreOwner(
            self.fe, self.rings,
            alive=lambda w: self.procs[w].is_alive(),
            push_timeout_s=push_timeout_s)
        self._pump_sleep = pump_sleep_s
        self._stop = threading.Event()
        self._closed = False
        self.pump_error: Optional[BaseException] = None
        self._pump_t = threading.Thread(target=self._pump_loop,
                                        daemon=True)
        self._pump_t.start()

    def alive(self) -> int:
        return sum(p.is_alive() for p in self.procs)

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            try:
                moved = self.owner.step()
            except BaseException as e:  # noqa: BLE001 — store died or a
                # live worker wedged its ring: fail LOUDLY, stop the
                # workers so every client sees EOF now
                self.pump_error = e
                self._stop.set()
                rt = self.fe._rt()
                if rt.obs is not None:
                    rt.obs.flight_dump("ipc_pump_error",
                                       dict(err=repr(e)))
                self._teardown_procs(timeout_s=5.0)
                raise
            if moved == 0 and self.fe.idle():
                time.sleep(0.001)
            else:
                time.sleep(self._pump_sleep)

    def _teardown_procs(self, timeout_s: float = 10.0) -> None:
        # SIGTERM -> the worker's clean close path (see shm_worker_main
        # on why this is a signal, not a shared Event); SIGKILL for
        # stragglers.  Both are no-ops on already-dead processes.
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        for p in self.procs:
            p.join(timeout=timeout_s)
        for p in self.procs:
            if p.is_alive():
                p.kill()
                p.join(timeout=2.0)

    def _close_rings(self) -> None:
        for req_ring, rsp_ring in self.rings:
            req_ring.close()
            rsp_ring.close()

    def close(self, drain_timeout_s: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._pump_t.join(timeout=5.0)
        # inline drain: resolve and scatter everything still in flight
        # before stopping the workers, so connected clients get their
        # answers instead of an EOF race
        if self.pump_error is None:
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                try:
                    moved = self.owner.step()
                except ShmBackpressure:
                    break
                if moved == 0 and self.fe.idle():
                    break
        self._teardown_procs()
        self._close_rings()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- the deterministic witness ------------------------------------------------


def run_shm_soak(cfg=None, scfg=None, n_workers: int = 2,
                 ops_per_worker: int = 512, batch: int = 64,
                 read_frac: float = 0.65, seed: int = 14,
                 nslots: int = 4, slot_rows: Optional[int] = None,
                 max_rounds: int = 50_000) -> dict:
    """Deterministic one-store soak: REAL shm rings, SIMULATED workers
    (in-process, single thread), a VirtualClock, and the owner's
    worker-id-order merge — the replay witness for the IPC plane.
    Every worker's outbound bytes are logged in drain order; same seed
    + config => byte-identical logs and identical counters, which is
    the determinism leg scripts/check_serving.py gates.

    Workers submit their streams batch-by-batch, skipping a round when
    their request ring is full (deterministic backpressure — nothing is
    dropped, the rows just wait), and drain their response rings after
    every owner round.  Runs until every submitted row has exactly one
    response row and the frontend envelope is empty."""
    import hashlib

    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.serving.server import (ColumnarFrontend,
                                           ServingConfig, VirtualClock,
                                           verify_columnar)

    cfg = cfg or HermesConfig(n_replicas=4, n_keys=1 << 10,
                              n_sessions=64, value_words=6)
    scfg = scfg or ServingConfig(queue_cap=4096,
                                 tenant_rate_per_s=1e9,
                                 tenant_burst=1e9, tenant_quota=1 << 20)
    store = KVS(cfg, record="array")  # the linearizability witness
    clock = VirtualClock()
    fe = ColumnarFrontend(store, scfg, clock=clock)
    u = fe.u
    rows = slot_rows or batch
    rings = [create_ring_pair(u, nslots, rows, w)
             for w in range(n_workers)]
    owner = StoreOwner(fe, rings)
    stride = wire.req_nbytes(u)
    try:
        # deterministic per-worker streams (encoded once, pushed in
        # ring-paced chunks)
        streams: List[np.ndarray] = []
        for w in range(n_workers):
            rng = np.random.default_rng(seed * 7919 + 31 * w + 1)
            k = ops_per_worker
            kind = np.where(
                rng.random(k) < read_frac, wire.K_GET,
                np.where(rng.random(k) < 0.5, wire.K_PUT, wire.K_RMW)
            ).astype(np.uint8)
            b = wire.ReqBatch(
                kind=kind,
                req_id=np.arange(1, k + 1, dtype=np.uint32),
                tenant=np.full(k, w, np.uint16),
                trace=np.zeros(k, np.uint16),
                deadline_us=np.zeros(k, np.uint32),
                key=rng.integers(0, cfg.n_keys, k).astype(np.int64),
                value=rng.integers(0, 1 << 20,
                                   (k, u)).astype(np.int32))
            raw = wire.encode_request_batch(b, u)
            streams.append(np.frombuffer(raw, np.uint8)
                           .reshape(k, stride))
        sent = [0] * n_workers
        recv = [0] * n_workers
        logs: List[List[bytes]] = [[] for _ in range(n_workers)]
        client_uids: List[Tuple[int, int]] = []
        for _ in range(max_rounds):
            # 1. workers submit (skip when the ring is full — the
            # deterministic backpressure shape)
            for w in range(n_workers):
                req_ring, _ = rings[w]
                while sent[w] < ops_per_worker:
                    slot = req_ring.try_claim()
                    if slot is None:
                        break
                    n = min(batch, ops_per_worker - sent[w], rows)
                    slot.cols["raw"][:n] = \
                        streams[w][sent[w]: sent[w] + n]
                    slot.cols["conn"][:n] = 1
                    req_ring.commit(n)
                    sent[w] += n
            # 2. one owner round
            owner.step()
            clock.advance(scfg.round_us * 1e-6)
            # 3. workers drain + log (the byte witness)
            for w in range(n_workers):
                _, rsp_ring = rings[w]
                while True:
                    slot = rsp_ring.poll()
                    if slot is None:
                        break
                    n = slot.count
                    c = slot.cols
                    # write uids the CLIENT saw commit, in drain order —
                    # the committed_write_lost witness set the serving
                    # gate cross-checks against the store history
                    minted = (np.asarray(c["status"][:n]) == wire.S_OK) \
                        & (np.asarray(c["has_uid"][:n]) != 0)
                    for i in np.nonzero(minted)[0].tolist():
                        client_uids.append((int(c["uid"][i, 0]),
                                            int(c["uid"][i, 1])))
                    conns = np.asarray(c["conn"][:n])
                    for cid in np.unique(conns).tolist():
                        idx = np.nonzero(conns == cid)[0]
                        rb = wire.RspBatch(
                            status=c["status"][:n][idx],
                            reason=c["reason"][:n][idx],
                            req_id=c["req_id"][:n][idx],
                            found=c["found"][:n][idx] != 0,
                            has_uid=c["has_uid"][:n][idx] != 0,
                            step=c["step"][:n][idx],
                            retry_after_us=c["retry_after_us"][:n][idx],
                            uid=c["uid"][:n][idx],
                            value=c["value"][:n][idx])
                        logs[w].append(
                            wire.encode_response_batch(rb, u))
                    recv[w] += int(n)
                    rsp_ring.ack()
            if (all(s == ops_per_worker for s in sent)
                    and all(r == ops_per_worker for r in recv)
                    and fe.idle()):
                break
        else:
            raise RuntimeError(
                f"shm soak failed to drain in {max_rounds} rounds: "
                f"sent={sent} recv={recv} idle={fe.idle()}")
        v = store.rt.check()
        assert v.ok, ("shm soak checker FAIL: "
                      f"{[f.reason[:160] for f in v.failures[:2]]}")
        ver = verify_columnar(fe)
        return dict(
            ok=True, checker_ok=bool(v.ok),
            worker_log_sha=[hashlib.sha256(b"".join(lg)).hexdigest()
                            for lg in logs],
            response_rows=list(recv),
            ipc=owner.counters(), verify=ver,
            counters=fe.counters(),
            _store=store, _client_uids=client_uids)
    finally:
        for req_ring, rsp_ring in rings:
            req_ring.close()
            rsp_ring.close()
