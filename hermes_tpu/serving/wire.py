"""RPC wire format of the serving front-end (round-14).

One client op = one fixed-size REQUEST message; one resolution = one
fixed-size RESPONSE.  Both are little-endian structs followed by the
config's fixed value-payload width (``value_words - 2`` int32 words —
the same "both ends derive the layout from the same config" discipline
as the replica wire codec, transport/codec.py), so a message's byte
length is known from the config alone.  Every message that crosses a
real socket rides a checksummed CRC frame (``codec.frame_pack`` /
``frame_unpack`` — the round-11 frame layer): corruption is detected on
receipt and the frame is dropped, never decoded into a scrambled
key/deadline/tenant.

Deadlines are RELATIVE microseconds in the request (0 = none); the
server stamps the absolute expiry on intake against ITS clock, so a
client never needs clock sync to bound its wait.  The response echoes
``req_id`` (client-chosen, unique per connection) and carries either the
op result or a loud refusal:

  * ``S_RETRY_AFTER`` — admission control / backpressure / load shed;
    ``retry_after_us`` is the server's earliest-retry hint and ``reason``
    says which rung refused (queue_full / quota / rate / shed_write /
    shed_read) — queue-full is an explicit signal, NEVER silent
    buffering;
  * ``S_DEADLINE`` — the op's deadline expired (at intake or at
    completion).  For updates this is a MAYBE: the broadcast may still
    commit (exactly the crash-'lost' semantics, kvs.C_LOST);
  * ``S_REJECTED`` — definitively did not happen (elastic fence /
    degraded-mode shed inside the store);
  * ``S_LOST`` — the serving replica died holding the op (maybe).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional

import numpy as np

# -- op kinds (wire) ---------------------------------------------------------
K_GET, K_PUT, K_RMW = 1, 2, 3
_KIND_NAMES = {K_GET: "get", K_PUT: "put", K_RMW: "rmw"}
_KIND_CODES = {v: k for k, v in _KIND_NAMES.items()}

# -- response statuses -------------------------------------------------------
S_OK = 0           # op completed (kind's normal completion)
S_RMW_ABORT = 1    # rmw lost its race (reference abort semantics)
S_REJECTED = 2     # definitively did NOT happen (fence / degraded shed)
S_RETRY_AFTER = 3  # refused at the front door; retry_after_us hints when
S_DEADLINE = 4     # deadline expired (updates: MAYBE committed)
S_LOST = 5         # replica crash holding the op (MAYBE committed)
STATUS_NAMES = {S_OK: "ok", S_RMW_ABORT: "rmw_abort", S_REJECTED: "rejected",
                S_RETRY_AFTER: "retry_after", S_DEADLINE: "deadline",
                S_LOST: "lost"}

# -- retry_after reasons (which admission rung refused) ----------------------
R_NONE = 0
R_QUEUE_FULL = 1   # bounded intake queue at capacity
R_QUOTA = 2        # tenant's in-flight session quota exhausted
R_RATE = 3         # tenant's token bucket empty
R_SHED_WRITE = 4   # overload ladder rung 1: new writes shed
R_SHED_READ = 5    # overload ladder rung 2: non-hot-key reads shed
REASON_NAMES = {R_NONE: "", R_QUEUE_FULL: "queue_full", R_QUOTA: "quota",
                R_RATE: "rate", R_SHED_WRITE: "shed_write",
                R_SHED_READ: "shed_read"}

REQ_MAGIC = 0x5251   # 'QR'
RSP_MAGIC = 0x5253   # 'SR'
# magic u16 | kind u8 | pad u8 | req_id u32 | tenant u16 | pad u16 |
# deadline_us u32 | key i64
_REQ = struct.Struct("<HBBIHHIq")
# magic u16 | status u8 | reason u8 | req_id u32 | found u8 | has_uid u8 |
# pad u16 | step i32 | retry_after_us u32 | uid_hi i32 | uid_lo i32
# (has_uid is explicit: uid (0, 0) is a REAL write id — replica 0,
# session 0, op 0 — and must not read back as "absent")
_RSP = struct.Struct("<HBBIBBHiIii")


def req_nbytes(u: int) -> int:
    """Wire size of one (unframed) request at payload width ``u``."""
    return _REQ.size + 4 * u


def rsp_nbytes(u: int) -> int:
    return _RSP.size + 4 * u


@dataclasses.dataclass
class Request:
    kind: str                 # 'get' | 'put' | 'rmw'
    req_id: int
    tenant: int
    key: int
    deadline_us: int = 0      # RELATIVE to server intake; 0 = none
    value: Optional[List[int]] = None  # payload words (updates)


@dataclasses.dataclass
class Response:
    status: int
    req_id: int
    reason: int = R_NONE
    found: bool = True
    step: int = -1
    retry_after_us: int = 0
    uid: Optional[tuple] = None
    value: Optional[List[int]] = None

    @property
    def status_name(self) -> str:
        return STATUS_NAMES[self.status]

    @property
    def reason_name(self) -> str:
        return REASON_NAMES[self.reason]


def encode_request(req: Request, u: int) -> bytes:
    if req.kind not in _KIND_CODES:
        raise ValueError(f"unknown op kind {req.kind!r}")
    if not (0 <= req.deadline_us < 1 << 32):
        raise ValueError("deadline_us must fit u32 (relative microseconds)")
    pay = np.zeros(u, np.int32)
    if req.value is not None:
        v = np.asarray(list(req.value), np.int32)
        if v.ndim != 1 or v.shape[0] > u:
            raise ValueError(f"value must be <= {u} int32 words")
        pay[: v.shape[0]] = v
    return _REQ.pack(REQ_MAGIC, _KIND_CODES[req.kind], 0, req.req_id,
                     req.tenant, 0, req.deadline_us,
                     req.key) + pay.tobytes()


def peek_req_id(buf: bytes) -> Optional[int]:
    """Best-effort req_id from a request whose BODY is undecodable (wrong
    payload width): the fixed header may still be intact, letting the
    server refuse the request loudly instead of leaving the client to
    time out.  None when even the header is unusable."""
    buf = bytes(buf)
    if len(buf) < _REQ.size:
        return None
    magic, _k, _p, req_id, *_rest = _REQ.unpack(buf[: _REQ.size])
    return req_id if magic == REQ_MAGIC else None


def decode_request(buf: bytes, u: int) -> Request:
    buf = bytes(buf)
    if len(buf) != req_nbytes(u):
        raise ValueError(f"request size {len(buf)} != {req_nbytes(u)} "
                         f"(payload width {u})")
    magic, kind, _p, req_id, tenant, _p2, dl, key = _REQ.unpack(
        buf[: _REQ.size])
    if magic != REQ_MAGIC:
        raise ValueError(f"bad request magic 0x{magic:04x}")
    if kind not in _KIND_NAMES:
        raise ValueError(f"unknown wire op kind {kind}")
    value = np.frombuffer(buf[_REQ.size:], np.int32).tolist()
    return Request(kind=_KIND_NAMES[kind], req_id=req_id, tenant=tenant,
                   key=key, deadline_us=dl,
                   value=value if _KIND_NAMES[kind] != "get" else None)


def encode_response(rsp: Response, u: int) -> bytes:
    pay = np.zeros(u, np.int32)
    if rsp.value is not None:
        v = np.asarray(list(rsp.value), np.int32)
        pay[: v.shape[0]] = v
    hi, lo = rsp.uid if rsp.uid is not None else (0, 0)
    return _RSP.pack(RSP_MAGIC, rsp.status, rsp.reason, rsp.req_id,
                     1 if rsp.found else 0,
                     1 if rsp.uid is not None else 0, 0, rsp.step,
                     rsp.retry_after_us, hi, lo) + pay.tobytes()


def decode_response(buf: bytes, u: int) -> Response:
    buf = bytes(buf)
    if len(buf) != rsp_nbytes(u):
        raise ValueError(f"response size {len(buf)} != {rsp_nbytes(u)}")
    (magic, status, reason, req_id, found, has_uid, _p2, step, retry,
     hi, lo) = _RSP.unpack(buf[: _RSP.size])
    if magic != RSP_MAGIC:
        raise ValueError(f"bad response magic 0x{magic:04x}")
    value = np.frombuffer(buf[_RSP.size:], np.int32).tolist()
    return Response(status=status, reason=reason, req_id=req_id,
                    found=bool(found), step=step, retry_after_us=retry,
                    uid=(hi, lo) if has_uid else None,
                    value=value if status == S_OK else None)
