"""RPC wire format of the serving front-end (round-14).

One client op = one fixed-size REQUEST message; one resolution = one
fixed-size RESPONSE.  Both are little-endian structs followed by the
config's fixed value-payload width (``value_words - 2`` int32 words —
the same "both ends derive the layout from the same config" discipline
as the replica wire codec, transport/codec.py), so a message's byte
length is known from the config alone.  Every message that crosses a
real socket rides a checksummed CRC frame (``codec.frame_pack`` /
``frame_unpack`` — the round-11 frame layer): corruption is detected on
receipt and the frame is dropped, never decoded into a scrambled
key/deadline/tenant.

Deadlines are RELATIVE microseconds in the request (0 = none); the
server stamps the absolute expiry on intake against ITS clock, so a
client never needs clock sync to bound its wait.  The response echoes
``req_id`` (client-chosen, unique per connection) and carries either the
op result or a loud refusal:

  * ``S_RETRY_AFTER`` — admission control / backpressure / load shed;
    ``retry_after_us`` is the server's earliest-retry hint and ``reason``
    says which rung refused (queue_full / quota / rate / shed_write /
    shed_read) — queue-full is an explicit signal, NEVER silent
    buffering;
  * ``S_DEADLINE`` — the op's deadline expired (at intake or at
    completion).  For updates this is a MAYBE: the broadcast may still
    commit (exactly the crash-'lost' semantics, kvs.C_LOST);
  * ``S_REJECTED`` — definitively did not happen (elastic fence /
    degraded-mode shed inside the store);
  * ``S_LOST`` — the serving replica died holding the op (maybe).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional

import numpy as np

# -- op kinds (wire) ---------------------------------------------------------
K_GET, K_PUT, K_RMW = 1, 2, 3
# round-16 batched read verbs: K_MGET carries a count-prefixed key
# vector, K_SCAN a [lo, hi) fleet/dense key range — both answered by the
# store's device-resident local-read fast path (kvs.KVS.multi_get /
# Fleet.multi_get), falling back to the round path per Invalid key
K_MGET, K_SCAN = 4, 5
_KIND_NAMES = {K_GET: "get", K_PUT: "put", K_RMW: "rmw"}
_KIND_CODES = {v: k for k, v in _KIND_NAMES.items()}
_READ_KIND_NAMES = {K_MGET: "mget", K_SCAN: "scan"}
_READ_KIND_CODES = {v: k for k, v in _READ_KIND_NAMES.items()}

# -- response statuses -------------------------------------------------------
S_OK = 0           # op completed (kind's normal completion)
S_RMW_ABORT = 1    # rmw lost its race (reference abort semantics)
S_REJECTED = 2     # definitively did NOT happen (fence / degraded shed)
S_RETRY_AFTER = 3  # refused at the front door; retry_after_us hints when
S_DEADLINE = 4     # deadline expired (updates: MAYBE committed)
S_LOST = 5         # replica crash holding the op (MAYBE committed)
STATUS_NAMES = {S_OK: "ok", S_RMW_ABORT: "rmw_abort", S_REJECTED: "rejected",
                S_RETRY_AFTER: "retry_after", S_DEADLINE: "deadline",
                S_LOST: "lost"}

# -- retry_after reasons (which admission rung refused) ----------------------
R_NONE = 0
R_QUEUE_FULL = 1   # bounded intake queue at capacity
R_QUOTA = 2        # tenant's in-flight session quota exhausted
R_RATE = 3         # tenant's token bucket empty
R_SHED_WRITE = 4   # overload ladder rung 1: new writes shed
R_SHED_READ = 5    # overload ladder rung 2: non-hot-key reads shed
REASON_NAMES = {R_NONE: "", R_QUEUE_FULL: "queue_full", R_QUOTA: "quota",
                R_RATE: "rate", R_SHED_WRITE: "shed_write",
                R_SHED_READ: "shed_read"}

REQ_MAGIC = 0x5251   # 'QR'
RSP_MAGIC = 0x5253   # 'SR'
# magic u16 | kind u8 | pad u8 | req_id u32 | tenant u16 | trace u16 |
# deadline_us u32 | key i64
# (trace was pad until round-18: nonzero = the op is sampled for per-op
# tracing, obs/tracing.py — same size, 0-compatible with old frames)
_REQ = struct.Struct("<HBBIHHIq")
# magic u16 | status u8 | reason u8 | req_id u32 | found u8 | has_uid u8 |
# pad u16 | step i32 | retry_after_us u32 | uid_hi i32 | uid_lo i32
# (has_uid is explicit: uid (0, 0) is a REAL write id — replica 0,
# session 0, op 0 — and must not read back as "absent")
_RSP = struct.Struct("<HBBIBBHiIii")


def req_nbytes(u: int) -> int:
    """Wire size of one (unframed) fixed-word request at payload width
    ``u`` (heap-mode messages are variable — see the vbytes tail)."""
    return _REQ.size + 4 * u


def rsp_nbytes(u: int) -> int:
    return _RSP.size + 4 * u


# -- round-17 value-heap payload tail ----------------------------------------
#
# With ``vbytes = cfg.max_value_bytes > 0`` (both ends derive it from the
# shared config, like ``u``), every K_PUT/K_RMW request and K_GET/K_RMW
# response swaps its fixed word payload for a LENGTH-PREFIXED byte tail:
# ``dlen u32 | dlen bytes`` — dlen = _DLEN_NONE marks "no payload" (a get
# request, a put response, the never-written key), distinct from a real
# zero-length value.  K_MGET/K_SCAN responses keep fixed-stride rows
# (numpy-packable) of ``found|local|code|pad | dlen u32 | vcap(vbytes)
# padded bytes``.  The CRC frame already bounds and checksums the whole
# message, so the prefix only has to carve the tail.

_DLEN_NONE = 0xFFFFFFFF


def _vcap(vbytes: int) -> int:
    """Fixed per-row byte capacity of a heap-mode read-response row
    (word-aligned so the row stride stays 4-byte aligned)."""
    return 4 * ((vbytes + 3) // 4)


def _pack_tail(data, vbytes: int) -> bytes:
    if data is None:
        return struct.pack("<I", _DLEN_NONE)
    raw = bytes(data)
    if len(raw) > vbytes:
        raise ValueError(f"payload is {len(raw)} bytes > max_value_bytes="
                         f"{vbytes}")
    return struct.pack("<I", len(raw)) + raw


def _unpack_tail(buf: bytes, off: int, vbytes: int):
    """(data, next_offset) of a length-prefixed tail at ``off``."""
    if off + 4 > len(buf):
        raise ValueError("payload tail truncated (no length prefix)")
    (dlen,) = struct.unpack_from("<I", buf, off)
    if dlen == _DLEN_NONE:
        return None, off + 4
    if dlen > vbytes or off + 4 + dlen > len(buf):
        raise ValueError(f"payload tail declares {dlen} bytes "
                         f"(max {vbytes}, have {len(buf) - off - 4})")
    return buf[off + 4: off + 4 + dlen], off + 4 + dlen


@dataclasses.dataclass
class Request:
    kind: str                 # 'get' | 'put' | 'rmw'
    req_id: int
    tenant: int
    key: int
    deadline_us: int = 0      # RELATIVE to server intake; 0 = none
    value: Optional[List[int]] = None  # payload words (updates)
    data: Optional[bytes] = None       # heap mode: variable byte payload
    # trace id (round-18, obs/tracing.py): nonzero u16 = this op is
    # sampled for per-op tracing; rides the formerly-pad u16 of the fixed
    # header, so the wire size is unchanged and 0 (the old pad value)
    # means "not sampled" — old peers interoperate bit-for-bit
    trace: int = 0


@dataclasses.dataclass
class Response:
    status: int
    req_id: int
    reason: int = R_NONE
    found: bool = True
    step: int = -1
    retry_after_us: int = 0
    uid: Optional[tuple] = None
    value: Optional[List[int]] = None
    data: Optional[bytes] = None       # heap mode: variable byte payload

    @property
    def status_name(self) -> str:
        return STATUS_NAMES[self.status]

    @property
    def reason_name(self) -> str:
        return REASON_NAMES[self.reason]


def encode_request(req: Request, u: int, vbytes: int = 0) -> bytes:
    if req.kind not in _KIND_CODES:
        raise ValueError(f"unknown op kind {req.kind!r}")
    if not (0 <= req.deadline_us < 1 << 32):
        raise ValueError("deadline_us must fit u32 (relative microseconds)")
    if not (0 <= req.trace <= 0xFFFF):
        raise ValueError("trace id must fit u16 (0 = not sampled)")
    head = _REQ.pack(REQ_MAGIC, _KIND_CODES[req.kind], 0, req.req_id,
                     req.tenant, req.trace, req.deadline_us, req.key)
    if vbytes:
        # heap mode: the length-prefixed byte tail replaces the fixed
        # word payload (an update's bytes; None for gets)
        return head + _pack_tail(
            req.data if req.kind != "get" else None, vbytes)
    pay = np.zeros(u, np.int32)
    if req.value is not None:
        v = np.asarray(list(req.value), np.int32)
        if v.ndim != 1 or v.shape[0] > u:
            raise ValueError(f"value must be <= {u} int32 words")
        pay[: v.shape[0]] = v
    return head + pay.tobytes()


def peek_req_id(buf: bytes) -> Optional[int]:
    """Best-effort req_id from a request whose BODY is undecodable (wrong
    payload width): the fixed header may still be intact, letting the
    server refuse the request loudly instead of leaving the client to
    time out.  None when even the header is unusable."""
    buf = bytes(buf)
    if len(buf) < _RREQ.size:
        return None
    magic, _k, _p, req_id = struct.unpack_from("<HBBI", buf, 0)
    # both request layouts put req_id at the same offset behind their magic
    return req_id if magic in (REQ_MAGIC, RREQ_MAGIC) else None


def decode_request(buf: bytes, u: int, vbytes: int = 0) -> Request:
    buf = bytes(buf)
    if len(buf) < _REQ.size:
        raise ValueError(f"request size {len(buf)} too short "
                         f"(header is {_REQ.size} bytes)")
    if not vbytes and len(buf) != req_nbytes(u):
        raise ValueError(f"request size {len(buf)} != {req_nbytes(u)} "
                         f"(payload width {u})")
    magic, kind, _p, req_id, tenant, trace, dl, key = _REQ.unpack(
        buf[: _REQ.size])
    if magic != REQ_MAGIC:
        raise ValueError(f"bad request magic 0x{magic:04x}")
    if kind not in _KIND_NAMES:
        raise ValueError(f"unknown wire op kind {kind}")
    if vbytes:
        data, end = _unpack_tail(buf, _REQ.size, vbytes)
        if end != len(buf):
            raise ValueError(f"request size {len(buf)} != {end} "
                             "(trailing bytes after the payload tail)")
        return Request(kind=_KIND_NAMES[kind], req_id=req_id, tenant=tenant,
                       key=key, deadline_us=dl, trace=trace,
                       data=data if _KIND_NAMES[kind] != "get" else None)
    value = np.frombuffer(buf[_REQ.size:], np.int32).tolist()
    return Request(kind=_KIND_NAMES[kind], req_id=req_id, tenant=tenant,
                   key=key, deadline_us=dl, trace=trace,
                   value=value if _KIND_NAMES[kind] != "get" else None)


def encode_response(rsp: Response, u: int, vbytes: int = 0) -> bytes:
    hi, lo = rsp.uid if rsp.uid is not None else (0, 0)
    head = _RSP.pack(RSP_MAGIC, rsp.status, rsp.reason, rsp.req_id,
                     1 if rsp.found else 0,
                     1 if rsp.uid is not None else 0, 0, rsp.step,
                     rsp.retry_after_us, hi, lo)
    if vbytes:
        return head + _pack_tail(
            rsp.data if rsp.status == S_OK else None, vbytes)
    pay = np.zeros(u, np.int32)
    if rsp.value is not None:
        v = np.asarray(list(rsp.value), np.int32)
        pay[: v.shape[0]] = v
    return head + pay.tobytes()


def decode_response(buf: bytes, u: int, vbytes: int = 0) -> Response:
    buf = bytes(buf)
    if len(buf) < _RSP.size:
        raise ValueError(f"response size {len(buf)} too short "
                         f"(header is {_RSP.size} bytes)")
    if not vbytes and len(buf) != rsp_nbytes(u):
        raise ValueError(f"response size {len(buf)} != {rsp_nbytes(u)}")
    (magic, status, reason, req_id, found, has_uid, _p2, step, retry,
     hi, lo) = _RSP.unpack(buf[: _RSP.size])
    if magic != RSP_MAGIC:
        raise ValueError(f"bad response magic 0x{magic:04x}")
    if vbytes:
        data, end = _unpack_tail(buf, _RSP.size, vbytes)
        if end != len(buf):
            raise ValueError(f"response size {len(buf)} != {end} "
                             "(trailing bytes after the payload tail)")
        return Response(status=status, reason=reason, req_id=req_id,
                        found=bool(found), step=step, retry_after_us=retry,
                        uid=(hi, lo) if has_uid else None,
                        data=data if status == S_OK else None)
    value = np.frombuffer(buf[_RSP.size:], np.int32).tolist()
    return Response(status=status, reason=reason, req_id=req_id,
                    found=bool(found), step=step, retry_after_us=retry,
                    uid=(hi, lo) if has_uid else None,
                    value=value if status == S_OK else None)


# -- round-16 batched-read structs (K_MGET / K_SCAN) -------------------------
#
# Variable-size messages: the CRC frame already carries the byte length
# (stream boundary), so a count prefix inside the struct is enough for
# both ends to agree on the vector extent — the payload rows keep the
# config-width discipline (u int32 words each, derived from the shared
# config like every other message).  Distinct magics keep the decoders
# honest: a read response can never be mis-decoded as a single-op one.

RREQ_MAGIC = 0x5255   # 'UR' — batched-read request
RRSP_MAGIC = 0x5254   # 'TR' — batched-read response
MGET_MAX_KEYS = 65_535  # count rides a u16

# magic u16 | kind u8 | pad u8 | req_id u32 | tenant u16 | count u16 |
# deadline_us u32   ...then count*i64 keys (mget) or lo i64, hi i64 (scan)
_RREQ = struct.Struct("<HBBIHHI")
# magic u16 | status u8 | reason u8 | req_id u32 | count u16 | pad u16 |
# step i32 | retry_after_us u32   ...then count rows of
# [found u8 | local u8 | code u8 | pad u8 | u*i32 payload]
_RRSP = struct.Struct("<HBBIHHiI")

# per-key row status codes in a read response
RK_OK = 0        # served (found flag says whether the key ever existed)
RK_REJECTED = 2  # draining/fenced range: definitively not served here


@dataclasses.dataclass
class ReadRequest:
    """One batched read RPC: ``mget`` over an explicit key vector or
    ``scan`` over the key range [lo, hi).  One admission unit — the
    ladder treats it as a read (rung 2 sheds it unless EVERY key is in
    the hot set; a range never is)."""

    kind: str                 # 'mget' | 'scan'
    req_id: int
    tenant: int
    keys: Optional[List[int]] = None  # mget
    lo: int = 0                       # scan
    hi: int = 0
    deadline_us: int = 0

    @property
    def count(self) -> int:
        return len(self.keys) if self.kind == "mget" else self.hi - self.lo


@dataclasses.dataclass
class ReadResponse:
    """Answer to one ReadRequest: per-key rows in request key order.
    Refusals (S_RETRY_AFTER / S_DEADLINE / S_REJECTED) carry count 0."""

    status: int
    req_id: int
    reason: int = R_NONE
    step: int = -1
    retry_after_us: int = 0
    found: Optional[List[bool]] = None
    local: Optional[List[bool]] = None   # served by the fast path
    codes: Optional[List[int]] = None    # RK_* per key
    values: Optional[List[List[int]]] = None
    # heap mode: per-key byte payloads (None = never written / not served)
    data: Optional[List[Optional[bytes]]] = None

    @property
    def status_name(self) -> str:
        return STATUS_NAMES[self.status]

    @property
    def reason_name(self) -> str:
        return REASON_NAMES[self.reason]


def rreq_nbytes(kind: str, count: int) -> int:
    return _RREQ.size + (8 * count if kind == "mget" else 16)


def rrsp_nbytes(u: int, count: int, vbytes: int = 0) -> int:
    """Read-response size: fixed-stride rows — word payloads at width
    ``u``, or (heap mode) a u32 length + vcap padded bytes per row."""
    if vbytes:
        return _RRSP.size + count * (8 + _vcap(vbytes))
    return _RRSP.size + count * (4 + 4 * u)


def encode_read_request(req: ReadRequest) -> bytes:
    if req.kind not in _READ_KIND_CODES:
        raise ValueError(f"unknown read kind {req.kind!r}")
    if not (0 <= req.deadline_us < 1 << 32):
        raise ValueError("deadline_us must fit u32 (relative microseconds)")
    if req.kind == "mget":
        keys = list(req.keys or ())
        if not (1 <= len(keys) <= MGET_MAX_KEYS):
            raise ValueError(
                f"mget wants 1..{MGET_MAX_KEYS} keys, got {len(keys)}")
        body = np.asarray(keys, np.int64).tobytes()
        count = len(keys)
    else:
        body = np.asarray([req.lo, req.hi], np.int64).tobytes()
        count = 0  # the range rides the body; count is mget-only
    return _RREQ.pack(RREQ_MAGIC, _READ_KIND_CODES[req.kind], 0, req.req_id,
                      req.tenant, count, req.deadline_us) + body


def decode_read_request(buf: bytes) -> ReadRequest:
    buf = bytes(buf)
    if len(buf) < _RREQ.size:
        raise ValueError(f"read request too short ({len(buf)} bytes)")
    magic, kind, _p, req_id, tenant, count, dl = _RREQ.unpack(
        buf[: _RREQ.size])
    if magic != RREQ_MAGIC:
        raise ValueError(f"bad read-request magic 0x{magic:04x}")
    if kind not in _READ_KIND_NAMES:
        raise ValueError(f"unknown wire read kind {kind}")
    name = _READ_KIND_NAMES[kind]
    if len(buf) != rreq_nbytes(name, count):
        raise ValueError(
            f"read request size {len(buf)} != {rreq_nbytes(name, count)}")
    body = np.frombuffer(buf[_RREQ.size:], np.int64)
    if name == "mget":
        return ReadRequest(kind="mget", req_id=req_id, tenant=tenant,
                           keys=body.tolist(), deadline_us=dl)
    return ReadRequest(kind="scan", req_id=req_id, tenant=tenant,
                       lo=int(body[0]), hi=int(body[1]), deadline_us=dl)


def encode_read_response(rsp: ReadResponse, u: int, vbytes: int = 0) -> bytes:
    n = len(rsp.found or ())
    head = _RRSP.pack(RRSP_MAGIC, rsp.status, rsp.reason, rsp.req_id, n, 0,
                      rsp.step, rsp.retry_after_us)
    if n == 0:
        return head
    if vbytes:
        cap = _vcap(vbytes)
        rows = np.zeros((n, 8 + cap), np.uint8)
        rows[:, 0] = np.asarray(rsp.found, np.uint8)
        rows[:, 1] = np.asarray(rsp.local or [0] * n, np.uint8)
        rows[:, 2] = np.asarray(rsp.codes or [RK_OK] * n, np.uint8)
        dlen = np.full(n, _DLEN_NONE, np.uint32)
        data = rsp.data or [None] * n
        for i, d in enumerate(data):
            if d is not None:
                raw = bytes(d)
                if len(raw) > vbytes:
                    raise ValueError(f"row {i} payload is {len(raw)} bytes "
                                     f"> max_value_bytes={vbytes}")
                dlen[i] = len(raw)
                rows[i, 8: 8 + len(raw)] = np.frombuffer(raw, np.uint8)
        rows[:, 4:8] = dlen.view(np.uint8).reshape(n, 4)
        return head + rows.tobytes()
    rows = np.zeros((n, 4 + 4 * u), np.uint8)
    rows[:, 0] = np.asarray(rsp.found, np.uint8)
    rows[:, 1] = np.asarray(rsp.local or [0] * n, np.uint8)
    rows[:, 2] = np.asarray(rsp.codes or [RK_OK] * n, np.uint8)
    vals = np.zeros((n, u), np.int32)
    if rsp.values is not None:
        vals[:] = np.asarray(rsp.values, np.int32)
    rows[:, 4:] = vals.view(np.uint8).reshape(n, 4 * u)
    return head + rows.tobytes()


def decode_read_response(buf: bytes, u: int, vbytes: int = 0) -> ReadResponse:
    buf = bytes(buf)
    if len(buf) < _RRSP.size:
        raise ValueError(f"read response too short ({len(buf)} bytes)")
    magic, status, reason, req_id, n, _p, step, retry = _RRSP.unpack(
        buf[: _RRSP.size])
    if magic != RRSP_MAGIC:
        raise ValueError(f"bad read-response magic 0x{magic:04x}")
    if len(buf) != rrsp_nbytes(u, n, vbytes):
        raise ValueError(
            f"read response size {len(buf)} != {rrsp_nbytes(u, n, vbytes)}")
    out = ReadResponse(status=status, reason=reason, req_id=req_id,
                       step=step, retry_after_us=retry)
    if n and vbytes:
        cap = _vcap(vbytes)
        rows = np.frombuffer(buf[_RRSP.size:], np.uint8).reshape(n, 8 + cap)
        out.found = (rows[:, 0] != 0).tolist()
        out.local = (rows[:, 1] != 0).tolist()
        out.codes = rows[:, 2].astype(int).tolist()
        dlen = np.ascontiguousarray(rows[:, 4:8]).view(np.uint32).ravel()
        out.data = []
        for i in range(n):
            if dlen[i] == _DLEN_NONE:
                out.data.append(None)
            elif dlen[i] > vbytes:
                raise ValueError(f"row {i} declares {int(dlen[i])} bytes > "
                                 f"max_value_bytes={vbytes}")
            else:
                out.data.append(rows[i, 8: 8 + int(dlen[i])].tobytes())
    elif n:
        rows = np.frombuffer(buf[_RRSP.size:], np.uint8).reshape(n, 4 + 4 * u)
        out.found = (rows[:, 0] != 0).tolist()
        out.local = (rows[:, 1] != 0).tolist()
        out.codes = rows[:, 2].astype(int).tolist()
        out.values = np.ascontiguousarray(
            rows[:, 4:]).view(np.int32).reshape(n, u).tolist()
    return out


def plausible_request_len(u: int, vbytes: int = 0):
    """Predicate over frame payload lengths a server may legitimately
    receive (FramedSocket's corruption-triage hook): the fixed single-op
    request size (heap mode: header + length prefix + up to vbytes), or
    a read-request size — header + count*i64 keys (mget) / + 2*i64
    (scan).  Only consulted when a frame FAILS its CRC, to decide
    skip-vs-teardown."""
    fixed = req_nbytes(u)

    def ok(length: int) -> bool:
        if vbytes:
            if _REQ.size + 4 <= length <= _REQ.size + 4 + vbytes:
                return True
        elif length == fixed:
            return True
        body = length - _RREQ.size
        return (body >= 8 and body % 8 == 0
                and body <= 8 * MGET_MAX_KEYS)

    return ok


def plausible_response_len(u: int, vbytes: int = 0):
    """Predicate over frame payload lengths a client may legitimately
    receive: the fixed single-op response size (heap mode: a bounded
    variable tail), or a read-response size (header + fixed-stride
    rows)."""
    fixed = rsp_nbytes(u)
    row = (8 + _vcap(vbytes)) if vbytes else (4 + 4 * u)

    def ok(length: int) -> bool:
        if vbytes:
            if _RSP.size + 4 <= length <= _RSP.size + 4 + vbytes:
                return True
        elif length == fixed:
            return True
        if length == _RRSP.size:
            return True
        body = length - _RRSP.size
        return body > 0 and body % row == 0 and body // row <= MGET_MAX_KEYS

    return ok


def response_extent(raw: bytes, off: int, u: int, vbytes: int = 0) -> int:
    """Byte length of the response record at ``off`` in a response log
    (either layout, either payload mode) — the walker primitive
    ``serving.soak.committed_uids`` steps with."""
    (magic,) = struct.unpack_from("<H", raw, off)
    if magic == RRSP_MAGIC:
        (count,) = struct.unpack_from("<H", raw, off + 8)
        return rrsp_nbytes(u, count, vbytes)
    if vbytes:
        (dlen,) = struct.unpack_from("<I", raw, off + _RSP.size)
        return _RSP.size + 4 + (0 if dlen == _DLEN_NONE else dlen)
    return rsp_nbytes(u)


# -- round-19 columnar batch codec -------------------------------------------
#
# The serving data plane processes requests the way the round does: as
# COLUMNS, not structs.  A drained socket buffer is k back-to-back
# single-op records (REQ_MAGIC delimits; a classic one-op frame is a
# 1-row batch) and decodes in one numpy pass into per-field arrays; a
# pump's resolutions encode back into one record stream per connection.
# The per-struct encode/decode above stay as the compat/fuzz ORACLE: for
# any batch,
#
#     encode_request_batch(b)  == b"".join(encode_request(r) for r in b)
#     encode_response_batch(b) == b"".join(encode_response(r) for r in b)
#
# byte-for-byte (both payload modes), and decode is the exact inverse —
# so the response-log walkers (response_extent / committed_uids) and old
# one-op peers read columnar streams unchanged.  The struct codec's
# asymmetries are mirrored exactly: heap-mode request encode drops data
# on gets, heap-mode response encode drops data on non-OK statuses, and
# fixed-mode encode writes the value column verbatim regardless of
# kind/status (decode nulls it back, same as the struct path).

from hermes_tpu.transport import codec as _codec

_REQ_KINDS = (K_GET, K_PUT, K_RMW)


def _put_col(M: np.ndarray, off: int, arr, dt: str) -> None:
    """Write a scalar column into byte-matrix ``M`` at byte ``off`` (one
    contiguous-view reinterpret, the rows_to_words discipline)."""
    k = M.shape[0]
    col = np.ascontiguousarray(np.asarray(arr).astype(dt, copy=False))
    w = col.dtype.itemsize
    M[:, off: off + w] = col.view(np.uint8).reshape(k, w)


def _get_col(M: np.ndarray, off: int, dt: str) -> np.ndarray:
    """Read a scalar column out of byte-matrix ``M`` at byte ``off``."""
    k = M.shape[0]
    w = np.dtype(dt).itemsize
    return np.ascontiguousarray(M[:, off: off + w]).view(dt).reshape(k)


@dataclasses.dataclass
class ReqBatch:
    """Columnar view of k single-op requests (one column per wire field;
    heap mode swaps the fixed ``value`` matrix for a ``vlen``/``voff``
    pair addressing one shared payload ``blob``, -1 = no tail)."""

    kind: np.ndarray          # (k,) uint8 — K_GET / K_PUT / K_RMW
    req_id: np.ndarray        # (k,) uint32
    tenant: np.ndarray        # (k,) uint16
    trace: np.ndarray         # (k,) uint16 — 0 = not sampled (round-18)
    deadline_us: np.ndarray   # (k,) uint32 — relative; 0 = none
    key: np.ndarray           # (k,) int64
    value: Optional[np.ndarray] = None  # fixed mode: (k, u) int32
    vlen: Optional[np.ndarray] = None   # heap mode: (k,) int64; -1 = none
    voff: Optional[np.ndarray] = None   # heap mode: (k,) offsets into blob
    blob: bytes = b""                   # heap mode: shared payload pool

    def __len__(self) -> int:
        return int(self.kind.shape[0])

    def row_data(self, i: int) -> Optional[bytes]:
        """Heap payload bytes of row ``i`` (None when absent)."""
        if self.vlen is None or self.vlen[i] < 0:
            return None
        o = int(self.voff[i]) if self.voff is not None else 0
        return self.blob[o: o + int(self.vlen[i])]

    def select(self, idx) -> "ReqBatch":
        """Row-gather a sub-batch (shares the heap blob)."""
        idx = np.asarray(idx)
        return ReqBatch(
            kind=self.kind[idx], req_id=self.req_id[idx],
            tenant=self.tenant[idx], trace=self.trace[idx],
            deadline_us=self.deadline_us[idx], key=self.key[idx],
            value=None if self.value is None else self.value[idx],
            vlen=None if self.vlen is None else self.vlen[idx],
            voff=None if self.voff is None else self.voff[idx],
            blob=self.blob)

    def to_requests(self) -> List[Request]:
        """Struct rows (the oracle direction) — mirrors decode_request's
        nulling rules (gets carry no value/data)."""
        out = []
        for i in range(len(self)):
            kname = _KIND_NAMES[int(self.kind[i])]
            value = data = None
            if kname != "get":
                if self.value is not None:
                    value = self.value[i].tolist()
                data = self.row_data(i)
            out.append(Request(
                kind=kname, req_id=int(self.req_id[i]),
                tenant=int(self.tenant[i]), key=int(self.key[i]),
                deadline_us=int(self.deadline_us[i]),
                trace=int(self.trace[i]), value=value, data=data))
        return out

    @staticmethod
    def from_requests(reqs: List[Request], u: int,
                      vbytes: int = 0) -> "ReqBatch":
        """Columnarize struct rows — mirrors encode_request's payload
        rules (fixed mode writes value verbatim even for gets; heap mode
        drops data on gets)."""
        k = len(reqs)
        b = ReqBatch(
            kind=np.array([_KIND_CODES[r.kind] for r in reqs], np.uint8),
            req_id=np.array([r.req_id for r in reqs], np.uint32),
            tenant=np.array([r.tenant for r in reqs], np.uint16),
            trace=np.array([r.trace for r in reqs], np.uint16),
            deadline_us=np.array([r.deadline_us for r in reqs], np.uint32),
            key=np.array([r.key for r in reqs], np.int64))
        if vbytes:
            vlen = np.full(k, -1, np.int64)
            voff = np.zeros(k, np.int64)
            parts = []
            off = 0
            for i, r in enumerate(reqs):
                if r.data is not None and r.kind != "get":
                    raw = bytes(r.data)
                    vlen[i] = len(raw)
                    voff[i] = off
                    parts.append(raw)
                    off += len(raw)
            b.vlen, b.voff, b.blob = vlen, voff, b"".join(parts)
        else:
            val = np.zeros((k, u), np.int32)
            for i, r in enumerate(reqs):
                if r.value is not None:
                    v = np.asarray(list(r.value), np.int32)
                    if v.ndim != 1 or v.shape[0] > u:
                        raise ValueError(f"value must be <= {u} int32 words")
                    val[i, : v.shape[0]] = v
            b.value = val
        return b


def _req_heads(b: ReqBatch, width: int) -> np.ndarray:
    k = len(b)
    kind = np.asarray(b.kind, np.uint8)
    if k and not np.isin(kind, _REQ_KINDS).all():
        bad = int(kind[~np.isin(kind, _REQ_KINDS)][0])
        raise ValueError(f"unknown wire op kind {bad} in batch")
    M = np.zeros((k, width), np.uint8)
    _put_col(M, 0, np.full(k, REQ_MAGIC), "<u2")
    M[:, 2] = kind
    _put_col(M, 4, b.req_id, "<u4")
    _put_col(M, 8, b.tenant, "<u2")
    _put_col(M, 10, b.trace, "<u2")
    _put_col(M, 12, b.deadline_us, "<u4")
    _put_col(M, 16, b.key, "<i8")
    return M


def encode_request_batch(b: ReqBatch, u: int, vbytes: int = 0) -> bytes:
    """k requests -> one record stream, byte-identical to concatenating
    ``encode_request`` over the rows (one numpy pass, no per-row Python
    beyond the heap-mode blob gather)."""
    k = len(b)
    if vbytes:
        vlen = (np.asarray(b.vlen, np.int64) if b.vlen is not None
                else np.full(k, -1, np.int64))
        # the struct codec's rule: gets never carry a payload tail
        vlen = np.where(np.asarray(b.kind, np.uint8) == K_GET, -1, vlen)
        if k and int(vlen.max(initial=-1)) > vbytes:
            raise ValueError(f"payload is {int(vlen.max())} bytes > "
                             f"max_value_bytes={vbytes}")
        plen = np.maximum(vlen, 0)
        recs = _REQ.size + 4 + plen
        offs = np.concatenate(([0], np.cumsum(recs)[:-1])) if k \
            else np.zeros(0, np.int64)
        out = np.zeros(int(recs.sum()), np.uint8)
        H = _req_heads(b, _REQ.size + 4)
        _put_col(H, _REQ.size,
                 np.where(vlen < 0, _DLEN_NONE, vlen).astype(np.uint32),
                 "<u4")
        _codec.scatter_records(out, offs, H)
        voff = (np.asarray(b.voff, np.int64) if b.voff is not None
                else np.zeros(k, np.int64))
        blob8 = np.frombuffer(b.blob, np.uint8)
        src = _codec.ragged_gather(blob8, voff, plen)
        _codec.ragged_scatter(out, offs + _REQ.size + 4, plen, src)
        return out.tobytes()
    M = _req_heads(b, req_nbytes(u))
    val = b.value if b.value is not None else np.zeros((k, u), np.int32)
    val = np.asarray(val, np.int32)
    if val.shape != (k, u):
        raise ValueError(f"value matrix shape {val.shape} != ({k}, {u})")
    if u:
        M[:, _REQ.size:] = np.ascontiguousarray(val).view(
            np.uint8).reshape(k, 4 * u)
    return M.tobytes()


def decode_request_batch(buf: bytes, u: int, vbytes: int = 0) -> ReqBatch:
    """One drained buffer of k back-to-back request records -> columns
    (the inverse of ``encode_request_batch``; raises ValueError on torn
    trailing bytes, bad magic, or an unknown kind — same triage rules as
    the struct decoder, applied batch-wide)."""
    buf = bytes(buf)
    if vbytes:
        offs, dls = [], []
        off, hsz = 0, _REQ.size
        while off < len(buf):
            if off + hsz + 4 > len(buf):
                raise ValueError(
                    f"torn batch: truncated request header at byte {off} "
                    f"({len(buf) - off} trailing bytes)")
            (dlen,) = struct.unpack_from("<I", buf, off + hsz)
            if dlen == _DLEN_NONE:
                dls.append(-1)
                end = off + hsz + 4
            else:
                if dlen > vbytes:
                    raise ValueError(f"payload tail declares {dlen} bytes "
                                     f"(max {vbytes})")
                if off + hsz + 4 + dlen > len(buf):
                    raise ValueError(
                        f"torn batch: payload tail at byte {off} wants "
                        f"{dlen} bytes, have {len(buf) - off - hsz - 4}")
                dls.append(dlen)
                end = off + hsz + 4 + dlen
            offs.append(off)
            off = end
        k = len(offs)
        offs_a = np.asarray(offs, np.int64)
        M = _codec.gather_records(np.frombuffer(buf, np.uint8), offs_a,
                                  hsz + 4)
        vlen = np.asarray(dls, np.int64)
        voff = offs_a + hsz + 4
        b = _decode_req_heads(M)
        b.vlen, b.voff, b.blob = vlen, voff, buf
        return b
    stride = req_nbytes(u)
    if len(buf) % stride:
        raise ValueError(
            f"torn batch: {len(buf)} bytes is not a whole number of "
            f"{stride}-byte requests ({len(buf) % stride} trailing bytes)")
    k = len(buf) // stride
    M = np.frombuffer(buf, np.uint8).reshape(k, stride)
    b = _decode_req_heads(M)
    b.value = np.ascontiguousarray(M[:, _REQ.size:]).view(
        np.int32).reshape(k, u)
    return b


def check_request_matrix(M: np.ndarray) -> None:
    """Batch-wide magic + kind triage of a (k, >=_REQ.size) request
    record matrix, WITHOUT building columns — the shm IPC worker's
    cheap validation pass before raw records land in ring slots
    (serving/ipc.py): a front-end process can refuse a garbage stream
    loudly while leaving the column decode to the store owner.  Raises
    ValueError with the struct decoder's triage wording."""
    k = M.shape[0]
    magic = _get_col(M, 0, "<u2")
    if k and (magic != REQ_MAGIC).any():
        i = int(np.nonzero(magic != REQ_MAGIC)[0][0])
        raise ValueError(f"bad request magic 0x{int(magic[i]):04x} "
                         f"at row {i}")
    kind = M[:, 2]
    if k and not np.isin(kind, _REQ_KINDS).all():
        bad = int(kind[~np.isin(kind, _REQ_KINDS)][0])
        raise ValueError(f"unknown wire op kind {bad}")


def decode_request_matrix(M: np.ndarray, u: int) -> ReqBatch:
    """Fixed-mode column decode of a (k, req_nbytes(u)) record matrix
    that ALREADY lives in memory as rows — the zero-copy shm path: ring
    slots hold raw record matrices, the store owner decodes the merged
    matrix once, no intermediate ``bytes`` round-trip
    (``decode_request_batch`` is this plus the byte-stream framing)."""
    if M.shape[1] != req_nbytes(u):
        raise ValueError(f"record matrix is {M.shape[1]} bytes/row, "
                         f"want {req_nbytes(u)} for u={u}")
    b = _decode_req_heads(M)
    b.value = np.ascontiguousarray(M[:, _REQ.size:]).view(
        np.int32).reshape(M.shape[0], u)
    return b


def _decode_req_heads(M: np.ndarray) -> ReqBatch:
    check_request_matrix(M)
    return ReqBatch(
        kind=M[:, 2].copy(), req_id=_get_col(M, 4, "<u4"),
        tenant=_get_col(M, 8, "<u2"), trace=_get_col(M, 10, "<u2"),
        deadline_us=_get_col(M, 12, "<u4"), key=_get_col(M, 16, "<i8"))


@dataclasses.dataclass
class RspBatch:
    """Columnar view of k single-op responses (same contract as
    ``ReqBatch``: byte-identical record stream, shared heap blob)."""

    status: np.ndarray          # (k,) uint8 — S_*
    reason: np.ndarray          # (k,) uint8 — R_*
    req_id: np.ndarray          # (k,) uint32
    found: np.ndarray           # (k,) bool
    has_uid: np.ndarray         # (k,) bool
    step: np.ndarray            # (k,) int32
    retry_after_us: np.ndarray  # (k,) uint32
    uid: np.ndarray             # (k, 2) int32 — (hi, lo)
    value: Optional[np.ndarray] = None  # fixed mode: (k, u) int32
    vlen: Optional[np.ndarray] = None   # heap mode: (k,) int64; -1 = none
    voff: Optional[np.ndarray] = None
    blob: bytes = b""

    def __len__(self) -> int:
        return int(self.status.shape[0])

    def row_data(self, i: int) -> Optional[bytes]:
        if self.vlen is None or self.vlen[i] < 0:
            return None
        o = int(self.voff[i]) if self.voff is not None else 0
        return self.blob[o: o + int(self.vlen[i])]

    def select(self, idx) -> "RspBatch":
        idx = np.asarray(idx)
        return RspBatch(
            status=self.status[idx], reason=self.reason[idx],
            req_id=self.req_id[idx], found=self.found[idx],
            has_uid=self.has_uid[idx], step=self.step[idx],
            retry_after_us=self.retry_after_us[idx], uid=self.uid[idx],
            value=None if self.value is None else self.value[idx],
            vlen=None if self.vlen is None else self.vlen[idx],
            voff=None if self.voff is None else self.voff[idx],
            blob=self.blob)

    def to_responses(self) -> List[Response]:
        """Struct rows — mirrors decode_response's nulling rules (value
        and data are only surfaced on S_OK)."""
        out = []
        for i in range(len(self)):
            st = int(self.status[i])
            value = data = None
            if st == S_OK:
                if self.value is not None:
                    value = self.value[i].tolist()
                data = self.row_data(i)
            out.append(Response(
                status=st, reason=int(self.reason[i]),
                req_id=int(self.req_id[i]), found=bool(self.found[i]),
                step=int(self.step[i]),
                retry_after_us=int(self.retry_after_us[i]),
                uid=((int(self.uid[i, 0]), int(self.uid[i, 1]))
                     if self.has_uid[i] else None),
                value=value, data=data))
        return out

    @staticmethod
    def from_responses(rsps: List[Response], u: int,
                       vbytes: int = 0) -> "RspBatch":
        k = len(rsps)
        b = RspBatch(
            status=np.array([r.status for r in rsps], np.uint8),
            reason=np.array([r.reason for r in rsps], np.uint8),
            req_id=np.array([r.req_id for r in rsps], np.uint32),
            found=np.array([r.found for r in rsps], bool),
            has_uid=np.array([r.uid is not None for r in rsps], bool),
            step=np.array([r.step for r in rsps], np.int32),
            retry_after_us=np.array([r.retry_after_us for r in rsps],
                                    np.uint32),
            uid=np.array([(r.uid if r.uid is not None else (0, 0))
                          for r in rsps], np.int32).reshape(k, 2))
        if vbytes:
            vlen = np.full(k, -1, np.int64)
            voff = np.zeros(k, np.int64)
            parts = []
            off = 0
            for i, r in enumerate(rsps):
                if r.data is not None and r.status == S_OK:
                    raw = bytes(r.data)
                    vlen[i] = len(raw)
                    voff[i] = off
                    parts.append(raw)
                    off += len(raw)
            b.vlen, b.voff, b.blob = vlen, voff, b"".join(parts)
        else:
            val = np.zeros((k, u), np.int32)
            for i, r in enumerate(rsps):
                if r.value is not None:
                    v = np.asarray(list(r.value), np.int32)
                    val[i, : v.shape[0]] = v
            b.value = val
        return b


def encode_response_batch(b: RspBatch, u: int, vbytes: int = 0) -> bytes:
    """k responses -> one record stream, byte-identical to concatenating
    ``encode_response`` over the rows."""
    k = len(b)
    status = np.asarray(b.status, np.uint8)

    def heads(width: int) -> np.ndarray:
        M = np.zeros((k, width), np.uint8)
        _put_col(M, 0, np.full(k, RSP_MAGIC), "<u2")
        M[:, 2] = status
        M[:, 3] = np.asarray(b.reason, np.uint8)
        _put_col(M, 4, b.req_id, "<u4")
        M[:, 8] = np.asarray(b.found, bool).astype(np.uint8)
        M[:, 9] = np.asarray(b.has_uid, bool).astype(np.uint8)
        _put_col(M, 12, b.step, "<i4")
        _put_col(M, 16, b.retry_after_us, "<u4")
        _put_col(M, 20, np.asarray(b.uid, np.int32)[:, 0], "<i4")
        _put_col(M, 24, np.asarray(b.uid, np.int32)[:, 1], "<i4")
        return M

    if vbytes:
        vlen = (np.asarray(b.vlen, np.int64) if b.vlen is not None
                else np.full(k, -1, np.int64))
        # the struct codec's rule: only S_OK rows carry a payload tail
        vlen = np.where(status == S_OK, vlen, -1)
        if k and int(vlen.max(initial=-1)) > vbytes:
            raise ValueError(f"payload is {int(vlen.max())} bytes > "
                             f"max_value_bytes={vbytes}")
        plen = np.maximum(vlen, 0)
        recs = _RSP.size + 4 + plen
        offs = np.concatenate(([0], np.cumsum(recs)[:-1])) if k \
            else np.zeros(0, np.int64)
        out = np.zeros(int(recs.sum()), np.uint8)
        H = heads(_RSP.size + 4)
        _put_col(H, _RSP.size,
                 np.where(vlen < 0, _DLEN_NONE, vlen).astype(np.uint32),
                 "<u4")
        _codec.scatter_records(out, offs, H)
        voff = (np.asarray(b.voff, np.int64) if b.voff is not None
                else np.zeros(k, np.int64))
        src = _codec.ragged_gather(np.frombuffer(b.blob, np.uint8),
                                   voff, plen)
        _codec.ragged_scatter(out, offs + _RSP.size + 4, plen, src)
        return out.tobytes()
    M = heads(rsp_nbytes(u))
    val = b.value if b.value is not None else np.zeros((k, u), np.int32)
    val = np.asarray(val, np.int32)
    if val.shape != (k, u):
        raise ValueError(f"value matrix shape {val.shape} != ({k}, {u})")
    if u:
        M[:, _RSP.size:] = np.ascontiguousarray(val).view(
            np.uint8).reshape(k, 4 * u)
    return M.tobytes()


def decode_response_batch(buf: bytes, u: int, vbytes: int = 0) -> RspBatch:
    """Inverse of ``encode_response_batch`` (torn/garbage triage rules
    match the struct decoder, batch-wide)."""
    buf = bytes(buf)
    if vbytes:
        offs, dls = [], []
        off, hsz = 0, _RSP.size
        while off < len(buf):
            if off + hsz + 4 > len(buf):
                raise ValueError(
                    f"torn batch: truncated response header at byte {off} "
                    f"({len(buf) - off} trailing bytes)")
            (dlen,) = struct.unpack_from("<I", buf, off + hsz)
            if dlen == _DLEN_NONE:
                dls.append(-1)
                end = off + hsz + 4
            else:
                if dlen > vbytes:
                    raise ValueError(f"payload tail declares {dlen} bytes "
                                     f"(max {vbytes})")
                if off + hsz + 4 + dlen > len(buf):
                    raise ValueError(
                        f"torn batch: payload tail at byte {off} wants "
                        f"{dlen} bytes, have {len(buf) - off - hsz - 4}")
                dls.append(dlen)
                end = off + hsz + 4 + dlen
            offs.append(off)
            off = end
        k = len(offs)
        offs_a = np.asarray(offs, np.int64)
        M = _codec.gather_records(np.frombuffer(buf, np.uint8), offs_a,
                                  hsz + 4)
        b = _decode_rsp_heads(M)
        b.vlen = np.asarray(dls, np.int64)
        b.voff = offs_a + hsz + 4
        b.blob = buf
        return b
    stride = rsp_nbytes(u)
    if len(buf) % stride:
        raise ValueError(
            f"torn batch: {len(buf)} bytes is not a whole number of "
            f"{stride}-byte responses ({len(buf) % stride} trailing bytes)")
    k = len(buf) // stride
    M = np.frombuffer(buf, np.uint8).reshape(k, stride)
    b = _decode_rsp_heads(M)
    b.value = np.ascontiguousarray(M[:, _RSP.size:]).view(
        np.int32).reshape(k, u)
    return b


def _decode_rsp_heads(M: np.ndarray) -> RspBatch:
    k = M.shape[0]
    magic = _get_col(M, 0, "<u2")
    if k and (magic != RSP_MAGIC).any():
        i = int(np.nonzero(magic != RSP_MAGIC)[0][0])
        raise ValueError(f"bad response magic 0x{int(magic[i]):04x} "
                         f"at row {i}")
    uid = np.stack([_get_col(M, 20, "<i4"), _get_col(M, 24, "<i4")],
                   axis=1) if k else np.zeros((0, 2), np.int32)
    return RspBatch(
        status=M[:, 2].copy(), reason=M[:, 3].copy(),
        req_id=_get_col(M, 4, "<u4"), found=M[:, 8] != 0,
        has_uid=M[:, 9] != 0, step=_get_col(M, 12, "<i4"),
        retry_after_us=_get_col(M, 16, "<u4"), uid=uid)


# -- kind/magic dispatch (one decoder entry per direction) -------------------

def encode_any_request(req, u: int, vbytes: int = 0) -> bytes:
    if isinstance(req, ReadRequest):
        return encode_read_request(req)
    return encode_request(req, u, vbytes)


def decode_any_request(buf: bytes, u: int, vbytes: int = 0):
    """Decode either request layout off its magic word."""
    buf = bytes(buf)
    if len(buf) >= 2:
        (magic,) = struct.unpack_from("<H", buf, 0)
        if magic == RREQ_MAGIC:
            return decode_read_request(buf)
    return decode_request(buf, u, vbytes)


def encode_any_response(rsp, u: int, vbytes: int = 0) -> bytes:
    if isinstance(rsp, ReadResponse):
        return encode_read_response(rsp, u, vbytes)
    return encode_response(rsp, u, vbytes)


def decode_any_response(buf: bytes, u: int, vbytes: int = 0):
    buf = bytes(buf)
    if len(buf) >= 2:
        (magic,) = struct.unpack_from("<H", buf, 0)
        if magic == RRSP_MAGIC:
            return decode_read_response(buf, u, vbytes)
    return decode_response(buf, u, vbytes)
