"""State-table snapshot / restore (SURVEY.md §5.4).

The reference has no durability story (Hermes is an in-memory store; the
paper scopes persistence out), so snapshots here serve operational needs,
not fidelity: seeding test bootstraps, capturing a run for offline
inspection, and fast-forwarding bench warmup.  A snapshot is a plain
``.npz`` of the FastState (or ReplicaState) pytree plus the host-side
control state (step index, epoch, live mask, frozen flags).

Restore semantics: a snapshot taken mid-protocol freezes in-flight writes
exactly as they were; resuming with the same config continues the run
deterministically (the op streams are derived from the config seed).
"""

from __future__ import annotations

import dataclasses
import json

import jax
import numpy as np


def _flatten(tree, prefix=""):
    out = {}
    if hasattr(tree, "_asdict"):
        for f, v in tree._asdict().items():
            out.update(_flatten(v, f"{prefix}{f}."))
    else:
        out[prefix[:-1]] = np.asarray(jax.device_get(tree))
    return out


def save(path: str, rt) -> None:
    """Snapshot a FastRuntime / Runtime (state pytree + host control), or a
    client ``KVS`` — which additionally captures the injected stream arrays
    and, in sparse-key mode, the KeyIndex (buckets + reverse map), so a
    restored KVS resolves the same client keys to the same dense slots.
    A KVS must be QUIESCENT (no queued or in-flight client ops): futures
    are host objects and cannot be serialized meaningfully."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()  # pipelined mode: land the deferred round's futures
        if kvs._inflight or kvs._queued_slots or kvs._bat:
            raise ValueError(
                "snapshot requires a quiescent KVS: resolve in-flight ops "
                "and active batches (run step()/run_until/run_batch) "
                "before saving"
            )
    if hasattr(rt, "flush_pipeline"):
        # harvest in-flight ring rounds: the recorder (if any) must not be
        # missing completions the restored run would re-record
        rt.flush_pipeline()
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    arrays = _flatten(state, "state.")
    arrays["ctl.step_idx"] = np.int64(rt.step_idx)
    arrays["ctl.epoch"] = np.asarray(rt.epoch)
    arrays["ctl.live"] = np.asarray(rt.live)
    arrays["ctl.frozen"] = np.asarray(rt.frozen)
    if hasattr(rt, "_ver_base"):
        # FastRuntime version-rebase bookkeeping (runtime.rebase_versions):
        # a post-rebase snapshot must carry the cumulative per-key version
        # deltas, or completions recorded after a restore would be
        # re-anchored from the wrong era and silently corrupt checker
        # histories.  quiesce/rebases/_next_rebase_at ride along so the
        # restored runtime resumes the exact rebase posture.
        # never-rebased runtimes write a ZERO-LENGTH sentinel, not n_keys of
        # int64 zeros (~8 MB of dead payload per snapshot at the 1M-key
        # shape); load() keys on the shape (round-5 advice #2)
        arrays["ctl.ver_base"] = (
            np.zeros(0, np.int64) if rt._ver_base is None
            else np.asarray(rt._ver_base)
        )
        arrays["ctl.rebases"] = np.int64(rt.rebases)
        arrays["ctl.next_rebase_at"] = np.int64(rt._next_rebase_at)
        arrays["ctl.quiesce"] = np.bool_(rt.quiesce)
    arrays["meta.cfg"] = np.frombuffer(
        json.dumps(dataclasses.asdict(rt.cfg)).encode(), dtype=np.uint8
    )
    if kvs is not None:
        arrays["kvs.op"] = kvs._op
        arrays["kvs.key"] = kvs._key
        arrays["kvs.uval"] = kvs._uval
        if kvs.index is not None:
            idx = kvs.index
            arrays["kvs.index.bucket_key"] = idx._bucket_key
            arrays["kvs.index.bucket_slot"] = idx._bucket_slot
            arrays["kvs.index.rev"] = idx._rev
            arrays["kvs.index.n_used"] = np.int64(idx.n_used)
    np.savez_compressed(path, **arrays)


def _leaf_keys(template, prefix=""):
    """Archive key names a restore of ``template`` will read (mirror of
    _flatten / _rebuild traversal)."""
    if hasattr(template, "_asdict"):
        out = []
        for f, v in template._asdict().items():
            out.extend(_leaf_keys(v, f"{prefix}{f}."))
        return out
    return [prefix[:-1]]


def _rebuild(template, arrays, prefix=""):
    if hasattr(template, "_asdict"):
        kw = {
            f: _rebuild(v, arrays, f"{prefix}{f}.")
            for f, v in template._asdict().items()
        }
        return type(template)(**kw)
    import jax.numpy as jnp

    return jnp.asarray(arrays[prefix[:-1]])


def load(path: str, rt) -> None:
    """Restore a snapshot into a runtime (or KVS) built with the SAME
    config.  Restoring a KVS snapshot re-installs the stream arrays and
    the KeyIndex, so client keys resolve to their saved dense slots.

    ALL validation (config match, KVS-mode match both directions, target
    quiescence) happens before any mutation: a rejected load leaves the
    target exactly as it was — except that the target's in-flight
    pipeline (round-8 harvest ring / deferred KVS round) is drained
    first, landing the OLD run's completions in the OLD run's version
    era; without this they would be harvested after the restore and
    re-anchored/recorded into the restored history."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()
    if hasattr(rt, "flush_pipeline"):
        rt.flush_pipeline()
    z = np.load(path)
    # -- validate everything first -----------------------------------------
    saved_cfg = json.loads(bytes(z["meta.cfg"]).decode())
    cur_cfg = dataclasses.asdict(rt.cfg)
    if saved_cfg != cur_cfg:
        raise ValueError(
            "snapshot config mismatch; rebuild the runtime with the saved "
            f"config (saved={saved_cfg}, current={cur_cfg})"
        )
    if kvs is not None:
        if "kvs.op" not in z:
            raise ValueError("snapshot was not taken from a KVS")
        if kvs._inflight or kvs._queued_slots or kvs._bat:
            raise ValueError(
                "load requires a quiescent KVS target: restoring over "
                "queued/in-flight client ops or active batches would "
                "strand their futures"
            )
        sparse_snap = "kvs.index.bucket_key" in z
        if kvs.index is not None and not sparse_snap:
            raise ValueError("snapshot has no KeyIndex (dense-key run); "
                             "build the KVS with sparse_keys=False")
        if kvs.index is None and sparse_snap:
            raise ValueError(
                "snapshot carries a KeyIndex (sparse-key run); build the "
                "KVS with sparse_keys=True or the client-key mapping is lost"
            )
    # every key the mutation phase will read must exist NOW: a truncated or
    # corrupt archive must reject before anything is overwritten
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    needed = _leaf_keys(state, "state.")
    needed += ["ctl.step_idx", "ctl.epoch", "ctl.live", "ctl.frozen"]
    if hasattr(rt, "_ver_base") and "ctl.ver_base" not in z:
        # Backstop, not a live migration path: genuinely old (pre-round-5)
        # archives already fail the config-equality check above (the config
        # dataclass gained fields), so an archive reaching here without
        # ctl.ver_base is either truncated or hand-edited.
        if any(k in z for k in ("ctl.rebases", "ctl.next_rebase_at",
                                "ctl.quiesce")):
            # other bookkeeping entries present without ver_base: a
            # TRUNCATED round-5 archive — reject
            raise ValueError(
                "snapshot archive is incomplete (truncated/corrupt?): "
                "rebase bookkeeping present but ctl.ver_base missing"
            )
        # pre-round-5 archive without rebase bookkeeping: only safe to
        # restore into a runtime that never rebased (nothing to reset);
        # otherwise the target's stale _ver_base would re-anchor restored-
        # era completions with deltas from the wrong era
        if rt._ver_base is not None:
            raise ValueError(
                "snapshot has no rebase bookkeeping (ctl.ver_base) but the "
                "target runtime has already rebased; restoring would "
                "re-anchor recorded versions from the wrong era — use a "
                "fresh runtime"
            )
    elif hasattr(rt, "_ver_base"):
        # archive carries rebase bookkeeping: all four entries must exist
        # before mutation (a truncation between them must reject cleanly)
        needed += ["ctl.rebases", "ctl.next_rebase_at", "ctl.quiesce"]
    if kvs is not None:
        needed += ["kvs.op", "kvs.key", "kvs.uval"]
        if kvs.index is not None:
            needed += ["kvs.index.bucket_key", "kvs.index.bucket_slot",
                       "kvs.index.rev", "kvs.index.n_used"]
    missing = [k for k in needed if k not in z]
    if missing:
        raise ValueError(
            f"snapshot archive is incomplete (truncated/corrupt?): missing "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    # -- mutate ------------------------------------------------------------
    if kvs is not None:
        kvs._op[:] = z["kvs.op"]
        kvs._key[:] = z["kvs.key"]
        kvs._uval[:] = z["kvs.uval"]
        kvs._dirty = True
        if kvs.index is not None:
            idx = kvs.index
            idx._bucket_key[:] = z["kvs.index.bucket_key"]
            idx._bucket_slot[:] = z["kvs.index.bucket_slot"]
            idx._rev[:] = z["kvs.index.rev"]
            idx.n_used = int(z["kvs.index.n_used"])
    restored = _rebuild(state, z, "state.")
    if hasattr(rt, "fs"):
        rt.fs = restored
    else:
        rt.rs = restored
    rt.step_idx = int(z["ctl.step_idx"])  # also re-seeds the device counter
    rt.epoch[:] = z["ctl.epoch"]
    rt.live[:] = z["ctl.live"]
    rt.frozen[:] = z["ctl.frozen"]
    # the in-place row writes above bypass the membership hooks, so the
    # cached device-side ctl (round-8) must be re-uploaded explicitly
    rt._ctl_dirty = True
    if hasattr(rt, "_ver_base") and "ctl.ver_base" in z:
        # zero-length = the never-rebased sentinel (round-6 archives); a
        # full-length all-zeros array is the pre-round-6 encoding of the
        # same fact and still maps to None
        vb = np.asarray(z["ctl.ver_base"]).astype(np.int64)
        rt._ver_base = vb.copy() if vb.size and vb.any() else None
        rt.rebases = int(z["ctl.rebases"])
        rt._next_rebase_at = int(z["ctl.next_rebase_at"])
        rt.quiesce = bool(z["ctl.quiesce"])
