"""State-table snapshot / restore (SURVEY.md §5.4).

The reference has no durability story (Hermes is an in-memory store; the
paper scopes persistence out), so snapshots here serve operational needs,
not fidelity: seeding test bootstraps, capturing a run for offline
inspection, and fast-forwarding bench warmup.  A snapshot is a plain
``.npz`` of the FastState (or ReplicaState) pytree plus the host-side
control state (step index, epoch, live mask, frozen flags).

Restore semantics: a snapshot taken mid-protocol freezes in-flight writes
exactly as they were; resuming with the same config continues the run
deterministically (the op streams are derived from the config seed).
"""

from __future__ import annotations

import dataclasses
import json

import jax
import numpy as np


def _flatten(tree, prefix=""):
    out = {}
    if hasattr(tree, "_asdict"):
        for f, v in tree._asdict().items():
            out.update(_flatten(v, f"{prefix}{f}."))
    else:
        out[prefix[:-1]] = np.asarray(jax.device_get(tree))
    return out


def save(path: str, rt) -> None:
    """Snapshot a FastRuntime / Runtime (state pytree + host control)."""
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    arrays = _flatten(state, "state.")
    arrays["ctl.step_idx"] = np.int64(rt.step_idx)
    arrays["ctl.epoch"] = np.asarray(rt.epoch)
    arrays["ctl.live"] = np.asarray(rt.live)
    arrays["ctl.frozen"] = np.asarray(rt.frozen)
    arrays["meta.cfg"] = np.frombuffer(
        json.dumps(dataclasses.asdict(rt.cfg)).encode(), dtype=np.uint8
    )
    np.savez_compressed(path, **arrays)


def _rebuild(template, arrays, prefix=""):
    if hasattr(template, "_asdict"):
        kw = {
            f: _rebuild(v, arrays, f"{prefix}{f}.")
            for f, v in template._asdict().items()
        }
        return type(template)(**kw)
    import jax.numpy as jnp

    return jnp.asarray(arrays[prefix[:-1]])


def load(path: str, rt) -> None:
    """Restore a snapshot into a runtime built with the SAME config."""
    z = np.load(path)
    saved_cfg = json.loads(bytes(z["meta.cfg"]).decode())
    cur_cfg = dataclasses.asdict(rt.cfg)
    if saved_cfg != cur_cfg:
        raise ValueError(
            "snapshot config mismatch; rebuild the runtime with the saved "
            f"config (saved={saved_cfg}, current={cur_cfg})"
        )
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    restored = _rebuild(state, z, "state.")
    if hasattr(rt, "fs"):
        rt.fs = restored
    else:
        rt.rs = restored
    rt.step_idx = int(z["ctl.step_idx"])
    rt.epoch[:] = z["ctl.epoch"]
    rt.live[:] = z["ctl.live"]
    rt.frozen[:] = z["ctl.frozen"]
