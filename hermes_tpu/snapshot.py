"""State-table snapshot / restore (SURVEY.md §5.4).

The reference has no durability story (Hermes is an in-memory store; the
paper scopes persistence out), so snapshots here serve operational needs,
not fidelity: seeding test bootstraps, capturing a run for offline
inspection, and fast-forwarding bench warmup.  A snapshot is a plain
``.npz`` of the FastState (or ReplicaState) pytree plus the host-side
control state (step index, epoch, live mask, frozen flags).

Restore semantics: a snapshot taken mid-protocol freezes in-flight writes
exactly as they were; resuming with the same config continues the run
deterministically (the op streams are derived from the config seed).

Crash consistency (round-9, chaos & recovery): ``save`` writes the archive
to a temp file and ``os.replace``s it into place — a crash mid-save leaves
the previous snapshot intact, never a torn one — and embeds a checksummed
MANIFEST (format version, config fingerprint, step, flushed ring depth,
per-array sha256).  ``load`` verifies the manifest before any mutation: a
bit-rotted or hand-edited array rejects loudly ("torn"), a missing array
flows to the targeted incompleteness errors below, and a config
fingerprint mismatch is reported before the full config diff.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import jax
import numpy as np

MANIFEST_KEY = "meta.manifest"
MANIFEST_VERSION = 1


def config_fingerprint(cfg) -> str:
    """Stable sha256 of the run config (the manifest's identity check)."""
    return hashlib.sha256(
        json.dumps(dataclasses.asdict(cfg), sort_keys=True).encode()
    ).hexdigest()


def _array_sha256(a) -> str:
    a = np.ascontiguousarray(a)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def read_manifest(path: str) -> dict:
    """The snapshot's manifest dict (no state arrays materialized beyond
    it); raises ValueError on archives without one."""
    with np.load(path) as z:
        if MANIFEST_KEY not in z:
            raise ValueError(
                "snapshot has no manifest (pre-round-9 or truncated "
                "archive); refusing to trust unverifiable state")
        return json.loads(bytes(z[MANIFEST_KEY]).decode())


def _verify_npz(z) -> dict:
    """Manifest + per-array checksum verification over an OPEN npz: a
    bit-rotted / hand-edited / undeclared member rejects loudly; a MISSING
    member is left to the caller's targeted checks.  Returns the manifest."""
    if MANIFEST_KEY not in z:
        raise ValueError(
            "snapshot has no manifest (pre-round-9 or truncated archive); "
            "refusing to restore unverifiable state")
    manifest = json.loads(bytes(z[MANIFEST_KEY]).decode())
    if manifest.get("version") != MANIFEST_VERSION:
        raise ValueError(
            f"snapshot manifest version {manifest.get('version')} != "
            f"{MANIFEST_VERSION}; archive written by an incompatible build")
    declared = manifest.get("arrays", {})
    for k in z.files:
        if k == MANIFEST_KEY:
            continue
        if k not in declared:
            raise ValueError(
                f"snapshot archive carries undeclared array {k!r} "
                "(corrupt or hand-edited?)")
        if _array_sha256(z[k]) != declared[k]:
            raise ValueError(
                f"snapshot checksum mismatch on {k!r} (torn or corrupt "
                "archive); refusing to restore")
    return manifest


def verify_archive(path: str, cfg=None) -> dict:
    """Full crash-consistency verification WITHOUT mutation: manifest +
    every array checksum (+ config fingerprint when ``cfg`` is given) —
    the ``load`` gate as a standalone check.  chaos.recovery runs it
    before trusting a snapshot for crash restore.  Returns the manifest."""
    with np.load(path) as z:
        manifest = _verify_npz(z)
    if cfg is not None and manifest.get("config_sha256") != config_fingerprint(cfg):
        raise ValueError(
            "snapshot config fingerprint mismatch (manifest "
            f"{manifest.get('config_sha256', '?')[:12]}.. vs config "
            f"{config_fingerprint(cfg)[:12]}..)")
    return manifest


def _flatten(tree, prefix=""):
    out = {}
    if hasattr(tree, "_asdict"):
        for f, v in tree._asdict().items():
            out.update(_flatten(v, f"{prefix}{f}."))
    else:
        out[prefix[:-1]] = np.asarray(jax.device_get(tree))
    return out


def save(path: str, rt) -> None:
    """Snapshot a FastRuntime / Runtime (state pytree + host control), or a
    client ``KVS`` — which additionally captures the injected stream arrays
    and, in sparse-key mode, the KeyIndex (buckets + reverse map), so a
    restored KVS resolves the same client keys to the same dense slots.
    A KVS must be QUIESCENT (no queued or in-flight client ops): futures
    are host objects and cannot be serialized meaningfully."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()  # pipelined mode: land the deferred round's futures
        if kvs._inflight or kvs._queued_slots or kvs._bat:
            # the quiescence trap, made loud WITH the evidence (round-9):
            # futures are host objects — serializing around them would
            # strand every pending client op in the restored run
            n_inflight = len(kvs._inflight)
            n_queued = sum(len(kvs._queues[k]) for k in kvs._queued_slots)
            n_batch = sum(len(b["bf"]) - b["bf"].done_count()
                          for b in kvs._bat.values())
            raise ValueError(
                f"snapshot requires a quiescent KVS: {n_inflight} op(s) in "
                f"flight, {n_queued} queued, {n_batch} unresolved batch "
                f"op(s) across {len(kvs._bat)} active batch(es); resolve "
                "them (run step()/run_until/run_batch) before saving"
            )
    ring_flushed = 0
    if hasattr(rt, "flush_pipeline"):
        # harvest in-flight ring rounds: the recorder (if any) must not be
        # missing completions the restored run would re-record
        ring_flushed = rt.flush_pipeline()
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    arrays = _flatten(state, "state.")
    arrays["ctl.step_idx"] = np.int64(rt.step_idx)
    arrays["ctl.epoch"] = np.asarray(rt.epoch)
    arrays["ctl.live"] = np.asarray(rt.live)
    arrays["ctl.frozen"] = np.asarray(rt.frozen)
    if hasattr(rt, "_ver_base"):
        # FastRuntime version-rebase bookkeeping (runtime.rebase_versions):
        # a post-rebase snapshot must carry the cumulative per-key version
        # deltas, or completions recorded after a restore would be
        # re-anchored from the wrong era and silently corrupt checker
        # histories.  quiesce/rebases/_next_rebase_at ride along so the
        # restored runtime resumes the exact rebase posture.
        # never-rebased runtimes write a ZERO-LENGTH sentinel, not n_keys of
        # int64 zeros (~8 MB of dead payload per snapshot at the 1M-key
        # shape); load() keys on the shape (round-5 advice #2)
        arrays["ctl.ver_base"] = (
            np.zeros(0, np.int64) if rt._ver_base is None
            else np.asarray(rt._ver_base)
        )
        arrays["ctl.rebases"] = np.int64(rt.rebases)
        arrays["ctl.next_rebase_at"] = np.int64(rt._next_rebase_at)
        arrays["ctl.quiesce"] = np.bool_(rt.quiesce)
    arrays["meta.cfg"] = np.frombuffer(
        json.dumps(dataclasses.asdict(rt.cfg)).encode(), dtype=np.uint8
    )
    if kvs is not None:
        arrays["kvs.op"] = kvs._op
        arrays["kvs.key"] = kvs._key
        arrays["kvs.uval"] = kvs._uval
        if kvs.index is not None:
            idx = kvs.index
            arrays["kvs.index.bucket_key"] = idx._bucket_key
            arrays["kvs.index.bucket_slot"] = idx._bucket_slot
            arrays["kvs.index.rev"] = idx._rev
            arrays["kvs.index.n_used"] = np.int64(idx.n_used)
    # -- checksummed manifest + tmp/rename (crash consistency, round-9) ----
    manifest = dict(
        version=MANIFEST_VERSION,
        config_sha256=config_fingerprint(rt.cfg),
        step=int(rt.step_idx),
        pipeline_depth=int(rt.cfg.pipeline_depth),
        ring_flushed=int(ring_flushed),
        arrays={k: _array_sha256(v) for k, v in arrays.items()},
    )
    arrays[MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest, sort_keys=True).encode(), dtype=np.uint8)
    if not path.endswith(".npz"):
        path += ".npz"  # np.savez's suffix rule, applied before the rename
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: a crash mid-save never tears PATH
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _leaf_keys(template, prefix=""):
    """Archive key names a restore of ``template`` will read (mirror of
    _flatten / _rebuild traversal)."""
    if hasattr(template, "_asdict"):
        out = []
        for f, v in template._asdict().items():
            out.extend(_leaf_keys(v, f"{prefix}{f}."))
        return out
    return [prefix[:-1]]


def _rebuild(template, arrays, prefix=""):
    if hasattr(template, "_asdict"):
        kw = {
            f: _rebuild(v, arrays, f"{prefix}{f}.")
            for f, v in template._asdict().items()
        }
        return type(template)(**kw)
    import jax.numpy as jnp

    return jnp.asarray(arrays[prefix[:-1]])


def load(path: str, rt) -> None:
    """Restore a snapshot into a runtime (or KVS) built with the SAME
    config.  Restoring a KVS snapshot re-installs the stream arrays and
    the KeyIndex, so client keys resolve to their saved dense slots.

    ALL validation (config match, KVS-mode match both directions, target
    quiescence) happens before any mutation: a rejected load leaves the
    target exactly as it was — except that the target's in-flight
    pipeline (round-8 harvest ring / deferred KVS round) is drained
    first, landing the OLD run's completions in the OLD run's version
    era; without this they would be harvested after the restore and
    re-anchored/recorded into the restored history."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()
    if hasattr(rt, "flush_pipeline"):
        rt.flush_pipeline()
    z = np.load(path)
    # -- validate everything first -----------------------------------------
    # manifest gate (round-9): config fingerprint + per-array checksums.  A
    # bit-rotted / hand-edited / torn array rejects HERE, loudly, before
    # anything is overwritten; a MISSING array is left to the targeted
    # incompleteness checks below (they name what is missing and why it
    # matters).  Archives without a manifest predate round-9 and cannot be
    # verified — refuse them outright.
    manifest = _verify_npz(z)
    if manifest.get("config_sha256") != config_fingerprint(rt.cfg):
        raise ValueError(
            "snapshot config fingerprint mismatch (manifest "
            f"{manifest.get('config_sha256', '?')[:12]}.. vs runtime "
            f"{config_fingerprint(rt.cfg)[:12]}..); rebuild the runtime "
            "with the saved config")
    saved_cfg = json.loads(bytes(z["meta.cfg"]).decode())
    cur_cfg = dataclasses.asdict(rt.cfg)
    if saved_cfg != cur_cfg:
        raise ValueError(
            "snapshot config mismatch; rebuild the runtime with the saved "
            f"config (saved={saved_cfg}, current={cur_cfg})"
        )
    if kvs is not None:
        if "kvs.op" not in z:
            raise ValueError("snapshot was not taken from a KVS")
        if kvs._inflight or kvs._queued_slots or kvs._bat:
            raise ValueError(
                "load requires a quiescent KVS target: restoring over "
                "queued/in-flight client ops or active batches would "
                "strand their futures"
            )
        sparse_snap = "kvs.index.bucket_key" in z
        if kvs.index is not None and not sparse_snap:
            raise ValueError("snapshot has no KeyIndex (dense-key run); "
                             "build the KVS with sparse_keys=False")
        if kvs.index is None and sparse_snap:
            raise ValueError(
                "snapshot carries a KeyIndex (sparse-key run); build the "
                "KVS with sparse_keys=True or the client-key mapping is lost"
            )
    # every key the mutation phase will read must exist NOW: a truncated or
    # corrupt archive must reject before anything is overwritten
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    needed = _leaf_keys(state, "state.")
    needed += ["ctl.step_idx", "ctl.epoch", "ctl.live", "ctl.frozen"]
    if hasattr(rt, "_ver_base") and "ctl.ver_base" not in z:
        # Backstop, not a live migration path: genuinely old (pre-round-5)
        # archives already fail the config-equality check above (the config
        # dataclass gained fields), so an archive reaching here without
        # ctl.ver_base is either truncated or hand-edited.
        if any(k in z for k in ("ctl.rebases", "ctl.next_rebase_at",
                                "ctl.quiesce")):
            # other bookkeeping entries present without ver_base: a
            # TRUNCATED round-5 archive — reject
            raise ValueError(
                "snapshot archive is incomplete (truncated/corrupt?): "
                "rebase bookkeeping present but ctl.ver_base missing"
            )
        # pre-round-5 archive without rebase bookkeeping: only safe to
        # restore into a runtime that never rebased (nothing to reset);
        # otherwise the target's stale _ver_base would re-anchor restored-
        # era completions with deltas from the wrong era
        if rt._ver_base is not None:
            raise ValueError(
                "snapshot has no rebase bookkeeping (ctl.ver_base) but the "
                "target runtime has already rebased; restoring would "
                "re-anchor recorded versions from the wrong era — use a "
                "fresh runtime"
            )
    elif hasattr(rt, "_ver_base"):
        # archive carries rebase bookkeeping: all four entries must exist
        # before mutation (a truncation between them must reject cleanly)
        needed += ["ctl.rebases", "ctl.next_rebase_at", "ctl.quiesce"]
    if kvs is not None:
        needed += ["kvs.op", "kvs.key", "kvs.uval"]
        if kvs.index is not None:
            needed += ["kvs.index.bucket_key", "kvs.index.bucket_slot",
                       "kvs.index.rev", "kvs.index.n_used"]
    missing = [k for k in needed if k not in z]
    if missing:
        raise ValueError(
            f"snapshot archive is incomplete (truncated/corrupt?): missing "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    # -- mutate ------------------------------------------------------------
    if kvs is not None:
        kvs._op[:] = z["kvs.op"]
        kvs._key[:] = z["kvs.key"]
        kvs._uval[:] = z["kvs.uval"]
        kvs._dirty = True
        if kvs.index is not None:
            idx = kvs.index
            idx._bucket_key[:] = z["kvs.index.bucket_key"]
            idx._bucket_slot[:] = z["kvs.index.bucket_slot"]
            idx._rev[:] = z["kvs.index.rev"]
            idx.n_used = int(z["kvs.index.n_used"])
    restored = _rebuild(state, z, "state.")
    if hasattr(rt, "fs"):
        rt.fs = restored
    else:
        rt.rs = restored
    rt.step_idx = int(z["ctl.step_idx"])  # also re-seeds the device counter
    rt.epoch[:] = z["ctl.epoch"]
    rt.live[:] = z["ctl.live"]
    rt.frozen[:] = z["ctl.frozen"]
    # the in-place row writes above bypass the membership hooks, so the
    # cached device-side ctl (round-8) must be re-uploaded explicitly
    rt._ctl_dirty = True
    if hasattr(rt, "_age_ring"):
        # pre-restore suspect-age copies belong to the OLD run's round
        # numbering; a restored run must not feed them to the detector
        rt._age_ring.clear()
        rt.harvested_ages = None
    if hasattr(rt, "_ver_base") and "ctl.ver_base" in z:
        # zero-length = the never-rebased sentinel (round-6 archives); a
        # full-length all-zeros array is the pre-round-6 encoding of the
        # same fact and still maps to None
        vb = np.asarray(z["ctl.ver_base"]).astype(np.int64)
        rt._ver_base = vb.copy() if vb.size and vb.any() else None
        rt.rebases = int(z["ctl.rebases"])
        rt._next_rebase_at = int(z["ctl.next_rebase_at"])
        rt.quiesce = bool(z["ctl.quiesce"])
