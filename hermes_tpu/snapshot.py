"""State-table snapshot / restore (SURVEY.md §5.4).

The reference has no durability story (Hermes is an in-memory store; the
paper scopes persistence out), so snapshots here serve operational needs,
not fidelity: seeding test bootstraps, capturing a run for offline
inspection, and fast-forwarding bench warmup.  A snapshot is a plain
``.npz`` of the FastState (or ReplicaState) pytree plus the host-side
control state (step index, epoch, live mask, frozen flags).

Restore semantics: a snapshot taken mid-protocol freezes in-flight writes
exactly as they were; resuming with the same config continues the run
deterministically (the op streams are derived from the config seed).

Crash consistency (round-9, chaos & recovery): ``save`` writes the archive
to a temp file and ``os.replace``s it into place — a crash mid-save leaves
the previous snapshot intact, never a torn one — and embeds a checksummed
MANIFEST (format version, config fingerprint, step, flushed ring depth,
per-array sha256).  ``load`` verifies the manifest before any mutation: a
bit-rotted or hand-edited array rejects loudly ("torn"), a missing array
flows to the targeted incompleteness errors below, and a config
fingerprint mismatch is reported before the full config diff.

Scope (round-10, elastic operations): every manifest declares what the
archive HOLDS — ``scope: "full"`` (the whole state tree, a crash-recovery
archive) or ``scope: "range:[lo,hi)"`` (just the table rows of a dense
key-slot range, a migration transfer archive written by ``save_range``).
``load`` refuses a range-scoped archive outright: a migration transfer can
never be mistaken for crash-recovery state, however valid its checksums
are.  ``load_range`` enforces the inverse.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import jax
import numpy as np

MANIFEST_KEY = "meta.manifest"
MANIFEST_VERSION = 1


def config_fingerprint(cfg) -> str:
    """Stable sha256 of the run config (the manifest's identity check)."""
    return hashlib.sha256(
        json.dumps(dataclasses.asdict(cfg), sort_keys=True).encode()
    ).hexdigest()


def _array_sha256(a) -> str:
    a = np.ascontiguousarray(a)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def read_manifest(path: str) -> dict:
    """The snapshot's manifest dict (no state arrays materialized beyond
    it); raises ValueError on archives without one."""
    with np.load(path) as z:
        if MANIFEST_KEY not in z:
            raise ValueError(
                "snapshot has no manifest (pre-round-9 or truncated "
                "archive); refusing to trust unverifiable state")
        return json.loads(bytes(z[MANIFEST_KEY]).decode())


def _verify_npz(z) -> dict:
    """Manifest + per-array checksum verification over an OPEN npz: a
    bit-rotted / hand-edited / undeclared member rejects loudly; a MISSING
    member is left to the caller's targeted checks.  Returns the manifest."""
    if MANIFEST_KEY not in z:
        raise ValueError(
            "snapshot has no manifest (pre-round-9 or truncated archive); "
            "refusing to restore unverifiable state")
    manifest = json.loads(bytes(z[MANIFEST_KEY]).decode())
    if manifest.get("version") != MANIFEST_VERSION:
        raise ValueError(
            f"snapshot manifest version {manifest.get('version')} != "
            f"{MANIFEST_VERSION}; archive written by an incompatible build")
    declared = manifest.get("arrays", {})
    for k in z.files:
        if k == MANIFEST_KEY:
            continue
        if k not in declared:
            raise ValueError(
                f"snapshot archive carries undeclared array {k!r} "
                "(corrupt or hand-edited?)")
        if _array_sha256(z[k]) != declared[k]:
            raise ValueError(
                f"snapshot checksum mismatch on {k!r} (torn or corrupt "
                "archive); refusing to restore")
    return manifest


def verify_archive(path: str, cfg=None) -> dict:
    """Full crash-consistency verification WITHOUT mutation: manifest +
    every array checksum (+ config fingerprint when ``cfg`` is given) —
    the ``load`` gate as a standalone check.  chaos.recovery runs it
    before trusting a snapshot for crash restore.  Returns the manifest."""
    with np.load(path) as z:
        manifest = _verify_npz(z)
    if cfg is not None and manifest.get("config_sha256") != config_fingerprint(cfg):
        raise ValueError(
            "snapshot config fingerprint mismatch (manifest "
            f"{manifest.get('config_sha256', '?')[:12]}.. vs config "
            f"{config_fingerprint(cfg)[:12]}..)")
    return manifest


def _flatten(tree, prefix=""):
    out = {}
    if hasattr(tree, "_asdict"):
        for f, v in tree._asdict().items():
            out.update(_flatten(v, f"{prefix}{f}."))
    else:
        out[prefix[:-1]] = np.asarray(jax.device_get(tree))
    return out


def save(path: str, rt) -> None:
    """Snapshot a FastRuntime / Runtime (state pytree + host control), or a
    client ``KVS`` — which additionally captures the injected stream arrays
    and, in sparse-key mode, the KeyIndex (buckets + reverse map), so a
    restored KVS resolves the same client keys to the same dense slots.
    A KVS must be QUIESCENT (no queued or in-flight client ops): futures
    are host objects and cannot be serialized meaningfully."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()  # pipelined mode: land the deferred round's futures
        if kvs._inflight or kvs._queued_slots or kvs._bat:
            # the quiescence trap, made loud WITH the evidence (round-9):
            # futures are host objects — serializing around them would
            # strand every pending client op in the restored run
            n_inflight = len(kvs._inflight)
            n_queued = sum(len(kvs._queues[k]) for k in kvs._queued_slots)
            n_batch = sum(len(b["bf"]) - b["bf"].done_count()
                          for b in kvs._bat.values())
            raise ValueError(
                f"snapshot requires a quiescent KVS: {n_inflight} op(s) in "
                f"flight, {n_queued} queued, {n_batch} unresolved batch "
                f"op(s) across {len(kvs._bat)} active batch(es); resolve "
                "them (run step()/run_until/run_batch) before saving"
            )
    ring_flushed = 0
    if hasattr(rt, "flush_pipeline"):
        # harvest in-flight ring rounds: the recorder (if any) must not be
        # missing completions the restored run would re-record
        ring_flushed = rt.flush_pipeline()
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    arrays = _flatten(state, "state.")
    arrays["ctl.step_idx"] = np.int64(rt.step_idx)
    arrays["ctl.epoch"] = np.asarray(rt.epoch)
    arrays["ctl.live"] = np.asarray(rt.live)
    arrays["ctl.frozen"] = np.asarray(rt.frozen)
    if hasattr(rt, "_ver_base"):
        # FastRuntime version-rebase bookkeeping (runtime.rebase_versions):
        # a post-rebase snapshot must carry the cumulative per-key version
        # deltas, or completions recorded after a restore would be
        # re-anchored from the wrong era and silently corrupt checker
        # histories.  quiesce/rebases/_next_rebase_at ride along so the
        # restored runtime resumes the exact rebase posture.
        # never-rebased runtimes write a ZERO-LENGTH sentinel, not n_keys of
        # int64 zeros (~8 MB of dead payload per snapshot at the 1M-key
        # shape); load() keys on the shape (round-5 advice #2)
        arrays["ctl.ver_base"] = (
            np.zeros(0, np.int64) if rt._ver_base is None
            else np.asarray(rt._ver_base)
        )
        arrays["ctl.rebases"] = np.int64(rt.rebases)
        arrays["ctl.next_rebase_at"] = np.int64(rt._next_rebase_at)
        arrays["ctl.quiesce"] = np.bool_(rt.quiesce)
    arrays["meta.cfg"] = np.frombuffer(
        json.dumps(dataclasses.asdict(rt.cfg)).encode(), dtype=np.uint8
    )
    if kvs is not None:
        arrays["kvs.op"] = kvs._op
        arrays["kvs.key"] = kvs._key
        arrays["kvs.uval"] = kvs._uval
        if kvs.index is not None:
            idx = kvs.index
            arrays["kvs.index.bucket_key"] = idx._bucket_key
            arrays["kvs.index.bucket_slot"] = idx._bucket_slot
            arrays["kvs.index.rev"] = idx._rev
            arrays["kvs.index.n_used"] = np.int64(idx.n_used)
        if getattr(kvs, "heap", None) is not None:
            # value heap (round-17): the allocated log prefix + bump
            # cursor ride the same checksummed manifest as the table —
            # a torn heap blob rejects at load exactly like a torn bank
            h = kvs.heap
            arrays["kvs.heap.log"] = h._mirror[: h.used_bytes()].copy()
            arrays["kvs.heap.cursor"] = np.int64(h._cursor)
    # -- checksummed manifest + tmp/rename (crash consistency, round-9) ----
    manifest = dict(
        version=MANIFEST_VERSION,
        scope="full",
        config_sha256=config_fingerprint(rt.cfg),
        step=int(rt.step_idx),
        pipeline_depth=int(rt.cfg.pipeline_depth),
        ring_flushed=int(ring_flushed),
        arrays={k: _array_sha256(v) for k, v in arrays.items()},
    )
    _atomic_savez(path, arrays, manifest)
    wal = getattr(rt, "wal", None)
    if wal is not None:
        # round-22: the durable snapshot now covers everything committed
        # at or before rt.step_idx — sealed WAL segments whose every
        # record falls behind it are dead weight; drop them (the open
        # segment and any segment straddling the boundary stay, and
        # replay stays idempotent for records the snapshot re-covers)
        wal.truncate_to(int(rt.step_idx))


def _atomic_savez(path: str, arrays: dict, manifest: dict) -> None:
    """Embed the manifest and write tmp+fsync+rename (shared by ``save``
    and ``save_range``): a crash mid-save never tears PATH."""
    arrays = dict(arrays)
    arrays[MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest, sort_keys=True).encode(), dtype=np.uint8)
    if not path.endswith(".npz"):
        path += ".npz"  # np.savez's suffix rule, applied before the rename
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: a crash mid-save never tears PATH
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _leaf_keys(template, prefix=""):
    """Archive key names a restore of ``template`` will read (mirror of
    _flatten / _rebuild traversal)."""
    if hasattr(template, "_asdict"):
        out = []
        for f, v in template._asdict().items():
            out.extend(_leaf_keys(v, f"{prefix}{f}."))
        return out
    return [prefix[:-1]]


def _rebuild(template, arrays, prefix=""):
    if hasattr(template, "_asdict"):
        kw = {
            f: _rebuild(v, arrays, f"{prefix}{f}.")
            for f, v in template._asdict().items()
        }
        return type(template)(**kw)
    import jax.numpy as jnp

    return jnp.asarray(arrays[prefix[:-1]])


def load(path: str, rt) -> None:
    """Restore a snapshot into a runtime (or KVS) built with the SAME
    config.  Restoring a KVS snapshot re-installs the stream arrays and
    the KeyIndex, so client keys resolve to their saved dense slots.

    ALL validation (config match, KVS-mode match both directions, target
    quiescence) happens before any mutation: a rejected load leaves the
    target exactly as it was — except that the target's in-flight
    pipeline (round-8 harvest ring / deferred KVS round) is drained
    first, landing the OLD run's completions in the OLD run's version
    era; without this they would be harvested after the restore and
    re-anchored/recorded into the restored history."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()
    if hasattr(rt, "flush_pipeline"):
        rt.flush_pipeline()
    z = np.load(path)
    # -- validate everything first -----------------------------------------
    # manifest gate (round-9): config fingerprint + per-array checksums.  A
    # bit-rotted / hand-edited / torn array rejects HERE, loudly, before
    # anything is overwritten; a MISSING array is left to the targeted
    # incompleteness checks below (they name what is missing and why it
    # matters).  Archives without a manifest predate round-9 and cannot be
    # verified — refuse them outright.
    manifest = _verify_npz(z)
    scope = manifest.get("scope", "full")  # pre-round-10 archives are full
    if scope != "full":
        raise ValueError(
            f"snapshot is scope={scope!r} — a key-range migration transfer "
            "archive (snapshot.save_range), not full crash-recovery state; "
            "restoring it as a full snapshot would resurrect a runtime "
            "from a sliver of one table.  Range archives restore through "
            "snapshot.load_range / hermes_tpu.elastic.migrate_range")
    if manifest.get("config_sha256") != config_fingerprint(rt.cfg):
        raise ValueError(
            "snapshot config fingerprint mismatch (manifest "
            f"{manifest.get('config_sha256', '?')[:12]}.. vs runtime "
            f"{config_fingerprint(rt.cfg)[:12]}..); rebuild the runtime "
            "with the saved config")
    saved_cfg = json.loads(bytes(z["meta.cfg"]).decode())
    cur_cfg = dataclasses.asdict(rt.cfg)
    if saved_cfg != cur_cfg:
        raise ValueError(
            "snapshot config mismatch; rebuild the runtime with the saved "
            f"config (saved={saved_cfg}, current={cur_cfg})"
        )
    if kvs is not None:
        if "kvs.op" not in z:
            raise ValueError("snapshot was not taken from a KVS")
        if kvs._inflight or kvs._queued_slots or kvs._bat:
            raise ValueError(
                "load requires a quiescent KVS target: restoring over "
                "queued/in-flight client ops or active batches would "
                "strand their futures"
            )
        sparse_snap = "kvs.index.bucket_key" in z
        if kvs.index is not None and not sparse_snap:
            raise ValueError("snapshot has no KeyIndex (dense-key run); "
                             "build the KVS with sparse_keys=False")
        if kvs.index is None and sparse_snap:
            raise ValueError(
                "snapshot carries a KeyIndex (sparse-key run); build the "
                "KVS with sparse_keys=True or the client-key mapping is lost"
            )
    # every key the mutation phase will read must exist NOW: a truncated or
    # corrupt archive must reject before anything is overwritten
    state = rt.fs if hasattr(rt, "fs") else rt.rs
    needed = _leaf_keys(state, "state.")
    needed += ["ctl.step_idx", "ctl.epoch", "ctl.live", "ctl.frozen"]
    if hasattr(rt, "_ver_base") and "ctl.ver_base" not in z:
        # Backstop, not a live migration path: genuinely old (pre-round-5)
        # archives already fail the config-equality check above (the config
        # dataclass gained fields), so an archive reaching here without
        # ctl.ver_base is either truncated or hand-edited.
        if any(k in z for k in ("ctl.rebases", "ctl.next_rebase_at",
                                "ctl.quiesce")):
            # other bookkeeping entries present without ver_base: a
            # TRUNCATED round-5 archive — reject
            raise ValueError(
                "snapshot archive is incomplete (truncated/corrupt?): "
                "rebase bookkeeping present but ctl.ver_base missing"
            )
        # pre-round-5 archive without rebase bookkeeping: only safe to
        # restore into a runtime that never rebased (nothing to reset);
        # otherwise the target's stale _ver_base would re-anchor restored-
        # era completions with deltas from the wrong era
        if rt._ver_base is not None:
            raise ValueError(
                "snapshot has no rebase bookkeeping (ctl.ver_base) but the "
                "target runtime has already rebased; restoring would "
                "re-anchor recorded versions from the wrong era — use a "
                "fresh runtime"
            )
    elif hasattr(rt, "_ver_base"):
        # archive carries rebase bookkeeping: all four entries must exist
        # before mutation (a truncation between them must reject cleanly)
        needed += ["ctl.rebases", "ctl.next_rebase_at", "ctl.quiesce"]
    if kvs is not None:
        needed += ["kvs.op", "kvs.key", "kvs.uval"]
        if kvs.index is not None:
            needed += ["kvs.index.bucket_key", "kvs.index.bucket_slot",
                       "kvs.index.rev", "kvs.index.n_used"]
        if getattr(kvs, "heap", None) is not None:
            # heap-mode targets need the log (mode mismatches are already
            # caught by the config-fingerprint gate — max_value_bytes is
            # part of the config — so a missing member here means a
            # truncated archive)
            needed += ["kvs.heap.log", "kvs.heap.cursor"]
    missing = [k for k in needed if k not in z]
    if missing:
        raise ValueError(
            f"snapshot archive is incomplete (truncated/corrupt?): missing "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    # -- mutate ------------------------------------------------------------
    if kvs is not None:
        kvs._op[:] = z["kvs.op"]
        kvs._key[:] = z["kvs.key"]
        kvs._uval[:] = z["kvs.uval"]
        kvs._dirty = True
        if kvs.index is not None:
            idx = kvs.index
            idx._bucket_key[:] = z["kvs.index.bucket_key"]
            idx._bucket_slot[:] = z["kvs.index.bucket_slot"]
            idx._rev[:] = z["kvs.index.rev"]
            idx.n_used = int(z["kvs.index.n_used"])
        if getattr(kvs, "heap", None) is not None:
            h = kvs.heap
            log = np.asarray(z["kvs.heap.log"], np.uint8)
            h._mirror[:] = 0
            h._mirror[: log.shape[0]] = log
            h._cursor = int(z["kvs.heap.cursor"])
            h._dev = None  # device log re-syncs lazily from the mirror
            h._synced = 1
            # accounting restarts with the restored log: counters from
            # the target's pre-load life would blend two stores (a stale
            # live_bytes feeds the heap_util gauge until the next GC)
            h.appends = h.append_bytes = 0
            h.gc_runs = h.gc_reclaimed_bytes = 0
            h.live_bytes = 0
    restored = _rebuild(state, z, "state.")
    if hasattr(rt, "fs"):
        rt.fs = restored
    else:
        rt.rs = restored
    rt.step_idx = int(z["ctl.step_idx"])  # also re-seeds the device counter
    rt.epoch[:] = z["ctl.epoch"]
    rt.live[:] = z["ctl.live"]
    rt.frozen[:] = z["ctl.frozen"]
    # the in-place row writes above bypass the membership hooks, so the
    # cached device-side ctl (round-8) must be re-uploaded explicitly
    rt._ctl_dirty = True
    if hasattr(rt, "_age_ring"):
        # pre-restore suspect-age copies belong to the OLD run's round
        # numbering; a restored run must not feed them to the detector
        rt._age_ring.clear()
        rt.harvested_ages = None
    if hasattr(rt, "_ver_base") and "ctl.ver_base" in z:
        # zero-length = the never-rebased sentinel (round-6 archives); a
        # full-length all-zeros array is the pre-round-6 encoding of the
        # same fact and still maps to None
        vb = np.asarray(z["ctl.ver_base"]).astype(np.int64)
        rt._ver_base = vb.copy() if vb.size and vb.any() else None
        rt.rebases = int(z["ctl.rebases"])
        rt._next_rebase_at = int(z["ctl.next_rebase_at"])
        rt.quiesce = bool(z["ctl.quiesce"])


# --------------------------------------------------------------------------
# Range-scoped archives (round-10 elastic operations: key-range migration)
# --------------------------------------------------------------------------
#
# A migration moves the table rows of a dense slot range [lo, hi) between
# replica groups.  The transfer artifact is a snapshot in this module's
# format — tmp+rename, checksummed manifest — but scope-tagged so the full
# restore path can NEVER be offered one (and vice versa).  Host-side the
# bank rows travel as int32 words via the same byte order faststep's
# _bank_to_i32 defines on device.


# Round-17: the host byte<->word codec is ONE implementation
# (transport/codec.rows_to_words — the heap and the serving wire share
# it); these aliases keep this module's historical names working.
from hermes_tpu.transport.codec import rows_to_words as _rows_to_i32  # noqa: E402
from hermes_tpu.transport.codec import words_to_rows as _i32_to_rows  # noqa: E402


def _range_rows(rt, lo: int, hi: int):
    """(vpts (n,) int32, bank (n, 4*(2+V)) int8) of slots [lo, hi), taken
    from the lowest live unfrozen replica's table copy.  On the sharded
    engine every OTHER live unfrozen copy must be byte-identical over the
    range — the drained-range precondition, verified loudly rather than
    trusted (a range with in-flight coordination is not transferable)."""
    import jax.lax

    cfg = rt.cfg
    K, n = cfg.n_keys, hi - lo
    tbl = rt.fs.table
    if tbl.vpts.shape[0] == K:  # batched: one shared authoritative copy
        vpts = jax.lax.dynamic_slice_in_dim(tbl.vpts, lo, n)
        bank = jax.lax.dynamic_slice_in_dim(tbl.bank, lo, n)
        return (np.asarray(jax.device_get(vpts)),
                np.asarray(jax.device_get(bank)))
    live = int(rt.live[0])
    cands = [r for r in range(cfg.n_replicas)
             if (live >> r) & 1 and not rt.frozen[r]]
    if not cands:
        raise RuntimeError("save_range needs at least one live unfrozen "
                           "replica to donate the range rows")
    got = {}
    for r in cands:
        vpts = jax.lax.dynamic_slice_in_dim(tbl.vpts, r * K + lo, n)
        bank = jax.lax.dynamic_slice_in_dim(tbl.bank, r * K + lo, n)
        got[r] = (np.asarray(jax.device_get(vpts)),
                  np.asarray(jax.device_get(bank)))
    donor = cands[0]
    for r in cands[1:]:
        if not (np.array_equal(got[r][0], got[donor][0])
                and np.array_equal(got[r][1], got[donor][1])):
            raise RuntimeError(
                f"range [{lo}, {hi}) is not quiesced: replicas {donor} and "
                f"{r} disagree on its rows — drain the range (reject-new + "
                "flush in-flight) before snapshotting it")
    return got[donor]


def save_range(path: str, rt, lo: int, hi: int) -> dict:
    """Snapshot ONLY the table rows of dense slots ``[lo, hi)`` of a
    FastRuntime (or the runtime under a KVS facade) into a range-scoped
    archive — the transfer artifact of a live key-range migration
    (hermes_tpu/elastic).  The range must be DRAINED: in-flight pipeline
    rounds are flushed here, and on the sharded engine the live replicas'
    copies of the range are verified byte-identical.  Carries the range's
    cumulative version-rebase deltas (``ver_base``) so the destination can
    re-anchor recorded versions into the source's global version space.
    Returns the manifest.

    Value heap (round-17): when the facade is a heap-mode KVS, the
    range's live extents travel WITH the rows — per-row byte lengths
    (-1 = no extent) plus one concatenated blob, under the same
    checksummed manifest, so a migration moves the bytes the ref words
    name and the destination re-appends them into ITS log."""
    kvs = None
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        kvs, rt = rt, rt.rt
        kvs.flush()
    if not hasattr(rt, "fs"):
        raise NotImplementedError(
            "save_range reads the faststep table (FastRuntime/KVS); the "
            "phases Runtime has no elastic migration path")
    if not (0 <= lo < hi <= rt.cfg.n_keys):
        raise ValueError(f"range [{lo}, {hi}) outside [0, {rt.cfg.n_keys})")
    rt.flush_pipeline()
    vpts, bank = _range_rows(rt, lo, hi)
    vb = (rt._ver_base[lo:hi].copy() if rt._ver_base is not None
          else np.zeros(hi - lo, np.int64))
    arrays = {
        "range.vpts": vpts,
        "range.bank": bank,
        "range.ver_base": vb,
        "meta.cfg": np.frombuffer(
            json.dumps(dataclasses.asdict(rt.cfg)).encode(), dtype=np.uint8),
    }
    heap = getattr(kvs, "heap", None) if kvs is not None else None
    if heap is not None:
        from hermes_tpu.core import faststep as fst

        refs = _rows_to_i32(bank)[:, fst.BANK_VAL + 2]
        lens = np.full(hi - lo, -1, np.int64)
        parts = []
        for i, ref in enumerate(refs):
            if int(ref):
                ext = heap.read(int(ref))
                lens[i] = len(ext)
                parts.append(np.frombuffer(ext, np.uint8))
        arrays["range.heap_lens"] = lens
        arrays["range.heap_blob"] = (
            np.concatenate(parts) if parts else np.zeros(0, np.uint8))
    manifest = dict(
        version=MANIFEST_VERSION,
        scope=f"range:[{lo},{hi})",
        lo=int(lo),
        hi=int(hi),
        value_words=int(rt.cfg.value_words),
        config_sha256=config_fingerprint(rt.cfg),
        step=int(rt.step_idx),
        arrays={k: _array_sha256(v) for k, v in arrays.items()},
    )
    _atomic_savez(path, arrays, manifest)
    return manifest


def read_range(path: str):
    """Verify and read a range-scoped archive WITHOUT touching any runtime:
    returns ``(manifest, slots, vpts, rows32, ver_base)`` where ``slots``
    is the archived ``[lo, hi)`` as an index array and ``rows32`` the bank
    rows as int32 words ``[pts | sst | val...]`` — the form a migration
    driver patches (uid re-mint) before restoring.  Refuses full-scoped
    archives (the inverse of ``load``'s scope gate)."""
    with np.load(path) as z:
        manifest = _verify_npz(z)
        scope = manifest.get("scope", "full")
        if not scope.startswith("range:"):
            raise ValueError(
                f"archive is scope={scope!r}, not a range transfer; full "
                "snapshots restore through snapshot.load")
        missing = [k for k in ("range.vpts", "range.bank", "range.ver_base")
                   if k not in z]
        if missing:
            raise ValueError(
                f"range archive is incomplete (truncated/corrupt?): "
                f"missing {missing}")
        vpts = np.asarray(z["range.vpts"])
        rows32 = _rows_to_i32(np.asarray(z["range.bank"]))
        ver_base = np.asarray(z["range.ver_base"]).astype(np.int64)
    lo, hi = int(manifest["lo"]), int(manifest["hi"])
    if vpts.shape[0] != hi - lo or rows32.shape[0] != hi - lo:
        raise ValueError(
            f"range archive row count {vpts.shape[0]} != declared "
            f"[{lo}, {hi})")
    return manifest, np.arange(lo, hi, dtype=np.int64), vpts, rows32, ver_base


def read_range_heap(path: str):
    """The value-heap extents of a range archive (round-17): returns
    ``(lens, extents)`` — per-row byte lengths (-1 = the row has no
    extent) and the per-row byte payloads (None where absent) — or None
    when the archive carries no heap section (a fixed-word source).
    Checksums were already verified by ``read_range``; this re-verifies
    independently so the two reads cannot get out of sync."""
    with np.load(path) as z:
        manifest = _verify_npz(z)
        if not manifest.get("scope", "full").startswith("range:"):
            raise ValueError("not a range archive")
        if "range.heap_lens" not in z:
            return None
        lens = np.asarray(z["range.heap_lens"], np.int64)
        blob = np.asarray(z["range.heap_blob"], np.uint8)
    have = lens[lens >= 0].sum()
    if have != blob.shape[0]:
        raise ValueError(
            f"range heap blob is {blob.shape[0]} bytes but the lengths "
            f"declare {int(have)} (truncated/corrupt archive)")
    out, off = [], 0
    for ln in lens:
        if ln < 0:
            out.append(None)
        else:
            out.append(blob[off:off + int(ln)].tobytes())
            off += int(ln)
    return lens, out


def write_rows(rt, dest_slots, vpts, rows32) -> None:
    """Write table rows into a FastRuntime at ``dest_slots`` (every replica
    copy on the sharded engine — migrated rows arrive converged, exactly as
    a committed VAL would leave them).  Mechanical: scope checks, uid
    re-minting and version re-anchoring are the caller's job
    (hermes_tpu.elastic.migrate_range / snapshot.load_range)."""
    import jax.numpy as jnp

    cfg = rt.cfg
    K = cfg.n_keys
    dest = np.asarray(dest_slots, np.int64)
    if dest.size == 0:
        return
    if dest.min() < 0 or dest.max() >= K or np.unique(dest).size != dest.size:
        raise ValueError("dest_slots must be distinct slots in [0, n_keys)")
    if rows32.shape != (dest.size, 2 + cfg.value_words):
        raise ValueError(
            f"rows32 shape {rows32.shape} != ({dest.size}, "
            f"{2 + cfg.value_words}) — value_words mismatch between the "
            "archive and the destination config")
    rt.flush_pipeline()
    tbl = rt.fs.table
    nv = tbl.vpts.shape[0] // K
    flat = (np.arange(nv, dtype=np.int64)[:, None] * K + dest[None, :]).ravel()
    bank8 = _i32_to_rows(np.ascontiguousarray(rows32, np.int32))
    rt.fs = rt.fs._replace(table=tbl._replace(
        vpts=tbl.vpts.at[flat].set(jnp.asarray(np.tile(vpts, nv))),
        bank=tbl.bank.at[flat].set(jnp.asarray(np.tile(bank8, (nv, 1)))),
    ))


def anchor_ver_base(rt, dest_slots, ver_base) -> None:
    """Adopt a migrated range's cumulative version-rebase deltas into the
    destination runtime's re-anchoring table (shared by ``load_range`` and
    elastic.migrate_range): completions recorded for the restored slots
    must re-anchor into the SOURCE's global version space or the checker's
    witness order would restart mid-history.  Fresh destination slots (the
    migration precondition) carry no deltas of their own, so assignment —
    not addition — is the correct fold."""
    ver_base = np.asarray(ver_base, np.int64)
    if not ver_base.any():
        return
    if rt._ver_base is None:
        rt._ver_base = np.zeros(rt.cfg.n_keys, np.int64)
    rt._ver_base[np.asarray(dest_slots, np.int64)] = ver_base


def load_range(path: str, rt, dest_slots=None) -> dict:
    """Restore a range-scoped archive into a FastRuntime (or KVS facade)
    at ``dest_slots`` (default: the archived slots — identity placement).
    The destination slots must be FRESH (no prior committed writes in the
    destination's history): migration owns that precondition via routing —
    a key lives in exactly one group.  Verifies scope + checksums first,
    re-anchors the destination's ``_ver_base`` over the restored slots with
    the source's deltas.  Returns the manifest.  NOTE: this mechanical
    restore keeps the rows' original write uids; checker-recorded
    destinations should migrate through hermes_tpu.elastic.migrate_range,
    which re-mints uids and seeds the destination history."""
    if hasattr(rt, "rt") and hasattr(rt, "index"):  # the KVS facade
        rt.flush()
        rt = rt.rt
    if not hasattr(rt, "fs"):
        raise NotImplementedError("load_range restores the faststep table")
    manifest, slots, vpts, rows32, ver_base = read_range(path)
    if int(manifest["value_words"]) != rt.cfg.value_words:
        raise ValueError(
            f"range archive value_words={manifest['value_words']} != "
            f"destination {rt.cfg.value_words}; rows are not portable "
            "across value widths")
    dest = slots if dest_slots is None else np.asarray(dest_slots, np.int64)
    if dest.shape != slots.shape:
        raise ValueError(
            f"dest_slots count {dest.size} != archived rows {slots.size}")
    write_rows(rt, dest, vpts, rows32)
    if hasattr(rt, "_ver_base"):
        anchor_ver_base(rt, dest, ver_base)
    return manifest
