"""Structured event-timeline tracing (hermes_tpu/obs pillar 3).

Trace records ride the same JSONL stream as interval metrics (one shared
monotonic clock, metrics.JsonlExporter), so a fault-injection run yields ONE
causally ordered file: span begin/end around host operations (step dispatch,
completion readback, rebase_versions, drain), point events for membership /
failure injection (freeze/thaw/remove/join/suspect) and checker verdicts,
interleaved with the interval throughput records — the "what did the cluster
look like when replica 3 was frozen" story scripts/obs_report.py renders.

Record kinds:
  * ``event``      — point event: {"t", "kind": "event", "name", ...fields}
  * ``span_begin`` — {"t", "kind": "span_begin", "name", ...fields}
  * ``span_end``   — {"t", "kind": "span_end", "name", "dur_s", ...fields}

Spans are two records (not one record stamped at begin-time) so the stream
stays strictly append-ordered: ``t`` is non-decreasing across ALL kinds,
which is what makes naive line-order merging of the timeline sound.
"""

from __future__ import annotations

import contextlib
import time


class Tracer:
    """Thin writer over an exporter (metrics.JsonlExporter /
    BufferExporter).  All methods are cheap host-side dict writes; callers
    on hot paths should keep their own ``if obs is None`` fast path."""

    def __init__(self, exporter):
        self.exporter = exporter

    def event(self, name: str, **fields) -> None:
        self.exporter.write({"name": name, **fields}, kind="event")

    def span_begin(self, name: str, **fields) -> float:
        self.exporter.write({"name": name, **fields}, kind="span_begin")
        return time.perf_counter()

    def span_end(self, name: str, t_begin: float, **fields) -> None:
        self.exporter.write(
            {"name": name,
             "dur_s": round(time.perf_counter() - t_begin, 6), **fields},
            kind="span_end")

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        t0 = self.span_begin(name, **fields)
        try:
            yield
        finally:
            self.span_end(name, t0)
