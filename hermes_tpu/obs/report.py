"""Run-timeline merge + human report renderer (``obs report``).

Consumes the JSONL records an Observability run emits (interval metrics,
trace events, span begin/end — one shared monotonic clock, see trace.py) and
renders one causally ordered story: interval throughput next to the fault
events that explain its dips, the per-op critical-path breakdown from the
round-18 trace spans, plus the device phase histograms from the final
summary.  Run as ``python -m hermes_tpu.obs.report run.jsonl``
(``scripts/obs_report.py`` is a thin shim over the same ``main``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, List, Optional

FAULT_EVENTS = ("freeze", "thaw", "remove", "join", "suspect",
                # round-14 serving envelope: shed-ladder transitions and
                # overload windows are fault-class events — an operator
                # reading the timeline sees WHEN the front door closed
                "shed", "shed_clear", "degraded", "degraded_clear",
                "overload", "overload_clear")


def load_records(paths: Iterable[str]) -> List[dict]:
    """Read + merge one or more obs JSONL files into a single timeline,
    stably sorted by ``t`` (records from one file keep their write order —
    the clock is monotonic per file).  Each record is tagged with a
    ``_src`` file index so cumulative counters from different run logs are
    never differenced against each other."""
    recs: List[dict] = []
    for src, path in enumerate(paths):
        with open(path) as fp:
            for line in fp:
                line = line.strip()
                if line:
                    rec = json.loads(line)
                    rec["_src"] = src
                    recs.append(rec)
    recs.sort(key=lambda r: r.get("t", 0.0))
    return recs


def interval_throughput(records: List[dict]) -> List[dict]:
    """Per-interval commit/read rates from consecutive cumulative metrics
    records (kind metrics/summary carrying ``commits``).  Counters are
    cumulative per run log, so deltas are taken within each ``_src``
    stream — a merged multi-file timeline never mixes streams."""
    out = []
    prev: dict = {}  # _src -> last metrics record of that stream
    for r in records:
        if r.get("kind") not in ("metrics", "summary") or "commits" not in r:
            continue
        p = prev.get(r.get("_src", 0))
        if p is not None:
            dc = r["commits"] - p["commits"]
            dr = r.get("n_read", 0) - p.get("n_read", 0)
            if dc < 0 or dr < 0 or r.get("steps", 0) < p.get("steps", 0):
                # counter reset: a fresh runtime wrote into the same log
                # (bench.py emits one summary per mix cell) — start a new
                # segment instead of differencing unrelated runs
                p = None
        if p is not None:
            dt = r["t"] - p["t"]
            out.append(dict(
                t0=p["t"], t1=r["t"],
                commits=dc,
                commits_per_s=round(dc / dt, 1) if dt > 0 else None,
                reads=dr,
            ))
        prev[r.get("_src", 0)] = r
    return out


def fleet_totals(records: List[dict]) -> Optional[dict]:
    """Per-group + fleet-wide aggregation over group-labeled records
    (round-13, hermes_tpu/fleet): the fleet facade emits interval/summary
    records and trace events carrying ``group``; this folds each group's
    LAST cumulative counters plus its event census into one table, with
    the fleet aggregate as the counter sums.  Returns None when no record
    carries a group label (single-group runs keep their old report)."""
    last: dict = {}   # group -> last group-labeled metrics/summary record
    events: dict = {}  # group -> event-name census
    for r in records:
        g = r.get("group")
        if g is None or g == "fleet":
            continue
        if r.get("kind") in ("metrics", "summary"):
            last[g] = r
        elif r.get("kind") == "event":
            events.setdefault(g, {})
            name = r.get("name", "?")
            events[g][name] = events[g].get(name, 0) + 1
    if not last and not events:
        return None
    counter_keys = ("n_read", "n_write", "n_rmw", "n_abort", "commits")
    groups = {}
    agg: dict = {}
    for g in sorted(set(last) | set(events)):
        row = {k: last[g][k] for k in counter_keys
               if g in last and k in last[g]}
        row["events"] = events.get(g, {})
        groups[g] = row
        for k, v in row.items():
            if k != "events":
                agg[k] = agg.get(k, 0) + v
    return dict(groups=groups, fleet=agg)


def critical_path(records: List[dict]) -> Optional[dict]:
    """Per-op latency attribution from the round-18 trace spans
    (obs/tracing.py): group the op spans by trace id and break the
    sampled population's p50/p99 down by phase, in PROTOCOL ROUNDS
    (r1 - r0 — the deterministic unit) plus wall p99 where the span
    measured one.  Returns None when the run traced nothing.

    The headline line this feeds: "p99 ops spend X rounds in the queue
    and Y rounds in device rounds"."""
    from hermes_tpu.obs.tracing import OP_SPANS
    from hermes_tpu.stats import percentile_nearest_rank

    per: dict = {}  # trace id -> {span name: record}
    for r in records:
        if r.get("kind") != "span_end" or r.get("name") not in OP_SPANS:
            continue
        tr = r.get("trace")
        if tr:
            per.setdefault(tr, {})[r["name"]] = r
    if not per:
        return None
    phases: dict = {}
    for name in OP_SPANS:
        spans = [s[name] for s in per.values() if name in s]
        rounds = sorted(s["r1"] - s["r0"] for s in spans)
        durs = sorted(s["dur_s"] for s in spans
                      if s.get("dur_s") is not None)
        if rounds:
            row = dict(
                n=len(rounds),
                p50_rounds=percentile_nearest_rank(rounds, 0.5),
                p99_rounds=percentile_nearest_rank(rounds, 0.99))
            if durs:
                row["p99_dur_s"] = percentile_nearest_rank(durs, 0.99)
            phases[name] = row
    return dict(traces=len(per), phases=phases)


_PHASE_LABELS = {"fe_queue": "intake queue (admit -> issue)",
                 "op_queue": "client queue (submit -> inject)",
                 "op_rounds": "device rounds (inject -> resolve)",
                 "fe_resolve": "end to end (admit -> resolve)"}


def _fmt_fields(r: dict, skip=("t", "kind", "name", "_src")) -> str:
    return " ".join(f"{k}={v}" for k, v in r.items()
                    if k not in skip and not isinstance(v, list))


def _render_hist(counts: List[int], width: int = 40) -> List[str]:
    from hermes_tpu.obs.metrics import percentile_from_counts

    total = sum(counts)
    lines = []
    if total == 0:
        return ["  (empty)"]
    peak = max(counts)
    for i, c in enumerate(counts):
        if c == 0:
            continue
        bar = "#" * max(1, round(c / peak * width))
        lines.append(f"  {i:>3} | {bar} {c}")
    p50 = percentile_from_counts(counts, 0.5)
    p99 = percentile_from_counts(counts, 0.99)
    lines.append(f"  n={total} p50={p50} p99={p99} (bins are protocol"
                 " rounds; last bin clips)")
    return lines


def render_report(records: List[dict], max_timeline: Optional[int] = None
                  ) -> str:
    """Human ``obs report``: kind census, fault-event list, merged
    timeline with per-interval throughput, and the phase histograms from
    the last record that carries them."""
    by_kind: dict = {}
    for r in records:
        by_kind[r.get("kind", "?")] = by_kind.get(r.get("kind", "?"), 0) + 1
    lines = ["== obs report =="]
    if records:
        span = records[-1].get("t", 0.0) - records[0].get("t", 0.0)
        census = " ".join(f"{k}={v}" for k, v in sorted(by_kind.items()))
        lines.append(f"{len(records)} records over {span:.3f}s ({census})")
    else:
        lines.append("no records")
        return "\n".join(lines) + "\n"

    faults = [r for r in records
              if r.get("kind") == "event" and r.get("name") in FAULT_EVENTS]
    lines.append("")
    lines.append(f"-- membership / fault events ({len(faults)}) --")
    for r in faults:
        lines.append(f"  t={r['t']:9.3f}s {r['name']:<8} {_fmt_fields(r)}")
    if not faults:
        lines.append("  (none)")

    ivals = interval_throughput(records)
    ival_by_t1 = {iv["t1"]: iv for iv in ivals}

    lines.append("")
    lines.append("-- timeline --")
    shown = records if max_timeline is None else records[-max_timeline:]
    for r in shown:
        kind = r.get("kind", "?")
        if kind in ("metrics", "summary"):
            iv = ival_by_t1.get(r.get("t"))
            rate = (f" [{iv['commits_per_s']}/s over "
                    f"{iv['t1'] - iv['t0']:.3f}s]" if iv else "")
            core = " ".join(
                f"{k}={r[k]}" for k in
                ("steps", "commits", "n_read", "n_abort", "ops_per_sec")
                if k in r)
            lines.append(f"  t={r['t']:9.3f}s {kind:<10} {core}{rate}")
        elif kind == "span_end":
            lines.append(f"  t={r['t']:9.3f}s span       "
                         f"{r.get('name')} dur={r.get('dur_s')}s "
                         f"{_fmt_fields(r, skip=('t', 'kind', 'name', 'dur_s', '_src'))}")
        elif kind == "span_begin":
            continue  # the end record carries the duration
        else:
            lines.append(f"  t={r['t']:9.3f}s {kind:<10} "
                         f"{r.get('name', '')} {_fmt_fields(r)}")

    # round-8 serving-pipeline overlap: the runtimes accumulate per-round
    # host work vs device wait into the registry (runtime.step_once /
    # harvest_comp); the last registry record carries the totals
    last_reg = None
    for r in records:
        if r.get("kind") == "registry" and "device_wait_s" in r:
            last_reg = r
    if last_reg is not None:
        host = float(last_reg.get("host_work_s", 0.0))
        wait = float(last_reg["device_wait_s"])
        tot = host + wait
        lines.append("")
        lines.append("-- serving-pipeline overlap --")
        lines.append(
            f"  host_work={host:.3f}s device_wait={wait:.3f}s"
            + (f" (host loop blocked on readback {wait / tot:.0%}"
               f" of its time)" if tot > 0 else "")
            + (f" ring depth={last_reg['pipeline_depth']}"
               if "pipeline_depth" in last_reg else ""))

    # round-18 per-op critical path: sampled traces broken down by phase
    cp = critical_path(records)
    if cp is not None:
        lines.append("")
        lines.append(f"-- per-op critical path ({cp['traces']} sampled "
                     f"trace(s)) --")
        for name, row in cp["phases"].items():
            extra = (f" p99_wall={row['p99_dur_s']}s"
                     if "p99_dur_s" in row else "")
            lines.append(
                f"  {name:<10} {_PHASE_LABELS.get(name, ''):<34} "
                f"n={row['n']} p50={row['p50_rounds']} "
                f"p99={row['p99_rounds']} rounds{extra}")

    # round-13 fleet aggregation: when records carry group labels, render
    # the per-group counter table and the fleet-wide sums
    ft = fleet_totals(records)
    if ft is not None:
        lines.append("")
        lines.append(f"-- fleet (per-group / aggregate, "
                     f"{len(ft['groups'])} group(s)) --")
        for g, row in ft["groups"].items():
            ev = " ".join(f"{k}={v}" for k, v in sorted(row["events"].items()))
            cts = " ".join(f"{k}={v}" for k, v in row.items()
                           if k != "events")
            lines.append(f"  group {g}: {cts}"
                         + (f"  [{ev}]" if ev else ""))
        lines.append("  fleet:   " + " ".join(
            f"{k}={v}" for k, v in ft["fleet"].items()))

    last_hists = None
    for r in records:
        if isinstance(r.get("lat_hist"), list) or isinstance(
                r.get("qwait_hist"), list):
            last_hists = r
    lines.append("")
    lines.append("-- phase histograms --")
    if last_hists is None:
        lines.append("  (no histogram-bearing record; run with hists=True "
                     "intervals, e.g. cli --metrics-out)")
    else:
        for field, title in (("lat_hist", "commit latency (load->commit)"),
                             ("qwait_hist", "ACK quorum-wait (issue->commit)")):
            h = last_hists.get(field)
            if isinstance(h, list):
                lines.append(f"  {title}:")
                lines.extend("  " + ln for ln in _render_hist(h))
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m hermes_tpu.obs.report`` — the profile.py pattern: the
    renderer is importable library code and its CLI lives beside it;
    ``scripts/obs_report.py`` stays as a thin shim."""
    ap = argparse.ArgumentParser(
        description="Render obs run logs (--metrics-out JSONL) as one "
                    "causally ordered timeline report.")
    ap.add_argument("paths", nargs="+", help="obs JSONL run logs to merge")
    ap.add_argument("--max-timeline", type=int, default=None,
                    help="show only the last N timeline records")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged record list as JSON instead of "
                    "the human report")
    args = ap.parse_args(argv)

    records = load_records(args.paths)
    if args.json:
        json.dump(records, sys.stdout)
        sys.stdout.write("\n")
        return 0
    sys.stdout.write(render_report(records, max_timeline=args.max_timeline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
