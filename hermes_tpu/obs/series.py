"""Bounded windowed time-series store (round-18, hermes_tpu/obs).

The observed-state API the ROADMAP item-6 controller will consume: the
registry's counters and gauges are POINT state, but a controller steers
on HISTORY — queue depth over the last N rounds, p99-vs-deadline trend,
commit rate per window.  A ``Series`` is a bounded ring of (x, v)
samples where ``x`` is a DETERMINISTIC run coordinate (protocol round
index, poll sequence — never wall time), so a seeded run's series are a
pure function of the run and snapshot-comparable across replays.

Feeding is host-cheap (two deque appends); eviction is O(1) per append
(the ring is a ``collections.deque(maxlen=...)``).  Queries are
windowed:

  * ``window(last_n)``      — the most recent samples;
  * ``rate(last_n)``        — dv/dx over the window (for cumulative
    counters: per-round commit rate);
  * ``percentile(q, last_n)``— nearest-rank percentile of the window's
    VALUES (for gauge-like series: queue depth p99).

``MetricsRegistry.series`` (obs/metrics.py) exposes get-or-create
access under the registry's one-name-one-metric discipline, and
``Observability.series_snapshot`` exports every series as one
``kind="series"`` JSONL record.
"""

from __future__ import annotations

import collections
from typing import List, Optional, Tuple


class Series:
    """One named bounded ring of (x, v) samples, x non-decreasing."""

    def __init__(self, name: str, capacity: int = 1024, help: str = ""):
        if capacity < 2:
            raise ValueError("series capacity must be >= 2 (rate needs "
                             "two samples)")
        self.name = name
        self.help = help
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(maxlen=capacity)

    def append(self, x, v) -> None:
        """Record value ``v`` at run coordinate ``x`` (round index, poll
        sequence — a deterministic clock, not wall time).  ``x`` must be
        non-decreasing; regressions raise (a series fed from two
        unsynchronized clocks is a bug, not data)."""
        if self._ring and x < self._ring[-1][0]:
            raise ValueError(
                f"series {self.name!r}: x went backwards "
                f"({x} < {self._ring[-1][0]}) — feed one monotone run "
                "coordinate per series")
        self._ring.append((x, v))

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def last(self) -> Optional[Tuple]:
        return self._ring[-1] if self._ring else None

    def window(self, last_n: Optional[int] = None) -> List[Tuple]:
        """The most recent ``last_n`` samples (all retained when None)."""
        if last_n is None or last_n >= len(self._ring):
            return list(self._ring)
        return [self._ring[i]
                for i in range(len(self._ring) - last_n, len(self._ring))]

    def values(self, last_n: Optional[int] = None) -> List:
        return [v for _x, v in self.window(last_n)]

    def rate(self, last_n: Optional[int] = None) -> Optional[float]:
        """dv/dx across the window — the per-round rate when ``v`` is a
        cumulative counter and ``x`` a round index.  None until two
        samples exist or while the window spans zero x."""
        w = self.window(last_n)
        if len(w) < 2:
            return None
        (x0, v0), (x1, v1) = w[0], w[-1]
        dx = x1 - x0
        if dx <= 0:
            return None
        return (v1 - v0) / dx

    def percentile(self, q: float, last_n: Optional[int] = None):
        """Nearest-rank percentile of the window's values (None when
        empty) — the p99-vs-deadline query, over history instead of one
        histogram snapshot."""
        # lazy: stats.py itself imports obs.metrics, which imports us
        from hermes_tpu.stats import percentile_nearest_rank

        return percentile_nearest_rank(sorted(self.values(last_n)), q)

    def snapshot(self) -> dict:
        """JSON-ready view: parallel x/v arrays (full retained window)."""
        return dict(x=[x for x, _v in self._ring],
                    v=[v for _x, v in self._ring])
