"""Host-side metrics registry + exporters (hermes_tpu/obs pillar 2).

The reference aggregates cache-line-padded per-thread counters in a stats
thread (SURVEY.md §5.5); the rebuild's device-side twin is the Meta column
block summed per round at zero host cost (core/state.Meta).  This module is
the HOST half: a ``MetricsRegistry`` of named counters / gauges / histograms
that the runtimes, transports, and scripts feed, with three exporters —

  * ``JsonlExporter``   — one JSON object per line.  ``stamp=True`` (the obs
    run-log mode) prefixes every record with the shared monotonic clock
    ``t`` and a ``kind`` tag, the schema ``scripts/obs_report.py`` merges
    into a run timeline.  ``stamp=False`` writes the record verbatim — the
    byte-compatible mode the legacy bench/soak stdout contracts ride.
  * ``prometheus_text`` — a Prometheus-style text snapshot of a registry.
  * ``render_report``   — the human renderer (hermes_tpu/obs/report.py).

Registries are plain host objects: feeding them costs dict lookups and int
adds, never a device sync — device counters enter via ``Counter.set_total``
at the caller's chosen poll interval.
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, Dict, List, Optional, Union

import numpy as np

from hermes_tpu.obs.series import Series


class Counter:
    """Monotone counter.  ``inc`` for host events; ``set_total`` for
    device-derived cumulative totals (Meta columns are absolute sums)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def set_total(self, total: Union[int, float]) -> None:
        self.value = total


class Gauge:
    """Point-in-time value (watermarks, rates, config echoes)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def set(self, v: Union[int, float]) -> None:
        self.value = v


class Histogram:
    """Fixed-bin histogram over non-negative integer observations (bin i
    counts value i; the last bin clips) — the same shape as the device
    latency histograms (state.LAT_BINS), so a device hist drops in via
    ``set_counts``."""

    def __init__(self, name: str, bins: int = 64, help: str = ""):
        self.name = name
        self.help = help
        self.counts = np.zeros(bins, np.int64)

    def observe(self, v: int, n: int = 1) -> None:
        self.counts[min(max(int(v), 0), len(self.counts) - 1)] += n

    def set_counts(self, counts) -> None:
        c = np.asarray(counts, np.int64)
        if c.shape != self.counts.shape:
            raise ValueError(
                f"histogram {self.name}: expected {self.counts.shape[0]} "
                f"bins, got {c.shape}")
        self.counts = c.copy()

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    def percentile(self, q: float) -> Optional[int]:
        return percentile_from_counts(self.counts, q)


def percentile_from_counts(counts: np.ndarray, q: float) -> Optional[int]:
    """q in [0, 1]; bin index of the q-quantile, or None when empty (an
    empty histogram has no percentile — never a sentinel that poisons
    downstream JSON)."""
    cum = np.asarray(counts).cumsum()
    if cum[-1] == 0:
        return None
    return int((cum >= q * cum[-1]).argmax())


class MetricsRegistry:
    """Named metric registry with get-or-create accessors.  A name maps to
    exactly one metric object for the registry's lifetime; asking for the
    same name with a different type is a bug and raises.

    The name->metric MAP is lock-guarded (round-20): serving-tier threads
    get-or-create concurrently, and an unlocked dict insert during a
    snapshot iteration raises RuntimeError (or mints two objects for one
    name).  Metric VALUES stay lock-free by design — int adds under the
    GIL, the zero-device-cost contract above."""

    def __init__(self):
        # a PLAIN threading.Lock, NEVER concurrency.make_lock: the
        # registry is the sink the lock sanitizer itself feeds
        # (lockgraph.ObsLock reports hold-time series INTO a registry);
        # instrumenting this lock would recurse the sanitizer into its
        # own sink and self-deadlock.  See concurrency.REGISTRY's
        # MetricsRegistry entry.
        self._lock = threading.Lock()
        self._metrics: Dict[str, Union[Counter, Gauge, Histogram,
                                       Series]] = {}

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    f"not {cls.__name__}")
            return m

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def _items(self) -> list:
        """Sorted (name, metric) snapshot — iteration currency for the
        exporters, so a concurrent get-or-create never invalidates it."""
        with self._lock:
            return sorted(self._metrics.items())

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def histogram(self, name: str, bins: int = 64, help: str = "") -> Histogram:
        return self._get(name, Histogram, bins=bins, help=help)

    def series(self, name: str, capacity: int = 1024,
               help: str = "") -> Series:
        """Bounded windowed time series (obs/series.py) under the same
        one-name-one-metric discipline.  ``capacity`` only applies at
        creation; later calls return the existing ring unchanged."""
        return self._get(name, Series, capacity=capacity, help=help)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._metrics

    def snapshot(self) -> dict:
        """Flat JSON-ready view: scalars verbatim; histograms as counts plus
        derived p50/p99 (None-omitted, matching stats.summarize)."""
        out: dict = {}
        for name, m in self._items():
            if isinstance(m, Series):
                continue  # full history exports via series_snapshot()
            if isinstance(m, Histogram):
                out[name] = m.counts.tolist()
                for q, tag in ((0.5, "p50"), (0.99, "p99")):
                    p = m.percentile(q)
                    if p is not None:
                        out[f"{name}_{tag}"] = p
            else:
                out[name] = m.value
        return out

    def series_snapshot(self) -> dict:
        """JSON-ready view of every time series: name -> parallel x/v
        arrays (the ``kind="series"`` record Observability exports)."""
        return {name: m.snapshot()
                for name, m in self._items()
                if isinstance(m, Series)}


def prometheus_text(reg: MetricsRegistry) -> str:
    """Prometheus text-exposition snapshot (counters/gauges as samples,
    histograms as cumulative ``_bucket`` series + ``_count``)."""
    lines: List[str] = []
    for name, m in reg._items():
        if isinstance(m, Series):
            continue  # rings have no Prometheus shape; JSONL-only
        if m.help:
            lines.append(f"# HELP {name} {m.help}")
        if isinstance(m, Counter):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {m.value}")
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {m.value}")
        else:
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for i, c in enumerate(m.counts.tolist()):
                cum += c
                lines.append(f'{name}_bucket{{le="{i}"}} {cum}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{name}_count {cum}")
    return "\n".join(lines) + "\n"


class JsonlExporter:
    """One JSON object per line.

    ``stamp=True``: every record is emitted as ``{"t": <monotonic seconds
    since exporter birth>, "kind": <tag>, ...}`` — the obs run-log schema
    (every record has ``t`` and ``kind``; ``t`` is non-decreasing because
    the clock is monotonic and records are written in call order).

    ``stamp=False``: the record is serialized verbatim, preserving key
    order — byte-compatible with the legacy ``print(json.dumps(...))``
    contract lines of bench.py / scripts/rebase_soak.py.
    """

    def __init__(self, fp: IO[str], stamp: bool = True):
        self.fp = fp
        self.stamp = stamp
        self.t0 = time.perf_counter()

    def now(self) -> float:
        return time.perf_counter() - self.t0

    def write(self, record: dict, kind: str = "metrics") -> None:
        if self.stamp:
            record = {"t": round(self.now(), 6), "kind": kind, **record}
        self.fp.write(json.dumps(record) + "\n")
        self.fp.flush()


class BufferExporter:
    """In-memory exporter (tests, report post-processing): same write()
    surface as JsonlExporter(stamp=True), records kept as dicts."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.records: List[dict] = []

    def now(self) -> float:
        return time.perf_counter() - self.t0

    def write(self, record: dict, kind: str = "metrics") -> None:
        self.records.append({"t": round(self.now(), 6), "kind": kind,
                             **record})
