"""Per-fusion round profiler + StableHLO op census (hermes_tpu/obs).

The engine's measured cost model (ARCHITECTURE.md "Sparse-op COUNT
dominates") prices a protocol round as (#sparse ops on the chain) x
~1.3-2.4 ms plus a dense tail, nearly independent of operand size — so the
single number that predicts round time on the target chip is the OP CENSUS
of the lowered program, and the way a refactor regresses the round is by
quietly re-adding a gather/scatter/sort to the chain.  This module is the
measurement half of the round-6 "op diet": it makes the census and the
per-fusion cost attribution first-class obs artifacts so CI can police
them (scripts/check_op_census.py, the same measure-then-gate pattern as
scripts/check_obs_overhead.py).

Three entry points:

  * ``op_census(cfg, backend, mesh)`` — StableHLO op counts of ONE lowered
    protocol round at cfg's shape (abstract lowering, nothing
    materialized; backend-independent by construction).
  * ``round_ledger(cfg, ...)`` — the per-fusion ledger: the batched round
    ablated into its protocol fusions (coordinate / apply_inv /
    acks+commit), each stage attributed the DELTA of sparse ops it adds
    and (optionally) the measured ms-per-round delta of scan-chunked
    timing, plus the full-round census.  Timing uses the honest protocol
    for this runtime (force-synchronous readback first; see bench.py).
  * ``check_budget(census_by_engine, budget)`` — the CI gate predicate:
    every budgeted count must not exceed its checked-in ceiling
    (OP_BUDGET.json at the repo root is the budget the gate script
    enforces).

Records export through the PR-1 obs run-log schema: ``export_profile``
writes one JSONL record per ledger row via ``JsonlExporter(stamp=True)``
(every record gets ``t`` + ``kind="profile"``), so scripts/obs_report.py
and any JSONL consumer read profiles like any other obs stream.

CLI (the promoted scripts/profile_round.py):

    python -m hermes_tpu.obs.profile [S] [C] [--rounds N] [--reps N]
        [--census-only] [--out PROFILE_JSONL]
"""

from __future__ import annotations

import json
import re
import sys
import time
from typing import Optional

# the ops the TPU cost model prices individually (sparse chain) and the
# wire collectives; everything else is the fused dense tail
SPARSE = ("stablehlo.gather", "stablehlo.scatter", "stablehlo.sort",
          "stablehlo.dynamic_gather")
COLLECTIVE = ("stablehlo.all_gather", "stablehlo.all_to_all",
              "stablehlo.collective_permute", "stablehlo.all_reduce")

# ARCHITECTURE.md cost model (round-2, measured): ~1.3-2.4 ms per dynamic
# sparse op.  Single source of truth — scripts/sharded_census.py's
# projection and the ledger's modeled pricing both import from here.
COST_LO, COST_MID, COST_HI = 1.3, 1.8, 2.4

# Round-15 serial-interior pricing (PALLAS_PROBE.json: the serial
# VMEM-resident Pallas loop runs ~6 ns/iteration on the current Mosaic
# toolchain; bracketed for scalarization overhead) — what the Pallas
# ledger prices a kernel's serial iteration bound at, in ns/iteration.
SERIAL_NS_LO, SERIAL_NS_MID, SERIAL_NS_HI = 2.0, 6.0, 12.0


def census_text(txt: str) -> dict:
    """Count the cost-model ops in StableHLO text (one lowered program)."""
    counts: dict = {}
    static_gathers = 0
    for line in txt.splitlines():
        m = re.search(r'= "?(stablehlo\.[a-z_]+)"?[( ]', line)
        if not m:
            continue
        op = m.group(1)
        if op == "stablehlo.gather" and "indices_are_sorted = true" in line:
            # byte-plane extraction (faststep._bank_to_i32): a strided
            # slice that jax lowers as a gather from STATIC iota indices
            # (hence sorted+unique) — XLA fuses these like slices; they are
            # not the ~1.3-2.4 ms dynamic sparse ops the cost model prices
            static_gathers += 1
            continue
        counts[op] = counts.get(op, 0) + 1
    out = {k: counts.get(k, 0) for k in SPARSE + COLLECTIVE}
    out["static_strided_gathers"] = static_gathers
    out["sparse_total"] = sum(counts.get(k, 0) for k in SPARSE)
    out["collective_total"] = sum(counts.get(k, 0) for k in COLLECTIVE)
    return out


# --------------------------------------------------------------------------
# Pallas-aware ledger (round-15): police kernel INTERIORS, not just the
# XLA op list.  The StableHLO census above prices the launch-taxed sparse
# chain; a Pallas mega-round kernel is ONE launch there — without this
# section the census would count it as one op and silently stop policing
# whatever the kernel does inside (a hidden interior gather, or an
# unbounded serial loop, would be invisible to CI).  This walks the
# ROUND JAXPR instead: every pallas_call's body is censused for
# cost-model primitives (must stay 0 — a kernel-interior gather/scatter
# would pay the same vector-unit cost without even XLA's fusion) and for
# its SERIAL ITERATION BOUND (grid size x nested scan trip counts — the
# real interior cost, priced at the probe-measured ~6 ns/iteration).
# --------------------------------------------------------------------------

_PALLAS_SPARSE_PRIMS = ("gather", "scatter", "scatter-max", "scatter-min",
                        "scatter-add", "sort", "dynamic_gather")
_REF_PRIMS = ("get", "swap", "addupdate")


def _sub_jaxprs(eqn):
    """(jaxpr, trip_multiplier) pairs nested under one equation."""
    from jax.extend.core import ClosedJaxpr

    name = eqn.primitive.name
    out = []
    if name == "scan":
        out.append((eqn.params["jaxpr"], int(eqn.params.get("length") or 1)))
    elif name == "while":
        # trip count unknowable statically: count the body once and let
        # the caller see a while flag (none of the in-tree kernels use
        # unbounded loops)
        out.append((eqn.params["body_jaxpr"], 1))
    elif name == "cond":
        for br in eqn.params["branches"]:
            out.append((br, 1))
    else:
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            inner = eqn.params.get(key)
            if inner is not None and name != "pallas_call":
                out.append((inner, 1))
    res = []
    for j, m in out:
        res.append((j.jaxpr if isinstance(j, ClosedJaxpr) else j, m))
    return res


def _kernel_interior(jaxpr) -> dict:
    """Recursive census of ONE kernel body: cost-model primitives,
    ref-access sites, and the serial iteration bound (scan trips,
    cond branches counted at their max)."""
    sparse = 0
    refs = 0
    iters = 0
    whiles = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _PALLAS_SPARSE_PRIMS:
            sparse += 1
        if name in _REF_PRIMS:
            refs += 1
        if name == "while":
            # a while's trip count is statically unknowable, so the
            # serial bound counts its body ONCE and the loop itself is
            # surfaced as a budgetable count (OP_BUDGET.json pins
            # pallas_while_loops at 0 — an unbounded in-kernel loop must
            # be a conscious budget change, never a silent pass)
            whiles += 1
        if name == "cond":
            best = None
            for sub, _m in _sub_jaxprs(eqn):
                r = _kernel_interior(sub)
                sparse += r["interior_sparse"]
                refs += r["ref_sites"]
                whiles += r["while_loops"]
                best = r["serial_iters"] if best is None else max(
                    best, r["serial_iters"])
            iters += best or 0
            continue
        for sub, mult in _sub_jaxprs(eqn):
            r = _kernel_interior(sub)
            sparse += r["interior_sparse"]
            refs += r["ref_sites"]
            whiles += r["while_loops"]
            iters += mult * max(1, r["serial_iters"]) if name == "scan" \
                else r["serial_iters"]
    return dict(interior_sparse=sparse, ref_sites=refs, serial_iters=iters,
                while_loops=whiles)


def pallas_ledger_of_jaxpr(jaxpr) -> dict:
    """Walk a round jaxpr; census every ``pallas_call``'s interior.
    Returns the census-extension dict (all keys budgetable via
    OP_BUDGET.json): ``pallas_calls``, ``pallas_interior_sparse``,
    ``pallas_serial_iter_bound`` (sum over calls of grid-size x in-kernel
    serial trips) and the modeled serial cost."""
    calls = []

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "pallas_call":
                gm = eqn.params["grid_mapping"]
                grid = 1
                for g in getattr(gm, "grid", ()) or ():
                    try:
                        grid *= int(g)
                    except Exception:
                        pass
                kj = eqn.params["jaxpr"]
                r = _kernel_interior(kj)
                calls.append(dict(grid=grid, **r))
            for sub, _m in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    bound = sum(c["grid"] * c["serial_iters"] for c in calls)
    return {
        "pallas_calls": len(calls),
        "pallas_interior_sparse": sum(c["interior_sparse"] for c in calls),
        "pallas_ref_sites": sum(c["ref_sites"] for c in calls),
        "pallas_while_loops": sum(c["while_loops"] for c in calls),
        "pallas_serial_iter_bound": bound,
        "pallas_serial_modeled_ms": [
            round(bound * SERIAL_NS_LO / 1e6, 2),
            round(bound * SERIAL_NS_HI / 1e6, 2)],
    }


def pallas_ledger(cfg, backend: str = "batched", mesh=None) -> dict:
    """The Pallas interior ledger of ONE protocol round at cfg's shape
    (abstract tracing, backend-independent — the jaxpr is the same
    whether the kernels later compile via Mosaic or interpret).  A thin
    filter over ``op_census`` (the one build-trace-ledger path), so the
    standalone entry point cannot drift from what the gate measures."""
    return {k: v for k, v in op_census(cfg, backend, mesh).items()
            if k.startswith("pallas_")}


def _abstract_round_args(cfg, n_local=None):
    import jax

    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    fs = jax.eval_shape(lambda: fst.init_fast_state(cfg, n_local=n_local))
    stream = jax.eval_shape(lambda: fst.prep_stream(ycsb.stub_stream(cfg)))
    ctl = jax.eval_shape(lambda: fst.make_fast_ctl(cfg, 0))
    return fs, stream, ctl


def census_shape(cfg) -> dict:
    """The config knobs that identify a census cell (the ``bench_shape`` /
    ``shape`` section of every census artifact and ledger) — ONE place, so
    adding a knob to the census identity cannot drift between the artifact
    writers (scripts/sharded_census.py, scripts/check_op_census.py --update,
    round_ledger)."""
    return dict(n_replicas=cfg.n_replicas, n_keys=cfg.n_keys,
                n_sessions=cfg.n_sessions, lane_budget=cfg.lane_budget,
                value_words=cfg.value_words, chain_writes=cfg.chain_writes,
                arb_mode=cfg.arb_mode, fused_sort=cfg.use_fused_sort)


def op_census(cfg, backend: str = "batched", mesh=None) -> dict:
    """StableHLO op counts of ONE protocol round at cfg's shape (abstract
    lowering — nothing is materialized).  Backend-independent: the census
    of the lowered program is the same on CPU and TPU, which is what lets
    CI police the TPU cost model without a chip."""
    from hermes_tpu.core import faststep as fst

    if backend == "batched":
        fn = fst.build_fast_batched(cfg)
        n_local = None
    elif backend == "sharded":
        if mesh is None:
            raise ValueError("sharded census needs a mesh")
        fn = fst.build_fast_sharded(cfg, mesh, rounds=1, donate=False)
        n_local = cfg.n_replicas
    else:
        raise ValueError(f"unknown backend {backend!r}")
    fs, stream, ctl = _abstract_round_args(cfg, n_local)
    # ONE trace serves both halves: the StableHLO text census (launch-
    # taxed XLA ops) and the round-15 Pallas interior ledger (kernel
    # interiors the text census cannot see — OP_BUDGET.json budgets the
    # interior-sparse count, while-loop count, and serial iteration
    # bound alongside the XLA op counts)
    traced = fn.trace(fs, stream, ctl)
    cen = census_text(traced.lower().as_text())
    cen.update(pallas_ledger_of_jaxpr(traced.jaxpr.jaxpr))
    return cen


# --------------------------------------------------------------------------
# Per-fusion ledger (the promoted scripts/profile_round.py methodology)
# --------------------------------------------------------------------------


def _stage_fns(cfg):
    """Ordered ablation prefixes of the batched round: each stage runs the
    round UP TO a protocol fusion boundary, so consecutive deltas attribute
    ops and time to the fusion added between them."""
    from hermes_tpu.core import faststep as fst

    def coordinate(ctl, fs, stream):
        fs2, *_ = fst._coordinate(cfg, ctl, fs, stream)
        return fs2

    def apply_inv(ctl, fs, stream):
        fs2, lanes, slot_lane, taken_lane, *_ = fst._coordinate(
            cfg, ctl, fs, stream)
        fs3, _post = fst._apply_inv_lanes(cfg, ctl, fs2, lanes, taken_lane)
        return fs3

    def full(ctl, fs, stream):
        nxt, _ = fst.fast_round_batched(cfg, ctl, fs, stream)
        return nxt

    return [
        ("coordinate", coordinate),   # intake/reads/arbiter+compaction sort
        ("apply_inv", apply_inv),     # + broadcast + ts scatter-max
        ("acks_commit_val", full),    # + ack derivation + winner row write
    ]


def _scan_chunk(cfg, round_fn, rounds: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chunk(fs, stream, ctl):
        def body(carry, off):
            return round_fn(ctl._replace(step=ctl.step + off), carry, stream), None

        fs, _ = jax.lax.scan(body, fs, jnp.arange(rounds, dtype=jnp.int32))
        return fs

    return chunk


def _timed_chunk(cfg, chunk, rounds: int, reps: int) -> float:
    """Median ms/round of a compiled scan chunk under the honest protocol
    for this runtime: a readback first (execution through the tunneled PJRT
    link is DEFERRED until the first device-to-host fetch), then timed
    dispatches synced per rep."""
    import jax

    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    fs = jax.device_put(fst.init_fast_state(cfg))
    # a REAL op stream (host-generated YCSB, same as the script this module
    # replaces): stub_stream is all-NOP — shape-correct for the census but
    # an idle round, which would make every timed cell a lie
    stream = jax.device_put(fst.prep_stream(ycsb.make_streams(cfg)))
    fs = chunk(fs, stream, fst.make_fast_ctl(cfg, 0))
    jax.block_until_ready(fs)
    jax.device_get(jax.tree.leaves(fs)[0].ravel()[:1])  # force sync mode
    ts = []
    for c in range(1, 1 + reps):
        t0 = time.perf_counter()
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
        jax.block_until_ready(fs)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2] / rounds * 1e3


def round_ledger(cfg, rounds: int = 30, reps: int = 3,
                 time_stages: bool = True) -> dict:
    """The per-fusion cost ledger of the batched round at cfg's shape:
    ``stages`` rows carry each fusion's sparse-op delta (from censusing the
    ablation prefixes), the cost-model pricing of that delta, and — when
    ``time_stages`` — the measured ms/round delta.  ``census`` is the full
    single-round census; ``round_ms`` the measured full round (None when
    census-only)."""
    import jax

    stages = _stage_fns(cfg)
    fs, stream, ctl = _abstract_round_args(cfg)
    rows = []
    prev_census: Optional[dict] = None
    prev_ms: Optional[float] = None
    full_census = None
    for name, fn in stages:
        chunk = _scan_chunk(cfg, fn, rounds)
        cen = census_text(jax.jit(chunk).lower(fs, stream, ctl).as_text())
        ms = _timed_chunk(cfg, chunk, rounds, reps) if time_stages else None
        ops = {
            k: cen[k] - (prev_census[k] if prev_census else 0)
            for k in SPARSE + COLLECTIVE
            if cen[k] - (prev_census[k] if prev_census else 0)
        }
        d_sparse = cen["sparse_total"] - (
            prev_census["sparse_total"] if prev_census else 0)
        rows.append({
            "fusion": name,
            "ops": ops,
            "sparse_delta": d_sparse,
            "modeled_ms": [round(d_sparse * COST_LO, 2),
                           round(d_sparse * COST_HI, 2)],
            "ms": (None if ms is None
                   else round(ms - (prev_ms or 0.0), 3)),
        })
        prev_census, prev_ms, full_census = cen, ms, cen
    return {
        "shape": census_shape(cfg),
        "rounds": rounds if time_stages else 0,
        "census": full_census,
        "stages": rows,
        "round_ms": None if prev_ms is None else round(prev_ms, 3),
    }


# --------------------------------------------------------------------------
# Budget gate + JSONL export
# --------------------------------------------------------------------------


def check_budget(census_by_engine: dict, budget: dict) -> list:
    """CI gate predicate: for every engine in ``budget``, every budgeted
    count in the measured census must not exceed its ceiling.  Returns the
    list of human-readable failures (empty = gate passes).  A budgeted
    engine missing from the census is itself a failure — a silently
    skipped engine must not read as a pass."""
    failures = []
    for engine, limits in sorted(budget.items()):
        cen = census_by_engine.get(engine)
        if cen is None:
            failures.append(f"{engine}: no census measured for budgeted engine")
            continue
        for metric, ceiling in sorted(limits.items()):
            got = cen.get(metric)
            if got is None:
                failures.append(f"{engine}: census lacks budgeted metric "
                                f"{metric!r}")
            elif got > ceiling:
                failures.append(
                    f"{engine}: {metric} = {got} exceeds budget {ceiling} — "
                    f"a sparse/collective op crept back onto the round chain "
                    f"(each is ~{COST_LO}-{COST_HI} ms/round on the target "
                    f"chip); re-diet the round or consciously raise "
                    f"OP_BUDGET.json")
    return failures


def export_profile(path_or_fp, records, extra: Optional[dict] = None) -> None:
    """Write profile records as obs run-log JSONL (kind="profile", shared
    monotonic ``t`` stamp — the PR-1 schema scripts/obs_report.py merges)."""
    from hermes_tpu.obs.metrics import JsonlExporter

    own = isinstance(path_or_fp, str)
    fp = open(path_or_fp, "w") if own else path_or_fp
    try:
        exp = JsonlExporter(fp, stamp=True)
        for rec in records:
            if extra:
                rec = {**extra, **rec}
            exp.write(rec, kind="profile")
    finally:
        if own:
            fp.close()


def round_record(census: dict, **extra) -> dict:
    """One obs "round" profile record: the census plus its cost-model
    pricing.  The single constructor for every producer (bench.py
    --profile-out, the cli's --profile-out), so the JSONL schema cannot
    drift between them."""
    return dict(
        record="round", census=census,
        modeled_sparse_ms=[round(census["sparse_total"] * COST_LO, 1),
                           round(census["sparse_total"] * COST_HI, 1)],
        **extra)


def ledger_records(ledger: dict) -> list:
    """Flatten a round_ledger() result into per-row JSONL records: one
    summary record (census + round_ms) + one record per fusion stage."""
    head = {k: ledger[k] for k in ("shape", "rounds", "census", "round_ms")}
    head["record"] = "round"
    rows = [{"record": "fusion", **row} for row in ledger["stages"]]
    return [head] + rows


# --------------------------------------------------------------------------
# CLI (the promoted scripts/profile_round.py)
# --------------------------------------------------------------------------


def _cli_cfg(S: int, C: int, arb_mode: str = "race", chain_writes: int = 0,
             fused_sort: bool = True):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    return HermesConfig(
        n_replicas=8, n_keys=1 << 20, value_words=8, n_sessions=S,
        replay_slots=256, ops_per_session=128, wrap_stream=True,
        lane_budget_cfg=C, rebroadcast_every=4, replay_scan_every=32,
        arb_mode=arb_mode, chain_writes=chain_writes, fused_sort=fused_sort,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hermes_tpu.obs.profile",
        description="Per-fusion cost ledger + op census of the fast round "
        "(honest timing protocol for the tunneled runtime; see module doc).")
    ap.add_argument("sessions", nargs="?", type=int, default=16384)
    ap.add_argument("lane_budget", nargs="?", type=int, default=None,
                    help="default: sessions // 2")
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--arb-mode", choices=["race", "sort"], default="race",
                    help="historical profile_round.py default is race; the "
                    "bench operating point is sort (+--chain-writes 128)")
    ap.add_argument("--chain-writes", type=int, default=0)
    ap.add_argument("--split-sort", action="store_true",
                    help="profile the split two-sort program (the fused-"
                    "sort A/B baseline; sort arbiter only)")
    ap.add_argument("--census-only", action="store_true",
                    help="skip timing (abstract lowering only; CPU-safe at "
                    "any shape)")
    ap.add_argument("--out", default=None, metavar="PROFILE_JSONL",
                    help="additionally export the ledger as obs-schema "
                    "JSONL records (kind=profile)")
    args = ap.parse_args(argv)

    cfg = _cli_cfg(args.sessions, args.lane_budget or args.sessions // 2,
                   arb_mode=args.arb_mode, chain_writes=args.chain_writes,
                   fused_sort=not args.split_sort)
    led = round_ledger(cfg, rounds=args.rounds, reps=args.reps,
                       time_stages=not args.census_only)
    print(f"S={cfg.n_sessions} C={cfg.lane_budget} "
          f"fused_sort={cfg.use_fused_sort}", file=sys.stderr)
    for row in led["stages"]:
        ms = "      -" if row["ms"] is None else f"{row['ms']:7.2f}"
        print(f"  {row['fusion']:<16}: {ms} ms  +{row['sparse_delta']} sparse "
              f"{row['ops']}", file=sys.stderr)
    print(f"  census: sparse_total={led['census']['sparse_total']} "
          f"collective_total={led['census']['collective_total']} "
          f"round_ms={led['round_ms']}", file=sys.stderr)
    if args.out:
        export_profile(args.out, ledger_records(led))
    print(json.dumps(dict(sparse_total=led["census"]["sparse_total"],
                          collective_total=led["census"]["collective_total"],
                          round_ms=led["round_ms"])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
