"""Crash flight recorder (round-18, hermes_tpu/obs).

An always-on bounded ring of the run's recent obs records plus the last
few harvested Meta counter summaries and the run's config fingerprint
(snapshot.config_fingerprint — the same identity the snapshot manifest
checks).  Recording costs one deque append per obs record (the recorder
tees off the exporter inside ``Observability``), so it stays on for
every instrumented run; nothing is written to disk until a trigger
fires:

  * checker red       — FastRuntime.check / ChaosRunner.run(check=True);
  * ``StuckOpError``  — the KVS strict-timeout watchdog, dumped BEFORE
    the raise so the archive holds the wedged op's diagnostics;
  * gate failure      — scripts/run_gates.py exports the dump dir to
    every gate process and uploads produced dumps into
    GATES_SUMMARY.json;
  * SIGTERM           — opt-in handler (``install_sigterm``) for soaks.

The dump is ONE self-checking JSON archive: ``{"payload": {...},
"sha256": <hex>}`` where the checksum covers the canonical payload
bytes.  ``load`` re-derives and verifies it — a truncated or tampered
archive is refused loudly, and the round-trip is the CI acceptance
test (a post-mortem you cannot trust is worse than none).

The dump directory resolves per trigger: an explicit ``dump_dir`` on
the recorder, else the ``HERMES_FLIGHT_DIR`` environment variable (how
run_gates.py attaches the recorder to gate subprocesses), else no
auto-dump — the ring stays readable in memory and ``dump(path)`` works
manually.  Triggers therefore never litter a test's working directory
unless the run opted in.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import signal
import time
from typing import List, Optional

#: Environment variable naming the auto-dump directory — exported by
#: scripts/run_gates.py so every gate subprocess's triggers land their
#: archives where the summary can collect them.
FLIGHT_DIR_ENV = "HERMES_FLIGHT_DIR"


def _canon(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()


class FlightArchiveError(ValueError):
    """A flight dump failed its checksum or structure check."""


class FlightRecorder:
    """Bounded black box: recent obs records + last-N Meta summaries +
    config fingerprint, dumped as one checksummed archive on demand."""

    def __init__(self, capacity: int = 512, meta_keep: int = 8,
                 dump_dir: Optional[str] = None):
        if capacity < 1 or meta_keep < 1:
            raise ValueError("capacity and meta_keep must be >= 1")
        self.events: collections.deque = collections.deque(maxlen=capacity)
        self.metas: collections.deque = collections.deque(maxlen=meta_keep)
        self.config_sha: Optional[str] = None
        self.dump_dir = dump_dir
        self.dumps: List[str] = []  # paths written by this recorder

    # -- feeding -------------------------------------------------------------

    def record(self, record: dict) -> None:
        """One obs record into the ring (called by the exporter tee)."""
        self.events.append(record)

    def note_meta(self, summary: dict) -> None:
        """One harvested Meta counter summary (runtime counters() polls
        feed this — the last few device-truth snapshots ride the dump)."""
        self.metas.append(dict(summary))

    def set_config(self, cfg) -> None:
        """Stamp the run's config identity (snapshot.config_fingerprint)."""
        from hermes_tpu.snapshot import config_fingerprint

        self.config_sha = config_fingerprint(cfg)

    # -- dumping -------------------------------------------------------------

    def payload(self, reason: str, extra: Optional[dict] = None) -> dict:
        p = dict(
            flight_recorder=1,
            reason=reason,
            config_sha256=self.config_sha,
            n_events=len(self.events),
            events=list(self.events),
            meta_summaries=list(self.metas),
        )
        if extra:
            p["extra"] = extra
        return p

    def dump(self, path: str, reason: str,
             extra: Optional[dict] = None) -> str:
        """Write one checksummed archive; returns the path."""
        payload = self.payload(reason, extra)
        archive = dict(payload=payload,
                       sha256=hashlib.sha256(_canon(payload)).hexdigest())
        with open(path, "w") as f:
            json.dump(archive, f, indent=1, sort_keys=True)
            f.write("\n")
        self.dumps.append(path)
        return path

    def auto_dump(self, reason: str,
                  extra: Optional[dict] = None) -> Optional[str]:
        """Trigger entry point: dump into the resolved directory, or
        return None when no directory is configured (ring stays in
        memory for a manual dump).  The filename carries the reason and
        a monotonic nanosecond stamp so two triggers in one process
        never clobber each other."""
        d = self.dump_dir or os.environ.get(FLIGHT_DIR_ENV)
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        name = f"flight_{reason}_{os.getpid()}_{time.monotonic_ns()}.json"
        return self.dump(os.path.join(d, name), reason, extra)


def load(path: str) -> dict:
    """Read one archive back, verifying its checksum; returns the
    payload.  Raises FlightArchiveError on any mismatch — corruption is
    refused, never silently returned as data."""
    with open(path) as f:
        archive = json.load(f)
    if not isinstance(archive, dict) or "payload" not in archive \
            or "sha256" not in archive:
        raise FlightArchiveError(f"{path}: not a flight archive")
    want = archive["sha256"]
    got = hashlib.sha256(_canon(archive["payload"])).hexdigest()
    if want != got:
        raise FlightArchiveError(
            f"{path}: checksum mismatch (archive says {want[:12]}.., "
            f"payload hashes to {got[:12]}..)")
    return archive["payload"]


def install_sigterm(flight: FlightRecorder, extra: Optional[dict] = None):
    """Install a SIGTERM handler that dumps the black box before
    deferring to the previous disposition.  Returns a zero-arg restore
    callable; soak drivers install around their run loop so an operator
    kill still leaves a post-mortem."""
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        flight.auto_dump("sigterm", extra)
        signal.signal(signal.SIGTERM, prev if prev is not None
                      else signal.SIG_DFL)
        signal.raise_signal(signal.SIGTERM)

    signal.signal(signal.SIGTERM, _handler)

    def restore():
        signal.signal(signal.SIGTERM, prev if prev is not None
                      else signal.SIG_DFL)

    return restore
