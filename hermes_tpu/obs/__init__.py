"""hermes_tpu.obs — unified observability: metrics registry, exporters,
event-timeline tracing (SURVEY.md §5.5; the reference's stats thread,
grown into a subsystem).

Three pillars (plus the round-6 op-census/profiler module
``hermes_tpu.obs.profile`` — imported explicitly, not re-exported here,
since it pulls the engine modules in):

  1. **Device-side phase metrics** — the Meta columns (core/state.Meta):
     base op counters + the phase counters/histograms the fast round sums
     per step at zero host cost (gated by ``HermesConfig.phase_metrics``).
  2. **Host-side registry + exporters** — ``MetricsRegistry`` (counter /
     gauge / histogram) with JSONL, Prometheus-text, and human-report
     exporters (obs/metrics.py, obs/report.py).
  3. **Event-timeline tracing** — span/point trace records on the same
     monotonic clock as interval metrics (obs/trace.py), merged by
     ``scripts/obs_report.py`` into one causally ordered run story.

Round-8 serving-pipeline metrics (fed by runtime.FastRuntime when an obs
context is attached): the registry counters ``host_work_s`` /
``device_wait_s`` split every step_once between host-side work and time
blocked in the completion readback (their ratio is the overlap the
harvest ring buys), the ``pipeline_depth`` gauge tracks the in-flight
ring occupancy, and the ``ctl_upload`` trace event counts control-row
H2D uploads (zero per steady-state round — membership rows are cached
on device behind a dirty flag).  ``scripts/obs_report.py`` renders the
overlap line from the last registry record.

``Observability`` is the facade the runtimes attach
(``Runtime.attach_obs`` / ``FastRuntime.attach_obs``): one registry, one
exporter (file or in-memory), one tracer, one clock.
"""

from __future__ import annotations

from typing import IO, Optional

from hermes_tpu.obs.flightrec import FlightRecorder
from hermes_tpu.obs.metrics import (
    BufferExporter,
    Counter,
    Gauge,
    Histogram,
    JsonlExporter,
    MetricsRegistry,
    percentile_from_counts,
    prometheus_text,
)
from hermes_tpu.obs.series import Series
from hermes_tpu.obs.trace import Tracer
from hermes_tpu.obs.tracing import (
    OP_SPANS,
    OpTracer,
    TraceSampler,
    canonical_span_bytes,
)

__all__ = [
    "BufferExporter", "Counter", "FlightRecorder", "Gauge", "Histogram",
    "JsonlExporter", "MetricsRegistry", "OP_SPANS", "Observability",
    "OpTracer", "Series", "TraceSampler", "Tracer", "canonical_span_bytes",
    "percentile_from_counts", "prometheus_text",
]


class Observability:
    """One obs context for a run: registry + exporter + tracer on a shared
    monotonic clock.

    ``path``/``fp`` select a JSONL file sink; with neither, records buffer
    in memory (``.records`` — tests and post-hoc report rendering).
    ``trace_steps`` additionally emits per-step dispatch/readback spans —
    off by default (two records per protocol step is run-log noise at
    bench scale; faults, intervals, drains and rebases are always traced).

    Round-18: every context also carries an always-on ``FlightRecorder``
    — the exporter tees each stamped record into the recorder's bounded
    ring, so any run with obs attached has a post-mortem black box at
    the cost of one deque append per record.  Dumps are opt-in (a
    ``flight_dir`` here, or HERMES_FLIGHT_DIR in the environment — see
    obs/flightrec.py); ``flight_dump`` is the trigger entry point the
    runtime checker, KVS watchdog, and soak drivers call.
    """

    def __init__(self, path: Optional[str] = None, fp: Optional[IO[str]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 trace_steps: bool = False,
                 flight: Optional[FlightRecorder] = None,
                 flight_dir: Optional[str] = None):
        self.registry = registry or MetricsRegistry()
        self._own_fp = None
        if fp is None and path is not None:
            fp = self._own_fp = open(path, "w")
        self.exporter = JsonlExporter(fp) if fp is not None else BufferExporter()
        self.tracer = Tracer(self.exporter)
        self.trace_steps = trace_steps
        self.flight = flight or FlightRecorder(dump_dir=flight_dir)
        if flight is not None and flight_dir is not None:
            self.flight.dump_dir = flight_dir
        # tee: the recorder's ring sees the same stamped records the sink
        # does, without disturbing the exporter's type (tests isinstance
        # on BufferExporter) or its byte output
        inner_write = self.exporter.write

        def _tee_write(record: dict, kind: str = "metrics",
                       _inner=inner_write) -> None:
            self.flight.record({"t": round(self.exporter.now(), 6),
                                "kind": kind, **record})
            _inner(record, kind=kind)

        self.exporter.write = _tee_write

    @property
    def records(self):
        """Buffered records (in-memory sink only)."""
        if not isinstance(self.exporter, BufferExporter):
            raise AttributeError(
                "records buffer only exists for the in-memory sink; "
                "read the JSONL file back via obs.report.load_records")
        return self.exporter.records

    def interval(self, record: dict) -> None:
        """Write one interval-metrics record (cumulative counters at a
        reporting boundary; obs/report.py derives per-interval rates)."""
        self.exporter.write(record, kind="metrics")

    def summary(self, record: dict) -> None:
        self.exporter.write(record, kind="summary")

    def registry_snapshot(self) -> None:
        """Flush the host registry's current values as one record."""
        self.exporter.write(self.registry.snapshot(), kind="registry")

    def series_snapshot(self) -> None:
        """Flush every time series as one ``kind="series"`` record
        (name -> parallel x/v arrays) — no-op when no series exist."""
        snap = self.registry.series_snapshot()
        if snap:
            self.exporter.write(snap, kind="series")

    def flight_dump(self, reason: str, extra: Optional[dict] = None):
        """Trigger the flight recorder: dump one checksummed archive into
        the configured dump dir (ctor ``flight_dir`` or HERMES_FLIGHT_DIR)
        and return its path, or None when no dir is configured."""
        return self.flight.auto_dump(reason, extra)

    def close(self) -> None:
        if isinstance(self.exporter, JsonlExporter):
            try:
                self.exporter.fp.flush()
            except ValueError:
                pass  # already closed
        if self._own_fp is not None:
            self._own_fp.close()
            self._own_fp = None
