"""hermes_tpu.obs — unified observability: metrics registry, exporters,
event-timeline tracing (SURVEY.md §5.5; the reference's stats thread,
grown into a subsystem).

Three pillars (plus the round-6 op-census/profiler module
``hermes_tpu.obs.profile`` — imported explicitly, not re-exported here,
since it pulls the engine modules in):

  1. **Device-side phase metrics** — the Meta columns (core/state.Meta):
     base op counters + the phase counters/histograms the fast round sums
     per step at zero host cost (gated by ``HermesConfig.phase_metrics``).
  2. **Host-side registry + exporters** — ``MetricsRegistry`` (counter /
     gauge / histogram) with JSONL, Prometheus-text, and human-report
     exporters (obs/metrics.py, obs/report.py).
  3. **Event-timeline tracing** — span/point trace records on the same
     monotonic clock as interval metrics (obs/trace.py), merged by
     ``scripts/obs_report.py`` into one causally ordered run story.

Round-8 serving-pipeline metrics (fed by runtime.FastRuntime when an obs
context is attached): the registry counters ``host_work_s`` /
``device_wait_s`` split every step_once between host-side work and time
blocked in the completion readback (their ratio is the overlap the
harvest ring buys), the ``pipeline_depth`` gauge tracks the in-flight
ring occupancy, and the ``ctl_upload`` trace event counts control-row
H2D uploads (zero per steady-state round — membership rows are cached
on device behind a dirty flag).  ``scripts/obs_report.py`` renders the
overlap line from the last registry record.

``Observability`` is the facade the runtimes attach
(``Runtime.attach_obs`` / ``FastRuntime.attach_obs``): one registry, one
exporter (file or in-memory), one tracer, one clock.
"""

from __future__ import annotations

from typing import IO, Optional

from hermes_tpu.obs.metrics import (
    BufferExporter,
    Counter,
    Gauge,
    Histogram,
    JsonlExporter,
    MetricsRegistry,
    percentile_from_counts,
    prometheus_text,
)
from hermes_tpu.obs.trace import Tracer

__all__ = [
    "BufferExporter", "Counter", "Gauge", "Histogram", "JsonlExporter",
    "MetricsRegistry", "Observability", "Tracer", "percentile_from_counts",
    "prometheus_text",
]


class Observability:
    """One obs context for a run: registry + exporter + tracer on a shared
    monotonic clock.

    ``path``/``fp`` select a JSONL file sink; with neither, records buffer
    in memory (``.records`` — tests and post-hoc report rendering).
    ``trace_steps`` additionally emits per-step dispatch/readback spans —
    off by default (two records per protocol step is run-log noise at
    bench scale; faults, intervals, drains and rebases are always traced).
    """

    def __init__(self, path: Optional[str] = None, fp: Optional[IO[str]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 trace_steps: bool = False):
        self.registry = registry or MetricsRegistry()
        self._own_fp = None
        if fp is None and path is not None:
            fp = self._own_fp = open(path, "w")
        self.exporter = JsonlExporter(fp) if fp is not None else BufferExporter()
        self.tracer = Tracer(self.exporter)
        self.trace_steps = trace_steps

    @property
    def records(self):
        """Buffered records (in-memory sink only)."""
        if not isinstance(self.exporter, BufferExporter):
            raise AttributeError(
                "records buffer only exists for the in-memory sink; "
                "read the JSONL file back via obs.report.load_records")
        return self.exporter.records

    def interval(self, record: dict) -> None:
        """Write one interval-metrics record (cumulative counters at a
        reporting boundary; obs/report.py derives per-interval rates)."""
        self.exporter.write(record, kind="metrics")

    def summary(self, record: dict) -> None:
        self.exporter.write(record, kind="summary")

    def registry_snapshot(self) -> None:
        """Flush the host registry's current values as one record."""
        self.exporter.write(self.registry.snapshot(), kind="registry")

    def close(self) -> None:
        if isinstance(self.exporter, JsonlExporter):
            try:
                self.exporter.fp.flush()
            except ValueError:
                pass  # already closed
        if self._own_fp is not None:
            self._own_fp.close()
            self._own_fp = None
