"""Per-op distributed tracing (round-18, hermes_tpu/obs pillar 3 grown
end-to-end).

Dapper-style sampled tracing (Sigelman et al., 2010) adapted to the house
determinism rules: the sampling decision and the trace id are SEEDED
HASHES of a monotone submit sequence — pure host integers, no RNG state,
no clock — so a seeded run traces the SAME ops with the SAME ids on every
replay and on every engine.  A trace id is a nonzero u16 (it rides the
formerly-pad u16 of the serving request struct, wire._REQ; 0 on the wire
= not sampled), minted at ``kvs.KVS`` submit or ``serving.Frontend``
admission and carried through the admission ladder, intake queue,
pipelined dispatch/harvest, and future resolution.

Span records ride the ordinary obs JSONL stream (kind ``span_end`` — one
record per closed phase, the schema scripts/obs_report.py already
renders).  Every span carries ONLY deterministic identity fields plus
the two wall-clock fields the exporter stamps (``t``) and the span
measures (``dur_s``):

  * ``fe_queue``  — admission -> store issue (serving intake queue);
  * ``op_queue``  — KVS submit -> slot injection (client-queue wait);
  * ``op_rounds`` — injection round -> resolution round (device rounds);
  * ``fe_resolve``— admission -> RPC resolution (end-to-end), with the
    terminal status.

All spans tag ``trace`` (the id), the op identity (kind/key), and
whatever placement is known at that layer (replica/session lane, tenant,
fleet group).  Round indices ride ``r0``/``r1`` — latency attribution in
PROTOCOL ROUNDS, the deterministic unit the rest of the repo reports in.

``canonical_span_bytes`` is the replay-gate projection: the span stream
minus its wall-clock fields, serialized canonically.  Two runs of the
same seeded workload — same engine or batched-vs-sharded — must produce
byte-identical projections (tests/test_tracing.py); wall time is the
only thing allowed to differ.

Behavior identity is by construction: nothing here touches the compiled
round (the op census cannot move — scripts/check_op_census.py proves the
traced config lowers to the identical program), and every emission site
keeps the ``obs is None`` fast path.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional

import numpy as np

#: Wire-field capacity: a trace id is a nonzero u16 (wire._REQ's second
#: pad).  0 = not sampled, so ids live in [1, TRACE_ID_MAX].
TRACE_ID_MAX = 0xFFFF

#: Span names of the per-op critical path, in causal order (the report's
#: breakdown iterates this).
OP_SPANS = ("fe_queue", "op_queue", "op_rounds", "fe_resolve")

_MIX = 0x9E3779B97F4A7C15  # splitmix64 increment (golden-ratio odd)


def _splitmix64(x: int) -> int:
    """One splitmix64 scramble round — a well-mixed 64-bit hash of a
    counter, in pure ints (deterministic across platforms/replays)."""
    x = (x + _MIX) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


class TraceSampler:
    """Seeded deterministic 1-in-``rate`` sampler.

    ``sample(seq)`` maps a monotone per-submitter sequence number to a
    trace id: 0 (not sampled) for all but ~1/rate of the sequence, a
    nonzero u16 otherwise.  The decision is ``hash(seed, seq) % rate ==
    0`` — a pure function, so the SAME ops are sampled on every replay
    of a seeded run, which is what makes the span log gateable
    byte-for-byte.  ``rate=1`` traces everything; constructing with
    ``rate <= 0`` is refused (0 means "tracing off" and belongs to the
    caller's config, not to a sampler)."""

    def __init__(self, rate: int, seed: int = 0):
        if rate <= 0:
            raise ValueError("sample rate must be >= 1 (one in N ops)")
        self.rate = int(rate)
        self.seed = int(seed)

    def sample(self, seq: int) -> int:
        """Trace id for submit-sequence ``seq``: 0 = not sampled."""
        h = _splitmix64((self.seed * 0x5851F42D4C957F2D + seq)
                        & 0xFFFFFFFFFFFFFFFF)
        if h % self.rate:
            return 0
        # fold the top bits into a nonzero u16 id; collisions across a
        # long run are harmless (spans also carry lane/key identity)
        return (h >> 40) % TRACE_ID_MAX + 1

    def sample_array(self, seqs) -> np.ndarray:
        """Vectorized ``sample`` over a submit-sequence column: one
        splitmix64 pass in uint64 numpy arithmetic, bit-exact with the
        scalar path row for row (tests/test_shm_ipc.py proves it) — the
        columnar front-end's trace mint no longer loops Python per
        unsampled row (round-21)."""
        m64 = np.uint64(0xFFFFFFFFFFFFFFFF)
        with np.errstate(over="ignore"):
            x = (np.uint64((self.seed * 0x5851F42D4C957F2D)
                           & 0xFFFFFFFFFFFFFFFF)
                 + np.asarray(seqs, np.uint64))
            x = x + np.uint64(_MIX)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            h = (x ^ (x >> np.uint64(31))) & m64
            ids = (h >> np.uint64(40)) % np.uint64(TRACE_ID_MAX) \
                + np.uint64(1)
        return np.where(h % np.uint64(self.rate), 0,
                        ids).astype(np.uint16)


class OpTracer:
    """Span writer for the per-op phases: one ``span_end`` record per
    closed phase, through the run's ordinary exporter (one shared clock,
    one merged timeline).  All methods are cheap host dict writes and
    are only reached for SAMPLED ops — unsampled ops never touch this
    object, and callers keep their own ``obs is None`` fast path."""

    def __init__(self, obs):
        self.obs = obs

    def span(self, name: str, trace: int, r0: int, r1: int,
             dur_s: Optional[float] = None, **tags) -> None:
        rec = {"name": name, "trace": int(trace),
               "dur_s": round(dur_s, 6) if dur_s is not None else None,
               "r0": int(r0), "r1": int(r1), **tags}
        if rec["dur_s"] is None:
            del rec["dur_s"]
        self.obs.exporter.write(rec, kind="span_end")


# -- replay-gate projection ---------------------------------------------------

#: Fields a span record may legitimately vary in between replays: the
#: shared-clock stamp and the measured wall duration.  Everything else
#: is identity and must replay byte-identically.
WALL_FIELDS = ("t", "dur_s")


def canonical_span_bytes(records: Iterable[dict],
                         names: Iterable[str] = OP_SPANS) -> bytes:
    """The determinism witness of a traced run: the op-span stream with
    wall-clock fields stripped, canonically serialized (sorted keys, one
    JSON object per line).  Same seed + same workload => byte-identical,
    on either engine — the property tests/test_tracing.py gates."""
    want = frozenset(names)
    out: List[str] = []
    for r in records:
        if r.get("kind") == "span_end" and r.get("name") in want:
            out.append(json.dumps(
                {k: v for k, v in r.items() if k not in WALL_FIELDS},
                sort_keys=True))
    return ("\n".join(out) + "\n").encode() if out else b""
