"""Run configuration for hermes_tpu.

The reference keeps its knobs as compile-time ``#define``s plus run-script
flags (SURVEY.md §2 "Config" row, §5.6).  The rebuild uses one frozen
dataclass; anything that changes compiled shapes (replicas, sessions, keys,
lanes) is static so a config maps 1:1 to a compiled XLA program.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Literal, Optional, Tuple

from hermes_tpu.core import layouts

#: Mega-round VMEM budget for the (K,) vpts arbiter column (round-15):
#: the apply kernel keeps the whole packed-ts column on-chip (4 bytes/key
#: — 4 MB at the 1M-key bench shape against ~16 MB VMEM/core); configs
#: past this must run the fused-sort program (config validation refuses
#: mega_round loudly instead of silently spilling to HBM).
MEGA_VPTS_VMEM_BYTES = 8 << 20

# The declared chain-rank field must hold every legal chain_writes value
# (the [0, 4096] protocol bound below); a layout edit that shrinks the
# field without revisiting the bound fails at import, not at runtime.
assert 4096 < layouts.LANE_WORD.field("chain_rank").cap
assert 4096 < layouts.ARB_WORD.field("chain_rank").cap


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """YCSB-style synthetic workload knobs (SURVEY.md §2 "Workload generator").

    The five acceptance configs (BASELINE.json:7-11) are expressible here:
    YCSB-A = read_frac .5, rmw_frac 0; YCSB-F = rmw mix; Zipfian hotspot via
    ``distribution='zipfian'`` with theta 0.99.
    """

    read_frac: float = 0.5
    rmw_frac: float = 0.0  # fraction of *update* ops that are RMWs (YCSB-F -> 1.0)
    distribution: Literal["uniform", "zipfian"] = "uniform"
    zipf_theta: float = 0.99
    seed: int = 0

    def __post_init__(self) -> None:
        if self.distribution == "zipfian" and not (0.0 < self.zipf_theta < 1.0):
            # the YCSB analytic inverse (ycsb._zipf_consts) divides by
            # 1-theta; theta >= 1 needs a different sampler entirely
            raise ValueError("zipf_theta must be in (0, 1)")


@dataclasses.dataclass(frozen=True)
class HermesConfig:
    """Static shape + protocol configuration.

    One TPU chip (or one simulated shard) is one Hermes replica
    (BASELINE.json:5).  All shapes are static: ``n_replicas`` sets mesh size,
    ``n_sessions`` the per-replica client-session count (= max in-flight
    updates per replica, the reference's session arrays in ``worker.c``
    [SURVEY.md §1 L5]), ``n_keys`` the KVS size.
    """

    n_replicas: int = 3
    n_keys: int = 1 << 16
    value_words: int = 2  # int32 words per value; word0/word1 hold the unique write id
    n_sessions: int = 256  # client sessions per replica; lane width of msg tensors
    replay_slots: int = 64  # concurrent replays per replica (SURVEY.md §3.4)
    ops_per_session: int = 1024  # pre-generated op-stream length per session

    # Protocol / failure handling (SURVEY.md §5.3).
    replay_age: int = 16  # steps a key may sit Invalid before the replay scan picks it up
    lease_steps: int = 8  # host-side membership lease (steps without heartbeat -> suspect)

    # Bench mode (SURVEY.md §7 M6): sessions cycle their op stream forever
    # instead of going DONE after ops_per_session ops, so a small pre-generated
    # stream drives an arbitrarily long run.  Write uids stay unique until the
    # total per-session op count reaches 2^31 / n_sessions.
    wrap_stream: bool = False

    # --- faststep knobs (core/faststep.py) --------------------------------
    # Outbound INV/VAL lanes compact to this budget per round (None = no
    # compaction, every lane gets a slot).  Overflowing lanes wait a round —
    # safe, since same-ts re-broadcast is idempotent (SURVEY.md §7 hard
    # part 2).
    lane_budget_cfg: Optional[int] = None
    # An unacked in-flight lane re-broadcasts its INV every this many rounds
    # (fresh issues always broadcast).
    rebroadcast_every: int = 4
    # The full-table stuck-key replay scan (SURVEY.md §3.4) runs every this
    # many rounds (it only matters after failures/drops).
    replay_scan_every: int = 8
    # Local-read drain depth: each protocol round runs this many
    # intake+read sub-steps before the issue path, so a session completes
    # up to read_unroll consecutive LOCAL reads per round and an update is
    # issued the same round it is drawn — the reference worker loop's
    # read-batching (reads never touch the network, SURVEY.md §3.2).
    # Sub-step completions are recorded in program order.
    read_unroll: int = 1

    # Override the issue-arbitration hash-table size (power of two).  None
    # = auto (arb_slots property).  Smaller tables scatter faster on this
    # chip but raise the false-collision deferral rate (~S/2HS per issue).
    arb_slots_cfg: Optional[int] = None

    # Same-replica same-key issue arbitration strategy (faststep):
    #   "race" — hash-slot scatter-min + gather (2 sparse ops; false
    #            collisions defer ~S/2HS of issues one round);
    #   "sort" — lexicographic (key, session) sort + one win-bit scatter
    #            (collision-free: every distinct wanted key issues).
    # Both are protocol-equivalent (lowest eligible session wins a key).
    arb_mode: Literal["race", "sort"] = "race"

    # Round-6 op diet: fuse the arbiter sort and the lane->slot compaction
    # sort into ONE per-round lax.sort over the lane axis (sort arbiter
    # only; see faststep._coordinate).  The fused key packs
    # (band << 29) | sub — band 0 = waiting/replay, 1 = fresh-issue runs
    # grouped by rotated key, 2 = ineligible — and lax.sort's stability
    # preserves the arbiter's lowest-session-wins order within equal-key
    # runs.  Each removed sort is ~1.8 ms of size-independent sparse-op
    # cost per round on the target chip.  False restores the split
    # two-sort program (the A/B cell scripts/fused_compare.py measures,
    # and the fallback when the packed key cannot hold the shape —
    # use_fused_sort is the resolved switch).
    fused_sort: bool = True

    # Round-15 Pallas mega-round (core/megaround.py): fuse the fused-sort
    # round's route-back scatter, the arbiter scatter-max + post-arbiter
    # verdict gather, and the cond-gated replay scan's sparse interior
    # into Pallas kernels stepping the packed per-key state (the
    # core/layouts.py word tables) with the vpts arbiter column resident
    # in VMEM — batched sparse census 12 -> 4, sharded 15 -> 7 (the
    # measured cost model prices each removed op at ~1.3-2.4 ms/round).
    # Resolution follows the fused_sort pattern: ``use_mega_round`` is the
    # resolved switch, the fused-sort program remains the A/B baseline and
    # the automatic fallback — core/megaround.resolve() additionally
    # refuses (loudly, via warnings) when the kernel self-check fails to
    # compile on this backend or the invariant analyzer flags the kernel
    # bodies.  Requires the fused sort (the mega route consumes its
    # sorted-order verdicts) and a VMEM-residable arbiter column.
    mega_round: bool = False

    # Intra-round same-key write chaining (sort arbiter only): up to this
    # many of a replica's wanting sessions for ONE key issue per round as a
    # packed-ts chain (ver+1, ver+2, ..) and commit together — the hot-key
    # service-rate lever (BASELINE.json:9): per-key throughput becomes
    # ~n_replicas*chain_writes per round instead of n_replicas.  Chained
    # writes are superseded in-round by the chain top exactly like
    # cross-replica same-version writes are today (ordered by ts, value
    # never observed), so linearizability is unchanged.  Only PLAIN writes
    # chain: an RMW issues alone at the head of a run and blocks chaining
    # behind it (its read-part must see the immediately-preceding value).
    # 0 disables (identical program to the unchained arbiter).  Version
    # budget: a hot key consumes ~chain_writes versions per round (replicas
    # mint overlapping ranges from the same committed base version — only
    # the max survives) against max_key_versions (~1M); the runtime
    # watermark guard catches a crossing loudly.
    chain_writes: int = 0

    # Version-rebase (round-4; removes the chaining version-budget cliff):
    # when a counter poll sees the packed-ts watermark past
    # rebase_fraction * max_key_versions, the runtime quiesces in-flight
    # writes and resets settled keys to version 1
    # (FastRuntime.rebase_versions), restoring the full budget; recorded
    # histories stay checker-valid across the reset (per-key deltas are
    # added back on record).  auto_rebase=False restores the old loud-
    # RuntimeError-only behavior.
    auto_rebase: bool = True
    rebase_fraction: float = 0.5

    # RMW nack handling (round-5; round-4 verdict weak #2).  0 = reference
    # behavior: a pending RMW aborts on any nack (a concurrent higher-ts
    # update intervened) and the client sees rmw_abort.  N > 0: the session
    # retries in place up to N times — it returns to the issue state with
    # its op/key/value (and write uid) intact, re-reads the key once the
    # winner's commit re-validates it (usually the very next round), and
    # re-issues at a fresh ts; the read-part is re-snapshotted at re-issue,
    # so the committed RMW still observed the immediately-preceding value
    # and linearizability is unchanged.  Only the FINAL failure aborts, so
    # contended mixes convert abort work into commits at the cost of up to
    # N extra rounds of client latency.  An earlier attempt's timestamp is
    # globally dead the moment it is nacked (it lost the scatter-max
    # arbitration everywhere and its row was never written), so no state
    # leaks between attempts.
    rmw_retries: int = 0

    # Device-side phase metrics (hermes_tpu/obs): per-round protocol-phase
    # counters and the ACK quorum-wait histogram summed into the Meta
    # columns (core/state.Meta: n_inv/n_rebcast/n_nack/n_retry/replay_peak/
    # qwait_*).  All dense elementwise+reduction work that XLA fuses into
    # the round; False compiles the uninstrumented program (the ablation
    # baseline scripts/check_obs_overhead.py measures against).  The base
    # counters (n_read/n_write/n_rmw/n_abort/lat_*) are always on — they
    # predate this flag and the acceptance drivers read them.
    phase_metrics: bool = True

    # --- serving pipeline (round-8, runtime.FastRuntime / kvs.KVS) --------
    # Donate the state tree to the compiled round: XLA aliases the ~46 MB
    # FastState buffers in place instead of copying them every dispatch.
    # On for the runtimes (the serving path never reuses a superseded
    # state reference — holding one raises loudly, see
    # tests/test_pipeline.py); False restores the copying program, kept as
    # the A/B baseline bench.py --pipeline measures against.  The raw
    # builders (build_fast_batched/...) keep their own defaults for
    # scripts that manage state lifetime themselves.
    donate_state: bool = True
    # In-flight dispatch ring depth for FastRuntime.step_once (and the
    # KVS client layer): 1 = synchronous (each round's completions are
    # fetched before the next dispatch — the pre-round-8 behavior);
    # depth >= 2 dispatches round k+1 before harvesting round k, so the
    # device->host completion readback and the host-side
    # recording/matching work overlap with the next device round.
    # Completions still surface strictly in round order (a FIFO ring), so
    # recorder/checker semantics are unchanged.  The KVS layer caps its
    # effective depth at 2: round k+1's op stream must retire round k's
    # completed slots (or idle sessions would re-issue the same client
    # op), so only the BULK value readback + future resolution lag one
    # round — see kvs.KVS.step.
    pipeline_depth: int = 1

    # KVS stuck-op watchdog (round-9 chaos & recovery): a client op still
    # pending after this many protocol rounds surfaces a ``stuck_op`` obs
    # event and a per-session diagnostic (kvs.KVS.stuck_ops: coordinator,
    # session, protocol phase, gathered-ack bitmap, age) instead of hanging
    # silently — under faults an op CAN legitimately stall (its quorum is
    # frozen), and a pipelined server must say so.  0 disables.  The
    # opt-in strict mode (KVS(strict_timeouts=True)) raises StuckOpError.
    op_timeout_rounds: int = 0

    # Bounded client retry for ops wedged by an adversary (round-11; needs
    # op_timeout_rounds > 0).  A stuck op whose coordinator replica is
    # FENCED (removed from the live set or frozen — e.g. partitioned away
    # and ejected by the detector) is salvaged exactly like a crash loses
    # it (history fold as maybe_w for updates, volatile slot wipe so the
    # dead uid never re-mints) and transparently re-submitted on a healthy
    # replica with a fresh write uid, up to this many times; the ORIGINAL
    # future resolves when the retry completes.  Exhausted retries resolve
    # kind='lost'.  A stuck op on a HEALTHY coordinator is never retried
    # (it may still commit — blind retry would double-write); the watchdog
    # re-examines it after an exponential backoff instead.  0 disables
    # (the round-9 diagnose-only watchdog).  Per-op-future path only; the
    # batch path keeps watchdog diagnostics.
    op_retry_limit: int = 0
    # Backoff multiplier between stuck-op re-examinations: the k-th check
    # of one op waits op_timeout_rounds * op_backoff**k rounds.
    op_backoff: int = 2

    # Per-op tracing sample rate (round-18, obs/tracing.py): 0 = off,
    # N >= 1 = trace ~1 in N submitted ops with a seeded deterministic
    # sampler (seeded from workload seed; same ops trace on every replay).
    # Host-only — the sampler, span emission, and id plumbing never touch
    # the compiled round, so the lowered program and its op census are
    # identical at any rate (scripts/check_op_census.py proves it).
    trace_sample: int = 0

    # Quorum-loss degraded mode (round-11): with fewer than this many
    # healthy (live, unfrozen, unretired) replicas, NEW puts/RMWs are shed
    # loudly at submission (kind='rejected' / C_REJECTED — the op never
    # entered the store, retry later) instead of queueing into a cluster
    # that cannot commit them; gets still serve.  Entry/exit land on the
    # obs timeline as ``degraded``/``degraded_clear``.  0 disables.
    min_healthy_for_writes: int = 0

    # Round-17 value heap (hermes_tpu/heap): variable-length byte values
    # up to this many bytes per key, stored in an HBM-resident
    # log-structured append heap (MICA-style, PAPER.md's KVS substrate)
    # instead of fixed config-width words.  The key's row carries ONE
    # packed (granule | length) ref word (core/layouts.py HEAP_REF) in
    # its first payload slot; the extent bytes land in the heap BEFORE
    # the INV issues, so the round moves only the ref word and the op
    # census is provably unchanged (scripts/check_op_census.py's round
    # sections do not move; the heap's own programs are budgeted under
    # the heap_path/heap_append sections).  0 disables (the pre-round-17
    # fixed-word store — every existing driver unchanged).  Heap mode
    # needs value_words >= 3 (payload word 0 carries the ref) and is a
    # KVS-level subsystem: stream-driven runs have no byte payloads.
    max_value_bytes: int = 0
    # Heap log capacity in bytes (heap mode only): granule-aligned
    # (layouts.HEAP_GRANULE), capped by the declared 19-bit granule
    # field at layouts.MAX_HEAP_BYTES (8 MiB).  Dead extents (overwritten
    # values) are reclaimed by compaction at version-rebase boundaries
    # and on allocation pressure (kvs.KVS.heap_gc).
    heap_bytes: int = 1 << 22

    # Round-22 durability tier (hermes_tpu/wal): a host-side write-ahead
    # extent+commit log fed from the harvest path.  ``wal_dir`` names the
    # segment directory (None disables — the pre-round-22 snapshot-bounded
    # crash model).  A dedicated flusher thread group-commits records
    # across rounds with one fsync per batch; ``wal_sync`` picks the
    # durability contract a client completion carries:
    #   "commit" — a write's future resolves only after its log record is
    #              fsync-durable (zero committed writes lost on power cut);
    #   "round"  — records are written+fsynced by the group-commit flusher
    #              but completions do NOT wait for it (a crash can lose
    #              the last dirty window; completions are loudly labeled);
    #   "off"    — records are written but never fsynced (page-cache
    #              durability only; loudly labeled).
    wal_dir: Optional[str] = None
    wal_sync: Literal["commit", "round", "off"] = "commit"
    # Segment rotation size: a segment past this many bytes is sealed
    # (fsynced) and a fresh one opened, so snapshot-save truncation can
    # drop whole sealed segments behind the snapshot step.
    wal_segment_bytes: int = 1 << 20
    # Backpressure bound: with more than this many appended-but-not-yet-
    # durable records, NEW puts/RMWs are shed loudly at submission
    # (kind='retry_after' / C_RETRY_AFTER) instead of silently stalling
    # behind a slow disk.
    wal_dirty_window: int = 256

    # Generate the op stream ON DEVICE from a counter hash instead of
    # gathering pre-generated arrays (SURVEY.md §2 "in-kernel PRNG"):
    # removes the stream-gather ops from the hot round.  Uniform or
    # scrambled-Zipfian keys (analytic inverse, no CDF table; n_keys must
    # be a power of two); workload.rmw_frac/read_frac honored;
    # ycsb.device_stream_host reproduces the stream host-side (bit-exact
    # for uniform; statistically for zipfian — f32 pow ULPs).
    device_stream: bool = False

    workload: WorkloadConfig = dataclasses.field(default_factory=WorkloadConfig)

    def __post_init__(self) -> None:
        if not (1 <= self.n_replicas <= 31):
            raise ValueError(
                "n_replicas must be in [1, 31] (live mask is an int32 bitmap and"
                " (1<<32)-1 overflows int32)"
            )
        if self.read_unroll < 1:
            raise ValueError("read_unroll must be >= 1")
        if self.arb_slots_cfg is not None and (
            self.arb_slots_cfg <= 0
            or self.arb_slots_cfg & (self.arb_slots_cfg - 1)
        ):
            raise ValueError("arb_slots_cfg must be a positive power of two")
        if self.arb_mode not in ("race", "sort"):
            raise ValueError("arb_mode must be 'race' or 'sort'")
        if not (0 <= self.chain_writes <= 4096):
            raise ValueError("chain_writes must be in [0, 4096]")
        if self.chain_writes and self.arb_mode != "sort":
            raise ValueError(
                "chain_writes needs arb_mode='sort' (chain ranks come from "
                "the sorted equal-key runs)"
            )
        if self.mega_round:
            # loud at construction for knob mismatches a caller controls;
            # platform/analysis refusals fall back automatically at build
            # time instead (core/megaround.resolve warns)
            if self.arb_mode != "sort" or not self.fused_sort:
                raise ValueError(
                    "mega_round needs arb_mode='sort' and fused_sort=True "
                    "(the mega route kernel consumes the fused sort's "
                    "sorted-order verdicts)")
            if 4 * self.n_keys > MEGA_VPTS_VMEM_BYTES:
                raise ValueError(
                    f"mega_round needs the vpts arbiter column VMEM-"
                    f"resident: 4*n_keys = {4 * self.n_keys} bytes exceeds "
                    f"the {MEGA_VPTS_VMEM_BYTES}-byte budget "
                    f"(config.MEGA_VPTS_VMEM_BYTES)")
        if not (0 <= self.rmw_retries <= (1 << 20)):
            raise ValueError("rmw_retries must be in [0, 2^20]")
        if self.op_timeout_rounds < 0:
            raise ValueError("op_timeout_rounds must be >= 0 (0 disables)")
        if self.op_retry_limit < 0:
            raise ValueError("op_retry_limit must be >= 0 (0 disables)")
        if self.op_retry_limit and not self.op_timeout_rounds:
            raise ValueError(
                "op_retry_limit needs op_timeout_rounds > 0 (the watchdog "
                "is what detects a wedged op in the first place)")
        if self.op_backoff < 1:
            raise ValueError("op_backoff must be >= 1")
        if self.trace_sample < 0:
            raise ValueError("trace_sample must be >= 0 (0 disables, N = "
                             "one in N ops)")
        if not (0 <= self.min_healthy_for_writes <= self.n_replicas):
            raise ValueError(
                "min_healthy_for_writes must be in [0, n_replicas]")
        if not (1 <= self.pipeline_depth <= 64):
            raise ValueError(
                "pipeline_depth must be in [1, 64] (each in-flight round "
                "pins a full Completions tuple in device memory)"
            )
        if self.n_keys > layouts.INV_PKF.field("key").cap:
            raise ValueError(
                "n_keys must fit the declared INV key field "
                f"({layouts.INV_PKF.field('key').bits} bits — faststep "
                "packs key|fresh|valid into one int32 INV word; see "
                "core/layouts.py)"
            )
        if self.value_words < 2:
            raise ValueError("value_words >= 2 (words 0-1 carry the unique write id)")
        if self.max_value_bytes < 0:
            raise ValueError("max_value_bytes must be >= 0 (0 disables the heap)")
        if self.max_value_bytes:
            if self.value_words < 3:
                raise ValueError(
                    "the value heap needs value_words >= 3 (2 uid words + "
                    "the packed heap-ref payload word, layouts.HEAP_REF)")
            if self.max_value_bytes > layouts.MAX_VALUE_BYTES:
                raise ValueError(
                    f"max_value_bytes {self.max_value_bytes} exceeds the "
                    f"declared heap-ref len field "
                    f"({layouts.MAX_VALUE_BYTES} bytes — core/layouts.py "
                    "HEAP_REF)")
            if self.heap_bytes % layouts.HEAP_GRANULE:
                raise ValueError(
                    f"heap_bytes must be a multiple of the "
                    f"{layouts.HEAP_GRANULE}-byte heap granule")
            if self.heap_bytes > layouts.MAX_HEAP_BYTES:
                raise ValueError(
                    f"heap_bytes {self.heap_bytes} exceeds the declared "
                    f"granule field's reach ({layouts.MAX_HEAP_BYTES} "
                    "bytes — core/layouts.py HEAP_REF)")
            # granule 0 is the null-ref sentinel; the log must hold at
            # least two max-size extents beyond it or the allocator can
            # never even double-buffer one value across a compaction
            if self.heap_bytes < layouts.HEAP_GRANULE + 2 * (
                    (self.max_value_bytes + layouts.HEAP_GRANULE - 1)
                    // layouts.HEAP_GRANULE) * layouts.HEAP_GRANULE:
                raise ValueError(
                    f"heap_bytes {self.heap_bytes} cannot hold two "
                    f"max_value_bytes={self.max_value_bytes} extents plus "
                    "the reserved null granule")
        if self.wal_sync not in ("commit", "round", "off"):
            raise ValueError("wal_sync must be 'commit', 'round' or 'off'")
        if self.wal_segment_bytes < 4096:
            raise ValueError(
                "wal_segment_bytes must be >= 4096 (a segment must hold "
                "its own header frame plus at least one record frame)")
        if self.wal_dirty_window < 1:
            raise ValueError(
                "wal_dirty_window must be >= 1 (0 would shed every write; "
                "disable the WAL with wal_dir=None instead)")
        # Unique write ids are (hi=replica, lo=session*G+op) int32 pairs.
        if self.n_sessions * self.ops_per_session >= 2**31:
            raise ValueError("n_sessions * ops_per_session must fit int32")
        if self.device_stream:
            if self.workload.distribution not in ("uniform", "zipfian"):
                raise ValueError(
                    "device_stream supports uniform or zipfian keys"
                )
            if self.n_keys & (self.n_keys - 1):
                raise ValueError("device_stream needs power-of-two n_keys")

    @property
    def full_mask(self) -> int:
        """Bitmap with one bit per configured replica."""
        return (1 << self.n_replicas) - 1

    @property
    def n_lanes(self) -> int:
        """Outbound message lanes per replica: one per session + one per replay slot."""
        return self.n_sessions + self.replay_slots

    @property
    def use_fused_sort(self) -> bool:
        """Resolved fused-sort switch (faststep._coordinate): the single
        arbiter+compaction sort needs the sort arbiter and a packed key of
        (band 2b | sub 29b, layouts.FUSED_KEY) — sub holds the rotated key
        for issue runs and the rotation index for waiting/replay lanes, so
        both n_keys (config-enforced) and n_lanes must fit the declared
        sub field.  Anything else falls back to the split two-sort
        program."""
        return (self.arb_mode == "sort" and self.fused_sort
                and self.n_lanes <= layouts.FUSED_KEY.field("sub").cap)

    @property
    def use_mega_round(self) -> bool:
        """Statically-resolved mega-round switch (round-15): the config
        half of the resolution — the knob is on and the fused sort
        resolves (the mega route consumes its sorted-order verdicts).
        The VMEM budget needs no re-check here: __post_init__ refuses a
        mega_round config whose vpts column exceeds MEGA_VPTS_VMEM_BYTES
        at construction (one source of truth, loud).  The build-time
        half (kernel self-check + invariant analysis, which can refuse
        per backend) lives in ``core/megaround.resolve``; the fused-sort
        program is the automatic fallback."""
        return self.mega_round and self.use_fused_sort

    @property
    def use_heap(self) -> bool:
        """Round-17 value-heap switch: variable-length byte values through
        the HBM append log (hermes_tpu/heap)."""
        return self.max_value_bytes > 0

    @property
    def use_wal(self) -> bool:
        """Round-22 durability-tier switch: the host-side write-ahead
        extent+commit log (hermes_tpu/wal)."""
        return self.wal_dir is not None

    @property
    def heap_granules(self) -> int:
        """Heap log capacity in granules (granule 0 = the null ref)."""
        return self.heap_bytes // layouts.HEAP_GRANULE

    @property
    def lane_budget(self) -> int:
        """Resolved faststep compaction budget (slots per outbound block)."""
        if self.lane_budget_cfg is not None:
            return min(self.lane_budget_cfg, self.n_lanes)
        return self.n_lanes

    @property
    def max_key_versions(self) -> int:
        """faststep's packed-ts limit: versions one key can take before the
        int32 sign bit corrupts the Lamport compare (the declared ver-field
        budget, core/layouts.py PTS)."""
        return layouts.MAX_KEY_VERSIONS

    @property
    def arb_slots(self) -> int:
        """Hash-slot count for same-replica same-key issue arbitration
        (faststep): power of two, >= 8x sessions (false-collision rate
        ~S/2HS per issue), capped at 512Ki; scatter cost grows with BOTH
        the update count and the table size on this chip, so the sweet
        spot is workload-dependent — override with arb_slots_cfg."""
        if self.arb_slots_cfg is not None:
            return self.arb_slots_cfg
        hs = 1
        while hs < min(8 * self.n_sessions, 1 << 19):
            hs <<= 1
        return hs


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Pod-scale key-sharded fleet shape (round-13, hermes_tpu/fleet).

    Hermes coordinates writes PER KEY (PAPER.md), so aggregate throughput
    scales by running G independent replica groups side by side, each
    owning a contiguous range of the fleet keyspace.  One FleetConfig maps
    to G compiled single-group programs laid out on a (groups, replicas)
    device grid (launch.fleet_meshes) — each group a full FastRuntime/KVS
    stack with its own membership service, chaos scope, and snapshot
    scope; nothing is shared between groups but the fleet router.

    ``ranges`` partitions the FLEET keyspace ``[0, total_keys)`` into one
    contiguous ``[lo, hi)`` per group (default: ``groups`` equal splits of
    ``groups * base.n_keys``).  A group's range must fit its dense table
    (``hi - lo <= group n_keys``) — fleet key ``k`` lands on local slot
    ``k - lo`` of its owning group until a migration remaps it.

    ``overrides[g]`` replaces HermesConfig fields for group g (per-group
    shapes, pipeline depth, chain depth...).  ``vary_seed`` (default) adds
    the group id to each group's workload seed so group op streams are
    distinct but deterministic.
    """

    groups: int = 2
    base: HermesConfig = dataclasses.field(default_factory=HermesConfig)
    ranges: Optional[Tuple[Tuple[int, int], ...]] = None
    overrides: Optional[Tuple[Optional[dict], ...]] = None
    vary_seed: bool = True

    def __post_init__(self) -> None:
        if self.groups < 1:
            raise ValueError("groups must be >= 1")
        if self.overrides is not None and len(self.overrides) != self.groups:
            raise ValueError(
                f"overrides must carry one entry per group "
                f"({len(self.overrides)} != {self.groups}; use None for "
                "groups with no overrides)")
        if self.ranges is not None:
            if len(self.ranges) != self.groups:
                raise ValueError(
                    f"ranges must carry one (lo, hi) per group "
                    f"({len(self.ranges)} != {self.groups})")
            cursor = 0
            for g, (lo, hi) in enumerate(self.ranges):
                if lo != cursor or hi <= lo:
                    raise ValueError(
                        f"ranges must tile the fleet keyspace contiguously "
                        f"from 0 (group {g} has [{lo}, {hi}), expected "
                        f"lo={cursor} and hi > lo)")
                cursor = hi
        # every group config must construct AND hold its range: surface a
        # bad per-group override at FleetConfig construction, not when the
        # g-th runtime compiles
        for g in range(self.groups):
            cfg = self.group_cfg(g)
            lo, hi = self.group_range(g)
            if hi - lo > cfg.n_keys:
                raise ValueError(
                    f"group {g} owns {hi - lo} fleet keys but its dense "
                    f"table holds n_keys={cfg.n_keys}; shrink the range or "
                    "grow the group")

    @property
    def total_keys(self) -> int:
        """Fleet keyspace size (the router's slot space)."""
        if self.ranges is not None:
            return self.ranges[-1][1]
        return self.groups * self.base.n_keys

    def group_range(self, g: int) -> Tuple[int, int]:
        """Fleet-key range ``[lo, hi)`` group ``g`` owns at construction
        (migrations move ownership afterwards — the fleet router is the
        live source of truth)."""
        if not (0 <= g < self.groups):
            raise ValueError(f"group {g} out of range [0, {self.groups})")
        if self.ranges is not None:
            return self.ranges[g]
        k = self.base.n_keys
        return (g * k, (g + 1) * k)

    def group_cfg(self, g: int) -> HermesConfig:
        """The g-th group's HermesConfig (base + overrides + seed vary)."""
        if not (0 <= g < self.groups):
            raise ValueError(f"group {g} out of range [0, {self.groups})")
        over = dict((self.overrides[g] or {})
                    if self.overrides is not None else {})
        wl = over.pop("workload", self.base.workload)
        if self.vary_seed:
            wl = dataclasses.replace(wl, seed=wl.seed + g)
        cfg = dataclasses.replace(self.base, workload=wl, **over)
        # Round-22: each group logs into its own WAL subdirectory — one
        # group's recovery must never replay another group's records (same
        # scoping rule as per-group snapshots).  An explicit per-group
        # wal_dir override wins.
        if cfg.wal_dir is not None and "wal_dir" not in over:
            cfg = dataclasses.replace(
                cfg, wal_dir=os.path.join(cfg.wal_dir, f"group{g:03d}"))
        return cfg


