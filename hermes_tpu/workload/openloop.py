"""Open-loop and closed-loop load shapes for the serving front-end
(round-14).

The round-9 chaos discipline applied to LOAD: every generator is seeded
and replay-deterministic — the same seed + parameters produce a
byte-identical arrival schedule and op mix (``tobytes()`` equality, CI-
and test-asserted), so an overload soak replays exactly like a chaos
schedule does.

  * ``poisson_arrivals`` — open-loop arrival times: the client sends on
    ITS schedule regardless of server progress (the honest overload
    shape — a closed loop self-throttles and can never overrun the
    server, which is exactly what an overload gate must not rely on).
  * ``ShapedArrivals`` — the same schedule driven through a live rate
    shaper: the chaos ``overload x=N`` verb compresses the remaining
    inter-arrival gaps by N deterministically (seeded burst windows as
    first-class adversary events).
  * ``make_mix`` — the op mix beside the arrivals: kinds by read
    fraction, keys uniform / zipfian(theta) / hot-key, tenants
    round-robin, payload words seeded.
  * ``scenario_matrix`` — the serving bench/gate scenarios (uniform,
    zipfian, hot-key), seed anchored to the CHECKED_ZIPFIAN.json
    artifact when present so the matrix is pinned to a committed
    checked run.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

import numpy as np

from hermes_tpu.workload.ycsb import scrambled_zipfian


def poisson_arrivals(rate_per_s: float, n: int, seed: int) -> np.ndarray:
    """``n`` open-loop arrival times (seconds, float64, strictly
    cumulative) of a Poisson process at ``rate_per_s``.  Same seed =>
    byte-identical schedule."""
    if rate_per_s <= 0:
        raise ValueError("rate_per_s must be > 0")
    rng = np.random.default_rng(
        (int(seed) * 0x9E3779B97F4A7C15 + 1) & 0xFFFFFFFFFFFFFFFF)
    gaps = rng.exponential(1.0 / rate_per_s, size=n)
    return np.cumsum(gaps)


class ShapedArrivals:
    """An arrival schedule with a live, deterministic rate shaper.

    Base inter-arrival gaps come from ``poisson_arrivals``; a chaos
    ``overload`` window calls ``set_rate_x(x)`` and every gap consumed
    AFTER that point is divided by ``x`` (x > 1 = burst, x < 1 = lull).
    Because the multiplier applies to the deterministic gap stream at a
    deterministic cursor, the executed schedule replays byte-identically
    given the same seed + the same (seeded) window program."""

    def __init__(self, rate_per_s: float, n: int, seed: int):
        base = poisson_arrivals(rate_per_s, n, seed)
        self._gaps = np.diff(np.concatenate([[0.0], base]))
        self._i = 0
        self._t = 0.0
        self.rate_x = 1.0
        self._next: Optional[float] = None

    def set_rate_x(self, x: float) -> None:
        if x <= 0:
            raise ValueError("rate multiplier must be > 0")
        self.rate_x = float(x)

    def __len__(self) -> int:
        return self._gaps.shape[0]

    def peek(self) -> Optional[float]:
        """Next arrival time, None when exhausted."""
        if self._next is None:
            if self._i >= self._gaps.shape[0]:
                return None
            self._t += self._gaps[self._i] / self.rate_x
            self._next = self._t
            self._i += 1
        return self._next

    def due(self, now: float) -> int:
        """Arrivals due at ``now`` (consumes them); returns the count."""
        k = 0
        while True:
            t = self.peek()
            if t is None or t > now:
                return k
            self._next = None
            k += 1


@dataclasses.dataclass(frozen=True)
class MixSpec:
    """One serving scenario: arrival mix shape (keys/kinds/tenants)."""

    name: str = "uniform"
    read_frac: float = 0.5
    rmw_frac: float = 0.0            # of the update half
    # uniform | zipfian | hotkey | latest (YCSB-D: reads skew to the most
    # recently WRITTEN keys of this same mix — ycsb.latest_ages)
    distribution: str = "uniform"
    zipf_theta: float = 0.99
    hot_frac: float = 0.8            # hotkey mode: share of ops on hot set
    hot_keys: int = 4                # hotkey mode: size of the hot set
    tenants: int = 4
    # round-17 value heap: > 0 adds a seeded memcached-shaped per-op
    # value-size column (``vlen``, ycsb.value_sizes) capped here; the
    # per-op bytes derive from ycsb.value_payload(seed, i, vlen[i])
    value_bytes: int = 0
    size_theta: float = 0.99


def make_mix(spec: MixSpec, n_keys: int, n: int, seed: int,
             value_words: int = 1) -> dict:
    """The op mix beside an arrival schedule: dict of numpy columns
    (kind: 0=get 1=put 2=rmw, key, tenant, value) — same seed =>
    byte-identical columns."""
    rng = np.random.default_rng(
        (int(seed) * 0xC2B2AE3D27D4EB4F + 2) & 0xFFFFFFFFFFFFFFFF)
    u = rng.random(n)
    kind = np.where(u < spec.read_frac, 0, 1).astype(np.int8)
    if spec.rmw_frac > 0:
        rmw = (kind == 1) & (rng.random(n) < spec.rmw_frac)
        kind[rmw] = 2
    if spec.distribution == "uniform":
        key = rng.integers(0, n_keys, size=n, dtype=np.int64)
    elif spec.distribution == "zipfian":
        key = scrambled_zipfian(rng, n_keys, spec.zipf_theta, seed,
                                n).astype(np.int64)
    elif spec.distribution == "hotkey":
        hot = rng.random(n) < spec.hot_frac
        key = rng.integers(0, n_keys, size=n, dtype=np.int64)
        key[hot] = rng.integers(0, max(1, spec.hot_keys),
                                size=int(hot.sum()), dtype=np.int64)
    elif spec.distribution == "latest":
        # YCSB-D: reads target the most recently written keys of THIS
        # mix — a Zipfian(theta)-over-age draw against the running write
        # log (ycsb.LATEST_WINDOW horizon), clamped to the writes that
        # exist yet; reads before the first write fall back to uniform.
        # Pure cursor arithmetic over seeded draws => byte-identical
        # replays like every other distribution here.
        from hermes_tpu.workload.ycsb import latest_ages

        key = rng.integers(0, n_keys, size=n, dtype=np.int64)
        ages = latest_ages(rng, n, spec.zipf_theta)
        written: list = []
        for i in range(n):
            if kind[i] == 0:
                if written:
                    key[i] = written[-1 - min(int(ages[i]),
                                              len(written) - 1)]
            else:
                written.append(int(key[i]))
    else:
        raise ValueError(f"unknown distribution {spec.distribution!r}")
    tenant = (np.arange(n, dtype=np.int64) % spec.tenants).astype(np.int32)
    value = rng.integers(1, 1 << 20, size=(n, value_words),
                         dtype=np.int64).astype(np.int32)
    mix = dict(kind=kind, key=key, tenant=tenant, value=value)
    if spec.value_bytes > 0:
        # heap mode (round-17): per-op byte LENGTHS ride the mix
        # (memcached-shaped, seeded — ycsb.value_sizes); the bytes
        # themselves derive from ycsb.value_payload so a soak never
        # materializes n * max_value_bytes of payload up front
        from hermes_tpu.workload.ycsb import value_sizes

        mix["vlen"] = value_sizes(
            dict(n=n, max_bytes=spec.value_bytes, theta=spec.size_theta),
            seed)
    return mix


def hot_set(spec: MixSpec) -> tuple:
    """The keys the shed ladder's rung 2 keeps serving for this mix."""
    if spec.distribution == "hotkey":
        return tuple(range(spec.hot_keys))
    return ()


_ANCHOR = "CHECKED_ZIPFIAN.json"


def scenario_seed(repo_root: Optional[str] = None) -> int:
    """Scenario-matrix seed, anchored to the committed CHECKED_ZIPFIAN
    artifact (the on-chip checked zipfian run): the matrix is pinned to
    evidence, not to an arbitrary constant.  Falls back to a fixed seed
    when the artifact is absent (fresh checkout)."""
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, _ANCHOR)
    try:
        with open(path) as f:
            art = json.load(f)
        return int(art.get("writes_committed", 0)) % (1 << 31) or 14
    except (OSError, ValueError):
        return 14


def scenario_matrix(tenants: int = 4, value_bytes: int = 0) -> List[MixSpec]:
    """The serving bench/gate scenarios: uniform, zipfian hot-rank, and
    explicit hot-key mixes (CHECKED_ZIPFIAN-anchored seed picks the
    draws; the SHAPES are fixed), plus the round-16 read-heavy YCSB
    B/C/D cells (ycsb.READ_MIXES — B = 95/5 zipfian, C = read-only
    zipfian, D = 95/5 latest-distribution reads).  ``value_bytes > 0``
    (round-17, heap-mode stores) appends the memcached-shaped
    variable-size value scenario — zipfian keys AND zipfian-over-size-
    classes payloads (ycsb.value_sizes)."""
    from hermes_tpu.workload.ycsb import READ_MIXES

    out = [
        MixSpec(name="uniform", distribution="uniform", tenants=tenants),
        MixSpec(name="zipfian", distribution="zipfian", zipf_theta=0.99,
                tenants=tenants),
        MixSpec(name="hotkey", distribution="hotkey", hot_frac=0.8,
                hot_keys=4, tenants=tenants),
    ]
    for name, kw in READ_MIXES.items():
        out.append(MixSpec(name=f"ycsb_{name}", tenants=tenants, **kw))
    if value_bytes > 0:
        out.append(MixSpec(name="values", distribution="zipfian",
                           zipf_theta=0.99, tenants=tenants,
                           value_bytes=value_bytes))
    return out


class ClosedLoop:
    """Closed-loop load: the next op is drawn (deterministically) when
    the previous resolves or the door refuses — ops offered as fast as
    the server's admission refills, so throughput is service-bound,
    never arrival-bound.  The capacity-measurement shape
    (``serving.soak.measure_capacity`` drives it)."""

    def __init__(self, spec: MixSpec, n_keys: int, n: int, seed: int,
                 value_words: int = 1):
        self.mix = make_mix(spec, n_keys, n, seed, value_words)
        self.n = n
        self.cursor = 0
        self._seed = int(seed)

    def next_op(self) -> Optional[dict]:
        if self.cursor >= self.n:
            return None
        i = self.cursor
        self.cursor += 1
        m = self.mix
        op = dict(kind=("get", "put", "rmw")[int(m["kind"][i])],
                  key=int(m["key"][i]), tenant=int(m["tenant"][i]),
                  value=m["value"][i].tolist())
        if "vlen" in m:
            # heap mode: the op's byte payload, derived not stored
            from hermes_tpu.workload.ycsb import value_payload

            op["data"] = value_payload(self._seed, i, int(m["vlen"][i]))
        return op
