"""YCSB-style op-stream generation (SURVEY.md §1 L6, §2 "Workload generator").

The reference drives itself with an in-process YCSB-like generator — write
ratio, key count, uniform/Zipfian(0.99) skew (BASELINE.json:7-9).  Here the
whole run's op stream is pre-generated host-side into (S, G) int32 arrays per
replica (the device derives write values on the fly, see
phases._write_value), so the hot loop never touches the host RNG.

Mixes map to the acceptance configs:
  * YCSB-A: read_frac=0.5, rmw_frac=0  (config 1)
  * YCSB-F: rmw_frac=1.0 on the update half (config 2)
  * Zipfian hotspot: distribution='zipfian', theta=0.99 (config 3)
"""

from __future__ import annotations

import functools

import numpy as np

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import state as st
from hermes_tpu.core import types as t


def _zipf_cdf(n: int, theta: float) -> np.ndarray:
    """CDF of the Zipfian(theta) distribution over ranks 1..n (YCSB's
    definition: p(rank i) ~ 1/i^theta)."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), theta)
    cdf = np.cumsum(w)
    return cdf / cdf[-1]


def scrambled_zipfian(
    rng: np.random.Generator, n_keys: int, theta: float, scramble_seed: int,
    size,
) -> np.ndarray:
    """Scrambled-zipfian key draw (YCSB): rank by the Zipfian(theta) CDF,
    then spread the hot ranks over the key space with a fixed permutation
    keyed off ``scramble_seed``.  The ONE implementation — the YCSB op
    streams and the serving mixes (workload.openloop) both draw through
    it."""
    cdf = _zipf_cdf(n_keys, theta)
    ranks = np.searchsorted(cdf, rng.random(size=size))
    perm = np.random.default_rng(scramble_seed ^ 0x5CA1AB1E).permutation(n_keys)
    return perm[ranks]


# Round-16 read-heavy mixes (the read-side scenario set beside the
# write-centric acceptance configs above).  YCSB-B/C/D per the YCSB core
# workloads: B = 95/5 read/update zipfian, C = read-only zipfian, D =
# 95/5 read/update with LATEST-distribution reads (reads skew to the
# most recently written keys — openloop.make_mix's 'latest' draw; this
# store has no insert op, so D's insert half is modeled as updates, the
# standard adaptation for update-in-place stores).  One table feeds the
# bench cells (bench.py --reads), the serving scenario matrix
# (workload.openloop.scenario_matrix), and the cli quickstart, so the
# three surfaces cannot drift.
READ_MIXES = {
    "b": dict(read_frac=0.95, rmw_frac=0.0, distribution="zipfian"),
    "c": dict(read_frac=1.0, rmw_frac=0.0, distribution="zipfian"),
    "d": dict(read_frac=0.95, rmw_frac=0.0, distribution="latest"),
}

# The recency horizon of the 'latest' draw: reads rank the last this-many
# writes by a Zipfian(theta) over age (YCSB's ScrambledZipfian-over-
# recency, windowed so the CDF is precomputable once).
LATEST_WINDOW = 1024

# Round-17 memcached-shaped value-size classes (bytes): the heap's
# workload truth.  Facebook's memcached traces (Atikoglu et al., and the
# distribution PAPER.md's "tens of bytes to KBs" echoes) put most values
# in the tens-of-bytes classes with a long tail into KBs — a Zipfian over
# ASCENDING size classes reproduces that shape: rank 0 (most probable) is
# the smallest class.
VALUE_SIZE_CLASSES = (16, 32, 64, 128, 256, 512, 1024, 2048)


def value_sizes(spec: dict, seed: int) -> np.ndarray:
    """Seeded memcached-shaped value-size draw (round-17): ``spec`` is
    ``{"n": count, "max_bytes": cap, "classes": sizes?, "theta": t?}`` —
    a Zipfian(theta) over the size classes <= cap, smallest class most
    probable.  Deterministic: same (spec, seed) => byte-identical array
    (``tobytes`` equality, test-asserted), the chaos-schedule replay
    discipline applied to payload shapes.  Returns (n,) int64 byte
    lengths."""
    n = int(spec["n"])
    cap = int(spec.get("max_bytes", VALUE_SIZE_CLASSES[-1]))
    if cap < 1:
        raise ValueError("max_bytes must be >= 1")
    classes = tuple(c for c in spec.get("classes", VALUE_SIZE_CLASSES)
                    if c <= cap)
    if not classes:
        classes = (cap,)
    theta = float(spec.get("theta", 0.99))
    rng = np.random.default_rng(
        (int(seed) * 0xA24BAED4963EE407 + 5) & 0xFFFFFFFFFFFFFFFF)
    cdf = _zipf_cdf(len(classes), theta)
    ranks = np.searchsorted(cdf, rng.random(size=n))
    return np.asarray(classes, np.int64)[ranks]


def value_payload(seed: int, i: int, nbytes: int) -> bytes:
    """Deterministic per-op payload bytes: a counter-hash fill (the
    device-stream _mix32 applied to byte indices), so a checked run can
    recompute any op's expected bytes from (seed, op index, length)
    without storing them."""
    if nbytes <= 0:
        return b""
    idx = np.arange((nbytes + 3) // 4, dtype=np.uint32)
    with np.errstate(over="ignore"):
        words = _mix32(idx ^ np.uint32((seed * 0x9E3779B9 + i * 0x85EBCA6B)
                                       & 0xFFFFFFFF))
    return words.tobytes()[:nbytes]


def latest_ages(rng: np.random.Generator, n: int, theta: float = 0.99
                ) -> np.ndarray:
    """Zipfian(theta) age draws in [0, LATEST_WINDOW): age 0 = the most
    recent write.  Deterministic per rng state; callers clamp to the
    writes that actually exist yet."""
    cdf = _zipf_cdf(LATEST_WINDOW, theta)
    return np.searchsorted(cdf, rng.random(size=n)).astype(np.int64)


def sample_keys(
    rng: np.random.Generator, cfg: HermesConfig, size: tuple[int, ...]
) -> np.ndarray:
    wl = cfg.workload
    if wl.distribution == "uniform":
        return rng.integers(0, cfg.n_keys, size=size, dtype=np.int32)
    if wl.distribution == "zipfian":
        return scrambled_zipfian(rng, cfg.n_keys, wl.zipf_theta, wl.seed,
                                 size).astype(np.int32)
    raise ValueError(f"unknown distribution {wl.distribution!r}")


def make_stream(cfg: HermesConfig, replica: int) -> st.OpStream:
    """Pre-generate one replica's (S, G) op stream."""
    wl = cfg.workload
    rng = np.random.default_rng((wl.seed << 8) ^ replica)
    shape = (cfg.n_sessions, cfg.ops_per_session)
    u = rng.random(size=shape)
    op = np.where(u < wl.read_frac, t.OP_READ, t.OP_WRITE).astype(np.int32)
    if wl.rmw_frac > 0:
        is_upd = op == t.OP_WRITE
        rmw = rng.random(size=shape) < wl.rmw_frac
        op = np.where(is_upd & rmw, t.OP_RMW, op).astype(np.int32)
    key = sample_keys(rng, cfg, shape)
    return st.OpStream(op=op, key=key)


def make_streams(cfg: HermesConfig) -> st.OpStream:
    """All replicas' streams, stacked on a leading R axis."""
    parts = [make_stream(cfg, r) for r in range(cfg.n_replicas)]
    return st.OpStream(
        op=np.stack([p.op for p in parts]),
        key=np.stack([p.key for p in parts]),
    )


# --------------------------------------------------------------------------
# Device-side stream (SURVEY.md §2 "in-kernel PRNG"): the op stream as a
# stateless counter hash, identical on device (core/faststep._coordinate)
# and host (this twin, used by tests and any checker bootstrap).
# --------------------------------------------------------------------------

def _mix32(x):
    """xxhash-style avalanche on uint32 (works for numpy and jax arrays;
    constants as numpy scalars so jax does not weak-type-promote)."""
    c1, c2 = np.uint32(0x7FEB352D), np.uint32(0x846CA68B)
    s16, s15 = np.uint32(16), np.uint32(15)
    x = (x ^ (x >> s16)) * c1
    x = (x ^ (x >> s15)) * c2
    return x ^ (x >> s16)


def device_stream_params(cfg: HermesConfig):
    """Thresholds the hash is compared against (16-bit fixed point)."""
    wl = cfg.workload
    read_t = int(wl.read_frac * 65536)
    rmw_t = int(wl.rmw_frac * 65536)
    return read_t, rmw_t


@functools.lru_cache(maxsize=None)
def _zipf_consts(n: int, theta: float):
    """Constants of the YCSB analytic Zipfian inverse (Gray et al.,
    "Quickly generating billion-record synthetic databases"): rank(u) =
    n * (eta*u - eta + 1)^(1/(1-theta)) with small-rank special cases.
    Host-side float64 precompute (zeta(n) is a 1-time O(n) sum, cached;
    accumulated in fixed-size chunks so n up to the 2^29 config bound costs
    ~32 MiB of temporaries, not two ~4 GiB arrays)."""
    chunk = 1 << 22
    zetan = 0.0
    for lo in range(1, n + 1, chunk):
        ranks = np.arange(lo, min(n + 1, lo + chunk), dtype=np.float64)
        zetan += float(np.sum(ranks ** -theta))
    zeta2 = 1.0 + 0.5 ** theta
    alpha = 1.0 / (1.0 - theta)
    eta = (1.0 - (2.0 / n) ** (1.0 - theta)) / (1.0 - zeta2 / zetan)
    return (np.float32(zetan), np.float32(zeta2), np.float32(eta),
            np.float32(alpha))


def _zipf_rank(cfg: HermesConfig, kh):
    """uint32 hash -> Zipfian rank (0 = hottest), pure elementwise float32
    math — the TPU-native sampling path: no CDF table, no gathers (a
    searchsorted/alias lookup would add ~1.5-2 ms of flat sparse-op cost
    per intake sub-step on this runtime; transcendentals are dense VPU
    work).  Backend-agnostic like the rest of the hash."""
    if isinstance(kh, (np.ndarray, np.generic)):
        xp = np
    else:  # jax tracer/array — np.where would force __array__ on tracers
        import jax.numpy as xp
    zetan, zeta2, eta, alpha = _zipf_consts(cfg.n_keys, cfg.workload.zipf_theta)
    one = np.float32(1.0)
    u = (kh >> np.uint32(8)).astype(np.float32) * np.float32(2.0 ** -24)
    uz = u * zetan
    # eta < 1 for theta < 1, so the pow base 1 - eta*(1-u) is always > 0
    tail = (np.float32(cfg.n_keys) * (eta * u - eta + one) ** alpha)
    rank = xp.where(uz < one, np.float32(0.0),
                    xp.where(uz < zeta2, one, tail))
    rank = xp.minimum(rank, np.float32(cfg.n_keys - 1))
    return rank.astype(np.uint32)


def stream_hash(cfg: HermesConfig, replica, session, op_idx):
    """The counter-hash op stream, backend-agnostic: works on numpy AND jax
    uint32 arrays (pure ^ * >> & arithmetic; the zipfian branch adds f32
    elementwise math), so the device engine (core/faststep._coordinate) and
    the host twin call ONE implementation — the two cannot drift (uniform
    is bit-exact; zipfian may differ on rank-boundary ULPs between numpy
    and XLA pow, so zipfian agreement is statistical, not per-element).
    Returns (u_op, u_rmw, key) as uint32."""
    seed_mixed = np.uint32((cfg.workload.seed * 0x9E3779B9) & 0xFFFFFFFF)
    base = _mix32(seed_mixed ^ _mix32(
        replica * np.uint32(0x85EBCA6B)
        ^ _mix32(session * np.uint32(0xC2B2AE35) ^ op_idx)))
    u_op = base & np.uint32(0xFFFF)
    u_rmw = (base >> np.uint32(16)) & np.uint32(0xFFFF)
    kh = _mix32(base ^ np.uint32(0x27220A95))
    if cfg.workload.distribution == "zipfian":
        # scrambled zipfian (YCSB): hash the rank over the key space so hot
        # ranks spread out; the power-of-two mask folds ranks onto keys
        # (collisions merge ranks — acceptable for a workload generator)
        rank = _zipf_rank(cfg, kh)
        key = _mix32(rank * np.uint32(0x9E3779B1)
                     ^ np.uint32(0x1B873593)) & np.uint32(cfg.n_keys - 1)
    else:
        key = kh & np.uint32(cfg.n_keys - 1)
    return u_op, u_rmw, key


def device_stream_host(cfg: HermesConfig, replica, session, op_idx):
    """Host twin of the device stream: (op, key) for broadcastable uint32
    index arrays (numpy)."""
    read_t, rmw_t = device_stream_params(cfg)
    with np.errstate(over="ignore"):
        u_op, u_rmw, key = stream_hash(
            cfg, np.uint32(replica), np.uint32(session), np.uint32(op_idx))
    op = np.where(u_op < read_t, t.OP_READ,
                  np.where(u_rmw < rmw_t, t.OP_RMW, t.OP_WRITE)).astype(np.int32)
    return op, key.astype(np.int64).astype(np.int32)


def stub_stream(cfg: HermesConfig) -> st.OpStream:
    """Placeholder stream for device_stream runs (the arrays are never
    read; keeps step signatures uniform)."""
    z = np.zeros((cfg.n_replicas, cfg.n_sessions, 1), np.int32)
    return st.OpStream(op=z, key=z)
