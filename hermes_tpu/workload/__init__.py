"""Workload generation (SURVEY.md §1 L6): YCSB-style synthetic op streams."""
