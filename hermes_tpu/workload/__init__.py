"""Workload generation (SURVEY.md §1 L6): YCSB-style synthetic op
streams (workload.ycsb) and the round-14 serving load shapes — seeded
open-loop Poisson arrivals, chaos-shapeable rates, closed-loop clients
(workload.openloop)."""

from hermes_tpu.workload.openloop import (ClosedLoop, MixSpec,
                                          ShapedArrivals, hot_set, make_mix,
                                          poisson_arrivals, scenario_matrix,
                                          scenario_seed)

__all__ = ["ClosedLoop", "MixSpec", "ShapedArrivals", "hot_set", "make_mix",
           "poisson_arrivals", "scenario_matrix", "scenario_seed"]
