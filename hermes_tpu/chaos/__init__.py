"""hermes_tpu.chaos — fault injection & recovery as a first-class
subsystem (round-9; PAPER.md §5.3 / §4.4, Jepsen-style schedule-driven
chaos per PAPERS.md).

Three legs:

  1. **Async failure detection** — the round program folds the heartbeat
     staleness reduction into itself (``core/state.Meta.suspect_age``);
     the runtime harvests it WITH completions through the round-8 ring,
     and ``membership.MembershipService`` runs the suspect → confirm →
     remove state machine off the harvested ages — an attached detector
     costs the dispatch path zero synchronous ``device_get``s.
  2. **Crash-consistent snapshots + recovery** — ``snapshot.save`` is
     tmp+rename with a checksummed manifest; ``chaos.recovery.
     restart_replica`` models a full host-crash (lost in-flight ops as
     ``maybe_w`` history rows, fence/remove, snapshot-or-peer restore,
     rejoin-with-state-transfer, coordinator re-validation).
  3. **Declarative schedules** — ``chaos.schedule`` parses/draws seeded
     fault programs (freeze/thaw/remove/join/crash-restart/heartbeat
     clock-skew, plus net drop/delay/dup on the sim transport) and
     ``ChaosRunner`` drives them against FastRuntime / KVS / sim Runtime,
     every event on the obs timeline, gated end-to-end by the
     linearizability checker (scripts/check_chaos.py is the CI gate).
  4. **Adversarial wire chaos** (round-11, ``chaos.net``) — the
     transport-generic ``FaultingTransport`` interposer injects seeded
     drop / duplicate / reorder / delay / corrupt / partition faults per
     directed peer pair over ANY HostTransport; frames carry a codec CRC
     so corruption is detected and downgraded to a drop; the ``partition``
     /``heal`` schedule verbs compose with the detector so a
     partitioned-but-alive replica is fenced, kept, and epoch-fenced back
     in (scripts/check_netchaos.py is the CI gate).
"""

from hermes_tpu.chaos.net import FaultingTransport, WireWindow, WIRE_OPS
from hermes_tpu.chaos.recovery import restart_replica
from hermes_tpu.chaos.schedule import (
    ChaosEvent,
    ChaosRunner,
    ChaosSpec,
    NetChaos,
    Schedule,
)

__all__ = [
    "ChaosEvent", "ChaosRunner", "ChaosSpec", "FaultingTransport",
    "NetChaos", "Schedule", "WireWindow", "WIRE_OPS", "restart_replica",
]
