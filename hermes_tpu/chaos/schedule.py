"""Declarative, seeded fault schedules + the runner that drives them.

Jepsen's lesson (PAPERS.md): fault handling you don't continuously
exercise under adversarial SCHEDULES — composed, randomized, replayable —
is fault handling you don't have.  This module turns the ad-hoc loop of
tests/test_fault_soak.py into a subsystem the tests, CLI, bench and CI all
drive:

  * ``ChaosEvent`` / ``Schedule`` — a parsed event program.  Text form,
    one event per line (``#`` comments allowed)::

        @12 freeze 2
        @18 thaw 2
        @30 crash_restart 2 donor=0
        @40 hb_skew 1 skew=9 until=55
        @15 net_drop 0 dst=3 until=40
        @20 netcorrupt 1 dst=2 until=35     # round-11 wire verbs: need a
        @25 partition 0 until=50            # FaultingTransport interposer
        @55 heal                            # (partition also drives the
                                            # fast engines' detector oracle)

    ``Schedule.parse`` / ``Schedule.format`` round-trip it;
    ``Schedule.random(cfg, seed, steps, spec)`` draws a seeded program
    (event kinds by ``ChaosSpec`` rates, targets left to pre-drawn
    uniforms the runner resolves against eligibility at run time — so the
    same seed + config replays the same executed schedule exactly).
  * ``ChaosRunner`` — drives a FastRuntime, KVS facade, or sim-backed
    Runtime through a schedule: applies each due event if legal (quorum
    floor, target eligibility), steps the workload, heals the cluster at
    the end, drains, and returns the run log.  Every applied event lands
    on the obs timeline (freeze/thaw/remove/join via the runtime hooks,
    crash_restart via chaos.recovery, hb_skew/net_* here), and the
    EXECUTED log (``result["events"]``) is deterministic: same seed +
    config => byte-identical log and final state.
  * ``NetChaos`` — a window-driven adversarial schedule for
    transport.sim.SimTransport (drop / delay / duplicate per directed
    edge), so net faults compose with membership/crash events on the sim
    engine.  The fast engines have no wire to corrupt; their "network"
    fault class is heartbeat clock-skew (``hb_skew`` biases the failure
    detector's observed ages via MembershipService.skew — false suspicion,
    confirm-window hysteresis and spontaneous recovery, without a real
    fault).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

EVENT_KINDS = ("freeze", "thaw", "remove", "join", "crash_restart",
               "hb_skew", "net_drop", "net_delay", "net_dup",
               # round-11 wire-adversary verbs (chaos/net.py interposer;
               # partition also drives the fast engines' detector oracle)
               "netdrop", "netdelay", "netdup", "netreorder", "netcorrupt",
               "partition", "heal",
               # round-14 overload adversary: multiply the attached load
               # shaper's open-loop arrival rate by x for a window — the
               # serving front-end's first-class, seeded failure mode
               "overload", "overload_clear",
               # round-22 durability adversary: SIGKILL the WHOLE store
               # process mid-soak (no flush, no close — the kill -9 shape
               # the WAL exists for).  Carried by an attached callable
               # (the gate's soak child kills itself; the parent recovers
               # via chaos.recovery.recover_store)
               "powercut")

# round-11 verb -> FaultingTransport wire op.  The legacy net_* verbs keep
# their NetChaos routing (sim-transport schedule windows) but fall back to
# the interposer when only a FaultingTransport is attached — the same
# fault, injected one layer up.
WIRE_EVENTS = {"netdrop": "drop", "netdelay": "delay", "netdup": "dup",
               "netreorder": "reorder", "netcorrupt": "corrupt"}
LEGACY_NET_EVENTS = {"net_drop": "drop", "net_delay": "delay",
                     "net_dup": "dup"}


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One schedule entry.  ``replica`` is the target (net_*: the source
    edge end; -1 = runner-resolved via ``u``).  Field use by kind:
    join/crash_restart -> ``donor``; hb_skew -> ``skew`` + ``until``;
    net_* -> ``dst`` (-1 = any) + ``until`` (+ ``skew`` as the delay)."""

    step: int
    kind: str
    replica: int = -1
    donor: int = -1
    dst: int = -1
    skew: int = 0
    until: int = -1
    x: float = 0.0  # overload rate multiplier (round-14)
    u: float = 0.0  # pre-drawn uniform for run-time target resolution

    def format(self) -> str:
        parts = [f"@{self.step}", self.kind]
        if self.replica >= 0:
            parts.append(str(self.replica))
        for f, dflt in (("donor", -1), ("dst", -1), ("skew", 0),
                        ("until", -1)):
            v = getattr(self, f)
            if v != dflt:
                parts.append(f"{f}={v}")
        if self.x:
            parts.append(f"x={self.x!r}")
        if self.u:
            parts.append(f"u={self.u!r}")
        return " ".join(parts)


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    """Seeded-schedule mix: per-step event probabilities (disjoint draws
    off one uniform) + shape knobs.  Defaults mirror the historical
    test_fault_soak mix, extended with the round-9 fault classes."""

    p_freeze: float = 0.06
    p_thaw: float = 0.04
    p_join: float = 0.06
    p_crash: float = 0.02
    p_skew: float = 0.02
    p_net: float = 0.0  # sim engine only; ignored elsewhere
    # round-11 wire adversary: per-step rate of drawing ONE of the five
    # interposer verbs (netdrop/netdelay/netdup/netreorder/netcorrupt,
    # uniform among them) and of opening a directed partition
    p_wire: float = 0.0
    p_partition: float = 0.0
    skew_amount: int = 6
    skew_window: int = 12
    net_window: int = 10
    net_delay: int = 2
    partition_window: int = 14
    # legality floor: never freeze/crash below this many healthy replicas
    min_healthy: int = 3
    # detector-less fallback: a replica frozen longer than this is removed
    # by the runner's lease rule (a MembershipService overrides this)
    lease_remove_after: int = 6


class Schedule:
    """An ordered fault program (events sorted by step, stable)."""

    def __init__(self, events: Sequence[ChaosEvent]):
        for e in events:
            if e.kind not in EVENT_KINDS:
                raise ValueError(f"unknown chaos event kind {e.kind!r}")
        self.events: List[ChaosEvent] = sorted(events, key=lambda e: e.step)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def format(self) -> str:
        return "\n".join(e.format() for e in self.events) + "\n"

    @classmethod
    def parse(cls, text: str) -> "Schedule":
        """Parse the declarative text form (see module docstring)."""
        events = []
        for ln, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            toks = line.split()
            if not toks[0].startswith("@"):
                raise ValueError(f"line {ln}: want '@STEP KIND ...', got {raw!r}")
            try:
                step = int(toks[0][1:])
            except ValueError:
                raise ValueError(f"line {ln}: bad step in {toks[0]!r}")
            if len(toks) < 2:
                raise ValueError(f"line {ln}: missing event kind")
            kind = toks[1]
            if kind not in EVENT_KINDS:
                raise ValueError(
                    f"line {ln}: unknown chaos event kind {kind!r} "
                    f"(want one of {', '.join(EVENT_KINDS)})")
            kw: dict = dict(step=step, kind=kind)
            pos = 2
            if pos < len(toks) and "=" not in toks[pos]:
                kw["replica"] = int(toks[pos])
                pos += 1
            for tok in toks[pos:]:
                if "=" not in tok:
                    raise ValueError(f"line {ln}: want key=value, got {tok!r}")
                k, v = tok.split("=", 1)
                if k not in ("donor", "dst", "skew", "until", "u", "x"):
                    raise ValueError(f"line {ln}: unknown field {k!r}")
                kw[k] = float(v) if k in ("u", "x") else int(v)
            try:
                events.append(ChaosEvent(**kw))
            except ValueError as e:
                raise ValueError(f"line {ln}: {e}")
        return cls(events)

    @classmethod
    def rolling_restart(cls, cfg, start: int = 4,
                        spacing: int = 12) -> "Schedule":
        """The rolling-restart drill program (round-10 elastic operations,
        hermes_tpu/elastic/drill.py): replica i crash-restarts at step
        ``start + i * spacing`` — every replica in sequence, each given
        ``spacing`` rounds to rejoin and re-validate before the next one
        dies.  Deterministic (no draws): the same config replays the same
        program, so drill runs are byte-identical on the same seed+config
        like every other schedule."""
        return cls([
            ChaosEvent(step=start + i * spacing, kind="crash_restart",
                       replica=i)
            for i in range(cfg.n_replicas)
        ])

    @classmethod
    def partition_drill(cls, cfg, rounds: int, window: int = 14,
                        spacing: int = 30, start: int = 8) -> "Schedule":
        """Deterministic partition+heal cycles (round-11): replica
        ``i % R``'s outbound side goes dark for ``window`` rounds starting
        at ``start + i*spacing``, followed by a ``heal`` two rounds after
        the window closes — so the cluster LOSES and REGAINS a replica
        each cycle (detector ejection -> epoch-fenced rejoin) instead of
        monotonically shrinking.  No draws: same config replays the same
        program (the bench partition cell and soak triage both want
        comparable cycles, not seed-lottery cluster sizes)."""
        events = []
        step, i = start, 0
        while step + window + 2 < rounds:
            events.append(ChaosEvent(step=step, kind="partition",
                                     replica=i % cfg.n_replicas,
                                     until=step + window))
            events.append(ChaosEvent(step=step + window + 2, kind="heal"))
            step += spacing
            i += 1
        return cls(events)

    @classmethod
    def overload_storm(cls, seed: int, steps: int, n_windows: int = 2,
                       x_range: Tuple[float, float] = (2.0, 6.0),
                       window: Tuple[int, int] = (8, 24)) -> "Schedule":
        """Seeded overload windows (round-14): ``n_windows`` bursts, each
        multiplying the attached load shaper's open-loop arrival rate by
        a drawn ``x`` for a drawn window length — the serving analogue of
        ``Schedule.random``'s fault draws.  Same seed => identical
        program => (with the seeded Poisson schedule) byte-identical
        executed arrivals; the runner REFUSES the program when no load
        shaper is attached (the net-fault routability rule)."""
        rng = np.random.default_rng(
            (int(seed) * 0xD1B54A32D192ED03 + 3) & 0xFFFFFFFFFFFFFFFF)
        events = []
        if n_windows <= 0:
            return cls(events)
        span = max(1, steps // n_windows)
        for i in range(n_windows):
            lo = i * span + 1
            w = int(rng.integers(window[0], window[1] + 1))
            start = lo + int(rng.integers(0, max(1, span - w)))
            xval = round(float(x_range[0] + (x_range[1] - x_range[0])
                               * rng.random()), 3)
            events.append(ChaosEvent(step=start, kind="overload", x=xval,
                                     until=min(steps - 1, start + w)))
        return cls(events)

    @classmethod
    def random(cls, cfg, seed: int, steps: int,
               spec: Optional[ChaosSpec] = None) -> "Schedule":
        """Seeded event program: one uniform per step selects the event
        class by the spec's rates; a second pre-drawn uniform resolves the
        target at RUN time (eligibility depends on cluster state, which is
        deterministic given the same seed + config)."""
        spec = spec or ChaosSpec()
        rng = np.random.default_rng(seed)
        events = []
        for step in range(steps):
            u = float(rng.random())
            pick = float(rng.random())
            lo = 0.0
            wire_verbs = tuple(WIRE_EVENTS)
            for kind, p in (("freeze", spec.p_freeze),
                            ("thaw", spec.p_thaw),
                            ("join", spec.p_join),
                            ("crash_restart", spec.p_crash),
                            ("hb_skew", spec.p_skew),
                            ("net_drop", spec.p_net / 3),
                            ("net_delay", spec.p_net / 3),
                            ("net_dup", spec.p_net / 3),
                            ("partition", spec.p_partition),
                            ) + tuple(
                                (v, spec.p_wire / len(wire_verbs))
                                for v in wire_verbs):
                if lo <= u < lo + p:
                    kw: dict = dict(step=step, kind=kind, u=pick)
                    if kind == "hb_skew":
                        kw.update(skew=spec.skew_amount,
                                  until=step + spec.skew_window)
                    elif kind.startswith("net_") or kind in WIRE_EVENTS:
                        kw.update(until=step + spec.net_window,
                                  skew=spec.net_delay)
                    elif kind == "partition":
                        # directed (dst=-1 -> the target's whole outbound
                        # side goes dark: an ASYMMETRIC partition — its
                        # inbound still flows)
                        kw.update(until=step + spec.partition_window)
                    events.append(ChaosEvent(**kw))
                    break
                lo += p
        return cls(events)


class NetChaos:
    """Window-driven adversarial schedule for SimTransport: active windows
    drop / delay / duplicate messages on matching directed edges.  Install
    as ``SimTransport(r, schedule=net_chaos)``; the runner opens windows
    from net_* events and ``clear()``s them when healing."""

    def __init__(self):
        # (kind, src, dst, from_step, until, delta); src/dst -1 = any
        self.windows: List[Tuple[str, int, int, int, int, int]] = []

    def add(self, kind: str, src: int, dst: int, from_step: int, until: int,
            delta: int = 0) -> None:
        self.windows.append((kind, src, dst, from_step, until, delta))

    def clear(self) -> None:
        self.windows.clear()

    def _match(self, kind: str, src: int, dst: int, step: int):
        for k, ws, wd, f, until, delta in self.windows:
            if k != kind:
                continue
            if ws >= 0 and ws != src:
                continue
            if wd >= 0 and wd != dst:
                continue
            if f <= step < until:
                return delta
        return None

    def __call__(self, kind: str, src: int, dst: int, step: int):
        if src == dst:
            return [step]  # loopback never traverses the faulty fabric
        if self._match("drop", src, dst, step) is not None:
            return []
        whens = [step]
        delta = self._match("delay", src, dst, step)
        if delta is not None:
            whens = [step + max(1, delta)]
        if self._match("dup", src, dst, step) is not None:
            whens = whens + [whens[0] + 1]
        return whens


class ChaosRunner:
    """Drive a workload target through a fault schedule (module docstring).

    ``target``: FastRuntime, KVS facade, or sim-backed Runtime.
    ``net``: the NetChaos installed in the target's SimTransport (sim
    engine only).
    ``wire``: the chaos.net.FaultingTransport interposer wrapping the
    target's HostTransport (round-11) — carries the netdrop/netdelay/
    netdup/netreorder/netcorrupt/partition verbs (and the legacy net_*
    verbs when ``net`` is absent).  Schedules with net-fault lines are
    REFUSED at construction when no carrier is attached (the error names
    the transport class).
    ``snapshot_path``: opts crash_restart into snapshot-seeded restore;
    with ``snapshot_every`` > 0 the runner refreshes the snapshot itself
    at that cadence (fast engines, quiescent boundaries only — the KVS
    save requires no in-flight client ops, so the runner snapshots the
    RUNTIME under the facade).
    ``powercut``: the round-22 whole-process kill carrier — a callable
    ``powercut(step)`` that SIGKILLs the store process (in the durability
    gate's soak child: ``os.kill(os.getpid(), signal.SIGKILL)``).  It is
    expected NOT to return; schedules with powercut lines are refused at
    construction when no carrier is attached, same contract as the wire
    verbs."""

    def __init__(self, target, schedule: Schedule,
                 spec: Optional[ChaosSpec] = None,
                 net: Optional[NetChaos] = None,
                 wire=None,
                 load=None,
                 snapshot_path: Optional[str] = None,
                 powercut: Optional[Callable[[int], None]] = None,
                 on_step: Optional[Callable[[int], None]] = None):
        self.kvs = target if (hasattr(target, "rt")
                              and hasattr(target, "index")) else None
        self.rt = target.rt if self.kvs is not None else target
        self.target = target
        self.schedule = schedule
        self.spec = spec or ChaosSpec()
        self.net = net
        # round-11: the transport-generic fault interposer
        # (chaos.net.FaultingTransport wrapping the target's HostTransport)
        self.wire = wire
        # round-14: the open-loop load shaper (workload.ShapedArrivals or
        # anything with set_rate_x) the overload verbs act on
        self.load = load
        self._overload_until: Optional[int] = None
        # round-22: the whole-process kill carrier (see class docstring)
        self.powercut = powercut
        self.snapshot_path = snapshot_path
        self.on_step = on_step
        self.log: List[dict] = []
        self.lost_ops = 0
        self.lost_client = 0
        self._frozen_since: Dict[int, int] = {}
        self._removed: set = set()
        self._skew_until: Dict[int, int] = {}
        # active partitions: (until, src, dst, start) — start is kept so
        # expiring one window can re-derive the oracle's severed set from
        # the windows still active (overlapping windows on the same src
        # must not end each other early)
        self._partition_until: List[Tuple[int, int, int, int]] = []
        # schedule cursor (tick() consumes events; run() drives tick —
        # round-13 fleet runners drive MANY runners' ticks in lockstep,
        # one per group, each over its own group-scoped target)
        self._ev_iter = iter(self.schedule)
        self._nxt = next(self._ev_iter, None)
        self._check_net_faults_routable()

    def _transport_name(self) -> str:
        tr = getattr(self.rt, "transport", None)
        if tr is not None:
            return type(tr).__name__
        return (f"{type(self.rt).__name__}"
                f"[{getattr(self.rt, 'backend', '?')}] (no host transport)")

    def _check_net_faults_routable(self) -> None:
        """Refuse net-fault schedule lines UP FRONT when no interposer can
        carry them (round-11 satellite): before this check, a sim-only
        composition failed silently (events logged 'skipped') or late.  The
        error names the transport class so the fix is actionable."""
        wire_lines = [e for e in self.schedule if e.kind in WIRE_EVENTS]
        legacy_lines = [e for e in self.schedule
                        if e.kind in LEGACY_NET_EVENTS]
        part_lines = [e for e in self.schedule if e.kind == "partition"]
        over_lines = [e for e in self.schedule
                      if e.kind in ("overload", "overload_clear")]
        cut_lines = [e for e in self.schedule if e.kind == "powercut"]
        name = self._transport_name()
        if cut_lines and self.powercut is None:
            ls = ", ".join(e.format() for e in cut_lines[:3])
            raise ValueError(
                f"schedule contains powercut events ({ls}) but no kill "
                "carrier is attached: a powercut SIGKILLs the WHOLE store "
                "process, which only a harness can arrange — pass "
                "ChaosRunner(..., powercut=<callable(step)>) (the "
                "durability gate's soak child kills its own pid)")
        if over_lines and self.load is None:
            ls = ", ".join(e.format() for e in over_lines[:3])
            raise ValueError(
                f"schedule contains overload events ({ls}) but no load "
                "shaper is attached: pass the open-loop arrival schedule "
                "(workload.ShapedArrivals, or anything with set_rate_x) "
                "as ChaosRunner(..., load=...)")
        if wire_lines and self.wire is None:
            ls = ", ".join(e.format() for e in wire_lines[:3])
            raise ValueError(
                f"schedule contains wire-fault events ({ls}) but no fault "
                f"interposer is attached to {name}: wrap the transport in "
                "chaos.net.FaultingTransport and pass it as "
                "ChaosRunner(..., wire=...)")
        if legacy_lines and self.wire is None and self.net is None:
            ls = ", ".join(e.format() for e in legacy_lines[:3])
            raise ValueError(
                f"schedule contains net-fault events ({ls}) but {name} has "
                "no fault hook: pass net=NetChaos() installed as the "
                "SimTransport schedule, or wire=chaos.net.FaultingTransport "
                "wrapping the transport")
        if part_lines and self.wire is None:
            # fast engines: partition is detector-level (membership oracle)
            if self.rt.membership is None:
                ls = ", ".join(e.format() for e in part_lines[:3])
                raise ValueError(
                    f"schedule contains partition events ({ls}) but {name} "
                    "has no fault interposer and no MembershipService: on "
                    "the fast engines a partition acts through the "
                    "detector — attach_membership(...) first (or run the "
                    "sim engine with wire=FaultingTransport(...))")

    # -- bookkeeping ---------------------------------------------------------

    def _healthy(self) -> List[int]:
        return self.rt.healthy_replicas()

    def _note(self, step: int, kind: str, **fields) -> None:
        self.log.append(dict(step=step, kind=kind, **fields))

    def _pick(self, cands: Sequence[int], u: float) -> int:
        return int(sorted(cands)[int(u * len(cands)) % len(cands)])

    # -- event application ---------------------------------------------------

    def _apply(self, step: int, e: ChaosEvent) -> None:
        rt = self.rt
        healthy = self._healthy()
        if e.kind == "freeze":
            cands = ([e.replica] if e.replica >= 0 else
                     [r for r in healthy if r not in self._frozen_since])
            if len(healthy) <= self.spec.min_healthy or not cands:
                return
            r = self._pick(cands, e.u)
            rt.freeze(r)
            self._frozen_since[r] = step
            self._note(step, "freeze", replica=r)
        elif e.kind == "thaw":
            cands = ([e.replica] if e.replica >= 0
                     else list(self._frozen_since))
            cands = [r for r in cands if r in self._frozen_since]
            if not cands:
                return
            r = self._pick(cands, e.u)
            rt.thaw(r)
            del self._frozen_since[r]
            self._note(step, "thaw", replica=r)
        elif e.kind == "remove":
            r = e.replica
            if r < 0 or not (int(rt.live[0]) >> r) & 1:
                return
            # the legality floor applies to removes of HEALTHY replicas
            # too (removing a frozen one is the normal lease outcome): an
            # over-aggressive declarative schedule degrades to what the
            # cluster can absorb instead of emptying it
            if r in healthy and len(healthy) <= self.spec.min_healthy:
                self._note(step, "skipped", event=e.kind, replica=r,
                           reason="healthy floor")
                return
            rt.remove(r)
            self._removed.add(r)
            self._frozen_since.pop(r, None)
            self._note(step, "remove", replica=r)
        elif e.kind == "join":
            cands = ([e.replica] if e.replica >= 0 else list(self._removed))
            cands = [r for r in cands if r in self._removed]
            if not cands or not healthy:
                return
            r = self._pick(cands, e.u)
            donor = e.donor if e.donor >= 0 else healthy[0]
            rt.join(r, from_replica=donor)
            self._removed.discard(r)
            self._note(step, "join", replica=r, donor=donor)
        elif e.kind == "crash_restart":
            from hermes_tpu.chaos import recovery

            if not hasattr(rt, "fs"):
                self._note(step, "skipped", event=e.kind,
                           reason="phases runtime")
                return
            cands = ([e.replica] if e.replica >= 0 else
                     [r for r in healthy if r not in self._frozen_since])
            if len(healthy) <= self.spec.min_healthy or not cands:
                return
            r = self._pick(cands, e.u)
            donor = e.donor if e.donor >= 0 else None
            s = recovery.restart_replica(self.target, r, donor=donor,
                                         snapshot_path=self.snapshot_path)
            self.lost_ops += s["lost_ops"]
            self.lost_client += s["lost_client_futures"]
            self._frozen_since.pop(r, None)
            self._removed.discard(r)
            self._note(step, "crash_restart", replica=r, donor=s["donor"],
                       source=s["source"], lost_ops=s["lost_ops"])
        elif e.kind == "hb_skew":
            svc = rt.membership
            if svc is None:
                self._note(step, "skipped", event=e.kind,
                           reason="no membership service")
                return
            cands = [e.replica] if e.replica >= 0 else healthy
            if not cands:
                return
            r = self._pick(cands, e.u)
            svc.skew[r] = e.skew
            self._skew_until[r] = e.until if e.until >= 0 else step + 8
            rt._trace("hb_skew", replica=r, skew=e.skew,
                      until=self._skew_until[r])
            self._note(step, "hb_skew", replica=r, skew=e.skew,
                       until=self._skew_until[r])
        elif e.kind in LEGACY_NET_EVENTS or e.kind in WIRE_EVENTS:
            # one body for both verb generations; only the carrier differs
            # (legacy net_* prefers the NetChaos sim schedule when present,
            # everything else rides the round-11 interposer — construction
            # refused schedules with no carrier at all)
            op = LEGACY_NET_EVENTS.get(e.kind) or WIRE_EVENTS[e.kind]
            R = rt.cfg.n_replicas
            src = e.replica if e.replica >= 0 else self._pick(range(R), e.u)
            until = e.until if e.until >= 0 else step + self.spec.net_window
            if e.kind in LEGACY_NET_EVENTS and self.net is not None:
                self.net.add(op, src, e.dst, step, until, delta=e.skew)
            else:
                self.wire.add(op, src, e.dst, step, until,
                              param=e.skew if e.skew else self.spec.net_delay)
            rt._trace(e.kind, src=src, dst=e.dst, until=until)
            self._note(step, e.kind, src=src, dst=e.dst, until=until)
            self._update_net_phase(step)
        elif e.kind == "partition":
            # directed: src -> dst goes dark (dst=-1: src's whole OUTBOUND
            # side — an asymmetric partition; src still hears the cluster).
            # On a wired engine the interposer blacks the edges out and the
            # detector sees the starvation organically; on the fast engines
            # (no wire) the membership oracle models exactly the
            # detector-visible consequence (membership.sever) — the data
            # plane of the fused round is untouched, so safety there rests
            # on the lease rule: the ejected replica is fenced by remove().
            R = rt.cfg.n_replicas
            src = e.replica if e.replica >= 0 else self._pick(range(R), e.u)
            until = e.until if e.until >= 0 else (
                step + self.spec.partition_window)
            if self.wire is not None:
                self.wire.add("partition", src, e.dst, step, until)
            svc = rt.membership
            if self.wire is None and svc is not None:
                svc.sever(src, e.dst, at_step=step)
            self._partition_until.append((until, src, e.dst, step))
            rt._trace("partition", src=src, dst=e.dst, until=until)
            self._note(step, "partition", src=src, dst=e.dst, until=until)
            self._update_net_phase(step)
        elif e.kind == "heal":
            self._heal_adversary(step)
            self._heal_cluster(step)
            self._note(step, "heal")
            self._update_net_phase(step)
        elif e.kind == "overload":
            x = e.x or 2.0
            self.load.set_rate_x(x)
            self._overload_until = e.until if e.until >= 0 else None
            rt._trace("overload", x=x, until=e.until)
            self._note(step, "overload", x=x, until=e.until)
        elif e.kind == "overload_clear":
            self.load.set_rate_x(1.0)
            self._overload_until = None
            rt._trace("overload_clear")
            self._note(step, "overload_clear")
        elif e.kind == "powercut":
            # note + trace BEFORE the carrier fires: it SIGKILLs this
            # process and does not return, so this log line (and whatever
            # the trace fsyncs) is all the forensic record the parent gets
            self._note(step, "powercut")
            rt._trace("powercut", step=step)
            self.powercut(step)
            # a mock carrier (tests) may return; nothing to clean up —
            # the real one never reaches here

    def _expire_overload(self, step: int) -> None:
        """Close an overload window whose ``until`` elapsed (explicit
        ``overload_clear`` events also close it)."""
        if self._overload_until is not None and step >= self._overload_until:
            self.load.set_rate_x(1.0)
            self._overload_until = None
            self.rt._trace("overload_clear")
            self._note(step, "overload_clear")

    def _expire_skews(self, step: int) -> None:
        svc = self.rt.membership
        for r, until in list(self._skew_until.items()):
            if step >= until:
                if svc is not None:
                    svc.skew[r] = 0
                del self._skew_until[r]

    def _expire_partitions(self, step: int) -> None:
        """Restore detector-oracle partitions whose window elapsed (wire
        windows expire by their own step test).  The severed set is
        RE-DERIVED from the still-active windows rather than edge-wise
        restored: a wildcard restore for one lapsed window must not end an
        overlapping window on the same src early."""
        if not self._partition_until:
            return
        svc = self.rt.membership
        live = [p for p in self._partition_until if p[0] > step]
        if len(live) != len(self._partition_until):
            self._partition_until = live
            if self.wire is None and svc is not None:
                svc.heal_partitions()
                # earliest-start first: sever() keeps the first since-step
                # per edge, so overlapping windows retain the oldest age
                for _until, src, dst, start in sorted(live,
                                                      key=lambda p: p[3]):
                    svc.sever(src, dst, at_step=start)
            self._update_net_phase(step)

    def _update_net_phase(self, step: int) -> None:
        """Publish the active adversary windows into the KVS stuck-op
        diagnostics channel (round-11 satellite: StuckOpError carries the
        partition/drop spec + affected peer pairs, like the round-10 drill
        phase)."""
        if self.kvs is None:
            return
        edges = []
        if self.wire is not None:
            edges = [f"{w['op']}:{w['src']}->{w['dst']}@{w['until']}"
                     for w in self.wire.active_windows(step)]
        else:
            edges = [f"partition:{src}->{dst}@{until}"
                     for until, src, dst, _start in self._partition_until
                     if until > step]
        self.kvs.net_phase = dict(windows=sorted(edges)) if edges else None

    def _heal_adversary(self, step: int) -> None:
        """Clear every active network-level fault: wire windows, legacy
        NetChaos windows, detector-oracle partitions, heartbeat skews."""
        rt = self.rt
        if self.net is not None:
            self.net.clear()
        if self.wire is not None:
            self.wire.heal(step)
        if rt.membership is not None:
            rt.membership.heal_partitions()
            for r in list(self._skew_until):
                rt.membership.skew[r] = 0
        self._skew_until.clear()
        self._partition_until.clear()
        # unconditional, like skews/partitions: an `overload x=N` with no
        # until= (open window awaiting an overload_clear) must not outlive
        # a heal
        if self.load is not None:
            self.load.set_rate_x(1.0)
            self._overload_until = None

    def _heal_cluster(self, step: int) -> None:
        """Thaw every frozen replica and rejoin every non-live one through
        the epoch-fenced state-transfer join — the partition+heal cycle's
        recovery half (a partitioned-but-alive replica kept its state; the
        join re-validates, it never diverges).  Skips loudly when no live
        donor exists."""
        rt = self.rt
        for r in list(self._frozen_since):
            rt.thaw(r)
            self._note(step, "thaw", replica=r, by="heal")
        self._frozen_since.clear()
        # the detector may have removed replicas on its own — rejoin every
        # non-live replica, not just the runner's bookkeeping
        for r in range(rt.cfg.n_replicas):
            if not (int(rt.live[0]) >> r) & 1:
                donors = self._healthy()
                if not donors:
                    self._note(step, "skipped", event="join", replica=r,
                               reason="no live donor")
                    continue
                rt.join(r, from_replica=donors[0])
                self._note(step, "join", replica=r, donor=donors[0],
                           by="heal")
        self._removed.clear()

    def _lease_rule(self, step: int) -> None:
        """Detector-less removal: a replica frozen past the lease window is
        ejected (the historical soak's stand-in for the membership
        service).  A real MembershipService owns this when attached."""
        if self.rt.membership is not None:
            return
        for r, since in list(self._frozen_since.items()):
            if step - since > self.spec.lease_remove_after:
                self.rt.remove(r)
                self._removed.add(r)
                del self._frozen_since[r]
                self._note(step, "remove", replica=r, by="lease")

    def _step_target(self) -> None:
        if self.kvs is not None:
            self.kvs.step()
        else:
            self.rt.step_once()

    # -- the drive -----------------------------------------------------------

    def tick(self, step: int) -> None:
        """Everything one scheduled round does EXCEPT stepping the
        target: expire lapsed windows, run the lease rule, apply due
        events.  ``run`` drives this loop for one target; a fleet runner
        (hermes_tpu.fleet.chaos) ticks one runner per group in lockstep
        and steps the groups itself."""
        self._expire_skews(step)
        self._expire_partitions(step)
        if self.load is not None:
            self._expire_overload(step)
        if self.kvs is not None and self.wire is not None:
            # wire windows expire by their own step test: refresh the
            # diagnostics channel so a stuck op is never blamed on a
            # window that already ended
            self._update_net_phase(step)
        self._lease_rule(step)
        while self._nxt is not None and self._nxt.step <= step:
            self._apply(step, self._nxt)
            self._nxt = next(self._ev_iter, None)

    def run(self, steps: int, heal: bool = True, drain_steps: int = 4000,
            check: bool = False) -> dict:
        """Run ``steps`` rounds with the schedule applied, then (``heal``)
        thaw/rejoin everything, clear skews and net windows, drain, and
        optionally run the linearizability gate.  Returns the result dict:
        executed event log, loss accounting, drained/verdict flags."""
        # run() always replays the schedule from its first event (the
        # pre-tick() contract): reset the cursor so a second run() — or a
        # run() after standalone tick() driving — is never silently empty
        self._ev_iter = iter(self.schedule)
        self._nxt = next(self._ev_iter, None)
        for step in range(steps):
            self.tick(step)
            self._step_target()
            if self.on_step is not None:
                self.on_step(step)
        result: dict = dict(steps=steps, lost_ops=self.lost_ops,
                            lost_client_futures=self.lost_client)
        if heal:
            rt = self.rt
            self._heal_adversary(steps)
            # (skip loudly if no live donor exists rather than crash: an
            # adversarial schedule can legally empty the healthy set)
            self._heal_cluster(steps)
            self._update_net_phase(steps)
            if self.kvs is not None:
                # pipelined KVS: _pending (the deferred round) refills on
                # every step, so quiescence is judged on client work only
                # and the final flush lands the last deferred round
                drained = True
                for _ in range(drain_steps):
                    if not (self.kvs._inflight or self.kvs._queued_slots
                            or self.kvs._bat):
                        break
                    self.kvs.step()
                else:
                    drained = False
                self.kvs.flush()
                rt.flush_pipeline()
            else:
                drained = rt.drain(drain_steps)
            result["drained"] = bool(drained)
        if check:
            v = self.rt.check()
            result["checked_ok"] = bool(v.ok)
            result["check_failures"] = [
                getattr(f, "reason", str(f))[:200]
                for f in (v.failures + v.undecided)[:3]]
            if not v.ok and self.rt.obs is not None \
                    and self.rt.obs.flight.dumps:
                # checker red: rt.check() just dumped the flight recorder
                # (round-18, obs/flightrec.py) — surface the archive path
                # in the chaos result so soak triage finds it
                result["flight_dump"] = self.rt.obs.flight.dumps[-1]
        result["events"] = self.log
        return result

    def log_json(self) -> str:
        """Canonical executed-event log (the determinism witness: same
        seed + config => byte-identical)."""
        return json.dumps(self.log, sort_keys=True, separators=(",", ":"))
