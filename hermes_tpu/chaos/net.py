"""Transport-generic adversarial wire interposer (round-11).

PR 5's ``NetChaos`` could drop/delay/duplicate — but only by being compiled
into the SimTransport's schedule, so the adversary was welded to one
transport and could never corrupt a byte or partition the wire.  This
module makes the adversary an INTERPOSER: ``FaultingTransport`` wraps any
``transport.base.HostTransport`` implementation — the deterministic sim,
the zero-delay lockstep loopback, the C++ tcp mesh adapter
(``transport.tcp.TcpHostTransport``) — and injects seeded, window-driven
faults per DIRECTED peer pair on the inbound path:

  * ``drop``       — the pair's frame this step never arrives
  * ``delay``      — the frame is held ``param`` steps, FIFO preserved
  * ``dup``        — an extra copy of the frame arrives 1-2 steps later
  * ``reorder``    — frames are held with hash-jittered due steps and
                     released in hash order (cross-step reordering)
  * ``corrupt``    — the frame is serialized (codec.pack), bytes are
                     flipped, and the framed CRC (codec.frame_pack /
                     frame_unpack) DETECTS the damage and downgrades it to
                     a drop — a corrupted frame is NEVER applied.  The red
                     path (``crc=False``) delivers the scrambled bytes
                     instead, proving what the checksum is for.
  * ``partition``  — a sustained directed blackout (all kinds); asymmetric
                     partitions are just windows on one direction.

Receive-side interposition is observationally equivalent to faulting the
wire itself (the receiver cannot distinguish a frame the network held from
one the interposer held) and is what makes the wrapper transport-generic:
it needs nothing from the inner transport beyond the exchange calls, so it
composes with the sim transport's OWN schedule (double adversary), with
the lockstep loopback, and — per rank — with a real socket mesh.

Every applied fault lands in ``fault_log`` in deterministic order: same
seed + config + schedule replays a byte-identical executed fault log
(``fault_log_json``), the round-9 determinism contract extended to the
wire.

Detection composes for free: heartbeat ``alive`` bits ride the INV blocks,
so a partitioned edge starves ``last_seen`` at the receiver and the PR-5
suspect -> confirm -> remove machine sees a partitioned-but-alive replica
exactly as stale — it is removed (and self-fences, the lease rule), its
STATE survives (unlike a crash: no volatile wipe, no ``maybe_w`` fold),
and on heal it rejoins through the epoch-fenced state-transfer join.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from hermes_tpu.transport import codec

# the wire-fault verbs, in match-priority order (partition dominates, dup
# composes with pass-through)
WIRE_OPS = ("partition", "drop", "corrupt", "delay", "reorder", "dup")


def _h(*args) -> int:
    """Deterministic 32-bit hash — the seeded adversary's only randomness
    source, so every decision replays bit-identically."""
    return int.from_bytes(
        hashlib.blake2b(repr(args).encode(), digest_size=4).digest(), "little")


@dataclasses.dataclass(frozen=True)
class WireWindow:
    """One active fault window on directed edges: ``src``/``dst`` of -1
    match any endpoint; the window is active for ``from_step <= step <
    until``; ``param`` is the op's knob (delay steps / reorder spread)."""

    op: str
    src: int
    dst: int
    from_step: int
    until: int
    param: int = 0

    def matches(self, src: int, dst: int, step: int) -> bool:
        return ((self.src < 0 or self.src == src)
                and (self.dst < 0 or self.dst == dst)
                and self.from_step <= step < self.until)


class FaultingTransport:
    """Adversarial interposer over any ``HostTransport`` (module docstring).

    ``inner``      — the wrapped transport (SimTransport,
                     LockstepHostTransport, TcpHostTransport, ...).
    ``local_rank`` — None for in-process transports (inbound blocks carry
                     leading ``(R_dst, R_src)`` axes); the owning rank for
                     per-process transports (inbound ``(R_src, ...)``,
                     dst implicit).
    ``crc``        — frame corrupted payloads through the codec CRC frame
                     (the default; corruption is detected and downgraded
                     to a drop).  False is the RED path: scrambled bytes
                     are delivered into the protocol — exists only so
                     tests can prove the checksum earns its keep.
    ``registry``   — optional obs MetricsRegistry: per-op fault counters
                     (``wire_drop``/``wire_corrupt_dropped``/...) so a
                     soak's metrics record how hostile the wire was.
    """

    def __init__(self, inner, n_replicas: int, seed: int = 0,
                 crc: bool = True, local_rank: Optional[int] = None,
                 registry=None):
        self.inner = inner
        self.r = n_replicas
        self.seed = seed
        self.crc = crc
        self.local_rank = local_rank
        self.registry = registry
        self.windows: List[WireWindow] = []
        # (kind, src, dst) -> list of (due_step, order_key, field dict)
        self._held: Dict[Tuple[str, int, int], List[tuple]] = (
            collections.defaultdict(list))
        self.fault_log: List[dict] = []
        self.counters: collections.Counter = collections.Counter()

    # -- window control ------------------------------------------------------

    def add(self, op: str, src: int, dst: int, from_step: int, until: int,
            param: int = 0) -> WireWindow:
        if op not in WIRE_OPS:
            raise ValueError(
                f"unknown wire fault {op!r} (want one of {', '.join(WIRE_OPS)})")
        w = WireWindow(op, src, dst, from_step, until, param)
        self.windows.append(w)
        return w

    def heal(self, step: int) -> int:
        """Clear every window (held frames still deliver: they are
        in-flight packets, not faults).  Returns the number cleared."""
        n = len(self.windows)
        self.windows.clear()
        if n:
            self._log(step, "heal", -1, -1, "*", cleared=n)
        return n

    def active_windows(self, step: int) -> List[dict]:
        """The live adversary spec at ``step`` — stuck-op diagnostics and
        soak triage read this instead of cross-referencing logs."""
        return [dataclasses.asdict(w) for w in self.windows
                if w.from_step <= step < w.until]

    def pending(self) -> int:
        held = sum(len(v) for v in self._held.values())
        inner_pending = getattr(self.inner, "pending", None)
        return held + (inner_pending() if inner_pending is not None else 0)

    # -- bookkeeping ---------------------------------------------------------

    def _log(self, step: int, op: str, src: int, dst: int, kind: str,
             **extra) -> None:
        self.fault_log.append(
            dict(step=step, op=op, src=src, dst=dst, kind=kind, **extra))
        self.counters[f"wire_{op}"] += 1
        if self.registry is not None:
            self.registry.counter(f"wire_{op}").inc()

    def fault_log_json(self) -> str:
        """Canonical executed fault log (the determinism witness: same
        seed + config + schedule => byte-identical)."""
        return json.dumps(self.fault_log, sort_keys=True,
                          separators=(",", ":"))

    def _match(self, op: str, src: int, dst: int, step: int
               ) -> Optional[WireWindow]:
        for w in self.windows:
            if w.op == op and w.matches(src, dst, step):
                return w
        return None

    # -- the interposition ---------------------------------------------------

    def _corrupt_frame(self, kind: str, src: int, dst: int, step: int,
                       fields: dict) -> Optional[dict]:
        """Serialize the pair's block, flip bytes, and run it through the
        frame checksum.  Returns the (scrambled) field dict if the frame
        survives delivery (crc=False red path), else None (detected ->
        dropped)."""
        tpl = tuple(fields.values())
        payload = codec.pack(tpl)
        frame = codec.frame_pack(payload)
        n = frame.nbytes
        flipped = frame.copy()
        for i in range(3):  # a short burst inside the payload region
            pos = codec.FRAME_OVERHEAD + (
                _h(self.seed, "pos", kind, src, dst, step, i)
                % max(1, n - codec.FRAME_OVERHEAD))
            flipped[pos] ^= 0x5A
        if self.crc:
            try:
                codec.frame_unpack(flipped)
            except codec.FrameCorrupt as e:
                self._log(step, "corrupt", src, dst, kind,
                          outcome="dropped_by_crc", detail=str(e)[:80])
                self.counters["wire_corrupt_dropped"] += 1
                return None
            raise AssertionError(
                "corrupted frame passed its checksum — flip did not land")
        # RED path: no checksum on the wire — the scrambled bytes ARE
        # delivered into the protocol (what CRC-less transports risk)
        scrambled = codec.unpack(
            tpl, flipped[codec.FRAME_OVERHEAD:])
        self._log(step, "corrupt", src, dst, kind, outcome="applied")
        self.counters["wire_corrupt_applied"] += 1
        return dict(zip(fields.keys(), scrambled))

    def _merge(self, blocks: List[dict]) -> Optional[dict]:
        """FIFO overlay merge of frames delivered together (the sim
        transport's latest-packet-wins rule, kind-generic): later valid
        lanes overlay earlier, ``alive`` ORs, ``valid`` unions."""
        merged = None
        for blk in blocks:
            if merged is None:
                merged = dict(blk)
                continue
            v = np.asarray(blk["valid"])
            for f, arr in blk.items():
                if f == "alive":
                    merged[f] = merged[f] | arr
                elif f == "valid":
                    continue
                elif np.asarray(arr).ndim > v.ndim:  # value words (L, V)
                    merged[f] = np.where(v[..., None], arr, merged[f])
                else:
                    merged[f] = np.where(v, arr, merged[f])
            merged["valid"] = merged["valid"] | v
        return merged

    def _fault_pair(self, kind: str, src: int, dst: int, step: int,
                    frame: Optional[dict]) -> Optional[dict]:
        """Apply the active windows to one directed pair's frame; returns
        the merged block to deliver this step (None = nothing arrives)."""
        chan = (kind, src, dst)
        if frame is not None and not (
                np.any(np.asarray(frame["valid"]))
                or np.any(np.asarray(frame.get("alive", False)))):
            # the inner transport delivered nothing for this pair (e.g. the
            # sim schedule dropped it): nothing to fault, nothing to log
            frame = None
        if frame is not None:
            # window priority: partition/drop kill, corrupt mangles,
            # delay/reorder hold; dup composes with whatever survives
            if (self._match("partition", src, dst, step) is not None
                    or self._match("drop", src, dst, step) is not None):
                op = ("partition"
                      if self._match("partition", src, dst, step) is not None
                      else "drop")
                self._log(step, op, src, dst, kind)
                frame = None
            elif self._match("corrupt", src, dst, step) is not None:
                frame = self._corrupt_frame(kind, src, dst, step, frame)
            else:
                w = self._match("delay", src, dst, step)
                if w is not None:
                    due = step + max(1, w.param)
                    # FIFO order key: the send step (delay preserves order)
                    self._held[chan].append((due, step, frame))
                    self._log(step, "delay", src, dst, kind, due=due)
                    frame = None
                else:
                    w = self._match("reorder", src, dst, step)
                    if w is not None:
                        due = step + 1 + (
                            _h(self.seed, "ro", kind, src, dst, step)
                            % max(1, w.param))
                        order = _h(self.seed, "ro2", kind, src, dst, step)
                        self._held[chan].append((due, order, frame))
                        self._log(step, "reorder", src, dst, kind, due=due)
                        frame = None
            if frame is not None and self._match("dup", src, dst, step) is not None:
                due = step + 1 + _h(self.seed, "dup", kind, src, dst, step) % 2
                self._held[chan].append((due, step, dict(frame)))
                self._log(step, "dup", src, dst, kind, due=due)
        # release everything due, in (due, order) order — reorder's hashed
        # order keys scramble delivery relative to send order.  A partition
        # is a SUSTAINED blackout of the edge: frames already in flight
        # (held by delay/reorder/dup) die in it too, they do not tunnel
        # through — without this, a held heartbeat released mid-blackout
        # would refresh the observer and delay detector ejection.
        q = self._held.get(chan)
        due_frames: List[dict] = []
        if q:
            q.sort(key=lambda e: (e[0], e[1]))
            while q and q[0][0] <= step:
                held = q.pop(0)[2]
                if self._match("partition", src, dst, step) is not None:
                    self._log(step, "partition", src, dst, kind,
                              held="dropped_in_blackout")
                    continue
                due_frames.append(held)
        if frame is not None:
            due_frames.append(frame)  # this step's frame arrives last
        if not due_frames:
            return None
        return self._merge(due_frames)

    def _interpose(self, kind: str, inb, step: int):
        """Fault every directed pair slice of the inbound block."""
        # lazily prune windows that can never match again (heal() is
        # otherwise the only pruner — a long run after a short schedule
        # must not keep scanning dead windows)
        if self.windows:
            self.windows = [w for w in self.windows if w.until > step]
        if not self.windows and not any(self._held.values()):
            return inb  # quiet wire: no copies, no per-pair work
        fields = {f: np.array(np.asarray(v))  # own copy: we mutate slices
                  for f, v in inb._asdict().items()}
        r = self.r
        if self.local_rank is None:
            pairs = [((dst, src), src, dst)
                     for dst in range(r) for src in range(r)]
        else:
            pairs = [((src,), src, self.local_rank) for src in range(r)]
        for idx, src, dst in pairs:
            if src == dst:
                continue  # loopback never traverses the faulty fabric
            # copy, not view: a held (delayed/reordered/dup'd) frame must
            # survive this pair's inbound slice being zeroed below
            frame = {f: np.array(v[idx]) for f, v in fields.items()}
            out = self._fault_pair(kind, src, dst, step, frame)
            if out is None:
                for f in fields:  # nothing arrived: zero block (valid=False)
                    fields[f][idx] = np.zeros_like(fields[f][idx])
            else:
                for f in fields:
                    fields[f][idx] = out[f]
        return inb._replace(**fields)

    # -- HostTransport surface ----------------------------------------------

    def exchange_inv(self, out_inv, step: int):
        return self._interpose("inv", self.inner.exchange_inv(out_inv, step),
                               step)

    def exchange_ack(self, out_ack, step: int):
        return self._interpose("ack", self.inner.exchange_ack(out_ack, step),
                               step)

    def exchange_val(self, out_val, step: int):
        return self._interpose("val", self.inner.exchange_val(out_val, step),
                               step)
