"""Crash-restart recovery: full host-crash of one replica, modeled end to
end (PAPER.md §5.3 / §4.4 — any-replica failure is absorbed by replays,
lease membership, and rejoin-with-state-transfer).

``restart_replica`` is the one entry point.  It composes mechanisms the
runtime already has (fence/remove, join-with-state-transfer, the replay
scan, maybe_w history accounting) into the full crash story the ad-hoc
fault drills never exercised:

  1. **Crash.** The replica's volatile state dies: every in-flight client
     op is lost.  In-flight UPDATES were already broadcast (a faststep
     write invalidates its key in its own issue round), so the cluster may
     still finish them via replay even though no client ever hears back —
     they are folded into the recorded history as ``maybe_w`` (allowed,
     not required, to linearize; checker/history.py) BEFORE the session
     rows are wiped.  Wiped sessions skip past the lost op (``op_idx`` + 1)
     so the restarted process never re-mints a dead op's unique write id.
     On a KVS, the dead replica's client futures resolve loudly as
     ``kind='lost'`` (kvs.C_LOST for batch slots) — the client layer's
     answer to a crashed coordinator.
  2. **Fence + remove.** A crashed replica must not serve reads; if the
     failure detector has not already ejected it, ``remove()`` does
     (epoch bump, quorum re-evaluation — unblocking writes it was holding
     up).
  3. **Restore.** With ``snapshot_path``, the manifest is verified first
     (a torn or foreign snapshot is REJECTED on the timeline and recovery
     falls back to peer transfer — never silently restoring garbage); the
     snapshot contributes its still-current table rows, counted against
     the donor as ``rows_current`` (the state-transfer volume a real
     deployment saves).  The donor's copy stays authoritative either way:
     a row whose packed ts matches the donor's is byte-identical by the
     protocol's (key, ts) -> value uniqueness, so the join transfer below
     is also the delta-restore.
  4. **Rejoin + re-validate.** ``join(replica, donor)`` runs the existing
     rejoin-with-state-transfer: the donor's in-flight coordination keys
     enter the joiner INVALID and the live coordinator's VAL (or the
     replay scan) re-validates them.

Everything lands on the obs timeline as a ``crash_restart`` event
(replica, donor, source, lost ops, rows_current).
"""

from __future__ import annotations

import dataclasses
import time
import zipfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hermes_tpu import snapshot as snapshot_lib
from hermes_tpu.core import types as t


def wipe_volatile(rt, sess_mask, replay_mask=None) -> int:
    """Lose the volatile per-session (and optionally replay) state of the
    masked slots — the crash/salvage primitive.  ``sess_mask`` is ``(R,
    S)`` bool, ``replay_mask`` ``(R, replay_slots)`` bool.  Loaded ops
    (READ/ISSUE/INFL) on masked slots vanish; their sessions step past
    them so a restarted (or salvaged) slot never re-mints a lost op's
    write uid.  Callers own the history fold (``recorder.fold_pending``)
    — it must happen BEFORE this wipe, while the in-flight rows still
    exist.  Returns the number of client ops lost.

    Used whole-replica by ``restart_replica`` (full host-crash) and
    slot-masked by the key-range migration's forced cutover
    (hermes_tpu.elastic.migrate_range): ops caught mid-flip are salvaged
    as ``maybe_w`` history rows + loudly-lost client futures, never
    silently dropped."""
    cfg = rt.cfg
    fs = rt.fs
    sess, replay = fs.sess, fs.replay
    m = jnp.asarray(np.asarray(sess_mask, bool))
    loaded = m & ((sess.status == t.S_READ) | (sess.status == t.S_ISSUE)
                  | (sess.status == t.S_INFL))
    op_idx = sess.op_idx + loaded.astype(jnp.int32)
    if cfg.wrap_stream:
        wiped_status = jnp.int32(t.S_IDLE)
    else:
        wiped_status = jnp.where(op_idx >= cfg.ops_per_session,
                                 jnp.int32(t.S_DONE), jnp.int32(t.S_IDLE))
    z = lambda a: jnp.where(m, jnp.zeros_like(a), a)
    new_sess = sess._replace(
        status=jnp.where(m, wiped_status, sess.status),
        op_idx=op_idx,
        pts=z(sess.pts),
        acks=z(sess.acks),
        retries=z(sess.retries),
        issue_step=z(sess.issue_step),
    )
    new_replay = replay
    if replay_mask is not None:
        rm = jnp.asarray(np.asarray(replay_mask, bool))
        new_replay = replay._replace(
            active=jnp.where(rm, False, replay.active))
    rt.fs = fs._replace(sess=new_sess, replay=new_replay)
    return int(jax.device_get(jnp.sum(loaded.astype(jnp.int32))))


def _wipe_replica_volatile(rt, replica: int) -> int:
    """Full host-crash of one replica: every session and replay slot of
    ``replica`` loses its volatile state (wipe_volatile, whole-row masks).
    Returns the number of client ops lost."""
    cfg = rt.cfg
    sess_mask = np.zeros((cfg.n_replicas, cfg.n_sessions), bool)
    sess_mask[replica] = True
    replay_mask = np.zeros((cfg.n_replicas, cfg.replay_slots), bool)
    replay_mask[replica] = True
    return wipe_volatile(rt, sess_mask, replay_mask)


def _snapshot_rows_current(rt, replica: int, donor: int,
                           snapshot_path: str) -> Optional[int]:
    """FULLY verify the snapshot (manifest + every array checksum + config
    fingerprint — snapshot.verify_archive; a torn archive must reject on
    BOTH engines, not just the members one engine happens to read) and
    count how many of its table rows for ``replica`` are still current
    against the donor (same packed ts => byte-identical row, so these rows
    need no transfer).  Returns None — with a ``snapshot_rejected``
    timeline event — when the snapshot cannot be trusted."""
    try:
        snapshot_lib.verify_archive(snapshot_path, rt.cfg)
        K = rt.cfg.n_keys
        vpts = rt.fs.table.vpts
        if vpts.shape[0] == K:
            # batched: the authoritative table is SHARED and survives the
            # crash — every (verified) row is current, nothing to transfer
            return K
        with np.load(snapshot_path) as z:
            snap = np.asarray(
                z["state.table.vpts"])[replica * K:(replica + 1) * K]
        donor_rows = np.asarray(jax.device_get(
            jax.lax.dynamic_slice_in_dim(vpts, donor * K, K)))
        return int((snap == donor_rows).sum())
    except (ValueError, OSError, KeyError, zipfile.BadZipFile) as e:
        rt._trace("snapshot_rejected", replica=replica,
                  path=str(snapshot_path), reason=str(e)[:160])
        return None


def restart_replica(target, replica: int, donor: Optional[int] = None,
                    snapshot_path: Optional[str] = None,
                    wal_dir: Optional[str] = None) -> dict:
    """Full host-crash + recovery of ``replica`` on a FastRuntime or a KVS
    facade (see module docstring).  ``donor`` defaults to the lowest live,
    unfrozen peer; ``snapshot_path`` opts into snapshot-seeded restore
    (falls back to pure peer transfer when the snapshot is invalid).
    ``wal_dir`` (round-22) additionally replays the durability log's tail
    into the rejoined replica's table copy AFTER the join transfer —
    idempotent catch-up for records the donor already re-validated, real
    catch-up when the whole cluster restarted from a snapshot and the
    donor itself came back via ``recover_store``.  Returns a summary dict
    (also emitted as the ``crash_restart`` obs event)."""
    kvs = None
    if hasattr(target, "rt") and hasattr(target, "index"):  # the KVS facade
        kvs, rt = target, target.rt
    else:
        rt = target
    if not hasattr(rt, "fs"):
        raise NotImplementedError(
            "restart_replica models the fast engines (FastRuntime / KVS); "
            "the phases Runtime keeps the scripted freeze/remove/join drills")
    cfg = rt.cfg
    if not (0 <= replica < cfg.n_replicas):
        raise ValueError(f"replica {replica} out of range")

    # land every in-flight round first: completions the device already
    # produced are pre-crash facts the clients/recorder must see
    rt.flush_pipeline()

    # 1. crash — salvage the history first (broadcast in-flight updates may
    # still commit via replay; the checker must be ALLOWED to linearize
    # them), then lose the volatile state
    if rt.recorder is not None:
        rt.recorder.fold_pending(rt._sess_view(), replica)
    lost_client = kvs._on_replica_crash(replica) if kvs is not None else 0
    lost_ops = _wipe_replica_volatile(rt, replica)

    # 2. fence + remove (unless the failure detector already ejected it)
    if (int(rt.live[0]) >> replica) & 1:
        rt.remove(replica)
    else:
        rt.frozen[replica] = True
        rt._ctl_dirty = True

    # donor: lowest live unfrozen peer
    if donor is None:
        live = int(rt.live[0])
        cands = [d for d in range(cfg.n_replicas)
                 if d != replica and (live >> d) & 1 and not rt.frozen[d]]
        if not cands:
            raise RuntimeError(
                "restart_replica needs a live unfrozen donor; none left")
        donor = cands[0]

    # 3. restore source: verified snapshot (delta vs the donor) or transfer
    rows_current = None
    if snapshot_path is not None:
        rows_current = _snapshot_rows_current(rt, replica, donor,
                                              snapshot_path)
    source = "snapshot" if rows_current is not None else "transfer"

    # 4. rejoin with state transfer; the live coordinator / replay scan
    # re-validates the donor's in-flight keys (runtime.join semantics)
    rt.join(replica, donor)

    # 5. round-22 WAL tail catch-up: replay the durability log into the
    # rejoined copy only (sharded; the batched table is shared).  Replay
    # is idempotent by packed ts, so records the donor transfer already
    # covered are no-ops — this is the fence-until-caught-up step for
    # snapshot-seeded restores whose log tail outran the snapshot.
    wal_applied = wal_skipped = None
    if wal_dir is not None:
        from hermes_tpu.wal import replay as wal_replay

        scan = wal_replay.read_records(wal_dir, obs=rt.obs)
        wal_replay.check_headers(scan["headers"], cfg, obs=rt.obs)
        wal_applied, wal_skipped = wal_replay.apply_records(
            rt, scan["records"], heap=getattr(kvs, "heap", None),
            replicas=[replica])

    summary = dict(replica=replica, donor=donor, source=source,
                   lost_ops=lost_ops, lost_client_futures=lost_client,
                   rows_current=rows_current)
    if wal_dir is not None:
        summary.update(wal_applied=wal_applied, wal_skipped=wal_skipped)
    rt._trace("crash_restart", **summary)
    return summary


def recover_store(cfg, wal_dir: Optional[str] = None,
                  backend: str = "batched", mesh=None,
                  snapshot_path: Optional[str] = None, record=False,
                  sparse_keys: bool = False):
    """Round-22 whole-store recovery: bring a killed store back with ZERO
    committed writes lost.  The power-cord sequence:

      1. parse + triage the WAL segments FIRST (wal.replay.read_records —
         a torn tail truncates cleanly, a torn interior refuses loudly
         with a flight dump; nothing is built on a corrupt log);
      2. build a fresh KVS on the same config/wal_dir (its log continues
         the segment sequence numbering);
      3. restore the last snapshot if given (snapshot.load — verified
         manifest, all-or-nothing);
      4. replay the log through the table apply machinery, idempotent by
         packed ts (records the snapshot covers are no-ops), minting
         fresh heap refs from the logged extent bytes;
      5. fence: resume step_idx past every replayed commit step, so the
         recovered store can never re-mint a replayed round's step;
      6. re-append the surviving records into the FRESH log and retire
         the old segments — the new log alone now covers the recovered
         state, and heap refs in it are the LIVE ones.

    Returns ``(kvs, summary)``."""
    from hermes_tpu.kvs import KVS
    from hermes_tpu.wal import replay as wal_replay

    t0 = time.perf_counter()
    wal_dir = wal_dir if wal_dir is not None else cfg.wal_dir
    if wal_dir is None:
        raise ValueError("recover_store needs a wal_dir (argument or "
                         "cfg.wal_dir)")
    cfg = dataclasses.replace(cfg, wal_dir=wal_dir)
    scan = wal_replay.read_records(wal_dir)
    wal_replay.check_headers(scan["headers"], cfg)
    kvs = KVS(cfg, backend=backend, mesh=mesh, record=record,
              sparse_keys=sparse_keys)
    if snapshot_path is not None:
        snapshot_lib.load(snapshot_path, kvs)
    applied, skipped = wal_replay.apply_records(
        kvs.rt, scan["records"], heap=kvs.heap)
    max_step = max((int(r["step"].max()) for r in scan["records"]
                    if r["step"].size), default=-1)
    kvs.rt.step_idx = max(kvs.rt.step_idx, max_step + 1)
    kvs.rt._ctl_dirty = True
    for rec in scan["records"]:
        kvs.wal.append_round(rec["round_idx"], rec["step"], rec["key"],
                             rec["ver"], rec["fc"], rec["wv"],
                             rec["lens"], rec["blob"])
    kvs.wal.sync()
    kvs.wal.retire_segments(scan["segments"])
    summary = dict(records=sum(int(r["key"].shape[0])
                               for r in scan["records"]),
                   applied=applied, skipped=skipped,
                   torn_tail=bool(scan["torn_tail"]),
                   old_segments=len(scan["segments"]),
                   resume_step=int(kvs.rt.step_idx),
                   seconds=round(time.perf_counter() - t0, 3))
    kvs.rt._trace("wal_recover", **summary)
    return kvs, summary
