"""Run driver: step loop, membership service hooks, history recording.

This is the rebuild of the reference's L0/L4/L7 host side (SURVEY.md §1):
``main()``+worker-loop becomes a host loop over compiled steps; the
membership service (epoch + live bitmap + lease bookkeeping, SURVEY.md §5.3)
lives here on the host, exactly where Hermes puts it (an external service,
not the data plane); stats are read off the device Meta counters.

Backends:
  * ``batched``  — R replicas on one device, fused jit step (test/bench mode,
                   the reference's single-process multi-replica pattern,
                   BASELINE.json:7)
  * ``sharded``  — one replica per mesh device, fused jit step with ICI
                   collectives (transport=tpu_ici, BASELINE.json:5)
  * ``sim``      — host-mediated exchanges through a SimTransport (or any
                   HostTransport): deterministic adversarial scheduling
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hermes_tpu.checker.history import HistoryRecorder
from hermes_tpu.checker.fast import ArrayRecorder, check_arrays
from hermes_tpu.checker import linearizability as lin
from hermes_tpu.config import HermesConfig
from hermes_tpu.core import state as st, step as step_lib
from hermes_tpu.core import types as t
from hermes_tpu.workload import ycsb


class _ObsHooks:
    """Shared observability surface of both run drivers (hermes_tpu.obs):
    ``attach_obs`` installs the run's Observability context; fault-injection
    and membership transitions emit point events on its timeline, drains and
    rebases emit spans.  Interval metrics stay the caller's job (cli.py /
    scripts poll ``counters()``/``stats.summarize`` at their own cadence).
    Everything is a no-op while no obs context is attached."""

    obs = None

    def attach_obs(self, obs):
        self.obs = obs
        return obs

    def _trace(self, name: str, **fields) -> None:
        if self.obs is not None:
            self.obs.tracer.event(name, step=self.step_idx, **fields)


class Runtime(_ObsHooks):
    def __init__(
        self,
        cfg: HermesConfig,
        backend: str = "batched",
        mesh=None,
        transport=None,
        record: bool = False,
        stream: Optional[st.OpStream] = None,
    ):
        self.cfg = cfg
        self.backend = backend
        r = cfg.n_replicas

        rs0 = st.init_replica_state(cfg)
        self.rs = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), rs0)
        raw = stream if stream is not None else ycsb.make_streams(cfg)
        self.stream = jax.tree.map(jnp.asarray, raw)

        self.step_idx = 0
        self.epoch = np.zeros((r,), np.int32)
        self.live = np.full((r,), cfg.full_mask, np.int32)
        self.frozen = np.zeros((r,), bool)

        self.recorder = HistoryRecorder(cfg) if record else None
        self.membership = None  # optional MembershipService (attach_membership)

        if backend == "batched":
            self._fused = step_lib.build_step_batched(cfg)
        elif backend == "sharded":
            if mesh is None:
                raise ValueError("sharded backend needs a mesh")
            self._fused = step_lib.build_step_sharded(cfg, mesh)
            self.rs, self.stream = step_lib.place_sharded(cfg, mesh, self.rs, self.stream)
        elif backend == "sim":
            from hermes_tpu.transport.sim import SimTransport

            self._fused = None
            self.transport = transport if transport is not None else SimTransport(r)
            ph = step_lib.vmapped_phases(cfg)
            self._ph = {k: jax.jit(v) for k, v in ph.items()}
        else:
            raise ValueError(f"unknown backend {backend!r}")

    # -- control -----------------------------------------------------------

    def _ctl(self) -> step_lib.StepCtl:
        return step_lib.StepCtl(
            step=jnp.int32(self.step_idx),
            epoch=jnp.asarray(self.epoch),
            live_mask=jnp.asarray(self.live),
            frozen=jnp.asarray(self.frozen),
        )

    def freeze(self, replica: int) -> None:
        """Failure injection: replica stops processing and emitting
        (config 4, BASELINE.json:10)."""
        self.frozen[replica] = True
        self._trace("freeze", replica=replica)

    def thaw(self, replica: int) -> None:
        self.frozen[replica] = False
        self._trace("thaw", replica=replica)

    def set_live(self, mask: int) -> None:
        """Membership change: new live bitmap, epoch bump everywhere (stale
        epoch messages are dropped on receipt)."""
        self.live[:] = mask
        self.epoch += 1

    def remove(self, replica: int) -> None:
        """Remove from membership AND fence: a removed replica must stop
        serving reads immediately (its keys can go stale the moment the
        quorum shrinks past it) — the lease self-fencing rule (SURVEY.md
        §5.3).  Freezing is how a fenced replica is modeled; join() unfences
        after state transfer."""
        self.frozen[replica] = True
        self.set_live(int(self.live[0]) & ~(1 << replica))
        self._trace("remove", replica=replica, live_mask=int(self.live[0]))

    def join(self, replica: int, from_replica: int) -> None:
        """Reconfiguration join (config 5, BASELINE.json:11): state transfer
        from a live replica, then admit.  Keys the donor holds in
        WRITE/TRANS/REPLAY (its own pending coordination) enter the joiner as
        INVALID — the joiner has no session/replay slot for them; the live
        coordinator's VAL (or the replay scan) validates them."""
        tbl = self.rs.table
        donor_state = tbl.state[from_replica]
        j_state = jnp.where(
            (donor_state == t.WRITE) | (donor_state == t.TRANS) | (donor_state == t.REPLAY),
            t.INVALID,
            donor_state,
        )
        new_tbl = st.KeyTable(
            state=tbl.state.at[replica].set(j_state),
            ver=tbl.ver.at[replica].set(tbl.ver[from_replica]),
            fc=tbl.fc.at[replica].set(tbl.fc[from_replica]),
            val=tbl.val.at[replica].set(tbl.val[from_replica]),
            inv_step=tbl.inv_step.at[replica].set(jnp.int32(self.step_idx)),
        )
        self.rs = self.rs._replace(table=new_tbl)
        self.frozen[replica] = False
        self.set_live(int(self.live[0]) | (1 << replica))
        self._trace("join", replica=replica, from_replica=from_replica,
                    live_mask=int(self.live[0]))
        if self.membership is not None:
            self.membership.note_join(self, replica)

    # -- stepping ----------------------------------------------------------

    def attach_membership(self, service) -> None:
        """Enable automatic lease-based failure detection: the service polls
        heartbeat clocks after every step (membership.MembershipService)."""
        self.membership = service

    def step_once(self) -> None:
        ctl = self._ctl()
        obs = self.obs
        trace = obs is not None and obs.trace_steps
        if trace:
            td = obs.tracer.span_begin("step_dispatch", step=self.step_idx)
        if self._fused is not None:
            self.rs, comp = self._fused(self.rs, self.stream, ctl)
        else:
            self.rs, comp = self._host_step(ctl)
        if trace:
            obs.tracer.span_end("step_dispatch", td)
        if self.recorder is not None:
            if trace:
                tr = obs.tracer.span_begin("readback", step=self.step_idx)
            comp_np = jax.device_get(comp)
            if trace:
                obs.tracer.span_end("readback", tr)
            self.recorder.record_step(comp_np)
        self.step_idx += 1
        if self.membership is not None:
            self.membership.poll(self)

    def _host_step(self, ctl: step_lib.StepCtl):
        """One step through step._step_core with host-mediated exchanges
        (sim/tcp transports) — the same body the fused backends run."""
        cfg = self.cfg
        pctl = step_lib._per_replica_ctl(cfg, ctl)
        step = self.step_idx

        def ex(fn):
            return lambda blk: _to_jnp(fn(jax.device_get(blk), step))

        return step_lib._step_core(
            cfg,
            self._ph,
            ex(self.transport.exchange_inv),
            ex(self.transport.exchange_ack),
            ex(self.transport.exchange_val),
            self.rs,
            self.stream,
            pctl,
        )

    def run(self, n_steps: int) -> None:
        for _ in range(n_steps):
            self.step_once()

    def drain(self, max_steps: int = 10_000) -> bool:
        """Step until every session finished its stream and the network is
        empty; returns False if max_steps elapsed first."""
        if self.obs is not None:
            with self.obs.tracer.span("drain", step=self.step_idx):
                return self._drain(max_steps)
        return self._drain(max_steps)

    def _drain(self, max_steps: int) -> bool:
        for _ in range(max_steps):
            status = np.asarray(jax.device_get(self.rs.sess.status))
            live0 = int(self.live[0])
            done = np.array(
                [
                    (status[r] == t.S_DONE).all() or not (live0 >> r) & 1 or self.frozen[r]
                    for r in range(self.cfg.n_replicas)
                ]
            ).all()
            pending = getattr(self, "transport", None)
            net_empty = pending.pending() == 0 if pending is not None else True
            if done and net_empty:
                return True
            self.step_once()
        return False

    # -- observability -----------------------------------------------------

    def counters(self) -> dict:
        m = jax.device_get(self.rs.meta)
        return dict(
            n_read=np.asarray(m.n_read).sum(),
            n_write=np.asarray(m.n_write).sum(),
            n_rmw=np.asarray(m.n_rmw).sum(),
            n_abort=np.asarray(m.n_abort).sum(),
            lat_sum=np.asarray(m.lat_sum).sum(),
            lat_cnt=np.asarray(m.lat_cnt).sum(),
            lat_hist=np.asarray(m.lat_hist).sum(axis=0),
        )

    def history_ops(self):
        assert self.recorder is not None, "construct Runtime(record=True)"
        return self.recorder.finalize(jax.device_get(self.rs.sess))

    def check(self, max_keys: Optional[int] = None) -> lin.Verdict:
        """Finalize the history and run the linearizability gate
        (BASELINE.json:2)."""
        ops = self.history_ops()
        if max_keys is not None:
            ops = lin.sample_keys(ops, max_keys=max_keys)
        v = lin.check_history(ops, aborted_uids=self.recorder.aborted_uids)
        self._trace("checker_verdict", ok=v.ok, keys_checked=v.keys_checked)
        return v


def _to_jnp(block):
    return jax.tree.map(jnp.asarray, block)


class FastRuntime(_ObsHooks):
    """Run driver for the TPU-optimized round (core/faststep.py): same
    membership / failure-injection / history-recording surface as Runtime,
    over the packed-column FastState.  Backends: ``batched`` (R replicas on
    one device) and ``sharded`` (one replica per mesh device — the
    transport=tpu_ici layout, BASELINE.json:5)."""

    def __init__(self, cfg: HermesConfig, backend: str = "batched", mesh=None,
                 record=False, stream: Optional[st.OpStream] = None):
        from hermes_tpu.core import faststep as fst

        self.cfg = cfg
        self.backend = backend
        r = cfg.n_replicas
        # sharded: every shard owns its own value table (n_local allocates
        # per-replica vals); batched shares one (see faststep.FastTable)
        self.fs = fst.init_fast_state(cfg, n_local=r if backend == "sharded" else None)
        if cfg.device_stream:
            if stream is not None:
                raise ValueError(
                    "device_stream generates ops on device; a caller-supplied "
                    "op stream would be silently ignored")
            raw = ycsb.stub_stream(cfg)
        else:
            raw = stream if stream is not None else ycsb.make_streams(cfg)
        self.stream = fst.prep_stream(raw)

        self.step_idx = 0
        self.epoch = np.zeros((r,), np.int32)
        self.live = np.full((r,), cfg.full_mask, np.int32)
        self.frozen = np.zeros((r,), bool)
        # version-rebase state (round-4, rebase_versions): host quiesce
        # flag (traced into FastCtl — flipping it never recompiles),
        # cumulative per-key version deltas for recorder continuity, and
        # the lazily-built rebase program
        self.quiesce = False
        self.rebases = 0
        # watermark value that TRIGGERED each auto-rebase (the true
        # pre-rebase peak — counter polls otherwise only ever see the
        # post-rebase value at the poll where a rebase fired)
        self.prerebase_peaks: list = []
        self._ver_base = None  # np.int64 (K,), allocated on first rebase
        self._rebase_fn = None
        self._in_rebase = False
        self._next_rebase_at = 0
        # completion consumer for rebase's internal quiesce drain: a client
        # layer that resolves futures off step_once's Completions (kvs.KVS)
        # installs its own step here so drained completions are never
        # dropped on the floor
        self.comp_sink = None
        # completion fetch per round (device->host).  At bench shape the
        # Completions tuple is tens of MB — a telemetry-only driver (e.g.
        # scripts/rebase_soak.py) sets this False to poll counters alone;
        # recording/client runs need it True (the default)
        self.fetch_completions = True
        # record: False | True (Python Op recorder) | "array" (columnar
        # recorder + native witness checker, checker/fast.py — bench scale)
        if record == "array":
            self.recorder = ArrayRecorder(cfg)
        else:
            self.recorder = HistoryRecorder(cfg) if record else None
        self.membership = None

        if backend == "batched":
            self._step = fst.build_fast_batched(cfg)
        elif backend == "sharded":
            if mesh is None:
                raise ValueError("sharded backend needs a mesh")
            self._step = fst.build_fast_sharded(cfg, mesh, rounds=1, donate=False)
            self.fs, self.stream = fst.place_fast_sharded(cfg, mesh, self.fs, self.stream)
            self.mesh = mesh
        else:
            raise ValueError(f"unknown backend {backend!r}")
        self._fst = fst

    def _ctl(self):
        fst = self._fst
        r = self.cfg.n_replicas
        return fst.FastCtl(
            step=jnp.int32(self.step_idx),
            my_cid=jnp.arange(r, dtype=jnp.int32),
            epoch=jnp.asarray(self.epoch),
            live_mask=jnp.asarray(self.live),
            frozen=jnp.asarray(self.frozen),
            quiesce=jnp.bool_(self.quiesce),
        )

    # -- membership / failure injection (same surface as Runtime) ----------

    def freeze(self, replica: int) -> None:
        self.frozen[replica] = True
        self._trace("freeze", replica=replica)

    def thaw(self, replica: int) -> None:
        self.frozen[replica] = False
        self._trace("thaw", replica=replica)

    def set_live(self, mask: int) -> None:
        self.live[:] = mask
        self.epoch += 1

    def remove(self, replica: int) -> None:
        self.frozen[replica] = True
        self.set_live(int(self.live[0]) & ~(1 << replica))
        self._trace("remove", replica=replica, live_mask=int(self.live[0]))

    def join(self, replica: int, from_replica: int) -> None:
        """Reconfiguration join (config 5, BASELINE.json:11): copy a live
        donor's table; the donor's own pending-coordination keys enter the
        joiner as Invalid (validated by the live coordinator's VAL/replay)."""
        fst = self._fst
        tbl = self.fs.table
        K = self.cfg.n_keys
        if tbl.vpts.shape[0] != K:
            # sharded: each shard owns its table — transfer the donor's
            # rows, folding its in-flight coordination states to Invalid (the
            # live coordinator's VAL or the replay scan re-validates them)
            dst, dsrc = replica * K, from_replica * K
            d_rows = fst._bank_to_i32(
                jax.lax.dynamic_slice_in_dim(tbl.bank, dsrc, K))
            d_state = fst.sst_state(d_rows[:, fst.BANK_SST])
            j_state = jnp.where(
                (d_state == t.WRITE) | (d_state == t.TRANS) | (d_state == t.REPLAY),
                t.INVALID, d_state,
            )
            j_rows = d_rows.at[:, fst.BANK_SST].set(
                fst.pack_sst(jnp.int32(self.step_idx), j_state)
            )
            # (No issue-ledger transfer exists: a faststep write always
            # broadcasts — and so invalidates its key — in its own round,
            # so the joiner's in-flight writes are visible in the table
            # itself; see faststep._coordinate's revert rule.)
            self.fs = self.fs._replace(table=tbl._replace(
                vpts=jax.lax.dynamic_update_slice_in_dim(
                    tbl.vpts, jax.lax.dynamic_slice_in_dim(tbl.vpts, dsrc, K),
                    dst, 0),
                bank=jax.lax.dynamic_update_slice_in_dim(
                    tbl.bank, fst._i32_to_bank(j_rows), dst, 0),
            ))
        # batched: the authoritative table is shared — it already IS the
        # joiner's state, so no transfer is needed.
        self.frozen[replica] = False
        self.set_live(int(self.live[0]) | (1 << replica))
        self._trace("join", replica=replica, from_replica=from_replica,
                    live_mask=int(self.live[0]))
        if self.membership is not None:
            self.membership.note_join(self, replica)

    def attach_membership(self, service) -> None:
        self.membership = service

    # -- stepping ----------------------------------------------------------

    def step_once(self):
        """One protocol round; returns the host-side Completions (also fed to
        the recorder when recording).  Multi-host runs (jax.distributed,
        hermes_tpu/launch.py) skip the completion fetch — the global arrays
        span non-addressable devices; use counters() (which allgathers) for
        observability there."""
        obs = self.obs
        trace = obs is not None and obs.trace_steps
        if trace:
            td = obs.tracer.span_begin("step_dispatch", step=self.step_idx)
        self.fs, comp = self._step(self.fs, self.stream, self._ctl())
        if trace:
            obs.tracer.span_end("step_dispatch", td)
        if jax.process_count() > 1:
            assert self.recorder is None, "history recording is single-host only"
            self.step_idx += 1
            return None
        if not self.fetch_completions and self.recorder is None:
            self.step_idx += 1
            if self.membership is not None:
                self.membership.poll(self)
            return None
        if trace:
            tr = obs.tracer.span_begin("readback", step=self.step_idx)
        comp_np = jax.device_get(comp)
        if trace:
            obs.tracer.span_end("readback", tr)
        if self._ver_base is not None:
            # re-anchor post-rebase versions into the global (monotone)
            # version space the recorder/checker needs (see rebase_versions)
            multi = isinstance(comp_np, tuple) and not isinstance(comp_np, st.Completions)
            fix = lambda c: c._replace(
                ver=np.asarray(c.ver).astype(np.int64)
                + self._ver_base[np.asarray(c.key)])
            comp_np = (tuple(fix(c) for c in comp_np) if multi
                       else fix(comp_np))
        if self.recorder is not None:
            # read_unroll > 1 yields one Completions per sub-step, in
            # program order; record each
            multi = isinstance(comp_np, tuple) and not isinstance(comp_np, st.Completions)
            subs = comp_np if multi else (comp_np,)
            for c in subs:
                self.recorder.record_step(c)
        self.step_idx += 1
        if self.membership is not None:
            self.membership.poll(self)
        return comp_np

    def run(self, n_steps: int) -> None:
        for _ in range(n_steps):
            self.step_once()

    # -- version rebase (round-4; round-3 verdict item 4) ------------------

    def _inflight_count(self) -> int:
        s = jnp.sum((self.fs.sess.status == t.S_INFL).astype(jnp.int32))
        rp = jnp.sum(self.fs.replay.active.astype(jnp.int32))
        return int(jax.device_get(s + rp))

    def rebase_versions(self, quiesce: bool = True,
                        max_quiesce_rounds: int = 256) -> int:
        """Restore packed-ts headroom by resetting quiesced keys to version
        1 (faststep.build_rebase).  With ``quiesce`` (default), new intake
        and issues pause (FastCtl.quiesce — traced, no recompile) while
        in-flight writes/replays drain, so in a healthy run EVERY written
        key becomes eligible; frozen/dead replicas can pin their keys busy,
        in which case the pass is best-effort (busy keys keep their
        versions — sound, just less headroom recovered).

        Recorded histories stay checkable across the rebase: the per-key
        version delta accumulates in ``_ver_base`` and is added back to
        every later completion, so the checker's (ver, fc) witness order
        is globally monotone even though on-device versions restart.

        Returns the number of keys rebased."""
        if self.obs is not None:
            with self.obs.tracer.span("rebase_versions", step=self.step_idx):
                return self._rebase_versions(quiesce, max_quiesce_rounds)
        return self._rebase_versions(quiesce, max_quiesce_rounds)

    def _rebase_versions(self, quiesce: bool, max_quiesce_rounds: int) -> int:
        fst = self._fst
        if jax.process_count() > 1:
            raise NotImplementedError("rebase_versions is single-host only")
        if quiesce:
            prev = self.quiesce  # host may already be quiescing — restore
            self.quiesce = True
            step = self.comp_sink or self.step_once
            try:
                for _ in range(max_quiesce_rounds):
                    if self._inflight_count() == 0:
                        break
                    step()
            finally:
                self.quiesce = prev
        if self._rebase_fn is None:
            self._rebase_fn = fst.build_rebase(
                self.cfg, backend=self.backend,
                mesh=getattr(self, "mesh", None))
        self.fs, delta = self._rebase_fn(self.fs)
        delta = np.asarray(jax.device_get(delta)).astype(np.int64)
        n = int(np.count_nonzero(delta))
        if n:
            if self._ver_base is None:
                self._ver_base = np.zeros(self.cfg.n_keys, np.int64)
            self._ver_base += delta
            self.rebases += 1
        return n

    def drain(self, max_steps: int = 10_000) -> bool:
        if jax.process_count() > 1:
            raise NotImplementedError(
                "drain() polls per-step session status and is single-host "
                "only; multi-host runs should use run(n_steps)")
        if self.obs is not None:
            with self.obs.tracer.span("drain", step=self.step_idx):
                return self._drain(max_steps)
        return self._drain(max_steps)

    def _drain(self, max_steps: int) -> bool:
        for _ in range(max_steps):
            status = np.asarray(jax.device_get(self.fs.sess.status))
            live0 = int(self.live[0])
            done = all(
                (status[r] == t.S_DONE).all() or not (live0 >> r) & 1 or self.frozen[r]
                for r in range(self.cfg.n_replicas)
            )
            if done:
                return True
            self.step_once()
        return False

    # -- observability -----------------------------------------------------

    def counters(self) -> dict:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # meta leaves are (R, ...) sharded over the global 'replica'
            # axis; tiled=True reassembles the global value on every host
            # (non-fully-addressable arrays reject the stacking default)
            m = multihost_utils.process_allgather(self.fs.meta, tiled=True)
        else:
            m = jax.device_get(self.fs.meta)
        max_ver = self._check_version_headroom(m)
        return dict(
            n_read=np.asarray(m.n_read).sum(),
            n_write=np.asarray(m.n_write).sum(),
            n_rmw=np.asarray(m.n_rmw).sum(),
            n_abort=np.asarray(m.n_abort).sum(),
            lat_sum=np.asarray(m.lat_sum).sum(),
            lat_cnt=np.asarray(m.lat_cnt).sum(),
            lat_hist=np.asarray(m.lat_hist).sum(axis=0),
            max_ver=max_ver,
        )

    def _check_version_headroom(self, m) -> int:
        """Packed-ts overflow guard (HermesConfig.max_key_versions): the
        engine tracks the max issued packed ts (Meta.max_pts); past the
        documented limit the int32 Lamport compare would corrupt silently.
        With ``cfg.auto_rebase`` (default), crossing the soft watermark
        (``cfg.rebase_fraction`` of the budget) at a counter poll triggers
        a quiesce+rebase (rebase_versions) that restores headroom instead
        of marching toward the cliff; the loud RuntimeError remains as the
        backstop for keys that cannot be rebased (e.g. pinned busy by a
        frozen coordinator).  Returns the high-water version."""
        from hermes_tpu.core import faststep as fst

        max_ver = int(np.asarray(m.max_pts).max()) >> fst.PTS_FC_BITS
        soft = int(self.cfg.rebase_fraction * self.cfg.max_key_versions)
        if (self.cfg.auto_rebase and not self._in_rebase
                and max_ver >= max(soft, self._next_rebase_at)
                and jax.process_count() == 1):
            self._in_rebase = True
            self.prerebase_peaks.append(max_ver)
            try:
                self.rebase_versions()
            finally:
                self._in_rebase = False
            max_ver = int(np.asarray(
                jax.device_get(self.fs.meta.max_pts)).max()) >> fst.PTS_FC_BITS
            # back off when a key can't be reclaimed (e.g. pinned busy by a
            # frozen coordinator): don't re-pay the quiesce drain on every
            # poll — only once the watermark has grown meaningfully again
            self._next_rebase_at = max_ver + max(
                1, self.cfg.max_key_versions // 64)
        if max_ver >= self.cfg.max_key_versions:
            raise RuntimeError(
                f"packed-timestamp overflow: a key reached version "
                f"{max_ver} >= max_key_versions={self.cfg.max_key_versions};"
                f" faststep's int32 packed ts cannot represent further "
                f"versions of this key — auto-rebase could not reclaim it "
                f"(busy/unquiesceable key); use the phases engine (Runtime) "
                f"for runs that rotate single keys this long"
            )
        return max_ver

    def _sess_view(self):
        fst = self._fst
        sess = jax.device_get(self.fs.sess)
        # sess.val holds int8 value BYTES; recorders read uid WORDS 0-1
        val32 = np.asarray(jax.device_get(fst._bank_to_i32(jnp.asarray(sess.val))))
        ver = np.asarray(fst.pts_ver(jnp.asarray(sess.pts))).astype(np.int64)
        if self._ver_base is not None:
            # pending in-flight ops carry current-era versions; re-anchor
            # them like step_once does for completions
            ver = ver + self._ver_base[np.asarray(sess.key)]
        return type("SessView", (), dict(
            status=sess.status, op=sess.op, key=sess.key, val=val32,
            ver=ver,
            fc=np.asarray(fst.pts_fc(jnp.asarray(sess.pts))),
            invoke_step=sess.invoke_step,
        ))

    def history_ops(self):
        assert self.recorder is not None, "construct FastRuntime(record=True)"
        rec = self.recorder.finalize(self._sess_view())
        return rec.to_ops() if isinstance(rec, ArrayRecorder) else rec

    def check(self, max_keys: Optional[int] = None) -> lin.Verdict:
        assert self.recorder is not None, "construct FastRuntime(record=True)"
        if isinstance(self.recorder, ArrayRecorder):
            self.recorder.finalize(self._sess_view())
            v = check_arrays(self.recorder, max_keys=max_keys)
        else:
            ops = self.history_ops()
            if max_keys is not None:
                ops = lin.sample_keys(ops, max_keys=max_keys)
            v = lin.check_history(ops, aborted_uids=self.recorder.aborted_uids)
        self._trace("checker_verdict", ok=v.ok, keys_checked=v.keys_checked)
        return v
